"""Worker process for cluster flight-recorder tests: one spooling reader.

Spawned K times (concurrently) by tests/test_fleet.py and the
tools/verify.sh fleet smoke. Each process joins the parent's trace via
``TFR_TRACE_CONTEXT`` (telemetry.adopt_from_env), reads the shared
dataset with the telemetry spool on, optionally saves its own Chrome
trace, optionally lingers (heartbeating) so the parent can kill it
mid-life, and prints one JSON line with its identity and per-process
totals for the parent to check exact aggregation against.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("data_dir")
    ap.add_argument("spool_dir")
    ap.add_argument("--role", default="reader")
    ap.add_argument("--trace-out", default=None)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--interval", type=float, default=0.1)
    ap.add_argument(
        "--linger", type=float, default=0.0,
        help="keep spool heartbeats going this long after the read "
        "(so a parent can SIGKILL a demonstrably-alive worker)",
    )
    args = ap.parse_args()

    from tpu_tfrecord import fleet, telemetry
    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.metrics import METRICS
    from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

    ctx = telemetry.adopt_from_env(role=args.role)
    schema = StructType(
        [StructField("id", LongType(), nullable=False), StructField("s", StringType())]
    )
    ds = TFRecordDataset(
        args.data_dir,
        batch_size=args.batch_size,
        schema=schema,
        drop_remainder=False,
        num_epochs=args.epochs,
        trace="on" if args.trace_out else "off",
        telemetry_spool_dir=args.spool_dir,
        spool_interval_s=args.interval,
        telemetry_role=args.role,
    )
    rows = 0
    # an explicit extra spool reference: heartbeats continue through the
    # --linger window after the read (so a parent can SIGKILL a worker the
    # spool still shows alive), and the release below lands the final
    # cumulative snapshot even for trace-only exits
    fleet.acquire_spool(args.spool_dir, role=args.role, interval_s=args.interval)
    try:
        with ds.batches() as it:
            for cb in it:
                rows += cb.num_rows
        deadline = time.time() + args.linger
        while time.time() < deadline:
            time.sleep(0.02)
    finally:
        fleet.release_spool(args.spool_dir)
    if args.trace_out:
        telemetry.RECORDER.save_chrome_trace(args.trace_out)
    decode = METRICS.stage("decode")
    print(
        json.dumps(
            {
                "pid": os.getpid(),
                "host": ctx.host,
                "role": ctx.role,
                "trace_id": ctx.trace_id,
                "parent_span_id": ctx.parent_span_id,
                "rows": rows,
                "decode_records": decode.records,
                "spool_path": fleet.spool_path(args.spool_dir, ctx),
            }
        )
    )


if __name__ == "__main__":
    main()
