"""HA data service suite (ISSUE 17): the static partition map
(rendezvous ownership, spec grammar, minimal remap on growth), the v2
line-oriented dispatcher journal (durable appends over a snapshot line,
replay-to-newest-consistent-prefix under every truncation shape, pinned
with the ``torn_write`` fault kind), zombie fencing via the journal
inode (``FencedWriteError`` before any stale byte lands) and
self-demotion after consecutive journal failures, warm-standby tailing
+ promotion (generation bump, address adoption), partitioned routing
end to end, the federated FleetScaler census (dedupe across partitions,
whipsaw guard on an unreadable partition, ``DispatcherHandle`` RPCs),
the federated serve-status doctor, and THE acceptance scenario: the
primary dispatcher SIGKILLed mid-epoch, the standby taking over, and
the consumers' epochs finishing byte-identical with zero fallbacks."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from tpu_tfrecord import checkpoint, elastic, fleet, service, telemetry
from tpu_tfrecord.columnar import batch_to_rows
from tpu_tfrecord.faults import FaultPlan, FaultRule, install_chaos
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.schema import (
    ArrayType,
    LongType,
    StringType,
    StructField,
    StructType,
)

DOCTOR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "tfrecord_doctor.py",
)

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),
        StructField("arr", ArrayType(LongType())),
    ]
)
ROWS = [
    [i, None if i % 7 == 0 else f"v{i}" * (i % 3 + 1), list(range(i % 5))]
    for i in range(180)
]
PER_SHARD = 30  # 6 shards


@pytest.fixture(autouse=True)
def _reset_metrics():
    METRICS.reset()
    yield


@pytest.fixture
def data_dir(sandbox):
    out = str(sandbox / "ds")
    DatasetWriter(
        out, SCHEMA, mode="overwrite", max_records_per_file=PER_SHARD
    ).write_rows(ROWS)
    return out


def make_ds(data_dir, **kw):
    return TFRecordDataset(
        data_dir, batch_size=8, schema=SCHEMA, drop_remainder=False,
        num_epochs=1, **kw,
    )


def collect(data_dir, **kw):
    ds = make_ds(data_dir, **kw)
    got = []
    with ds.batches() as it:
        for b in it:
            got.extend(batch_to_rows(b, ds.schema))
    return got


@pytest.fixture
def local_rows(data_dir):
    return collect(data_dir)


def wait_for(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _register(d, wid):
    r = d._handle({"op": "register_worker", "worker_id": wid,
                   "addr": f"h:{wid}", "pid": 0})
    return r


def _route(d, shard_index, exclude=()):
    return d._handle(
        {
            "op": "route",
            "job": "j",
            "path": f"/data/shard-{shard_index}",
            "shard_index": shard_index,
            "exclude": list(exclude),
        }
    )


def _journal_records(path):
    with open(path, "rb") as fh:
        data = fh.read()
    return [json.loads(ln) for ln in data.split(b"\n") if ln.strip()]


class FakeAggregator:
    """Script-controlled verdict source (the scaler's test seam)."""

    def __init__(self, verdict="balanced", running=True):
        self.verdict = verdict
        self.running = running

    def aggregate(self, roles=None):
        procs = []
        if self.running:
            procs = [fleet.ProcessSnapshot(
                path="fake", host="h", pid=1, role="trainer", trace_id=None,
                heartbeat=time.time(), interval_s=1.0, seq=1,
                gauges={telemetry.OCCUPANCY_GAUGE: 0.1},
            )]
        return fleet.FleetSnapshot(
            processes=procs, alive=procs, dead=[], counters={}, stages={},
            hists={}, verdict=self.verdict, occupancy=None,
        )


# ---------------------------------------------------------------------------
# PartitionMap: spec grammar + rendezvous ownership
# ---------------------------------------------------------------------------


class TestPartitionMap:
    def test_spec_forms_and_roundtrip(self):
        pm = service.PartitionMap.parse("127.0.0.1:70")
        assert pm.k == 1 and pm.addrs(0) == ["127.0.0.1:70"]
        pm = service.PartitionMap.parse("h:1|h:2, h:3|h:4")
        assert pm.k == 2
        # primary first, then the standby — the client's rotation order
        assert pm.addrs(0) == ["h:1", "h:2"]
        assert pm.addrs(1) == ["h:3", "h:4"]
        assert pm.to_spec() == "h:1|h:2,h:3|h:4"
        assert service.PartitionMap.parse(pm.to_spec()).partitions == pm.partitions

    def test_file_spec(self, tmp_path):
        p = tmp_path / "map.json"
        p.write_text(json.dumps(
            {"partitions": [["h:1", "h:2"], ["h:3"]]}
        ))
        pm = service.PartitionMap.parse(f"@{p}")
        assert pm.k == 2 and pm.addrs(0) == ["h:1", "h:2"]

    def test_garbage_specs_are_loud(self, tmp_path):
        for spec in ("nonsense", "", "h:1,|", f"@{tmp_path}/absent.json"):
            with pytest.raises((OSError, ValueError)):
                service.PartitionMap.parse(spec)

    def test_rendezvous_is_deterministic_and_covers_every_partition(self):
        pm = service.PartitionMap.parse("h:1,h:2,h:3")
        tenants = [f"tenant-{i:04x}" for i in range(300)]
        owners = [pm.partition_for(t) for t in tenants]
        assert owners == [pm.partition_for(t) for t in tenants]
        assert set(owners) == {0, 1, 2}

    def test_growing_k_remaps_only_a_minority(self):
        """The rendezvous property the map exists for: adding partition
        N+1 steals ~1/(N+1) of the tenants and moves NOTHING else."""
        pm2 = service.PartitionMap([["h:1"], ["h:2"]])
        pm3 = service.PartitionMap([["h:1"], ["h:2"], ["h:3"]])
        tenants = [f"tenant-{i:04x}" for i in range(300)]
        moved = 0
        for t in tenants:
            before, after = pm2.partition_for(t), pm3.partition_for(t)
            if before != after:
                moved += 1
                # a moved tenant moved TO the new partition, never
                # between survivors
                assert after == 2
        assert 0 < moved < 150  # ~100 expected; never a majority


# ---------------------------------------------------------------------------
# Journal v2: snapshot + durable delta lines
# ---------------------------------------------------------------------------


class TestJournalV2:
    def test_snapshot_plus_deltas_roundtrip(self, tmp_path):
        j = str(tmp_path / "j.json")
        d = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            _register(d, "w0")
            _register(d, "w1")
            assert _route(d, 0)["ok"]
            d._handle({"op": "shard_done", "job": "j",
                       "path": "/data/shard-0", "worker_id": "w0"})
        finally:
            d.stop()
        recs = _journal_records(j)
        assert recs[0]["kind"] == "snapshot"
        assert recs[0]["version"] == service.JOURNAL_VERSION
        assert recs[0]["generation"] == 0
        assert [r["kind"] for r in recs[1:]] == [
            "register", "register", "lease", "done",
        ]
        d2 = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            st = d2.status()
            assert {w["worker_id"] for w in st["workers"]} == {"w0", "w1"}
            assert st["shards_done"] == 1 and st["active_leases"] == 0
        finally:
            d2.stop()

    def test_v1_journal_upgraded_in_place(self, tmp_path):
        j = str(tmp_path / "j.json")
        with open(j, "wb") as fh:
            fh.write(json.dumps({
                "workers": {"w0": {"addr": "h:w0", "pid": 7}},
                "leases": {"t/data-0": "w0"},
                "done": {},
                "reassignments": 3,
            }).encode())
        d = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            st = d.status()
            assert [w["worker_id"] for w in st["workers"]] == ["w0"]
            assert st["active_leases"] == 1
            assert st["lease_reassignments"] == 3
        finally:
            d.stop()
        # birth compaction rewrote the legacy object as a v2 snapshot line
        recs = _journal_records(j)
        assert len(recs) == 1
        assert recs[0]["kind"] == "snapshot"
        assert recs[0]["version"] == service.JOURNAL_VERSION

    def test_compaction_bounds_the_delta_tail(self, tmp_path, monkeypatch):
        monkeypatch.setattr(service, "JOURNAL_COMPACT_EVERY", 4)
        j = str(tmp_path / "j.json")
        d = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            for i in range(10):
                _register(d, f"w{i}")
            recs = _journal_records(j)
            # 10 appends with compaction every 4: the file is snapshot +
            # at most 3 trailing deltas, never the raw mutation history
            assert recs[0]["kind"] == "snapshot"
            assert len(recs) <= 4
            assert len(recs[0]["workers"]) >= 7
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# Truncation replay: newest consistent prefix (satellite 3)
# ---------------------------------------------------------------------------


class TestJournalTruncation:
    def test_empty_journal_is_a_fresh_start(self, tmp_path):
        j = str(tmp_path / "j.json")
        open(j, "wb").close()
        d = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            assert d.status()["workers"] == []
            assert d.accepting
        finally:
            d.stop()

    def test_torn_final_line_drops_only_the_tail(self, tmp_path):
        j = str(tmp_path / "j.json")
        d = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            _register(d, "w0")
            _register(d, "w1")
        finally:
            d.stop()
        with open(j, "ab") as fh:
            fh.write(b'{"kind": "register", "worker_id": "w')  # no newline
        d2 = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            st = d2.status()
            assert {w["worker_id"] for w in st["workers"]} == {"w0", "w1"}
            assert d2.accepting
        finally:
            d2.stop()

    def test_mid_record_tear_keeps_the_prefix_before_it(self, tmp_path):
        """A tear in the MIDDLE of the file (a record that is complete as
        a line but not as JSON): everything before it replays, everything
        after it is ignored — records past a tear were written by a
        writer that already knew its append failed."""
        j = str(tmp_path / "j.json")
        snap = {"kind": "snapshot", "version": 2, "generation": 0,
                "workers": {}, "leases": {}, "done": {}}
        with open(j, "wb") as fh:
            fh.write(json.dumps(snap).encode() + b"\n")
            fh.write(b'{"kind": "register", "worker_id": "w0", '
                     b'"addr": "h:0", "pid": 0}\n')
            fh.write(b'{"kind": "regis\n')  # torn, newline landed
            fh.write(b'{"kind": "register", "worker_id": "w1", '
                     b'"addr": "h:1", "pid": 0}\n')
        d = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            st = d.status()
            assert [w["worker_id"] for w in st["workers"]] == ["w0"]
        finally:
            d.stop()

    def test_parse_journal_units(self):
        parse = service.ServiceDispatcher._parse_journal
        assert parse(b"") == []
        assert parse(b"   \n") == []
        snap = json.dumps({"kind": "snapshot", "generation": 1}).encode()
        assert parse(snap + b"\n")[0]["generation"] == 1
        # torn tail after the last newline is dropped by construction
        assert len(parse(snap + b"\n" + b'{"kind": "reg')) == 1
        # a complete line WITHOUT a "kind" ends the consistent prefix
        assert len(parse(snap + b"\n" + b'{"nope": 1}\n' + snap + b"\n")) == 1

    def test_torn_write_fault_kind_pins_crash_mid_append(self, tmp_path):
        """The ISSUE-17 pin: tear a journal append at a byte cap with the
        ``torn_write`` fault kind (the exact bytes a host crash
        mid-append leaves behind), then replay — the torn record is
        absorbed, the prefix survives, and the failure was counted."""
        j = str(tmp_path / "j.json")
        plan = FaultPlan(
            [FaultRule(op="journal", kind="torn_write", cap_bytes=12,
                       ordinal=1)]  # ordinal 0 is the birth compaction
        )
        with install_chaos(plan):
            d = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
            try:
                _register(d, "w0")  # this append tears
            finally:
                d.stop()
        fired = [e for e in plan.ledger if e["kind"] == "torn_write"]
        assert len(fired) == 1 and fired[0]["cap_bytes"] == 12
        assert METRICS.counter("service.journal_errors") == 1
        with open(j, "rb") as fh:
            data = fh.read()
        # 12 record bytes landed after the snapshot's newline — a torn
        # tail, not a parseable record
        tail = data.split(b"\n")[-1]
        assert len(tail) == 12
        d2 = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        try:
            assert d2.status()["workers"] == []  # torn register absorbed
            assert d2.accepting
        finally:
            d2.stop()


# ---------------------------------------------------------------------------
# Fencing + self-demotion (satellite 2)
# ---------------------------------------------------------------------------


class TestFencingAndDemotion:
    def test_durable_append_fences_before_any_byte_lands(self, tmp_path):
        p = str(tmp_path / "log")
        checkpoint.durable_write(p, b"a\n")
        ino = os.stat(p).st_ino
        assert checkpoint.durable_append(p, b"b\n", expect_ino=ino) == ino
        checkpoint.durable_write(p, b"replaced\n")  # new inode
        with pytest.raises(checkpoint.FencedWriteError):
            checkpoint.durable_append(p, b"stale\n", expect_ino=ino)
        with open(p, "rb") as fh:
            assert fh.read() == b"replaced\n"

    def test_resurrected_zombie_is_fenced_and_demoted(self, tmp_path):
        """The zero-duplicate-grants pin: after a standby promotes, the
        old primary's very next journaled mutation hits the inode fence,
        lands zero bytes, and demotes it — every later lease op is
        rejected with ``not_primary``."""
        j = str(tmp_path / "j.json")
        a = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0)
        b = None
        try:
            _register(a, "w0")
            assert _route(a, 0)["worker_id"] == "w0"
            b = service.ServiceDispatcher(
                journal=j, standby_of=a.addr, lease_ttl_s=5.0,
                takeover_addr=False,
            )
            b.promote()
            assert b.accepting and b.generation == 1 and b.failed_over
            # the zombie still believes it is primary; one mutation is
            # all it gets
            assert a.accepting
            _register(a, "w9")
            assert METRICS.counter("service.fenced_writes") == 1
            assert METRICS.counter("service.demotions") == 1
            assert not a.accepting
            r = _route(a, 1)
            assert r["error"] == "not_primary" and r["demoted"] is True
            # not a single stale byte interleaved into the successor's
            # journal: it is exactly the generation-1 snapshot
            recs = _journal_records(j)
            assert recs[0]["generation"] == 1
            assert all("w9" not in json.dumps(r) for r in recs)
            assert METRICS.counter("service.not_primary_rejects") >= 1
        finally:
            a.stop()
            if b is not None:
                b.stop()

    def test_demotes_after_n_consecutive_journal_failures(self, tmp_path):
        j = str(tmp_path / "j.json")
        plan = FaultPlan(
            [FaultRule(op="journal", kind="permanent_error", ordinal=1,
                       times=None)]
        )
        with install_chaos(plan):
            d = service.ServiceDispatcher(
                journal=j, lease_ttl_s=5.0, demote_after=3
            )
            try:
                _register(d, "w0")
                _register(d, "w1")
                assert d.accepting  # 2 failures < demote_after
                _register(d, "w2")
                assert not d.accepting
                assert METRICS.counter("service.demotions") == 1
                assert METRICS.counter("service.journal_errors") == 3
                r = _route(d, 0)
                assert r["error"] == "not_primary" and r["demoted"] is True
                # and it tells pingers honestly — takeover bait for a
                # standby that would recover journaled (consistent) state
                ping = d._handle({"op": "ping"})
                assert ping["ok"] and ping["accepting"] is False
            finally:
                d.stop()

    def test_dirty_journal_heals_by_compaction_on_next_write(self, tmp_path):
        """One failed append leaves an undefined tail; the next mutation
        must rewrite the whole journal as a fresh snapshot (covering both
        mutations), clearing the failure streak."""
        j = str(tmp_path / "j.json")
        plan = FaultPlan(
            [FaultRule(op="journal", kind="permanent_error", ordinal=1,
                       times=1)]
        )
        with install_chaos(plan):
            d = service.ServiceDispatcher(
                journal=j, lease_ttl_s=5.0, demote_after=3
            )
            try:
                _register(d, "w0")  # append fails -> dirty
                _register(d, "w1")  # heals: full snapshot compaction
                recs = _journal_records(j)
                assert len(recs) == 1 and recs[0]["kind"] == "snapshot"
                assert set(recs[0]["workers"]) == {"w0", "w1"}
                assert d.accepting
                assert d._journal_fail_streak == 0
            finally:
                d.stop()


# ---------------------------------------------------------------------------
# Warm standby: tailing, promotion, address adoption
# ---------------------------------------------------------------------------


class TestStandbyFailover:
    def test_standby_rejects_lease_ops_and_names_its_primary(self, tmp_path):
        j = str(tmp_path / "j.json")
        b = service.ServiceDispatcher(
            journal=j, standby_of="127.0.0.1:9", lease_ttl_s=5.0,
            ping_interval_s=30.0, takeover_addr=False,
        )
        try:
            r = _route(b, 0)
            assert r["error"] == "not_primary"
            assert r["role"] == "standby" and r["primary"] == "127.0.0.1:9"
            st = b.status()
            assert st["role"] == "standby" and st["accepting"] is False
            assert st["standby_of"] == "127.0.0.1:9"
            # register/heartbeat still land: the standby keeps fleet
            # freshness warm for takeover
            assert _register(b, "w0")["ok"]
            assert b._handle({"op": "heartbeat", "worker_id": "w0"})["known"]
        finally:
            b.stop()

    def test_standby_tails_journal_and_promotes_on_primary_death(
        self, tmp_path
    ):
        j = str(tmp_path / "j.json")
        a = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0).start()
        b = None
        try:
            _register(a, "w0")
            _register(a, "w1")
            assert _route(a, 0)["ok"]
            b = service.ServiceDispatcher(
                journal=j, standby_of=a.addr, lease_ttl_s=5.0,
                ping_interval_s=0.1, takeover_misses=2, takeover_addr=False,
            ).start()
            wait_for(
                lambda: len(b.status()["workers"]) == 2,
                msg="standby journal tail",
            )
            assert not b.accepting
            a.stop()
            # the counter lands AFTER the promotion compaction — waiting
            # on it (not on ``accepting``, which flips first) means the
            # journal read below sees the promoted snapshot
            wait_for(
                lambda: METRICS.counter("service.failovers") == 1,
                msg="standby promotion",
            )
            st = b.status()
            assert b.accepting
            assert st["role"] == "dispatcher" and st["failed_over"] is True
            assert b.generation == 1
            # the promotion compaction IS the fence: a fresh snapshot
            # carrying the bumped generation and the tailed lease state
            recs = _journal_records(j)
            assert recs[0]["kind"] == "snapshot"
            assert recs[0]["generation"] == 1
            assert set(recs[0]["workers"]) == {"w0", "w1"}
            assert st["active_leases"] == 1
        finally:
            if b is not None:
                b.stop()
            a.stop()

    def test_promoted_standby_adopts_the_primarys_address(self, tmp_path):
        j = str(tmp_path / "j.json")
        a = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0).start()
        primary_addr = a.addr
        b = service.ServiceDispatcher(
            journal=j, standby_of=primary_addr, lease_ttl_s=5.0,
            ping_interval_s=0.1, takeover_misses=2,
        ).start()
        try:
            a.stop()
            wait_for(
                lambda: METRICS.counter("service.failovers") == 1,
                msg="standby promotion",
            )

            def answered():
                try:
                    return service.fetch_status(primary_addr, timeout=1.0)
                except OSError:
                    return None

            wait_for(lambda: answered() is not None, msg="address adoption")
            st = answered()
            # a client that only ever knew the dead primary's host:port
            # reconnects and finds the promoted standby answering there
            assert st["role"] == "dispatcher"
            assert st["failed_over"] is True and st["generation"] == 1
            assert st["addr"] == b.addr
        finally:
            b.stop()
            a.stop()


# ---------------------------------------------------------------------------
# Partitioned routing: the consumer/worker side of K > 1
# ---------------------------------------------------------------------------


class TestPartitionedRouting:
    def test_client_routes_to_the_owning_partition(self, data_dir):
        d0 = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        d1 = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        try:
            spec = f"{d0.addr},{d1.addr}"
            ds = make_ds(data_dir, service=spec)
            client = service.ServiceClient(ds)
            try:
                pm = service.PartitionMap.parse(spec)
                owner = pm.partition_for(client._tenant)
                assert client.partition == owner
                assert client.addr == pm.addrs(owner)[0]
                assert METRICS.gauge_value("service.partition") == float(owner)
            finally:
                client.close()
        finally:
            d0.stop()
            d1.stop()

    def test_worker_registers_everywhere_and_the_epoch_reads_clean(
        self, data_dir, local_rows
    ):
        d0 = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        d1 = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        w = None
        try:
            spec = f"{d0.addr},{d1.addr}"
            w = service.DecodeWorker(spec).start()
            assert w.wait_registered(10)
            # one worker, K partitions: every partition can route to it
            wait_for(
                lambda: len(d0.status()["workers"]) == 1
                and len(d1.status()["workers"]) == 1,
                msg="registration with every partition",
            )
            got = collect(data_dir, service=spec, service_deadline_ms=3000)
            assert got == local_rows
            assert METRICS.counter("service.fallbacks") == 0
            # the tenant's lease table lives on exactly ONE partition
            owner_leased = [
                d for d in (d0, d1) if d.status()["shards_done"] > 0
            ]
            assert len(owner_leased) == 1
        finally:
            if w is not None:
                w.stop()
            d0.stop()
            d1.stop()


# ---------------------------------------------------------------------------
# Federated FleetScaler: merged census, whipsaw guard, remote handles
# ---------------------------------------------------------------------------


class _DeadPartition:
    """A partition whose primary AND standby are unreachable."""

    scaler_status = None

    def status(self):
        raise OSError("unreachable")

    def drain(self, worker_id):
        raise OSError("unreachable")


class TestFederatedScaler:
    def test_census_merges_partitions_and_dedupes_workers(self):
        d0 = service.ServiceDispatcher(lease_ttl_s=5.0)
        d1 = service.ServiceDispatcher(lease_ttl_s=5.0)
        try:
            _register(d0, "w0")
            _register(d1, "w0")  # same worker, every partition
            _register(d0, "w1")
            s = elastic.FleetScaler(
                [d0, d1], lambda: None, aggregator=FakeAggregator(),
                policy=elastic.ScalerPolicy(min_workers=1, max_workers=4),
            )
            c = s._census()
            assert sorted(c["active"]) == ["w0", "w1"]
            # draining on ANY partition means draining in the merged view
            assert d0.drain("w1")
            c = s._census()
            assert c["active"] == ["w0"] and c["draining"] == ["w1"]
            # the ctor published its status block to every partition
            assert d0.scaler_status is not None
            assert d1.scaler_status is not None
        finally:
            d0.stop()
            d1.stop()

    def test_unreadable_partition_skips_the_tick_no_whipsaw(self):
        d0 = service.ServiceDispatcher(lease_ttl_s=5.0)
        try:
            _register(d0, "w0")
            spawned = []
            s = elastic.FleetScaler(
                [d0, _DeadPartition()], lambda: spawned.append(1),
                aggregator=FakeAggregator("producer_bound"),
                policy=elastic.ScalerPolicy(
                    hysteresis=1, cooldown_s=0.0, min_workers=1,
                    max_workers=4,
                ),
            )
            for _ in range(3):
                assert s.step() is None, (
                    "scaler acted on a partial fleet view"
                )
            assert spawned == []
            assert METRICS.counter("elastic.census_errors") >= 3
            assert METRICS.counter("elastic.scale_ups") == 0
            assert METRICS.counter("elastic.scale_downs") == 0
        finally:
            d0.stop()

    def test_drain_fans_out_to_every_partition(self):
        d0 = service.ServiceDispatcher(lease_ttl_s=5.0)
        d1 = service.ServiceDispatcher(lease_ttl_s=5.0)
        try:
            _register(d0, "w0")
            _register(d1, "w0")
            assert _route(d1, 0)["worker_id"] == "w0"
            s = elastic.FleetScaler(
                [d0, d1], lambda: None, aggregator=FakeAggregator(),
                policy=elastic.ScalerPolicy(min_workers=1, max_workers=4),
            )
            assert s._drain_one(["w0"], "idle") is not None
            # the victim's leases were handed back on the partition that
            # actually routed to it, and both partitions mark it draining
            assert d0.status()["draining"] == ["w0"]
            assert d1.status()["draining"] == ["w0"]
            assert d1.status()["active_leases"] == 0
        finally:
            d0.stop()
            d1.stop()

    def test_dispatcher_handle_walks_members_and_proxies_rpcs(self):
        d = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        try:
            _register(d, "w0")
            # dead member first: the handle walks to the live one
            h = elastic.DispatcherHandle(f"127.0.0.1:9|{d.addr}", timeout=2.0)
            st = h.status()
            assert [w["worker_id"] for w in st["workers"]] == ["w0"]
            h.scaler_status = {"workers": 1, "verdict": "balanced"}
            assert d.scaler_status == {"workers": 1, "verdict": "balanced"}
            assert h.drain("w0") is True
            assert d.status()["draining"] == ["w0"]
        finally:
            d.stop()

    def test_dispatcher_handle_skips_standbys_for_primary_only_ops(
        self, tmp_path
    ):
        j = str(tmp_path / "j.json")
        a = service.ServiceDispatcher(journal=j, lease_ttl_s=5.0).start()
        b = service.ServiceDispatcher(
            journal=j, standby_of=a.addr, lease_ttl_s=5.0,
            ping_interval_s=30.0, takeover_addr=False,
        ).start()
        try:
            _register(a, "w0")
            # standby listed FIRST: a drain routed there would be
            # rejected; the handle must skip to the acting primary
            h = elastic.DispatcherHandle([b.addr, a.addr], timeout=2.0)
            assert h.drain("w0") is True
            assert a.status()["draining"] == ["w0"]
        finally:
            b.stop()
            a.stop()


# ---------------------------------------------------------------------------
# Federated serve-status doctor
# ---------------------------------------------------------------------------


def _doctor(*argv):
    proc = subprocess.run(
        [sys.executable, DOCTOR, "serve-status", *argv],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    events = [json.loads(ln) for ln in proc.stdout.splitlines() if ln]
    return proc.returncode, events


class TestDoctorFederated:
    def test_two_partitions_exit_0_with_ha_summary(self):
        d0 = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        d1 = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        try:
            _register(d0, "w0")
            _register(d1, "w0")  # registered with every partition
            rc, events = _doctor(f"{d0.addr},{d1.addr}", "--timeout", "2")
            assert rc == 0
            services = [e for e in events if e["event"] == "service"]
            assert [e["partition"] for e in services] == [0, 1]
            assert all(
                e["role"] == "dispatcher" and e["generation"] == 0
                and e["accepting"] for e in services
            )
            workers = [e for e in events if e["event"] == "worker"]
            assert {e["partition"] for e in workers} == {0, 1}
            (ha,) = [e for e in events if e["event"] == "ha"]
            assert ha["partitions"] == 2 and ha["answered"] == 2
            assert ha["acting_primaries"] == 2 and ha["failed_over"] == 0
            assert ha["workers"] == 1  # deduped across partitions
        finally:
            d0.stop()
            d1.stop()

    def test_unreachable_partition_exits_2(self):
        d0 = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        try:
            rc, events = _doctor(f"{d0.addr},127.0.0.1:9", "--timeout", "1")
            assert rc == 2
            (err,) = [e for e in events if e["event"] == "error"]
            assert err["partition"] == 1
            (ha,) = [e for e in events if e["event"] == "ha"]
            assert ha["answered"] == 1 and ha["partitions"] == 2
        finally:
            d0.stop()

    def test_standby_answer_counts_the_partition_alive(self, tmp_path):
        j = str(tmp_path / "j.json")
        b = service.ServiceDispatcher(
            journal=j, standby_of="127.0.0.1:9", lease_ttl_s=5.0,
            ping_interval_s=30.0, takeover_addr=False,
        ).start()
        try:
            rc, events = _doctor(f"127.0.0.1:9|{b.addr}", "--timeout", "1")
            assert rc == 0  # the partition is alive, if not accepting
            (svc,) = [e for e in events if e["event"] == "service"]
            assert svc["role"] == "standby" and svc["accepting"] is False
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# THE chaos acceptance: SIGKILL the primary mid-epoch, ride the standby
# ---------------------------------------------------------------------------


def _spawn_worker_proc(dispatcher_spec):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_tfrecord.service", "worker",
         "--dispatcher", dispatcher_spec],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    ready = json.loads(proc.stdout.readline())
    assert ready["event"] == "ready"
    return proc, ready


class TestHAChaosAcceptance:
    def test_sigkill_primary_mid_epoch_standby_takeover_byte_identical(
        self, data_dir, local_rows, tmp_path
    ):
        """THE acceptance scenario (ISSUE 17): the primary dispatcher —
        a real subprocess — is SIGKILLed mid-epoch while 2 consumers
        stream from 2 decode-worker subprocesses. The warm standby tails
        the journal, detects the death by heartbeat loss, promotes
        (generation bump), and both consumers finish the epoch
        byte-identical to a local read — zero fallbacks, zero duplicated
        or missing rows, every shard served exactly once — then the
        serve-status doctor reports the completed failover with exit 0."""
        journal = str(tmp_path / "journal.json")
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        }
        prim = subprocess.Popen(
            [sys.executable, "-m", "tpu_tfrecord.service", "dispatcher",
             "--journal", journal, "--lease-ttl-s", "10"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        procs = []
        standby = None
        try:
            ready = json.loads(prim.stdout.readline())
            assert ready["event"] == "ready"
            primary_addr = ready["addr"]
            standby = service.ServiceDispatcher(
                journal=journal, standby_of=primary_addr, lease_ttl_s=10.0,
                ping_interval_s=0.2, takeover_misses=3, takeover_addr=False,
            ).start()
            spec = f"{primary_addr}|{standby.addr}"
            for _ in range(2):
                procs.append(_spawn_worker_proc(spec))
            # the standby learns the fleet from the journal tail alone
            wait_for(
                lambda: len(standby.status()["workers"]) == 2,
                timeout=30, msg="standby tailed worker registrations",
            )

            chaos_done = threading.Event()
            gate = threading.Barrier(3, timeout=120)  # 2 consumers + chaos

            def consume(out):
                ds = make_ds(data_dir, service=spec, service_deadline_ms=3000)
                rows = []
                paused = False
                with ds.batches() as it:
                    for b in it:
                        rows.extend(batch_to_rows(b, ds.schema))
                        if len(rows) >= 40 and not paused:
                            paused = True
                            gate.wait()
                            chaos_done.wait()
                out.extend(rows)

            def chaos():
                gate.wait()
                os.kill(prim.pid, signal.SIGKILL)  # no atexit, no goodbye
                prim.wait()
                # hold the consumers until the standby has detected the
                # death (heartbeat loss x takeover_misses) and promoted —
                # the same shape as the dispatcher-restart acceptance,
                # where the replacement is up before consumers resume.
                # Consumers still exercise the client half of failover:
                # their persistent dispatcher conns are dead, and the
                # next RPC reconnects through the partition-map rotation.
                wait_for(lambda: standby.accepting, timeout=30,
                         msg="standby promotion")
                chaos_done.set()

            outs = [[], []]
            threads = [
                threading.Thread(target=consume, args=(outs[k],))
                for k in range(2)
            ]
            threads.append(threading.Thread(target=chaos))
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "acceptance run wedged"

            assert outs[0] == local_rows
            assert outs[1] == local_rows
            assert METRICS.counter("service.fallbacks") == 0
            wait_for(
                lambda: METRICS.counter("service.failovers") == 1,
                msg="failover counted",
            )
            assert standby.accepting and standby.failed_over
            assert standby.generation == 1
            # exactly-once at the books too: 6 shards, 6 completions,
            # across the generation boundary
            assert standby.status()["shards_done"] == 6
            # and the doctor sees the completed failover as a finding,
            # not a failure
            rc, events = _doctor(spec, "--timeout", "2")
            assert rc == 0
            (svc,) = [e for e in events if e["event"] == "service"]
            assert svc["failed_over"] is True and svc["generation"] == 1
            assert svc["role"] == "dispatcher"
        finally:
            for proc, _ in procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc, _ in procs:
                if proc.poll() is None:
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        proc.kill()
            if standby is not None:
                standby.stop()
            if prim.poll() is None:
                prim.kill()
