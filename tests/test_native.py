"""Tests for the C++ fast path: CRC32C, frame scan, batch decode — each
checked against the pure-Python implementation as the correctness oracle."""

import numpy as np
import pytest

from tpu_tfrecord import _native, wire
from tpu_tfrecord.columnar import ColumnarDecoder
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.proto import (
    Example,
    Feature,
    FeatureList,
    SequenceExample,
    encode_example,
    encode_sequence_example,
)
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import NullValueError

pytestmark = pytest.mark.skipif(
    not _native.available(), reason=f"native lib unavailable: {_native.load_error()}"
)


class TestCrc32c:
    def test_matches_python(self):
        for data in [b"", b"123456789", b"\x00" * 32, bytes(range(256)) * 7]:
            assert _native.crc32c(data) == wire.crc32c_py(data)

    def test_check_value(self):
        assert _native.crc32c(b"123456789") == 0xE3069283


class TestScan:
    def test_matches_python_scan(self):
        records = [b"a", b"bb" * 100, b"", b"xyz"]
        buf = b"".join(wire.encode_record(r) for r in records)
        offsets, lengths = _native.scan(buf)
        got = [buf[o : o + l] for o, l in zip(offsets.tolist(), lengths.tolist())]
        assert got == records

    def test_detects_corruption(self):
        buf = bytearray(wire.encode_record(b"payload"))
        buf[13] ^= 0x55
        with pytest.raises(wire.TFRecordCorruptionError):
            _native.scan(bytes(buf))
        # without verification it scans fine
        offsets, lengths = _native.scan(bytes(buf), verify_crc=False)
        assert len(offsets) == 1

    def test_detects_truncation(self):
        buf = wire.encode_record(b"payload")[:-2]
        with pytest.raises(wire.TFRecordCorruptionError):
            _native.scan(buf)


SCHEMA = StructType(
    [
        StructField("i", IntegerType()),
        StructField("l", LongType()),
        StructField("f", FloatType()),
        StructField("d", DoubleType()),
        StructField("s", StringType()),
        StructField("b", BinaryType()),
        StructField("fv", ArrayType(FloatType())),
        StructField("lv", ArrayType(LongType())),
        StructField("sv", ArrayType(StringType())),
    ]
)


def make_records(n=50, seed=0):
    rng = np.random.default_rng(seed)
    records = []
    for k in range(n):
        feats = {}
        if k % 7 != 3:  # some rows miss some features
            feats["i"] = Feature.int64_list([int(rng.integers(-(2**33), 2**33))])
            feats["l"] = Feature.int64_list([int(rng.integers(-(2**62), 2**62))])
        feats["f"] = Feature.float_list([float(rng.normal())])
        feats["d"] = Feature.float_list([float(rng.normal())])
        feats["s"] = Feature.bytes_list([f"str-{k}-é".encode("utf-8")])
        feats["b"] = Feature.bytes_list([bytes(rng.integers(0, 256, size=k % 5, dtype=np.uint8))])
        feats["fv"] = Feature.float_list(rng.normal(size=k % 4).tolist())
        feats["lv"] = Feature.int64_list(rng.integers(0, 100, size=(k * 3) % 7).tolist())
        feats["sv"] = Feature.bytes_list([f"t{j}".encode() for j in range(k % 3)])
        feats["extra_unrequested"] = Feature.int64_list([1, 2, 3])
        records.append(encode_example(Example(features=feats)))
    return records


def assert_batches_equal(got, want):
    assert got.num_rows == want.num_rows
    assert set(got.columns) == set(want.columns)
    for name in want.columns:
        g, w = got[name], want[name]
        np.testing.assert_array_equal(g.mask, w.mask, err_msg=f"{name}.mask")
        if w.offsets is not None:
            np.testing.assert_array_equal(g.offsets, w.offsets, err_msg=f"{name}.offsets")
        if w.inner_offsets is not None:
            np.testing.assert_array_equal(
                g.inner_offsets, w.inner_offsets, err_msg=f"{name}.inner_offsets"
            )
        if w.values is not None:
            assert g.values.dtype == w.values.dtype, name
            np.testing.assert_array_equal(g.values, w.values, err_msg=f"{name}.values")
        if w.blobs is not None:
            assert g.blobs == w.blobs, name


class TestNativeExampleDecode:
    def test_matches_python_oracle(self):
        records = make_records(80)
        want = ColumnarDecoder(SCHEMA).decode_batch(records)
        got = _native.NativeDecoder(SCHEMA).decode_batch(records)
        assert_batches_equal(got, want)

    def test_int32_truncation_matches(self):
        schema = StructType([StructField("x", IntegerType())])
        recs = [encode_example(Example(features={"x": Feature.int64_list([2**31 + 10])}))]
        got = _native.NativeDecoder(schema).decode_batch(recs)
        want = ColumnarDecoder(schema).decode_batch(recs)
        assert got["x"].values[0] == want["x"].values[0] == -(2**31) + 10

    def test_missing_non_nullable_raises(self):
        schema = StructType([StructField("x", LongType(), nullable=False)])
        with pytest.raises(NullValueError):
            _native.NativeDecoder(schema).decode_batch([encode_example(Example())])

    def test_kind_mismatch_raises(self):
        schema = StructType([StructField("x", FloatType())])
        recs = [encode_example(Example(features={"x": Feature.int64_list([1])}))]
        with pytest.raises(ValueError, match="kind"):
            _native.NativeDecoder(schema).decode_batch(recs)

    def test_decode_spans_from_file_buffer(self, sandbox):
        records = make_records(20)
        path = str(sandbox / "x.tfrecord")
        wire.write_records(path, records)
        buf = open(path, "rb").read()
        offsets, lengths = _native.scan(buf)
        got = _native.NativeDecoder(SCHEMA).decode_spans(buf, offsets, lengths)
        want = ColumnarDecoder(SCHEMA).decode_batch(records)
        assert_batches_equal(got, want)


class TestTurboShapeVariants:
    """The turbo parser keeps per-slot alternate entry-shape caches keyed by
    total entry length (varint ints drift among a handful of byte lengths).
    These cases force constant MRU misses so the alternate-probe lane and
    its promotion/eviction paths all execute, pinned to the Python oracle."""

    def _roundtrip(self, schema, rows_feats, **kw):
        recs = [encode_example(Example(features=f)) for f in rows_feats]
        got = _native.NativeDecoder(schema, **kw).decode_batch(recs)
        want = ColumnarDecoder(schema).decode_batch(recs)
        assert_batches_equal(got, want)
        return got

    def test_alternating_varint_lengths_match_oracle(self):
        # Cycle each int through 1..10-byte varints (incl. negatives, which
        # encode as 10 bytes) so every record misses the MRU for some field.
        schema = StructType(
            [StructField("a", LongType()), StructField("b", LongType())]
        )
        vals = [1, 2**7, 2**14, 2**21, 2**28, 2**35, 2**42, 2**49, 2**56, -1]
        rows = [
            {
                "a": Feature.int64_list([vals[k % len(vals)]]),
                "b": Feature.int64_list([vals[(k * 3 + 1) % len(vals)]]),
            }
            for k in range(64)
        ]
        self._roundtrip(schema, rows)

    def test_more_lengths_than_alternate_slots(self):
        # >6 distinct shapes per slot: round-robin eviction must stay correct
        # (worst case it just re-parses field-wise; values must not change).
        schema = StructType([StructField("x", LongType())])
        rng = np.random.default_rng(7)
        rows = [
            {"x": Feature.int64_list([int(rng.integers(0, 2**63 - 1)) >> (7 * (k % 9))])}
            for k in range(200)
        ]
        self._roundtrip(schema, rows)

    def test_variable_length_bytes_and_pruned_columns(self):
        # bytes values of drifting lengths exercise the alternate lane for
        # BYTES kinds; the unrequested wide column exercises the pruned-slot
        # (idx<0) alternates.
        schema = StructType([StructField("s", StringType()), StructField("n", LongType())])
        rows = []
        for k in range(64):
            rows.append(
                {
                    "s": Feature.bytes_list([b"x" * (1 + (k * 5) % 23)]),
                    "n": Feature.int64_list([k * (2**27)]),
                    "skip_me": Feature.bytes_list([b"y" * ((k * 11) % 37)]),
                }
            )
        self._roundtrip(schema, rows)

    def test_large_entries_alternate_between_two_shapes(self):
        # Entries whose total length needs a 2-BYTE length varint (>= ~130
        # bytes, e.g. long bytes values): the alternate probe must decode
        # the 2-byte varint to preselect, and remember() must keep such
        # shapes in the alternate set (r4; previously they occupied slots
        # the 1-byte-only probe could never match). Alternating two large
        # shapes makes EVERY record an MRU miss that only the large-entry
        # probe lane can serve; correctness is pinned to the oracle either
        # way (a probe miss just re-parses field-wise).
        schema = StructType([StructField("doc", StringType()), StructField("n", LongType())])
        rows = []
        for k in range(64):
            size = 200 if k % 2 == 0 else 900
            rows.append(
                {
                    "doc": Feature.bytes_list([bytes([65 + k % 26]) * size]),
                    "n": Feature.int64_list([k]),
                }
            )
        self._roundtrip(schema, rows)

    def test_oversized_entries_never_occupy_alternate_slots(self):
        # Shapes beyond the probe's 2-byte reach (> 16386 total) must not
        # round-robin-evict live alternates; decode stays oracle-equal.
        schema = StructType([StructField("blob", BinaryType()), StructField("n", LongType())])
        rows = []
        for k in range(32):
            size = 20_000 if k % 3 == 0 else (140 + (k % 5) * 70)
            rows.append(
                {
                    "blob": Feature.bytes_list([bytes([k % 251]) * size]),
                    "n": Feature.int64_list([k * 2**40]),
                }
            )
        self._roundtrip(schema, rows)

    def test_hashed_bytes_with_drifting_lengths(self):
        from tpu_tfrecord.tpu.ingest import hash_bytes_column

        schema = StructType([StructField("c", StringType())])
        blobs = [b"k" * (1 + (k * 3) % 17) for k in range(48)]
        rows = [{"c": Feature.bytes_list([b])} for b in blobs]
        recs = [encode_example(Example(features=f)) for f in rows]
        got = _native.NativeDecoder(schema, hash_buckets={"c": 1 << 10}).decode_batch(recs)
        want = hash_bytes_column(blobs, 1 << 10)
        np.testing.assert_array_equal(got["c"].values, np.asarray(want, dtype=np.int32))


class TestNativeSequenceExampleDecode:
    SCHEMA = StructType(
        [
            StructField("id", LongType()),
            StructField("frames", ArrayType(ArrayType(FloatType()))),
            StructField("toks", ArrayType(LongType())),
            StructField("names", ArrayType(ArrayType(StringType()))),
        ]
    )

    def make(self, n=30):
        rng = np.random.default_rng(1)
        out = []
        for k in range(n):
            fl = FeatureList(
                [Feature.float_list(rng.normal(size=int(rng.integers(0, 4))).tolist())
                 for _ in range(int(rng.integers(0, 3)))]
            )
            toks = FeatureList(
                [Feature.int64_list([int(v)]) for v in rng.integers(0, 9, size=k % 4)]
            )
            names = FeatureList(
                [Feature.bytes_list([f"n{j}".encode() for j in range(int(rng.integers(1, 3)))])
                 for _ in range(k % 3)]
            )
            se = SequenceExample(
                context={"id": Feature.int64_list([k])},
                feature_lists={"frames": fl, "toks": toks, "names": names},
            )
            out.append(encode_sequence_example(se))
        return out

    def test_matches_python_oracle(self):
        records = self.make()
        want = ColumnarDecoder(self.SCHEMA, RecordType.SEQUENCE_EXAMPLE).decode_batch(records)
        got = _native.NativeDecoder(self.SCHEMA, RecordType.SEQUENCE_EXAMPLE).decode_batch(records)
        assert_batches_equal(got, want)


class TestFrameRecords:
    def test_native_framing_matches_python(self):
        lib = _native.load()
        records = [b"abc", b"", b"x" * 500]
        payloads = b"".join(records)
        lengths = np.array([len(r) for r in records], dtype=np.uint64)
        offsets = np.zeros(3, dtype=np.uint64)
        np.cumsum(lengths[:-1], out=offsets[1:])
        out = np.empty(sum(len(r) + 16 for r in records), dtype=np.uint8)
        import ctypes

        n = lib.tfr_frame_records(
            payloads,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            3,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(out),
        )
        assert n == len(out)
        want = b"".join(wire.encode_record(r) for r in records)
        assert out.tobytes() == want


class TestReviewRegressions:
    """Pins for review findings: overflow-safe scan, empty-bytes scalar
    parity, duplicate-key last-wins parity, scan copy semantics."""

    def test_scan_huge_length_no_oob(self):
        # 8-byte length near UINT64_MAX must raise, not wrap the bounds check
        import struct as _s

        evil = _s.pack("<Q", 0xFFFFFFFFFFFFFFF0) + b"\x00" * 8
        with pytest.raises(wire.TFRecordCorruptionError):
            _native.scan(evil, verify_crc=False)

    def test_scan_returns_compact_copies(self):
        buf = wire.encode_record(b"x" * 10_000)
        offsets, lengths = _native.scan(buf)
        # must not pin the cap-sized backing array (len(buf)/16 entries)
        assert offsets.base is None or offsets.base.nbytes <= offsets.nbytes * 2

    def test_empty_bytes_scalar_matches_python(self):
        schema = StructType([StructField("s", StringType())])
        recs = [encode_example(Example(features={"s": Feature(1, [])}))]  # empty BytesList
        want = ColumnarDecoder(schema).decode_batch(recs)
        got = _native.NativeDecoder(schema).decode_batch(recs)
        assert want["s"].blobs == [b""] and got["s"].blobs == [b""]
        np.testing.assert_array_equal(got["s"].mask, want["s"].mask)

    def test_duplicate_map_key_last_wins_both_paths(self):
        # hand-build an Example whose features map has "x" twice
        def entry(value_varint):
            int64_list = bytes([0x0A, 0x01, value_varint])  # field1 packed len1
            feature = bytes([0x1A, len(int64_list)]) + int64_list
            e = bytes([0x0A, 1, ord("x"), 0x12, len(feature)]) + feature
            return bytes([0x0A, len(e)]) + e

        features_payload = entry(5) + entry(9)  # two map entries, same key
        record = bytes([0x0A, len(features_payload)]) + features_payload
        schema = StructType([StructField("x", LongType())])
        want = ColumnarDecoder(schema).decode_batch([record])
        got = _native.NativeDecoder(schema).decode_batch([record])
        assert want["x"].values[0] == 9  # protobuf map: last wins
        assert got["x"].values[0] == 9

    def test_duplicate_featurelist_key_last_wins_both_paths(self):
        # hand-build a SequenceExample whose feature_lists map has "x" twice
        # (proto.py's dict-based builder can't emit duplicate keys)
        def int64_feature(v):
            il = bytes([0x0A, 0x01, v])  # Int64List field1 packed, one value
            return bytes([0x1A, len(il)]) + il

        def fl_entry(vals):
            feats = b"".join(
                bytes([0x0A, len(int64_feature(v))]) + int64_feature(v)
                for v in vals
            )
            e = bytes([0x0A, 1, ord("x"), 0x12, len(feats)]) + feats
            return bytes([0x0A, len(e)]) + e

        payload = fl_entry([5, 6]) + fl_entry([9])
        record = bytes([0x12, len(payload)]) + payload  # SequenceExample.feature_lists
        schema = StructType([StructField("x", ArrayType(LongType()))])
        want = ColumnarDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([record])
        got = _native.NativeDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([record])
        # protobuf map semantics: the LAST occurrence wins on both paths
        np.testing.assert_array_equal(want["x"].values, [9])
        np.testing.assert_array_equal(got["x"].values, want["x"].values)
        np.testing.assert_array_equal(got["x"].offsets, want["x"].offsets)

    def test_duplicate_featurelist_key_last_wins_ragged2(self):
        # same, for a 2-D column: each inner Feature carries multiple values
        def int64_feature(vals):
            il = bytes([0x0A, len(vals)] + list(vals))
            return bytes([0x1A, len(il)]) + il

        def fl_entry(frames):
            feats = b"".join(
                bytes([0x0A, len(int64_feature(f))]) + int64_feature(f)
                for f in frames
            )
            e = bytes([0x0A, 1, ord("m"), 0x12, len(feats)]) + feats
            return bytes([0x0A, len(e)]) + e

        payload = fl_entry([[1, 2], [3]]) + fl_entry([[7]])
        record = bytes([0x12, len(payload)]) + payload
        schema = StructType([StructField("m", ArrayType(ArrayType(LongType())))])
        want = ColumnarDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([record])
        got = _native.NativeDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([record])
        np.testing.assert_array_equal(want["m"].values, [7])
        np.testing.assert_array_equal(got["m"].values, want["m"].values)
        np.testing.assert_array_equal(got["m"].offsets, want["m"].offsets)
        np.testing.assert_array_equal(got["m"].inner_offsets, want["m"].inner_offsets)

    def test_context_beats_feature_lists_both_wire_orders(self):
        """Same key in context AND feature_lists: the oracle gives context
        priority (columnar.py:340-346) regardless of the order the two maps
        appear in the wire — native must agree (a FL-duplicate rollback must
        never evict a context value)."""
        def int64_feature(vals):
            il = bytes([0x0A, len(vals)] + list(vals))
            return bytes([0x1A, len(il)]) + il

        # context { x: [1, 2] }  (Features map entry, SequenceExample field 1)
        feat = int64_feature([1, 2])
        ctx_entry = bytes([0x0A, 1, ord("x"), 0x12, len(feat)]) + feat
        ctx_payload = bytes([0x0A, len(ctx_entry)]) + ctx_entry
        context = bytes([0x0A, len(ctx_payload)]) + ctx_payload
        # feature_lists { x: [[9]] }  (field 2)
        inner = int64_feature([9])
        fl = bytes([0x0A, len(inner)]) + inner
        fl_entry = bytes([0x0A, 1, ord("x"), 0x12, len(fl)]) + fl
        fl_payload = bytes([0x0A, len(fl_entry)]) + fl_entry
        flists = bytes([0x12, len(fl_payload)]) + fl_payload

        schema = StructType([StructField("x", ArrayType(LongType()))])
        for record in (context + flists, flists + context):
            want = ColumnarDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([record])
            got = _native.NativeDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([record])
            np.testing.assert_array_equal(want["x"].values, [1, 2])
            np.testing.assert_array_equal(got["x"].values, want["x"].values)
            np.testing.assert_array_equal(got["x"].offsets, want["x"].offsets)

    def test_decode_first_native_call_hashes_correctly(self):
        """tfr_decode_batch must init the CRC table itself: in a process
        whose FIRST native call is a fused-hash decode, bucket indices must
        match the Python oracle (on non-SSE4.2 builds a zeroed software
        table would silently skew them)."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "from tpu_tfrecord import _native\n"
            "from tpu_tfrecord.proto import Example, Feature, encode_example\n"
            "from tpu_tfrecord.schema import StringType, StructField, StructType\n"
            "schema = StructType([StructField('c', StringType())])\n"
            "rec = encode_example(Example(features={'c': Feature.bytes_list([b'hello'])}))\n"
            "dec = _native.NativeDecoder(schema, hash_buckets={'c': 1000})\n"
            "cb = dec.decode_batch([rec])\n"
            "print('BUCKET', int(cb['c'].values[0]))\n" % repo
        )
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=120
        )
        assert out.returncode == 0, out.stderr
        want = wire.crc32c_py(b"hello") % 1000
        assert f"BUCKET {want}" in out.stdout

    def test_empty_inner_numeric_feature_raises_named_error(self):
        from tpu_tfrecord.proto import FeatureList, SequenceExample, encode_sequence_example

        schema = StructType([StructField("toks", ArrayType(LongType()))])
        se = SequenceExample(feature_lists={"toks": FeatureList([Feature(3, [])])})
        rec = encode_sequence_example(se)
        with pytest.raises(ValueError, match="toks"):
            ColumnarDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([rec])
        with pytest.raises(ValueError, match="empty inner"):
            _native.NativeDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch([rec])


class TestFusedHashing:
    """hash_buckets fused into decode: bytes columns emerge as int32."""

    def test_matches_post_hoc_hashing(self):
        from tpu_tfrecord.tpu.ingest import hash_bytes_column

        schema = StructType([StructField("c", StringType()), StructField("x", LongType())])
        recs = [
            encode_example(Example(features={
                "c": Feature.bytes_list([f"cat-{k % 5}".encode()]),
                "x": Feature.int64_list([k]),
            }))
            for k in range(40)
        ]
        plain = _native.NativeDecoder(schema).decode_batch(recs)
        want = hash_bytes_column(plain["c"], 97)
        fused = _native.NativeDecoder(schema, hash_buckets={"c": 97}).decode_batch(recs)
        assert fused["c"].values.dtype == np.int32
        assert fused["c"].blob is None
        np.testing.assert_array_equal(fused["c"].values, want)
        np.testing.assert_array_equal(fused["x"].values, plain["x"].values)

    def test_missing_hashed_column_masks_zero(self):
        schema = StructType([StructField("c", StringType())])
        recs = [encode_example(Example())]
        fused = _native.NativeDecoder(schema, hash_buckets={"c": 8}).decode_batch(recs)
        np.testing.assert_array_equal(fused["c"].mask, [False])
        np.testing.assert_array_equal(fused["c"].values, [0])

    def test_hashing_non_bytes_column_rejected(self):
        schema = StructType([StructField("x", LongType())])
        with pytest.raises(ValueError, match="not a string/binary column"):
            _native.NativeDecoder(schema, hash_buckets={"x": 8})

    def test_dataset_fused_hash_to_host_batch(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.tpu.ingest import host_batch_from_columnar

        schema = StructType([StructField("c", StringType()), StructField("x", LongType())])
        rows = [[f"u{k % 7}", k] for k in range(32)]
        out = str(sandbox / "fh")
        tfio.write(rows, schema, out, mode="overwrite")
        hb_spec = {"c": 64}
        ds = TFRecordDataset(out, batch_size=32, schema=schema, hash_buckets=hb_spec)
        with ds.batches() as it:
            cb = next(it)
        hb = host_batch_from_columnar(cb, ds.schema, hash_buckets=hb_spec)
        # compare against the unfused pipeline
        ds2 = TFRecordDataset(out, batch_size=32, schema=schema)
        with ds2.batches() as it2:
            cb2 = next(it2)
        hb2 = host_batch_from_columnar(cb2, ds2.schema, hash_buckets=hb_spec)
        np.testing.assert_array_equal(hb["c"], hb2["c"])
        np.testing.assert_array_equal(hb["x"], hb2["x"])


class TestFusedHashingRegressions:
    def test_empty_bytes_list_fused_matches_unfused(self):
        from tpu_tfrecord.tpu.ingest import hash_bytes_column

        schema = StructType([StructField("c", StringType())])
        recs = [
            encode_example(Example(features={"c": Feature.bytes_list([b"x"])})),
            encode_example(Example(features={"c": Feature(1, [])})),  # empty BytesList
            encode_example(Example(features={"c": Feature.bytes_list([b"y"])})),
        ]
        fused = _native.NativeDecoder(schema, hash_buckets={"c": 97}).decode_batch(recs)
        plain = _native.NativeDecoder(schema).decode_batch(recs)
        want = hash_bytes_column(plain["c"], 97)
        assert len(fused["c"].values) == 3  # no desync with mask/rows
        np.testing.assert_array_equal(fused["c"].values, want)
        np.testing.assert_array_equal(fused["c"].mask, plain["c"].mask)

    def test_negative_buckets_rejected(self):
        schema = StructType([StructField("c", StringType())])
        with pytest.raises(ValueError, match="positive"):
            _native.NativeDecoder(schema, hash_buckets={"c": -5})

    def test_bucket_mismatch_raises_in_host_batch(self):
        from tpu_tfrecord.tpu.ingest import host_batch_from_columnar

        schema = StructType([StructField("c", StringType())])
        recs = [encode_example(Example(features={"c": Feature.bytes_list([b"x"])}))]
        fused = _native.NativeDecoder(schema, hash_buckets={"c": 64}).decode_batch(recs)
        with pytest.raises(ValueError, match="hash_buckets=64"):
            host_batch_from_columnar(fused, schema, hash_buckets={"c": 128})

    def test_bucket_count_survives_slice_concat(self):
        from tpu_tfrecord.columnar import concat_batches, slice_batch

        schema = StructType([StructField("c", StringType())])
        recs = [
            encode_example(Example(features={"c": Feature.bytes_list([f"v{k}".encode()])}))
            for k in range(6)
        ]
        fused = _native.NativeDecoder(schema, hash_buckets={"c": 31}).decode_batch(recs)
        a = slice_batch(fused, 0, 3)
        b = slice_batch(fused, 3, 6)
        merged = concat_batches([a, b])
        assert merged["c"].hash_buckets == 31


class TestGroupPacking:
    """pack: scalar column groups decode into [B, K] matrices in C++."""

    SCHEMA = StructType(
        [StructField("label", LongType())]
        + [StructField(f"I{i}", LongType()) for i in range(4)]
        + [StructField(f"C{i}", StringType()) for i in range(3)]
    )

    def make_recs(self, n=30):
        rng = np.random.default_rng(3)
        recs = []
        for k in range(n):
            feats = {"label": Feature.int64_list([k % 2])}
            for i in range(4):
                if (k + i) % 9 != 5:  # some missing
                    feats[f"I{i}"] = Feature.int64_list([int(rng.integers(0, 1 << 40))])
            for i in range(3):
                feats[f"C{i}"] = Feature.bytes_list([f"c{k % 7}".encode()])
            recs.append(encode_example(Example(features=feats)))
        return recs

    def test_group_matrix_matches_stacked_columns(self):
        recs = self.make_recs()
        hb = {f"C{i}": 53 for i in range(3)}
        pack = {"dense": [f"I{i}" for i in range(4)], "cat": [f"C{i}" for i in range(3)]}
        packed = _native.NativeDecoder(self.SCHEMA, hash_buckets=hb, pack=pack).decode_batch(recs)
        plain = _native.NativeDecoder(self.SCHEMA, hash_buckets=hb).decode_batch(recs)
        dense_want = np.stack([plain[f"I{i}"].values for i in range(4)], axis=1)
        cat_want = np.stack([plain[f"C{i}"].values for i in range(3)], axis=1)
        np.testing.assert_array_equal(packed["dense"].values, dense_want)
        assert packed["dense"].values.dtype == np.int64
        np.testing.assert_array_equal(packed["cat"].values, cat_want)
        assert packed["cat"].values.dtype == np.int32
        # ungrouped column still a normal scalar column
        np.testing.assert_array_equal(packed["label"].values, plain["label"].values)
        # member columns are not emitted separately
        assert "I0" not in packed.columns

    def test_missing_grouped_field_is_zero(self):
        schema = StructType([StructField("a", LongType()), StructField("b", LongType())])
        recs = [encode_example(Example(features={"a": Feature.int64_list([7])}))]
        packed = _native.NativeDecoder(schema, pack={"g": ["a", "b"]}).decode_batch(recs)
        np.testing.assert_array_equal(packed["g"].values, [[7, 0]])

    def test_mixed_dtype_group_rejected(self):
        schema = StructType([StructField("a", LongType()), StructField("b", FloatType())])
        with pytest.raises(ValueError, match="one dtype"):
            _native.NativeDecoder(schema, pack={"g": ["a", "b"]})

    def test_dataset_pack_end_to_end(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.tpu.ingest import host_batch_from_columnar

        schema = StructType(
            [StructField("x", LongType()), StructField("y", LongType()),
             StructField("c", StringType())]
        )
        rows = [[k, k * 2, f"u{k % 5}"] for k in range(40)]
        out = str(sandbox / "gp")
        tfio.write(rows, schema, out, mode="overwrite")
        hb = {"c": 16}
        pack = {"dense": ["x", "y"]}
        ds = TFRecordDataset(out, batch_size=20, schema=schema,
                             hash_buckets=hb, pack=pack)
        host_batches = []
        with ds.batches() as it:
            for cb in it:
                assert "dense" in cb.columns
                host_batches.append(
                    host_batch_from_columnar(cb, ds.schema, hash_buckets=hb, pack=pack)
                )
        # unpacked pipeline must agree
        ds2 = TFRecordDataset(out, batch_size=20, schema=schema, hash_buckets=hb)
        ref = []
        with ds2.batches() as it2:
            for cb in it2:
                ref.append(host_batch_from_columnar(cb, ds2.schema, hash_buckets=hb, pack=pack))
        for a, b in zip(host_batches, ref):
            assert set(a) == set(b)
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])

    def test_dataset_pack_validation(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset

        schema = StructType([StructField("x", LongType()), StructField("c", StringType())])
        out = str(sandbox / "gpv")
        tfio.write([[1, "a"]], schema, out, mode="overwrite")
        with pytest.raises(ValueError, match="no such data column"):
            TFRecordDataset(out, batch_size=1, schema=schema, pack={"g": ["zz"]})
        with pytest.raises(ValueError, match="hash_buckets"):
            TFRecordDataset(out, batch_size=1, schema=schema, pack={"g": ["c"]})
        with pytest.raises(ValueError, match="collides"):
            TFRecordDataset(out, batch_size=1, schema=schema, pack={"x": ["x"]})

    def test_dataset_mixed_dtype_pack_rejected(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset

        schema = StructType([StructField("a", LongType()), StructField("f", FloatType())])
        out = str(sandbox / "mx")
        tfio.write([[1, 1.5]], schema, out, mode="overwrite")
        with pytest.raises(ValueError, match="share one dtype"):
            TFRecordDataset(out, batch_size=1, schema=schema, pack={"g": ["a", "f"]})

    def test_duplicate_pack_membership_rejected(self):
        schema = StructType([StructField("a", LongType()), StructField("b", LongType())])
        with pytest.raises(ValueError, match="packed once"):
            _native.NativeDecoder(schema, pack={"g1": ["a"], "g2": ["a", "b"]})
        with pytest.raises(ValueError, match="packed once"):
            _native.NativeDecoder(schema, pack={"g": ["a", "a"]})

    def test_empty_pack_group_rejected(self):
        schema = StructType([StructField("a", LongType())])
        with pytest.raises(ValueError, match="no members"):
            _native.NativeDecoder(schema, pack={"g": []})

    def test_duplicate_key_missing_last_occurrence_grouped(self):
        """Duplicate map key where the LAST occurrence has an unset oneof:
        missing->0 must hold in the group matrix (stale value zeroed)."""
        def entry(payload_feature):
            e = bytes([0x0A, 1, ord("a"), 0x12, len(payload_feature)]) + payload_feature
            return bytes([0x0A, len(e)]) + e

        int64_list = bytes([0x0A, 0x01, 7])
        full = bytes([0x1A, len(int64_list)]) + int64_list  # int64_list [7]
        empty_feature = b""  # unset oneof
        features = entry(full) + entry(empty_feature)
        record = bytes([0x0A, len(features)]) + features
        schema = StructType([StructField("a", LongType()), StructField("b", LongType())])
        packed = _native.NativeDecoder(schema, pack={"g": ["a", "b"]}).decode_batch([record])
        np.testing.assert_array_equal(packed["g"].values, [[0, 0]])
        plain = _native.NativeDecoder(schema).decode_batch([record])
        assert plain["a"].values[0] == 0 and not plain["a"].mask[0]


class TestMultiHotHashing:
    """hash_buckets on ArrayType(String): ragged multi-hot categoricals."""

    SCHEMA = StructType([StructField("tags", ArrayType(StringType())),
                         StructField("x", LongType())])

    def make_recs(self, n=30):
        rng = np.random.default_rng(5)
        recs = []
        for k in range(n):
            feats = {
                "x": Feature.int64_list([k]),
                "tags": Feature.bytes_list(
                    [f"tag{int(v)}".encode() for v in rng.integers(0, 50, size=k % 5)]
                ),
            }
            recs.append(encode_example(Example(features=feats)))
        return recs

    def test_fused_ragged_hash_matches_post_hoc(self):
        from tpu_tfrecord.tpu.ingest import hash_bytes_column

        recs = self.make_recs()
        plain = _native.NativeDecoder(self.SCHEMA).decode_batch(recs)
        want = hash_bytes_column(plain["tags"], 97)
        fused = _native.NativeDecoder(self.SCHEMA, hash_buckets={"tags": 97}).decode_batch(recs)
        assert fused["tags"].values.dtype == np.int32
        np.testing.assert_array_equal(fused["tags"].offsets, plain["tags"].offsets)
        np.testing.assert_array_equal(fused["tags"].values, want)

    def test_host_batch_pads_multi_hot(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.tpu.ingest import batch_spec, host_batch_from_columnar

        rows = [[["a", "b"], 0], [[], 1], [["c"], 2], [["a", "b", "c", "d", "e"], 3]]
        out = str(sandbox / "mh")
        tfio.write(rows, self.SCHEMA, out, mode="overwrite")
        hb_spec = {"tags": 64}
        pads = {"tags": 4}
        ds = TFRecordDataset(out, batch_size=4, schema=self.SCHEMA,
                             hash_buckets=hb_spec, drop_remainder=False)
        with ds.batches() as it:
            cb = next(it)
        hb = host_batch_from_columnar(cb, ds.schema, pad_to=pads, hash_buckets=hb_spec)
        assert hb["tags"].shape == (4, 4) and hb["tags"].dtype == np.int32
        order = np.argsort(hb["x"])
        np.testing.assert_array_equal(hb["tags_len"][order], [2, 0, 1, 4])  # 5 truncated
        spec = batch_spec(ds.schema, 4, pad_to=pads, hash_buckets=hb_spec)
        for k in hb:
            assert spec[k].shape == hb[k].shape and spec[k].dtype == hb[k].dtype

    def test_python_fallback_matches_fused(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.tpu.ingest import host_batch_from_columnar

        rows = [[["u", "vv"], 0], [["w"], 1]]
        out = str(sandbox / "pf")
        tfio.write(rows, self.SCHEMA, out, mode="overwrite")
        hb_spec, pads = {"tags": 16}, {"tags": 3}
        ds = TFRecordDataset(out, batch_size=2, schema=self.SCHEMA,
                             hash_buckets=hb_spec, drop_remainder=False)
        with ds.batches() as it:
            fused = host_batch_from_columnar(next(it), ds.schema, pad_to=pads,
                                             hash_buckets=hb_spec)
        # unfused (no hash at decode): host_batch hashes the blobs
        ds2 = TFRecordDataset(out, batch_size=2, schema=self.SCHEMA,
                              drop_remainder=False)
        with ds2.batches() as it2:
            plain = host_batch_from_columnar(next(it2), ds2.schema, pad_to=pads,
                                             hash_buckets=hb_spec)
        for k in fused:
            np.testing.assert_array_equal(fused[k], plain[k])

    def test_missing_pad_to_raises(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.tpu.ingest import host_batch_from_columnar

        out = str(sandbox / "nopad")
        tfio.write([[["a"], 0]], self.SCHEMA, out, mode="overwrite")
        ds = TFRecordDataset(out, batch_size=1, schema=self.SCHEMA,
                             hash_buckets={"tags": 8}, drop_remainder=False)
        with ds.batches() as it:
            cb = next(it)
        with pytest.raises(ValueError, match="multi-hot"):
            host_batch_from_columnar(cb, ds.schema, hash_buckets={"tags": 8})
