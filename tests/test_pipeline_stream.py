"""Microbatch-streamed serving mode (ISSUE 15): `PipelineStream` must
serve BITWISE what batch-mode `pipeline_apply` computes on the same
slices, with a per-call feed of exactly ONE [mb, ...] slice (no
[M, mb, ...] stream materialized anywhere — pinned via the compiled
step's argument bytes) and a gather-free per-tick step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hlo_util import per_device_argument_bytes
from test_pipeline_parallel import make_stages
from tools.graftlint import hlo_contracts
from tpu_tfrecord.models import pipeline
from tpu_tfrecord.tpu import create_mesh


def serve(stream, xs):
    """Push every slice of xs through the stream; outputs in FIFO order."""
    outs = []
    for i in range(xs.shape[0]):
        outs.extend(stream.push(xs[i]))
    outs.extend(stream.flush())
    return outs


class TestStreamParity:
    @pytest.mark.parametrize("n_stages,n_virtual,m", [
        (4, 1, 6),    # classic schedule
        (4, 2, 9),    # interleaved, ragged request count
        (2, 2, 5),
        (2, 4, 8),
    ])
    def test_streamed_outputs_bitwise_equal_batch_mode(
        self, n_stages, n_virtual, m
    ):
        """The acceptance pin: the serving path cannot drift from the
        trained graph — same slices, same bits."""
        mesh = create_mesh({"pipe": n_stages}, jax.devices()[:n_stages])
        params, stage_fn = make_stages(
            n_stages, seed=n_stages + n_virtual, n_virtual=n_virtual
        )
        xs = np.random.default_rng(m).normal(size=(m, 2, 8)).astype(
            np.float32
        )
        batch = np.asarray(
            pipeline.pipeline_apply(
                stage_fn, params, jnp.asarray(xs), mesh, n_virtual=n_virtual
            )
        )
        stream = pipeline.PipelineStream(
            stage_fn, params, mesh, n_virtual=n_virtual
        )
        outs = serve(stream, xs)
        assert len(outs) == m
        assert stream.served == m and stream.in_flight == 0
        for i in range(m):
            np.testing.assert_array_equal(outs[i], batch[i])

    def test_reset_replays_identically(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = np.random.default_rng(0).normal(size=(5, 2, 8)).astype(
            np.float32
        )
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        first = serve(stream, xs)
        stream.reset()
        second = serve(stream, xs)
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a, b)

    def test_outputs_pop_fifo_with_pipeline_latency(self):
        """V=1: warmup pushes return nothing, then one output pops per
        push (steady state within a round)."""
        s = 4
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s)
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        xs = np.random.default_rng(1).normal(size=(8, 2, 8)).astype(
            np.float32
        )
        per_push = [len(stream.push(xs[i])) for i in range(8)]
        # latency S ticks: the first S - 1 pushes cannot complete
        assert sum(per_push[: s - 1]) == 0
        assert per_push[s:] == [1] * (8 - s)
        assert len(stream.flush()) == 8 - sum(per_push)

    def test_interleaved_outputs_pop_in_round_bursts(self):
        """V>1: a round's outputs are born during the (V-1)·S gap ticks
        the NEXT round's first push advances through — nothing pops
        before push S, then pops arrive in bursts, still FIFO and still
        all delivered."""
        s, v = 2, 2
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        stream = pipeline.PipelineStream(stage_fn, params, mesh, n_virtual=v)
        xs = np.random.default_rng(2).normal(size=(8, 2, 8)).astype(
            np.float32
        )
        per_push = [len(stream.push(xs[i])) for i in range(8)]
        assert sum(per_push[:s]) == 0          # first pop at push S
        tail = stream.flush()
        assert sum(per_push) + len(tail) == 8  # every push answered
        assert stream.served == 8 and stream.in_flight == 0

    @pytest.mark.parametrize("n_stages,n_virtual", [(2, 1), (4, 2)])
    def test_push_after_flush_rebases_the_schedule(
        self, n_stages, n_virtual
    ):
        """A serving loop drains during idle (flush) and then accepts new
        requests: flush advances the tick clock past the nominal next
        injection slot, so push must re-base onto the first usable slot —
        outputs stay exact, not silently garbage (regression: review of
        ISSUE 15)."""
        mesh = create_mesh({"pipe": n_stages}, jax.devices()[:n_stages])
        params, stage_fn = make_stages(n_stages, n_virtual=n_virtual)
        xs = np.random.default_rng(9).normal(size=(6, 2, 8)).astype(
            np.float32
        )
        stream = pipeline.PipelineStream(
            stage_fn, params, mesh, n_virtual=n_virtual
        )
        outs = []
        for i in range(6):
            outs.extend(stream.push(xs[i]))
            if i % 2 == 0:
                outs.extend(stream.flush())  # idle drain mid-serve
        outs.extend(stream.flush())
        ref = np.asarray(
            pipeline.pipeline_apply(
                stage_fn, params, jnp.asarray(xs), mesh,
                n_virtual=n_virtual,
            )
        )
        assert len(outs) == 6
        for i in range(6):
            np.testing.assert_array_equal(outs[i], ref[i])

    def test_shape_change_rejected(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        stream.push(np.zeros((2, 8), np.float32))
        with pytest.raises(ValueError, match="one compiled step"):
            stream.push(np.zeros((3, 8), np.float32))

    def test_dtype_change_rejected(self):
        """A same-shape push with a different dtype must fail loudly too —
        a silent retrace would break the one-compiled-step contract and
        the bitwise parity with the batch path."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        stream.push(np.zeros((2, 8), np.float32))
        with pytest.raises(ValueError, match="one dtype"):
            stream.push(np.zeros((2, 8), np.int32))


class TestStreamScaleShape:
    def test_per_call_feed_is_one_slice(self):
        """The no-[M, mb, ...]-materialization pin: the compiled step's
        per-device argument bytes are EXACTLY the stage-weight shard +
        the carry (tick scalar + one activation slice) + ONE replicated
        [mb, ...] feed slice — independent of how many microbatches get
        served, because the stream never takes more."""
        s, v, mb = 4, 2, (2, 8)
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
        stream = pipeline.PipelineStream(
            stage_fn, p_sh, mesh, n_virtual=v, microbatch_shape=mb
        )
        step, args = stream.step_spec()
        slice_bytes = int(np.prod(mb)) * 4
        weights_bytes = sum(
            a.size * a.dtype.itemsize for a in jax.tree.leaves(params)
        ) // s
        expect = (
            weights_bytes
            + 4            # the tick counter (int32, replicated)
            + slice_bytes  # the carry's activation slice (pipe-sharded)
            + slice_bytes  # THE per-call feed: one [mb, ...] slice
        )
        assert per_device_argument_bytes(step, *args) == expect

    def test_arg_bytes_flat_in_request_count(self):
        """Serving 3 vs 30 microbatches runs the SAME compiled step with
        the SAME per-device argument bytes — nothing accumulates."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        sizes = []
        for m in (3, 30):
            stream.reset()
            xs = np.random.default_rng(m).normal(size=(m, 2, 8)).astype(
                np.float32
            )
            serve(stream, xs)
            step, args = stream.step_spec()
            sizes.append(per_device_argument_bytes(step, *args))
        assert sizes[0] == sizes[1], sizes

    def test_hlo_gather_free(self):
        """Per-tick step pin from the shared manifest: collective-permute
        only — streaming adds no gather, no reduce, no all-to-all."""
        hlo_contracts.verify("pipeline_stream_step")
