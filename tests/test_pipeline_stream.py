"""Microbatch-streamed serving mode (ISSUE 15): `PipelineStream` must
serve BITWISE what batch-mode `pipeline_apply` computes on the same
slices, with a per-call feed of exactly ONE [mb, ...] slice (no
[M, mb, ...] stream materialized anywhere — pinned via the compiled
step's argument bytes) and a gather-free per-tick step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hlo_util import per_device_argument_bytes
from test_pipeline_parallel import make_stages
from tools.graftlint import hlo_contracts
from tpu_tfrecord.models import pipeline
from tpu_tfrecord.tpu import create_mesh


def serve(stream, xs):
    """Push every slice of xs through the stream; outputs in FIFO order."""
    outs = []
    for i in range(xs.shape[0]):
        outs.extend(stream.push(xs[i]))
    outs.extend(stream.flush())
    return outs


class TestStreamParity:
    @pytest.mark.parametrize("n_stages,n_virtual,m", [
        (4, 1, 6),    # classic schedule
        (4, 2, 9),    # interleaved, ragged request count
        (2, 2, 5),
        (2, 4, 8),
    ])
    def test_streamed_outputs_bitwise_equal_batch_mode(
        self, n_stages, n_virtual, m
    ):
        """The acceptance pin: the serving path cannot drift from the
        trained graph — same slices, same bits."""
        mesh = create_mesh({"pipe": n_stages}, jax.devices()[:n_stages])
        params, stage_fn = make_stages(
            n_stages, seed=n_stages + n_virtual, n_virtual=n_virtual
        )
        xs = np.random.default_rng(m).normal(size=(m, 2, 8)).astype(
            np.float32
        )
        batch = np.asarray(
            pipeline.pipeline_apply(
                stage_fn, params, jnp.asarray(xs), mesh, n_virtual=n_virtual
            )
        )
        stream = pipeline.PipelineStream(
            stage_fn, params, mesh, n_virtual=n_virtual
        )
        outs = serve(stream, xs)
        assert len(outs) == m
        assert stream.served == m and stream.in_flight == 0
        for i in range(m):
            np.testing.assert_array_equal(outs[i], batch[i])

    def test_reset_replays_identically(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = np.random.default_rng(0).normal(size=(5, 2, 8)).astype(
            np.float32
        )
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        first = serve(stream, xs)
        stream.reset()
        second = serve(stream, xs)
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a, b)

    def test_outputs_pop_fifo_with_pipeline_latency(self):
        """V=1: warmup pushes return nothing, then one output pops per
        push (steady state within a round)."""
        s = 4
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s)
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        xs = np.random.default_rng(1).normal(size=(8, 2, 8)).astype(
            np.float32
        )
        per_push = [len(stream.push(xs[i])) for i in range(8)]
        # latency S ticks: the first S - 1 pushes cannot complete
        assert sum(per_push[: s - 1]) == 0
        assert per_push[s:] == [1] * (8 - s)
        assert len(stream.flush()) == 8 - sum(per_push)

    def test_interleaved_outputs_pop_in_round_bursts(self):
        """V>1: a round's outputs are born during the (V-1)·S gap ticks
        the NEXT round's first push advances through — nothing pops
        before push S, then pops arrive in bursts, still FIFO and still
        all delivered."""
        s, v = 2, 2
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        stream = pipeline.PipelineStream(stage_fn, params, mesh, n_virtual=v)
        xs = np.random.default_rng(2).normal(size=(8, 2, 8)).astype(
            np.float32
        )
        per_push = [len(stream.push(xs[i])) for i in range(8)]
        assert sum(per_push[:s]) == 0          # first pop at push S
        tail = stream.flush()
        assert sum(per_push) + len(tail) == 8  # every push answered
        assert stream.served == 8 and stream.in_flight == 0

    @pytest.mark.parametrize("n_stages,n_virtual", [(2, 1), (4, 2)])
    def test_push_after_flush_rebases_the_schedule(
        self, n_stages, n_virtual
    ):
        """A serving loop drains during idle (flush) and then accepts new
        requests: flush advances the tick clock past the nominal next
        injection slot, so push must re-base onto the first usable slot —
        outputs stay exact, not silently garbage (regression: review of
        ISSUE 15)."""
        mesh = create_mesh({"pipe": n_stages}, jax.devices()[:n_stages])
        params, stage_fn = make_stages(n_stages, n_virtual=n_virtual)
        xs = np.random.default_rng(9).normal(size=(6, 2, 8)).astype(
            np.float32
        )
        stream = pipeline.PipelineStream(
            stage_fn, params, mesh, n_virtual=n_virtual
        )
        outs = []
        for i in range(6):
            outs.extend(stream.push(xs[i]))
            if i % 2 == 0:
                outs.extend(stream.flush())  # idle drain mid-serve
        outs.extend(stream.flush())
        ref = np.asarray(
            pipeline.pipeline_apply(
                stage_fn, params, jnp.asarray(xs), mesh,
                n_virtual=n_virtual,
            )
        )
        assert len(outs) == 6
        for i in range(6):
            np.testing.assert_array_equal(outs[i], ref[i])

    def test_shape_change_rejected(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        stream.push(np.zeros((2, 8), np.float32))
        with pytest.raises(ValueError, match="one compiled step"):
            stream.push(np.zeros((3, 8), np.float32))

    def test_dtype_change_rejected(self):
        """A same-shape push with a different dtype must fail loudly too —
        a silent retrace would break the one-compiled-step contract and
        the bitwise parity with the batch path."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        stream.push(np.zeros((2, 8), np.float32))
        with pytest.raises(ValueError, match="one dtype"):
            stream.push(np.zeros((2, 8), np.int32))


class TestSlotIsolation:
    """The property continuous batching (ISSUE 18) silently depends on:
    a microbatch row ("slot") is a pure function of ITS OWN contents —
    refilling one slot with a new request mid-flight must not perturb any
    other slot's bytes at any tick."""

    def test_changing_one_slot_perturbs_no_other_slot(self):
        s, v, m, rows = 2, 2, 6, 4
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        xs = np.random.default_rng(21).normal(size=(m, rows, 8)).astype(
            np.float32
        )
        stream = pipeline.PipelineStream(stage_fn, params, mesh, n_virtual=v)
        base = serve(stream, xs)
        # "refill" slot 1 of microbatch 3 mid-flight: same schedule, one
        # row's contents replaced
        xs2 = xs.copy()
        xs2[3, 1, :] = np.random.default_rng(99).normal(size=8).astype(
            np.float32
        )
        stream.reset()
        got = serve(stream, xs2)
        assert len(got) == m
        for i in range(m):
            if i == 3:
                continue
            np.testing.assert_array_equal(
                got[i], base[i],
                err_msg=f"microbatch {i} perturbed by a slot refill in 3",
            )
        keep = [r for r in range(rows) if r != 1]
        np.testing.assert_array_equal(
            np.asarray(got[3])[keep], np.asarray(base[3])[keep],
            err_msg="sibling slots perturbed by refilling slot 1",
        )
        assert not np.array_equal(got[3][1], base[3][1]), (
            "the refilled slot must actually change (test is vacuous)"
        )

    def test_slot_outputs_invariant_to_row_position(self):
        """A request's logits do not depend on WHICH slot it rides — the
        scheduler may pack a continuation into any free row."""
        s, v, rows = 2, 2, 4
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        row = np.random.default_rng(5).normal(size=(1, 8)).astype(np.float32)
        fill = np.zeros((rows, 8), np.float32)
        outs = []
        for slot in range(rows):
            x = fill.copy()
            x[slot] = row
            stream = pipeline.PipelineStream(
                stage_fn, params, mesh, n_virtual=v
            )
            (out,) = [*stream.push(x), *stream.flush()]
            outs.append(np.asarray(out)[slot])
        for slot in range(1, rows):
            np.testing.assert_array_equal(outs[slot], outs[0])


class TestTaggedStream:
    """Host-side tag plumbing (ISSUE 18): tags ride the pending FIFO next
    to their microbatch and pop with its output — they never enter the
    compiled step (the argument-bytes pin above still holds)."""

    def test_tags_pop_fifo_with_their_outputs(self):
        s, v, m = 2, 2, 7
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        xs = np.random.default_rng(7).normal(size=(m, 2, 8)).astype(
            np.float32
        )
        stream = pipeline.PipelineStream(stage_fn, params, mesh, n_virtual=v)
        got = []
        for i in range(m):
            got.extend(stream.push_tagged(xs[i], tag=("req", i)))
        got.extend(stream.flush_tagged())
        assert [t for _, t in got] == [("req", i) for i in range(m)]
        ref = np.asarray(
            pipeline.pipeline_apply(
                stage_fn, params, jnp.asarray(xs), mesh, n_virtual=v
            )
        )
        for i, (out, _) in enumerate(got):
            np.testing.assert_array_equal(out, ref[i])

    def test_untagged_push_unchanged(self):
        """push/flush are exact unwraps of the tagged twins (default tag
        None) — existing serving loops see identical outputs."""
        mesh = create_mesh({"pipe": 2}, jax.devices()[:2])
        params, stage_fn = make_stages(2)
        xs = np.random.default_rng(3).normal(size=(4, 2, 8)).astype(
            np.float32
        )
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        plain = serve(stream, xs)
        stream.reset()
        tagged = []
        for i in range(4):
            tagged.extend(stream.push_tagged(xs[i]))
        tagged.extend(stream.flush_tagged())
        assert [t for _, t in tagged] == [None] * 4
        for a, (b, _) in zip(plain, tagged):
            np.testing.assert_array_equal(a, b)


class TestStreamScaleShape:
    def test_per_call_feed_is_one_slice(self):
        """The no-[M, mb, ...]-materialization pin: the compiled step's
        per-device argument bytes are EXACTLY the stage-weight shard +
        the carry (tick scalar + one activation slice) + ONE replicated
        [mb, ...] feed slice — independent of how many microbatches get
        served, because the stream never takes more."""
        s, v, mb = 4, 2, (2, 8)
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
        stream = pipeline.PipelineStream(
            stage_fn, p_sh, mesh, n_virtual=v, microbatch_shape=mb
        )
        step, args = stream.step_spec()
        slice_bytes = int(np.prod(mb)) * 4
        weights_bytes = sum(
            a.size * a.dtype.itemsize for a in jax.tree.leaves(params)
        ) // s
        expect = (
            weights_bytes
            + 4            # the tick counter (int32, replicated)
            + slice_bytes  # the carry's activation slice (pipe-sharded)
            + slice_bytes  # THE per-call feed: one [mb, ...] slice
        )
        assert per_device_argument_bytes(step, *args) == expect

    def test_arg_bytes_flat_in_request_count(self):
        """Serving 3 vs 30 microbatches runs the SAME compiled step with
        the SAME per-device argument bytes — nothing accumulates."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        stream = pipeline.PipelineStream(stage_fn, params, mesh)
        sizes = []
        for m in (3, 30):
            stream.reset()
            xs = np.random.default_rng(m).normal(size=(m, 2, 8)).astype(
                np.float32
            )
            serve(stream, xs)
            step, args = stream.step_spec()
            sizes.append(per_device_argument_bytes(step, *args))
        assert sizes[0] == sizes[1], sizes

    def test_hlo_gather_free(self):
        """Per-tick step pin from the shared manifest: collective-permute
        only — streaming adds no gather, no reduce, no all-to-all."""
        hlo_contracts.verify("pipeline_stream_step")
