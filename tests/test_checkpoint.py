"""Tests for checkpoint persistence of iterator state."""

import importlib.util
import os

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import checkpoint
from tpu_tfrecord.io.dataset import IteratorState, TFRecordDataset
from tpu_tfrecord.schema import LongType, StructField, StructType

SCHEMA = StructType([StructField("uid", LongType())])


def test_save_load_round_trip(tmp_path):
    st = IteratorState(epoch=2, shard_cursor=5, record_offset=77)
    path = checkpoint.save_state(str(tmp_path), st, process_index=3, step=42)
    assert os.path.basename(path) == "_input_state.3.json"
    assert checkpoint.load_state(str(tmp_path), process_index=3) == st
    assert checkpoint.load_state(str(tmp_path), process_index=9) is None


def test_from_json_tolerates_unknown_keys():
    """Regression (ADVICE r2): a newer writer's extra state fields (the way
    'fingerprint' was added within format version 1) must load in an older
    reader as a clean IteratorState, not crash with TypeError."""
    st = IteratorState.from_json(
        {"epoch": 1, "shard_cursor": 2, "record_offset": 3,
         "fingerprint": "abc", "some_future_field": {"x": 1}}
    )
    assert st == IteratorState(epoch=1, shard_cursor=2, record_offset=3)
    assert st.fingerprint == "abc"


def test_save_from_live_iterator_and_resume(sandbox, tmp_path):
    out = str(sandbox / "ds")
    for s in range(3):
        tfio.write([[s * 10 + i] for i in range(6)], SCHEMA, out, mode="append")
    full = []
    ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
    with ds.batches() as it:
        for b in it:
            full.extend(b["uid"].values.tolist())

    ds1 = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
    with ds1.batches() as it:
        first = next(it)["uid"].values.tolist()
        checkpoint.save_state(str(tmp_path), it, process_index=0)
    st = checkpoint.load_state(str(tmp_path), process_index=0)
    rest = []
    ds2 = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
    with ds2.batches(st) as it:
        for b in it:
            rest.extend(b["uid"].values.tolist())
    assert first + rest == full


def test_state_file_inside_dataset_dir_is_ignored_by_discovery(sandbox):
    out = str(sandbox / "ds2")
    tfio.write([[1], [2]], SCHEMA, out, mode="overwrite")
    checkpoint.save_state(out, IteratorState(), process_index=0)
    shards = tfio.discover_shards(out)
    assert all("input_state" not in s.path for s in shards)
    assert len(tfio.read(out, schema=SCHEMA)) == 2


class TestIdentityGuard:
    """Resuming against a CHANGED dataset must fail loudly, never silently
    read wrong/duplicate data (the fingerprint covers the global shard list,
    process slot, shuffle seed, and record type)."""

    def _write(self, out, n_shards=2):
        for s in range(n_shards):
            tfio.write([[s * 10 + i] for i in range(6)], SCHEMA, out, mode="append")

    def _saved_state(self, out, tmp_path):
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        with ds.batches() as it:
            next(it)
            checkpoint.save_state(str(tmp_path), it, process_index=0)
        return checkpoint.load_state(str(tmp_path), process_index=0)

    def test_mutated_shard_list_rejected(self, sandbox, tmp_path):
        out = str(sandbox / "mut")
        self._write(out)
        st = self._saved_state(out, tmp_path)
        assert st.fingerprint is not None
        # mutate the dataset: add a shard
        tfio.write([[99]], SCHEMA, out, mode="append")
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        with pytest.raises(ValueError, match="fingerprint"):
            ds.batches(st)

    def test_different_seed_rejected(self, sandbox, tmp_path):
        out = str(sandbox / "seed")
        self._write(out)
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA, shuffle=True, seed=1)
        with ds.batches() as it:
            next(it)
            st = it.state()
        ds2 = TFRecordDataset(out, batch_size=6, schema=SCHEMA, shuffle=True, seed=2)
        with pytest.raises(ValueError, match="fingerprint"):
            ds2.batches(st)

    def test_different_process_slot_rejected(self, sandbox, tmp_path):
        out = str(sandbox / "slot")
        self._write(out, n_shards=4)
        ds = TFRecordDataset(
            out, batch_size=6, schema=SCHEMA, process_index=0, process_count=2
        )
        with ds.batches() as it:
            next(it)
            st = it.state()
        ds2 = TFRecordDataset(
            out, batch_size=6, schema=SCHEMA, process_index=1, process_count=2
        )
        with pytest.raises(ValueError, match="fingerprint"):
            ds2.batches(st)

    def test_matching_dataset_resumes(self, sandbox, tmp_path):
        out = str(sandbox / "ok")
        self._write(out)
        st = self._saved_state(out, tmp_path)
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        with ds.batches(st) as it:
            got = [b["uid"].values.tolist() for b in it]
        assert got  # resumed cleanly past the first batch

    def test_legacy_state_without_fingerprint_accepted(self, sandbox):
        out = str(sandbox / "legacy")
        self._write(out)
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        legacy = IteratorState(epoch=0, shard_cursor=0, record_offset=6)
        with ds.batches(legacy) as it:
            assert next(it).num_rows == 6


@pytest.mark.skipif(
    importlib.util.find_spec("orbax") is None
    or importlib.util.find_spec("orbax.checkpoint") is None,
    reason="TrainCheckpointer requires the optional orbax-checkpoint package",
)
class TestTrainCheckpointer:
    def test_model_and_input_state_restore_together(self, sandbox, tmp_path):
        """Params and input position persist under ONE orbax step dir, so a
        restore can never pair step-N params with a stale input position."""
        import jax.numpy as jnp
        import numpy as np

        out = str(sandbox / "ds")
        tfio.write([[i] for i in range(30)], SCHEMA, out, mode="overwrite")
        ckdir = str(tmp_path / "ck")
        ck = checkpoint.TrainCheckpointer(ckdir, max_to_keep=2)
        ds = TFRecordDataset(out, batch_size=10, schema=SCHEMA)
        it = ds.batches()
        first = next(it)["uid"].values.tolist()
        ck.save(1, {"w": jnp.full((3,), 7.0)}, it)
        it.close()
        ck.close()

        ck2 = checkpoint.TrainCheckpointer(ckdir)
        step, restored, resume = ck2.restore({"w": jnp.zeros((3,))})
        assert step == 1
        np.testing.assert_array_equal(np.asarray(restored["w"]), [7.0] * 3)
        assert resume is not None and resume.fingerprint
        rest = []
        with TFRecordDataset(out, batch_size=10, schema=SCHEMA).batches(resume) as it2:
            for b in it2:
                rest.extend(b["uid"].values.tolist())
        assert first + rest == list(range(30))
        ck2.close()

    def test_restore_without_checkpoint(self, tmp_path):
        ck = checkpoint.TrainCheckpointer(str(tmp_path / "empty"))
        step, tpl, resume = ck.restore({"a": 1})
        assert step is None and resume is None and tpl == {"a": 1}
        ck.close()


def test_version_check(tmp_path):
    import json

    path = checkpoint.state_path(str(tmp_path), 0)
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump({"version": 999, "state": {}}, fh)
    with pytest.raises(ValueError, match="version"):
        checkpoint.load_state(str(tmp_path), process_index=0)


# ---------------------------------------------------------------------------
# ISSUE 16: async snapshot/commit checkpointing
# ---------------------------------------------------------------------------


def _tree():
    import numpy as np

    return {
        "w": np.arange(24, dtype=np.float64).reshape(4, 6),
        "b": np.full(6, 3.5),
    }


class TestDurableWrite:
    def test_writes_bytes_atomically(self, tmp_path):
        p = str(tmp_path / "out.json")
        checkpoint.durable_write(p, b'{"ok": 1}')
        assert open(p, "rb").read() == b'{"ok": 1}'
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]

    def test_failure_cleans_stage_file(self, tmp_path):
        p = str(tmp_path / "out.bin")

        def boom(fh):
            raise RuntimeError("disk says no")

        with pytest.raises(RuntimeError):
            checkpoint.durable_write(p, write_fn=boom)
        assert not os.path.exists(p)
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]

    def test_torn_state_file_raises_named_error(self, tmp_path):
        path = checkpoint.state_path(str(tmp_path), 0)
        with open(path, "w") as fh:  # graftlint: allow(atomic-write: test constructs a deliberately torn file)
            fh.write('{"version": 1, "sta')  # a torn tail
        with pytest.raises(checkpoint.TornStateError, match="torn"):
            checkpoint.load_state(str(tmp_path), process_index=0)


class TestAsyncCheckpointer:
    def test_round_trip_bitwise(self, tmp_path):
        import numpy as np

        state = _tree()
        with checkpoint.AsyncCheckpointer(
            str(tmp_path), process_index=0, process_count=1
        ) as ck:
            ck.save(8, state, {"rows": "abc"})
            ck.wait()
            step, restored, payload = ck.restore(_tree())
        assert step == 8 and payload == {"rows": "abc"}
        for k in state:
            assert np.array_equal(state[k], restored[k])
            assert state[k].dtype == restored[k].dtype

    def test_sync_twin_same_bytes(self, tmp_path):
        """sync=True must produce the identical generation layout/bytes —
        it is the measurement twin, not a different format."""
        a, s = str(tmp_path / "a"), str(tmp_path / "s")
        with checkpoint.AsyncCheckpointer(
            a, process_index=0, process_count=1
        ) as ck:
            ck.save(4, _tree(), {"x": 1})
            ck.wait()
        with checkpoint.AsyncCheckpointer(
            s, process_index=0, process_count=1, sync=True
        ) as ck:
            ck.save(4, _tree(), {"x": 1})
        rel = os.path.join("gen-00000004", "shard-00000.npz")
        assert (
            open(os.path.join(a, rel), "rb").read()
            == open(os.path.join(s, rel), "rb").read()
        )
        assert sorted(os.listdir(os.path.join(a, "gen-00000004"))) == sorted(
            os.listdir(os.path.join(s, "gen-00000004"))
        )

    def test_backpressure_one_commit_in_flight(self, tmp_path):
        """The next save() waits out the previous commit and the wait is
        counted (ckpt.commit_wait), never silently dropped."""
        from tpu_tfrecord.metrics import Metrics

        m = Metrics()
        ck = checkpoint.AsyncCheckpointer(
            str(tmp_path), process_index=0, process_count=1,
            commit_delay_s=0.2, metrics=m,
        )
        ck.save(1, _tree(), None)
        ck.save(2, _tree(), None)  # must block ~0.2s on commit 1
        ck.close()
        snap = m.snapshot()
        assert snap["ckpt.commit_wait"]["records"] == 1
        assert snap["ckpt.commit_wait"]["seconds"] >= 0.15
        assert snap["ckpt.commit"]["records"] == 2
        assert snap["ckpt.inflight"] == {"gauge": 0.0}
        assert snap["ckpt.bytes_written"]["records"] > 0

    def test_retention_and_dead_generation_sweep(self, tmp_path):
        """keep=2 retires old complete generations; a dead generation
        (shards, no manifest — an interrupted commit) is swept too."""
        d = str(tmp_path)
        # fabricate a dead generation an earlier life left behind
        dead = os.path.join(d, "gen-00000003")
        os.makedirs(dead)
        open(os.path.join(dead, "shard-00000.npz"), "wb").close()  # graftlint: allow(atomic-write: zero-byte test fixture)
        from tpu_tfrecord.metrics import Metrics

        m = Metrics()
        with checkpoint.AsyncCheckpointer(
            d, keep=2, process_index=0, process_count=1, metrics=m
        ) as ck:
            for step in (4, 8, 12):
                ck.save(step, _tree(), None)
            ck.wait()
        gens = sorted(n for n in os.listdir(d) if n.startswith("gen-"))
        assert gens == ["gen-00000008", "gen-00000012"]
        assert m.snapshot()["ckpt.generations_swept"]["records"] == 2

    def test_commit_failure_surfaces_on_next_save(self, tmp_path, monkeypatch):
        ck = checkpoint.AsyncCheckpointer(
            str(tmp_path), process_index=0, process_count=1
        )
        monkeypatch.setattr(
            checkpoint, "durable_write",
            lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")),
        )
        ck.save(1, _tree(), None)
        with pytest.raises(checkpoint.CheckpointCommitError, match="disk full"):
            ck.save(2, _tree(), None)

    def test_torn_manifest_falls_back_a_generation(self, tmp_path):
        with checkpoint.AsyncCheckpointer(
            str(tmp_path), process_index=0, process_count=1
        ) as ck:
            ck.save(4, _tree(), {"gen": 4})
            ck.save(8, _tree(), {"gen": 8})
            ck.wait()
            # tear generation 8's manifest the way a crash mid-write would
            m8 = os.path.join(str(tmp_path), "gen-00000008", ck.MANIFEST)
            with open(m8, "w") as fh:  # graftlint: allow(atomic-write: test constructs a deliberately torn file)
                fh.write('{"version": 1, "sha')
            step, _, payload = ck.restore(_tree())
        assert step == 4 and payload == {"gen": 4}

    def test_missing_shard_is_incomplete(self, tmp_path):
        with checkpoint.AsyncCheckpointer(
            str(tmp_path), process_index=0, process_count=1
        ) as ck:
            ck.save(4, _tree(), None)
            ck.wait()
            os.remove(
                os.path.join(str(tmp_path), "gen-00000004", "shard-00000.npz")
            )
            assert ck.latest_step() is None

    def test_clear_removes_all_generations(self, tmp_path):
        with checkpoint.AsyncCheckpointer(
            str(tmp_path), process_index=0, process_count=1
        ) as ck:
            ck.save(4, _tree(), None)
            ck.clear()
            assert ck.latest_step() is None
            assert not [
                n for n in os.listdir(str(tmp_path)) if n.startswith("gen-")
            ]


class TestAsyncStateSaver:
    def test_same_file_same_bytes_as_save_state(self, tmp_path):
        """The async saver is a twin, not a fork: identical path and
        bytes to the inline save_state."""
        st = IteratorState(epoch=1, shard_cursor=3, record_offset=70)
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        checkpoint.save_state(a, st, step=7, process_index=0)
        with checkpoint.AsyncStateSaver(b, process_index=0) as saver:
            saver.save(st, step=7)
            saver.wait()
        pa = checkpoint.state_path(a, 0)
        pb = checkpoint.state_path(b, 0)
        assert os.path.basename(pa) == os.path.basename(pb)
        assert open(pa, "rb").read() == open(pb, "rb").read()

    def test_round_trip_through_load_state(self, tmp_path):
        st = IteratorState(epoch=2, shard_cursor=1, record_offset=9)
        with checkpoint.AsyncStateSaver(
            str(tmp_path), process_index=0
        ) as saver:
            saver.save(st, step=3)
            saver.wait()
        assert checkpoint.load_state(str(tmp_path), process_index=0) == st
