"""Tests for checkpoint persistence of iterator state."""

import importlib.util
import os

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import checkpoint
from tpu_tfrecord.io.dataset import IteratorState, TFRecordDataset
from tpu_tfrecord.schema import LongType, StructField, StructType

SCHEMA = StructType([StructField("uid", LongType())])


def test_save_load_round_trip(tmp_path):
    st = IteratorState(epoch=2, shard_cursor=5, record_offset=77)
    path = checkpoint.save_state(str(tmp_path), st, process_index=3, step=42)
    assert os.path.basename(path) == "_input_state.3.json"
    assert checkpoint.load_state(str(tmp_path), process_index=3) == st
    assert checkpoint.load_state(str(tmp_path), process_index=9) is None


def test_from_json_tolerates_unknown_keys():
    """Regression (ADVICE r2): a newer writer's extra state fields (the way
    'fingerprint' was added within format version 1) must load in an older
    reader as a clean IteratorState, not crash with TypeError."""
    st = IteratorState.from_json(
        {"epoch": 1, "shard_cursor": 2, "record_offset": 3,
         "fingerprint": "abc", "some_future_field": {"x": 1}}
    )
    assert st == IteratorState(epoch=1, shard_cursor=2, record_offset=3)
    assert st.fingerprint == "abc"


def test_save_from_live_iterator_and_resume(sandbox, tmp_path):
    out = str(sandbox / "ds")
    for s in range(3):
        tfio.write([[s * 10 + i] for i in range(6)], SCHEMA, out, mode="append")
    full = []
    ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
    with ds.batches() as it:
        for b in it:
            full.extend(b["uid"].values.tolist())

    ds1 = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
    with ds1.batches() as it:
        first = next(it)["uid"].values.tolist()
        checkpoint.save_state(str(tmp_path), it, process_index=0)
    st = checkpoint.load_state(str(tmp_path), process_index=0)
    rest = []
    ds2 = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
    with ds2.batches(st) as it:
        for b in it:
            rest.extend(b["uid"].values.tolist())
    assert first + rest == full


def test_state_file_inside_dataset_dir_is_ignored_by_discovery(sandbox):
    out = str(sandbox / "ds2")
    tfio.write([[1], [2]], SCHEMA, out, mode="overwrite")
    checkpoint.save_state(out, IteratorState(), process_index=0)
    shards = tfio.discover_shards(out)
    assert all("input_state" not in s.path for s in shards)
    assert len(tfio.read(out, schema=SCHEMA)) == 2


class TestIdentityGuard:
    """Resuming against a CHANGED dataset must fail loudly, never silently
    read wrong/duplicate data (the fingerprint covers the global shard list,
    process slot, shuffle seed, and record type)."""

    def _write(self, out, n_shards=2):
        for s in range(n_shards):
            tfio.write([[s * 10 + i] for i in range(6)], SCHEMA, out, mode="append")

    def _saved_state(self, out, tmp_path):
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        with ds.batches() as it:
            next(it)
            checkpoint.save_state(str(tmp_path), it, process_index=0)
        return checkpoint.load_state(str(tmp_path), process_index=0)

    def test_mutated_shard_list_rejected(self, sandbox, tmp_path):
        out = str(sandbox / "mut")
        self._write(out)
        st = self._saved_state(out, tmp_path)
        assert st.fingerprint is not None
        # mutate the dataset: add a shard
        tfio.write([[99]], SCHEMA, out, mode="append")
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        with pytest.raises(ValueError, match="fingerprint"):
            ds.batches(st)

    def test_different_seed_rejected(self, sandbox, tmp_path):
        out = str(sandbox / "seed")
        self._write(out)
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA, shuffle=True, seed=1)
        with ds.batches() as it:
            next(it)
            st = it.state()
        ds2 = TFRecordDataset(out, batch_size=6, schema=SCHEMA, shuffle=True, seed=2)
        with pytest.raises(ValueError, match="fingerprint"):
            ds2.batches(st)

    def test_different_process_slot_rejected(self, sandbox, tmp_path):
        out = str(sandbox / "slot")
        self._write(out, n_shards=4)
        ds = TFRecordDataset(
            out, batch_size=6, schema=SCHEMA, process_index=0, process_count=2
        )
        with ds.batches() as it:
            next(it)
            st = it.state()
        ds2 = TFRecordDataset(
            out, batch_size=6, schema=SCHEMA, process_index=1, process_count=2
        )
        with pytest.raises(ValueError, match="fingerprint"):
            ds2.batches(st)

    def test_matching_dataset_resumes(self, sandbox, tmp_path):
        out = str(sandbox / "ok")
        self._write(out)
        st = self._saved_state(out, tmp_path)
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        with ds.batches(st) as it:
            got = [b["uid"].values.tolist() for b in it]
        assert got  # resumed cleanly past the first batch

    def test_legacy_state_without_fingerprint_accepted(self, sandbox):
        out = str(sandbox / "legacy")
        self._write(out)
        ds = TFRecordDataset(out, batch_size=6, schema=SCHEMA)
        legacy = IteratorState(epoch=0, shard_cursor=0, record_offset=6)
        with ds.batches(legacy) as it:
            assert next(it).num_rows == 6


@pytest.mark.skipif(
    importlib.util.find_spec("orbax") is None
    or importlib.util.find_spec("orbax.checkpoint") is None,
    reason="TrainCheckpointer requires the optional orbax-checkpoint package",
)
class TestTrainCheckpointer:
    def test_model_and_input_state_restore_together(self, sandbox, tmp_path):
        """Params and input position persist under ONE orbax step dir, so a
        restore can never pair step-N params with a stale input position."""
        import jax.numpy as jnp
        import numpy as np

        out = str(sandbox / "ds")
        tfio.write([[i] for i in range(30)], SCHEMA, out, mode="overwrite")
        ckdir = str(tmp_path / "ck")
        ck = checkpoint.TrainCheckpointer(ckdir, max_to_keep=2)
        ds = TFRecordDataset(out, batch_size=10, schema=SCHEMA)
        it = ds.batches()
        first = next(it)["uid"].values.tolist()
        ck.save(1, {"w": jnp.full((3,), 7.0)}, it)
        it.close()
        ck.close()

        ck2 = checkpoint.TrainCheckpointer(ckdir)
        step, restored, resume = ck2.restore({"w": jnp.zeros((3,))})
        assert step == 1
        np.testing.assert_array_equal(np.asarray(restored["w"]), [7.0] * 3)
        assert resume is not None and resume.fingerprint
        rest = []
        with TFRecordDataset(out, batch_size=10, schema=SCHEMA).batches(resume) as it2:
            for b in it2:
                rest.extend(b["uid"].values.tolist())
        assert first + rest == list(range(30))
        ck2.close()

    def test_restore_without_checkpoint(self, tmp_path):
        ck = checkpoint.TrainCheckpointer(str(tmp_path / "empty"))
        step, tpl, resume = ck.restore({"a": 1})
        assert step is None and resume is None and tpl == {"a": 1}
        ck.close()


def test_version_check(tmp_path):
    import json

    path = checkpoint.state_path(str(tmp_path), 0)
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump({"version": 999, "state": {}}, fh)
    with pytest.raises(ValueError, match="version"):
        checkpoint.load_state(str(tmp_path), process_index=0)
