"""Red/green decode-throughput floor (VERDICT r3 item 3), calibrated to
the box it runs on (VERDICT r5 item 5).

A decode regression must be caught by CI as a failing test, not discovered
rounds later as a mysteriously degraded bench headline. This pins the
device-free pipeline — native frame scan + CRC + Example decode +
categorical hashing + column-group packing at the bench's Criteo shape —
above a floor DERIVED from an in-process microbench INTERLEAVED with the
measurement windows.

Why calibrate: a fixed floor must sit low enough for the slowest CI box,
which on the reference box left a 2.6-3x cushion — a 30% decode regression
sailed under it. The microbench (memcpy + zlib.crc32 over a 4MB buffer)
tracks the box's single-thread memory/CPU speed — the same resources the
decode path is bound by — but shares NO code with it, so a decode-path
regression moves the measurement and not the floor.

Why interleave: this box's throughput swings ±40% minute to minute under
other tenants' load, so a floor calibrated once at import would compare a
loaded measurement against an idle calibration (or vice versa). Each test
alternates microbench sample / decode window and takes the best of each —
both one-sided noise estimators over the SAME interference regime — and
the floor is ``REGRESSION_TRIP`` x the reference decode-per-microbench
ratio x this run's best microbench rate. The best/best ratio was measured
stable within ~10% across load levels on the reference box while single
windows swung 3x (the constants below are its observed center).

TFR_PERF_FLOOR_EX_S / TFR_SEQ_PERF_FLOOR_EX_S still override outright;
TFR_PERF_FLOOR_SELFTEST_PCT=30 degrades the measured value by 30% before
the assert — the red-path check that the calibrated floor actually trips
(wired into tools/verify.sh runs of this file is overkill; run it by hand
when touching the calibration).
"""

import os
import time
import zlib

import numpy as np
import pytest

from tpu_tfrecord import _native, wire
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import TFRecordSerializer, encode_row

# Reference ratios (examples decoded per MB/s of microbench rate),
# measured interleaved on the bench box across idle and loaded phases:
# Criteo best/best 905-990 (center 960), seq best/best 149-168 (165 holds
# the 30% self-test honest while leaving ~20% false-fail headroom).
_REF_CRITEO_RATIO = 960.0
_REF_SEQ_RATIO = 165.0
# a 30% regression must trip: floor = 75% of the box-expected rate
# (0.75 rather than 0.70 buys the self-test margin against ratio noise)
REGRESSION_TRIP = 0.75

_MEMCRC_BUF = np.random.default_rng(0).integers(0, 256, 4 << 20, np.uint8).tobytes()


def _memcrc_mbps() -> float:
    """One microbench sample: memcpy + zlib.crc32 over a 4MB buffer,
    best-of-2 inner reps, in MB/s."""
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        reps = 4
        for _ in range(reps):
            zlib.crc32(_MEMCRC_BUF)
            bytes(memoryview(_MEMCRC_BUF))  # the memcpy half
        dt = time.perf_counter() - t0
        best = max(best, reps * len(_MEMCRC_BUF) / dt)
    return best / 1e6


def _calibrated_floor(env_var: str, ratio: float, micro_mbps: float) -> float:
    override = os.environ.get(env_var)
    if override is not None:
        return float(override)
    return REGRESSION_TRIP * ratio * micro_mbps


# red-path self-test: degrade the measurement by this percent before the
# assert (TFR_PERF_FLOOR_SELFTEST_PCT=30 must FAIL both floors)
_SELFTEST_SCALE = 1.0 - float(os.environ.get("TFR_PERF_FLOOR_SELFTEST_PCT", 0)) / 100.0
N_RECORDS = 16384
BATCH = 4096


def _write_criteo_shard(path: str, n: int) -> None:
    fields = [StructField("label", LongType(), nullable=False)]
    fields += [StructField(f"I{i}", LongType()) for i in range(1, 14)]
    fields += [StructField(f"C{i}", StringType()) for i in range(1, 27)]
    ser = TFRecordSerializer(StructType(fields))
    rng = np.random.default_rng(0)
    ints = rng.integers(0, 1 << 31, size=(n, 13))
    cats = rng.integers(0, 16, size=(n, 26, 8), dtype=np.uint8) + 97

    def rows():
        for r in range(n):
            row = [r & 1]
            row += [int(v) for v in ints[r]]
            row += [cats[r, c].tobytes().decode() for c in range(26)]
            yield encode_row(ser, RecordType.EXAMPLE, row)

    wire.write_records(path, rows())


@pytest.mark.perf
@pytest.mark.skipif(not _native.available(), reason="native decoder unavailable")
def test_criteo_decode_hash_pack_floor(tmp_path):
    from tpu_tfrecord.tpu import host_batch_from_columnar

    for s in range(2):
        _write_criteo_shard(str(tmp_path / f"part-{s:05d}.tfrecord"), N_RECORDS)
    read_fields = [StructField("label", IntegerType(), nullable=False)]
    read_fields += [StructField(f"I{i}", IntegerType()) for i in range(1, 14)]
    read_fields += [StructField(f"C{i}", StringType()) for i in range(1, 27)]
    schema = StructType(read_fields)
    hash_buckets = {f"C{i}": 1 << 20 for i in range(1, 27)}
    pack = {
        "packed": ["label"]
        + [f"I{i}" for i in range(1, 14)]
        + [f"C{i}" for i in range(1, 27)],
    }
    ds = TFRecordDataset(
        str(tmp_path),
        batch_size=BATCH,
        schema=schema,
        prefetch=4,
        num_epochs=None,
        hash_buckets=hash_buckets,
        pack=pack,
    )
    best = 0.0
    micro = 0.0
    with ds.batches() as it:
        for _ in range(3):  # warm decode thread + entry-shape caches
            host_batch_from_columnar(next(it), ds.schema,
                                     hash_buckets=hash_buckets, pack=pack)
        # best-of-3 half-second windows interleaved with the calibration
        # microbench: one-sided noise on a shared box (other tenants only
        # slow us down), so the max is the estimator for BOTH, and both
        # sample the same interference regime
        for _ in range(3):
            micro = max(micro, _memcrc_mbps())
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 0.5:
                hb = host_batch_from_columnar(
                    next(it), ds.schema, hash_buckets=hash_buckets, pack=pack
                )
                n += hb["packed"].shape[0]
            best = max(best, n / (time.perf_counter() - t0))
    floor = _calibrated_floor("TFR_PERF_FLOOR_EX_S", _REF_CRITEO_RATIO, micro)
    best *= _SELFTEST_SCALE
    assert best >= floor, (
        f"device-free decode+hash+pack throughput {best:,.0f} ex/s fell "
        f"below the calibrated floor {floor:,.0f} ex/s (microbench "
        f"{micro:,.0f} MB/s) — decode-path regression "
        "(native disabled? turbo cache broken? per-batch copies?)"
    )


SEQ_MAX_LEN = 64
SEQ_DIM = 16
SEQ_BATCH = 1024


def _write_seq_shard(path: str, n: int) -> None:
    from tpu_tfrecord.schema import ArrayType, FloatType

    fields = [
        StructField("label", LongType(), nullable=False),
        StructField("frames", ArrayType(ArrayType(FloatType()))),
    ]
    ser = TFRecordSerializer(StructType(fields))
    rng = np.random.default_rng(1)

    def rows():
        for r in range(n):
            ln = int(rng.integers(8, SEQ_MAX_LEN + 1))
            frames = rng.normal(size=(ln, SEQ_DIM)).astype(np.float32)
            yield encode_row(
                ser,
                RecordType.SEQUENCE_EXAMPLE,
                [r & 1, [row.tolist() for row in frames]],
            )

    wire.write_records(path, rows())


@pytest.mark.perf
@pytest.mark.skipif(not _native.available(), reason="native decoder unavailable")
def test_sequence_pad_bf16_floor(tmp_path):
    """Floor for the SequenceExample host path (VERDICT r4 item 1): ragged^2
    decode + fused native pad+bf16 ([B, 64, 16] frames). Without this, a
    regression on half the reference's record-type surface
    (TFRecordDeserializer.scala:37-61) is invisible until a bench round."""
    import ml_dtypes

    from tpu_tfrecord.schema import ArrayType, FloatType
    from tpu_tfrecord.tpu import host_batch_from_columnar

    for s in range(2):
        _write_seq_shard(str(tmp_path / f"part-{s:05d}.tfrecord"), 8192)
    schema = StructType([
        StructField("label", LongType(), nullable=False),
        StructField("frames", ArrayType(ArrayType(FloatType()))),
    ])
    pad_to = {"frames": (SEQ_MAX_LEN, SEQ_DIM)}
    cast = {"frames": ml_dtypes.bfloat16}
    ds = TFRecordDataset(
        str(tmp_path),
        batch_size=SEQ_BATCH,
        schema=schema,
        prefetch=4,
        num_epochs=None,
        recordType="SequenceExample",
    )
    best = 0.0
    micro = 0.0
    with ds.batches() as it:
        for _ in range(3):
            host_batch_from_columnar(next(it), ds.schema, pad_to=pad_to, cast=cast)
        for _ in range(3):
            micro = max(micro, _memcrc_mbps())
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 0.5:
                hb = host_batch_from_columnar(
                    next(it), ds.schema, pad_to=pad_to, cast=cast
                )
                n += hb["frames"].shape[0]
            best = max(best, n / (time.perf_counter() - t0))
    assert hb["frames"].dtype == ml_dtypes.bfloat16
    floor = _calibrated_floor("TFR_SEQ_PERF_FLOOR_EX_S", _REF_SEQ_RATIO, micro)
    best *= _SELFTEST_SCALE
    assert best >= floor, (
        f"SequenceExample decode+pad+bf16 throughput {best:,.0f} ex/s fell "
        f"below the calibrated floor {floor:,.0f} ex/s (microbench "
        f"{micro:,.0f} MB/s) — ragged^2 path regression "
        "(fused native pad lost? per-row padding reintroduced?)"
    )
