"""Red/green decode-throughput floor (VERDICT r3 item 3).

A decode regression must be caught by CI as a failing test, not discovered
rounds later as a mysteriously degraded bench headline. This pins the
device-free pipeline — native frame scan + CRC + Example decode +
categorical hashing + column-group packing at the bench's Criteo shape —
above a conservative floor.

Floor calibration: the bench box measures ~1.4-1.7M ex/s on this path
(BENCH_r03.json host_side_value). The default floor of 500k ex/s holds
across slower CI machines while still tripping on the regression classes
that matter: native decoder silently disabled (~10x), turbo entry-shape
cache broken (falls back to field-wise parse, ~2-3x), per-batch copies
reintroduced. TFR_PERF_FLOOR_EX_S overrides for stricter local runs.
"""

import os
import time

import numpy as np
import pytest

from tpu_tfrecord import _native, wire
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import TFRecordSerializer, encode_row

FLOOR = float(os.environ.get("TFR_PERF_FLOOR_EX_S", 500_000))
# SequenceExample floor: the bench box measures ~250k ex/s on the fused
# native pad+bf16 path ([B, 64, 16] frames); 80k holds the same ~3x slack
# as the Criteo floor while tripping on the regression classes that matter
# here: fused pad kernel lost (falls back through numpy, and a further fall
# to any per-row path lands at ~16k).
SEQ_FLOOR = float(os.environ.get("TFR_SEQ_PERF_FLOOR_EX_S", 80_000))
N_RECORDS = 16384
BATCH = 4096


def _write_criteo_shard(path: str, n: int) -> None:
    fields = [StructField("label", LongType(), nullable=False)]
    fields += [StructField(f"I{i}", LongType()) for i in range(1, 14)]
    fields += [StructField(f"C{i}", StringType()) for i in range(1, 27)]
    ser = TFRecordSerializer(StructType(fields))
    rng = np.random.default_rng(0)
    ints = rng.integers(0, 1 << 31, size=(n, 13))
    cats = rng.integers(0, 16, size=(n, 26, 8), dtype=np.uint8) + 97

    def rows():
        for r in range(n):
            row = [r & 1]
            row += [int(v) for v in ints[r]]
            row += [cats[r, c].tobytes().decode() for c in range(26)]
            yield encode_row(ser, RecordType.EXAMPLE, row)

    wire.write_records(path, rows())


@pytest.mark.perf
@pytest.mark.skipif(not _native.available(), reason="native decoder unavailable")
def test_criteo_decode_hash_pack_floor(tmp_path):
    from tpu_tfrecord.tpu import host_batch_from_columnar

    for s in range(2):
        _write_criteo_shard(str(tmp_path / f"part-{s:05d}.tfrecord"), N_RECORDS)
    read_fields = [StructField("label", IntegerType(), nullable=False)]
    read_fields += [StructField(f"I{i}", IntegerType()) for i in range(1, 14)]
    read_fields += [StructField(f"C{i}", StringType()) for i in range(1, 27)]
    schema = StructType(read_fields)
    hash_buckets = {f"C{i}": 1 << 20 for i in range(1, 27)}
    pack = {
        "packed": ["label"]
        + [f"I{i}" for i in range(1, 14)]
        + [f"C{i}" for i in range(1, 27)],
    }
    ds = TFRecordDataset(
        str(tmp_path),
        batch_size=BATCH,
        schema=schema,
        prefetch=4,
        num_epochs=None,
        hash_buckets=hash_buckets,
        pack=pack,
    )
    best = 0.0
    with ds.batches() as it:
        for _ in range(3):  # warm decode thread + entry-shape caches
            host_batch_from_columnar(next(it), ds.schema,
                                     hash_buckets=hash_buckets, pack=pack)
        # best-of-3 half-second windows: one-sided noise on a shared box
        # (other tenants only slow us down), so the max is the estimator
        for _ in range(3):
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 0.5:
                hb = host_batch_from_columnar(
                    next(it), ds.schema, hash_buckets=hash_buckets, pack=pack
                )
                n += hb["packed"].shape[0]
            best = max(best, n / (time.perf_counter() - t0))
    assert best >= FLOOR, (
        f"device-free decode+hash+pack throughput {best:,.0f} ex/s fell "
        f"below the floor {FLOOR:,.0f} ex/s — decode-path regression "
        "(native disabled? turbo cache broken? per-batch copies?)"
    )


SEQ_MAX_LEN = 64
SEQ_DIM = 16
SEQ_BATCH = 1024


def _write_seq_shard(path: str, n: int) -> None:
    from tpu_tfrecord.schema import ArrayType, FloatType

    fields = [
        StructField("label", LongType(), nullable=False),
        StructField("frames", ArrayType(ArrayType(FloatType()))),
    ]
    ser = TFRecordSerializer(StructType(fields))
    rng = np.random.default_rng(1)

    def rows():
        for r in range(n):
            ln = int(rng.integers(8, SEQ_MAX_LEN + 1))
            frames = rng.normal(size=(ln, SEQ_DIM)).astype(np.float32)
            yield encode_row(
                ser,
                RecordType.SEQUENCE_EXAMPLE,
                [r & 1, [row.tolist() for row in frames]],
            )

    wire.write_records(path, rows())


@pytest.mark.perf
@pytest.mark.skipif(not _native.available(), reason="native decoder unavailable")
def test_sequence_pad_bf16_floor(tmp_path):
    """Floor for the SequenceExample host path (VERDICT r4 item 1): ragged^2
    decode + fused native pad+bf16 ([B, 64, 16] frames). Without this, a
    regression on half the reference's record-type surface
    (TFRecordDeserializer.scala:37-61) is invisible until a bench round."""
    import ml_dtypes

    from tpu_tfrecord.schema import ArrayType, FloatType
    from tpu_tfrecord.tpu import host_batch_from_columnar

    for s in range(2):
        _write_seq_shard(str(tmp_path / f"part-{s:05d}.tfrecord"), 8192)
    schema = StructType([
        StructField("label", LongType(), nullable=False),
        StructField("frames", ArrayType(ArrayType(FloatType()))),
    ])
    pad_to = {"frames": (SEQ_MAX_LEN, SEQ_DIM)}
    cast = {"frames": ml_dtypes.bfloat16}
    ds = TFRecordDataset(
        str(tmp_path),
        batch_size=SEQ_BATCH,
        schema=schema,
        prefetch=4,
        num_epochs=None,
        recordType="SequenceExample",
    )
    best = 0.0
    with ds.batches() as it:
        for _ in range(3):
            host_batch_from_columnar(next(it), ds.schema, pad_to=pad_to, cast=cast)
        for _ in range(3):
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 0.5:
                hb = host_batch_from_columnar(
                    next(it), ds.schema, pad_to=pad_to, cast=cast
                )
                n += hb["frames"].shape[0]
            best = max(best, n / (time.perf_counter() - t0))
    assert hb["frames"].dtype == ml_dtypes.bfloat16
    assert best >= SEQ_FLOOR, (
        f"SequenceExample decode+pad+bf16 throughput {best:,.0f} ex/s fell "
        f"below the floor {SEQ_FLOOR:,.0f} ex/s — ragged^2 path regression "
        "(fused native pad lost? per-row padding reintroduced?)"
    )
