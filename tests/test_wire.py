"""Tier-1 tests for the TFRecord wire format (framing + masked CRC32C).

The reference gets this layer from the shaded tensorflow-hadoop jar and has no
direct unit tests for it; we pin it hard since we re-implemented it.
"""

import gzip
import struct

import pytest

from tpu_tfrecord import wire


class TestCrc32c:
    def test_known_vectors(self):
        # Standard CRC32C check value.
        assert wire.crc32c_py(b"123456789") == 0xE3069283
        assert wire.crc32c_py(b"") == 0
        # RFC 3720 test pattern: 32 bytes of zeros.
        assert wire.crc32c_py(b"\x00" * 32) == 0x8A9136AA
        assert wire.crc32c_py(b"\xff" * 32) == 0x62A8AB43
        assert wire.crc32c_py(bytes(range(32))) == 0x46DD794E

    def test_incremental_matches_one_shot(self):
        data = b"the quick brown fox jumps over the lazy dog" * 7
        # slicing-by-8 path vs byte-at-a-time tail must agree for all splits
        for split in (0, 1, 7, 8, 9, len(data)):
            whole = wire.crc32c_py(data)
            assert wire.crc32c_py(data[:split] + data[split:]) == whole

    def test_masked_crc_matches_tfrecord_spec(self):
        # Masked CRC of the little-endian length header for a 24-byte record,
        # checked against TensorFlow's tf.io.TFRecordWriter output framing.
        header = struct.pack("<Q", 24)
        crc = wire.crc32c_py(header)
        expected_mask = ((((crc >> 15) | (crc << 17)) & 0xFFFFFFFF) + 0xA282EAD8) & 0xFFFFFFFF
        assert wire.masked_crc32c(header) == expected_mask


class TestFraming:
    def test_round_trip(self, sandbox):
        path = str(sandbox / "a.tfrecord")
        records = [b"hello", b"", b"x" * 10_000, bytes(range(256))]
        assert wire.write_records(path, records) == 4
        assert list(wire.read_records(path)) == records

    def test_golden_frame_layout(self):
        framed = wire.encode_record(b"abc")
        assert len(framed) == 12 + 3 + 4
        (length,) = struct.unpack_from("<Q", framed, 0)
        assert length == 3
        assert framed[12:15] == b"abc"

    def test_corrupt_data_crc_detected(self, sandbox):
        path = str(sandbox / "bad.tfrecord")
        wire.write_records(path, [b"hello world"])
        raw = bytearray(open(path, "rb").read())
        raw[14] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(raw)
        with pytest.raises(wire.TFRecordCorruptionError):
            list(wire.read_records(path))
        # verify_crc=False must not raise
        recs = list(wire.read_records(path, verify_crc=False))
        assert len(recs) == 1

    def test_corrupt_length_crc_detected(self, sandbox):
        path = str(sandbox / "bad2.tfrecord")
        wire.write_records(path, [b"hello world"])
        raw = bytearray(open(path, "rb").read())
        raw[9] ^= 0x01  # flip a length-crc byte
        open(path, "wb").write(raw)
        with pytest.raises(wire.TFRecordCorruptionError):
            list(wire.read_records(path))

    def test_truncated_file_detected(self, sandbox):
        path = str(sandbox / "trunc.tfrecord")
        wire.write_records(path, [b"hello world"])
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[:-2])
        with pytest.raises(wire.TFRecordCorruptionError):
            list(wire.read_records(path))

    def test_empty_file(self, sandbox):
        path = str(sandbox / "empty.tfrecord")
        open(path, "wb").close()
        assert list(wire.read_records(path)) == []
        assert wire.file_is_empty(path)

    def test_scan_buffer(self):
        records = [b"one", b"two2", b"three33"]
        buf = b"".join(wire.encode_record(r) for r in records)
        spans = list(wire.scan_buffer(buf))
        assert [buf[s : s + l] for s, l in spans] == records

    def test_scan_buffer_corruption(self):
        buf = bytearray(wire.encode_record(b"payload"))
        buf[13] ^= 0x55
        with pytest.raises(wire.TFRecordCorruptionError):
            list(wire.scan_buffer(bytes(buf)))


class TestCodecs:
    @pytest.mark.parametrize("codec,ext", [("gzip", ".gz"), ("deflate", ".deflate")])
    def test_compressed_round_trip(self, sandbox, codec, ext):
        path = str(sandbox / f"c.tfrecord{ext}")
        records = [b"r1", b"r2" * 500, b"r3"]
        wire.write_records(path, records, codec=codec)
        # auto-detect by extension, like Hadoop's codec factory on read
        assert list(wire.read_records(path)) == records
        # explicit codec works too
        assert list(wire.read_records(path, codec=codec)) == records

    def test_gzip_is_real_gzip(self, sandbox):
        path = str(sandbox / "g.tfrecord.gz")
        wire.write_records(path, [b"data"], codec="gzip")
        with gzip.open(path, "rb") as fh:
            raw = fh.read()
        assert raw == wire.encode_record(b"data")

    def test_codec_aliases(self):
        assert wire.normalize_codec("org.apache.hadoop.io.compress.GzipCodec") == "gzip"
        assert wire.normalize_codec("org.apache.hadoop.io.compress.DefaultCodec") == "deflate"
        assert wire.normalize_codec("GZIP") == "gzip"
        assert wire.normalize_codec(None) is None
        assert wire.normalize_codec("") is None
        with pytest.raises(ValueError):
            wire.normalize_codec("snappy-oops")

    def test_codec_extension(self):
        assert wire.codec_extension(None) == ""
        assert wire.codec_extension("gzip") == ".gz"
        assert wire.codec_extension("deflate") == ".deflate"

    def test_codec_from_path(self):
        assert wire.codec_from_path("part-0.tfrecord.gz") == "gzip"
        assert wire.codec_from_path("part-0.tfrecord.deflate") == "deflate"
        assert wire.codec_from_path("part-0.tfrecord") is None


class TestZstd:
    """Hadoop ZStandardCodec parity, gated on the optional zstandard pkg."""

    zstandard = pytest.importorskip("zstandard")

    def test_round_trip_and_autodetect(self, sandbox):
        path = str(sandbox / "z.tfrecord.zst")
        records = [b"r1", b"r2" * 500, b"r3"]
        wire.write_records(path, records, codec="zstd")
        assert list(wire.read_records(path)) == records  # by extension
        assert list(wire.read_records(path, codec="zstd")) == records
        # the file is a real zstd frame the reference ecosystem can read
        import zstandard

        with open(path, "rb") as fh:
            raw = zstandard.ZstdDecompressor().decompress(
                fh.read(), max_output_size=1 << 20
            )
        assert raw == b"".join(wire.encode_record(r) for r in records)

    def test_aliases(self):
        assert wire.normalize_codec("zstd") == "zstd"
        assert wire.normalize_codec("org.apache.hadoop.io.compress.ZStandardCodec") == "zstd"
        assert wire.codec_extension("zstd") == ".zst"
        assert wire.codec_from_path("part-0.tfrecord.zst") == "zstd"

    def test_truncated_mid_frame_raises_even_on_record_boundary(self, sandbox):
        """stream_reader returns a clean short EOF on a truncated frame —
        reading must detect the incomplete FRAME (decompressobj.eof), not
        rely on the cut landing mid-TFRecord: compressible records whose
        decoded prefix ends on a record boundary previously lost trailing
        rows silently."""
        path = str(sandbox / "t.tfrecord.zst")
        records = [b"abc" * 100] * 10
        wire.write_records(path, records, codec="zstd")
        blob = open(path, "rb").read()
        for cut in (len(blob) * 9 // 10, len(blob) // 2, len(blob) - 1):
            open(path, "wb").write(blob[:cut])
            with pytest.raises(wire.TFRecordCorruptionError):
                list(wire.read_records(path))

    def test_concatenated_frames_read_fully(self, sandbox):
        """Hadoop-style concatenated zstd frames in one file."""
        import zstandard

        path = str(sandbox / "c.tfrecord.zst")
        frame = lambda recs: zstandard.ZstdCompressor().compress(
            b"".join(wire.encode_record(r) for r in recs)
        )
        with open(path, "wb") as fh:
            fh.write(frame([b"a", b"b"]))
            fh.write(frame([b"c" * 500, b"d"]))
        assert list(wire.read_records(path)) == [b"a", b"b", b"c" * 500, b"d"]

    def test_frame_ending_exactly_at_read_chunk_boundary(self, sandbox, monkeypatch):
        """Regression (ADVICE r2): when a frame ends EXACTLY at the
        _READ_CHUNK boundary, the decompressobj finishes with empty
        unused_data; the next _fill must start a fresh decompressobj for the
        following concatenated frame instead of feeding the finished one
        (python-zstandard raises 'cannot use a decompressobj multiple
        times', which was misreported as corruption on a valid file)."""
        import zstandard

        from tpu_tfrecord.wire import _ZstdFile

        path = str(sandbox / "b.tfrecord.zst")
        frame = lambda recs: zstandard.ZstdCompressor().compress(
            b"".join(wire.encode_record(r) for r in recs)
        )
        f1 = frame([b"a" * 300, b"b"])
        f2 = frame([b"c", b"d" * 200])
        with open(path, "wb") as fh:
            fh.write(f1)
            fh.write(f2)
        # Shrink the chunk size so the first frame ends exactly on a chunk
        # boundary (constructing an exactly-1MiB compressed frame is flaky).
        monkeypatch.setattr(_ZstdFile, "_READ_CHUNK", len(f1))
        assert list(wire.read_records(path)) == [b"a" * 300, b"b", b"c", b"d" * 200]
        # Also exercise a boundary mid-second-frame for good measure.
        monkeypatch.setattr(_ZstdFile, "_READ_CHUNK", len(f1) + 3)
        assert list(wire.read_records(path)) == [b"a" * 300, b"b", b"c", b"d" * 200]

    def test_dataset_reads_zstd_shards(self, sandbox):
        import tpu_tfrecord.io as tfio
        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.schema import LongType, StructField, StructType

        schema = StructType([StructField("x", LongType())])
        out = str(sandbox / "zd")
        tfio.write([[i] for i in range(50)], schema, out, mode="overwrite",
                   codec="zstd")
        ds = TFRecordDataset(out, batch_size=10, schema=schema)
        got = []
        with ds.batches() as it:
            for cb in it:
                got.extend(cb["x"].values.tolist())
        assert sorted(got) == list(range(50))


class TestDeflateStreaming:
    """_DeflateFile reads must stream through zlib.decompressobj, not
    materialize the whole shard on open (the slab-streaming bounded-memory
    contract, io/dataset.py _shard_slabs)."""

    def _write_incompressible(self, path, nbytes):
        rng = __import__("numpy").random.default_rng(7)
        data = rng.integers(0, 256, size=nbytes, dtype="uint8").tobytes()
        with wire.open_compressed(path, "wb", "deflate") as fh:
            fh.write(data)
        return data

    def test_small_read_does_not_consume_whole_file(self, sandbox):
        import os

        path = str(sandbox / "big.deflate")
        data = self._write_incompressible(path, 5 << 20)  # ~5 MB compressed
        fh = wire._DeflateFile(path, "rb")
        try:
            head = fh.read(4096)
            assert head == data[:4096]
            # only ~one compressed chunk should have been read from disk
            assert fh._fh.tell() <= wire._DeflateFile._READ_CHUNK + 4096
            assert fh._fh.tell() < os.path.getsize(path) // 2
        finally:
            fh.close()

    def test_incremental_reads_round_trip(self, sandbox):
        path = str(sandbox / "inc.deflate")
        data = self._write_incompressible(path, 3 << 20)
        fh = wire._DeflateFile(path, "rb")
        try:
            # odd-sized reads walk the unconsumed_tail path repeatedly
            chunks, n = [], 0
            while True:
                c = fh.read(70_001)
                if not c:
                    break
                chunks.append(c)
                n += len(c)
            assert b"".join(chunks) == data and n == len(data)
        finally:
            fh.close()

    def test_read_all_after_partial(self, sandbox):
        path = str(sandbox / "all.deflate")
        data = self._write_incompressible(path, 1 << 20)
        fh = wire._DeflateFile(path, "rb")
        try:
            head = fh.read(10)
            rest = fh.read(-1)
            assert head + rest == data
        finally:
            fh.close()

    def test_truncated_stream_raises(self, sandbox):
        """A .deflate file cut mid-stream must raise, not silently return a
        prefix (whole-file zlib.decompress raised Error -5 here)."""
        import os

        path = str(sandbox / "trunc.deflate")
        self._write_incompressible(path, 1 << 20)
        with open(path, "rb") as fh:
            blob = fh.read()
        with open(path, "wb") as fh:
            fh.write(blob[: len(blob) // 2])
        fh = wire._DeflateFile(path, "rb")
        try:
            with pytest.raises(wire.TFRecordCorruptionError, match="truncated deflate"):
                while fh.read(1 << 16):
                    pass
        finally:
            fh.close()
