"""Training flight recorder tests (ISSUE 13): step-phase decomposition,
the training verdict, in-jit model diagnostics (MoE counts/drops/entropy
pinned against the routing oracle, measured pipeline bubble vs the
analytic), trainer spooling + mixed-role fleet aggregation, the
``tfrecord_doctor train`` subcommand, and the ``--json`` document mode.

Unit tests drive private Metrics/TelemetrySpool instances; the
integration tests run the real ``examples/train_lm.py`` trainer and the
doctor CLI as subprocesses.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_tfrecord import fleet, telemetry
from tpu_tfrecord.fleet import TelemetryAggregator, TelemetrySpool
from tpu_tfrecord.metrics import METRICS, Metrics
from tpu_tfrecord.models import lm, moe, pipeline
from tpu_tfrecord.telemetry import TraceContext, training_verdict
from tpu_tfrecord.tpu import create_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCTOR = os.path.join(REPO, "tools", "tfrecord_doctor.py")
TRAIN_LM = os.path.join(REPO, "examples", "train_lm.py")

sys.path.insert(0, os.path.join(REPO, "examples"))
import _harness  # noqa: E402

from hlo_util import compiled_memory_bytes  # noqa: E402
from tools.graftlint import hlo_contracts  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_registry():
    METRICS.reset()
    yield
    METRICS.reset()
    telemetry.disable()


# ---------------------------------------------------------------------------
# Training verdict
# ---------------------------------------------------------------------------


class TestTrainingVerdict:
    def test_thresholds(self):
        assert training_verdict(None) == "unknown"
        assert training_verdict({}) == "unknown"
        assert training_verdict({"compute": 0.0}) == "unknown"
        assert training_verdict({"compute": 1.0}) == "compute_bound"
        # input = data_wait + h2d
        assert (
            training_verdict({"data_wait": 0.3, "h2d": 0.25, "compute": 0.45})
            == "input_bound"
        )
        assert (
            training_verdict({"data_wait": 0.3, "h2d": 0.1, "compute": 0.6})
            == "compute_bound"
        )
        # ckpt wins even when input is also heavy: different fix
        assert (
            training_verdict({"data_wait": 0.5, "ckpt": 0.3, "compute": 0.2})
            == "ckpt_bound"
        )
        assert (
            training_verdict({"ckpt": 0.25, "compute": 0.75}) == "ckpt_bound"
        )
        assert (
            training_verdict({"ckpt": 0.24, "compute": 0.76})
            == "compute_bound"
        )


# ---------------------------------------------------------------------------
# StepPhases: the harness-side recorder
# ---------------------------------------------------------------------------


class _FakeDeviceIt:
    def __init__(self):
        self.transfer_seconds = 0.0


class TestStepPhases:
    def test_phases_land_as_train_stages_with_histograms(self):
        m = Metrics()
        rec = _harness.StepPhases(window=2, metrics=m)
        for _ in range(2):
            with rec.phase("data_wait"):
                pass
            with rec.phase("compute"):
                time.sleep(0.01)
            rec.end_step()
        snap = m.snapshot()
        assert snap["train.compute"]["records"] == 2
        assert snap["train.compute"]["seconds"] >= 0.02
        assert snap["train.compute"]["hist_count"] == 2  # latency histogram
        assert m.counter("train.steps") == 2
        assert snap["train.step"]["hist_count"] == 2
        # window completed: share gauges published
        assert m.gauge_value("train.share.compute") > 0.9
        assert m.gauge_value("train.share.data_wait") is not None
        assert rec.verdict() == "compute_bound"

    def test_inline_transfer_reattributed_from_wait_to_h2d(self):
        m = Metrics()
        rec = _harness.StepPhases(metrics=m)
        it = _FakeDeviceIt()
        with rec.phase("data_wait", iterator=it):
            it.transfer_seconds += 0.05
            time.sleep(0.06)
        rec.end_step()
        # exactly the iterator's transfer delta lands in h2d...
        assert m.stage("train.h2d").seconds == pytest.approx(0.05)
        # ...and data_wait keeps only the remainder of the wall
        assert m.stage("train.data_wait").seconds >= 0.005
        assert m.stage("train.data_wait").seconds < 0.06

    def test_transfer_delta_capped_at_observed_wall(self):
        # a transfer THREAD can progress more than this wait's wall time;
        # attribution must never go negative or exceed the wall
        m = Metrics()
        rec = _harness.StepPhases(metrics=m)
        it = _FakeDeviceIt()
        with rec.phase("data_wait", iterator=it):
            it.transfer_seconds += 10.0
            time.sleep(0.01)
        rec.end_step()
        assert m.stage("train.data_wait").seconds == 0.0
        assert m.stage("train.h2d").seconds < 1.0

    def test_aborted_discovery_iteration_records_nothing(self):
        # the loop's final next(it) that only DISCOVERS exhaustion can
        # block on the drained pipeline: abort_step must drop it so
        # stage records, shares, and spans agree with train.steps
        m = Metrics()
        rec = _harness.StepPhases(window=1, metrics=m)
        with rec.phase("compute"):
            time.sleep(0.005)
        rec.end_step()
        with rec.phase("data_wait"):
            time.sleep(0.05)  # the exhaustion probe's long wait
        rec.abort_step()
        rec.flush()
        assert rec.steps == 1
        assert m.counter("train.steps") == 1
        assert m.stage("train.data_wait").records == 0
        assert m.stage("train.data_wait").seconds == 0.0
        # the verdict stays compute_bound: the probe wait never voted
        assert rec.verdict() == "compute_bound"
        assert m.gauge_value("train.share.data_wait") == 0.0

    def test_exhausted_loop_spans_match_step_count(self):
        # drive run_train_loop to EXHAUSTION (max_steps=None): exactly
        # one train.step span per counted step, none for the discovery
        # iteration
        telemetry.RECORDER.clear()
        telemetry.enable()
        try:
            rec = _harness.StepPhases(metrics=Metrics())
            it = iter([1, 2, 3])
            state, steps, _ = _harness.run_train_loop(
                it, produce=lambda cb: cb,
                step_fn=lambda s, gb: (s, None),
                state=(), phases=rec, log_every=1000,
            )
            assert steps == 3 and rec.steps == 3
            spans = [
                s for s in telemetry.RECORDER.spans()
                if s[0] == "train.step" and s[5] == "X"
            ]
            assert len(spans) == 3
        finally:
            telemetry.disable()
            telemetry.RECORDER.clear()

    def test_flush_publishes_partial_window(self):
        m = Metrics()
        rec = _harness.StepPhases(window=100, metrics=m)
        with rec.phase("compute"):
            time.sleep(0.002)
        rec.end_step()
        assert m.gauge_value("train.share.compute") is None
        rec.flush()
        assert m.gauge_value("train.share.compute") == pytest.approx(
            1.0, abs=0.01
        )

    def test_input_bound_verdict_from_wait_heavy_steps(self):
        m = Metrics()
        rec = _harness.StepPhases(window=2, metrics=m)
        for _ in range(2):
            with rec.phase("data_wait"):
                time.sleep(0.02)
            with rec.phase("compute"):
                time.sleep(0.002)
            rec.end_step()
        assert rec.verdict() == "input_bound"

    def test_window_validation(self):
        with pytest.raises(ValueError):
            _harness.StepPhases(window=0)


# ---------------------------------------------------------------------------
# MoE in-jit diagnostics vs the routing oracle
# ---------------------------------------------------------------------------


def _moe_setup(top_k, capacity_factor=1.0, seed=0):
    cfg = moe.MoEConfig(
        d_model=8, d_ff=16, n_experts=4, top_k=top_k,
        capacity_factor=capacity_factor,
    )
    params = moe.init_params(jax.random.key(seed), cfg)
    x = jnp.asarray(
        np.random.default_rng(seed).normal(size=(16, 8)), jnp.float32
    )
    return cfg, params, x


class TestMoEDiagnostics:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_dense_counts_pin_against_oracle(self, top_k):
        cfg, params, x = _moe_setup(top_k)
        y, aux, diag = jax.jit(
            lambda p, x: moe.moe_apply(p, x, cfg, diagnostics=True)
        )(params, x)
        ref, rdiag = moe.moe_reference(params, x, cfg, return_diag=True)
        np.testing.assert_allclose(
            np.asarray(diag["expert_tokens"]), rdiag["expert_tokens"]
        )
        np.testing.assert_allclose(
            np.asarray(diag["expert_kept"]), rdiag["expert_kept"]
        )
        assert float(diag["dropped_fraction"]) == pytest.approx(
            rdiag["dropped_fraction"], abs=1e-6
        )
        assert float(diag["gate_entropy"]) == pytest.approx(
            rdiag["gate_entropy"], abs=1e-4
        )
        # routed assignments always sum to tokens * top_k
        assert float(diag["expert_tokens"].sum()) == 16 * top_k
        # the output itself is unchanged by the flag (different compiled
        # program -> float-association noise only)
        y2, aux2 = moe.moe_apply(params, x, cfg)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(y2), atol=1e-6
        )

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_ep_shard_map_counts_pin_against_sharded_oracle(self, top_k):
        cfg, params, x = _moe_setup(top_k)
        mesh = create_mesh({"expert": 4, "data": 2})
        y, aux, diag = jax.jit(
            lambda p, x: moe.moe_apply_ep(p, x, cfg, mesh, diagnostics=True)
        )(params, x)
        ref, rdiag = moe.moe_reference(
            params, x, cfg, shards=4, return_diag=True
        )
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)
        # psum'd GLOBAL counts == the oracle's cross-block tallies
        np.testing.assert_allclose(
            np.asarray(diag["expert_tokens"]), rdiag["expert_tokens"]
        )
        np.testing.assert_allclose(
            np.asarray(diag["expert_kept"]), rdiag["expert_kept"]
        )
        assert float(diag["dropped_fraction"]) == pytest.approx(
            rdiag["dropped_fraction"], abs=1e-6
        )
        assert float(diag["gate_entropy"]) == pytest.approx(
            rdiag["gate_entropy"], abs=1e-4
        )
        assert float(diag["expert_tokens"].sum()) == 16 * top_k

    def test_valid_mask_excludes_padding_from_counts(self):
        cfg, params, x = _moe_setup(2)
        valid = jnp.asarray([True] * 10 + [False] * 6)
        y, aux, diag = moe.moe_apply(
            params, x, cfg, valid=valid, diagnostics=True
        )
        ref, rdiag = moe.moe_reference(
            params, x, cfg, valid=np.asarray(valid), return_diag=True
        )
        np.testing.assert_allclose(
            np.asarray(diag["expert_tokens"]), rdiag["expert_tokens"]
        )
        assert float(diag["expert_tokens"].sum()) == 10 * 2
        assert float(diag["gate_entropy"]) == pytest.approx(
            rdiag["gate_entropy"], abs=1e-4
        )

    def test_drops_show_up_at_tight_capacity(self):
        # capacity_factor far below balanced: drops are guaranteed
        cfg = moe.MoEConfig(
            d_model=8, d_ff=16, n_experts=4, top_k=2, capacity_factor=0.3
        )
        params = moe.init_params(jax.random.key(0), cfg)
        x = jnp.asarray(
            np.random.default_rng(1).normal(size=(32, 8)), jnp.float32
        )
        _, _, diag = moe.moe_apply(params, x, cfg, diagnostics=True)
        _, rdiag = moe.moe_reference(params, x, cfg, return_diag=True)
        assert float(diag["dropped_fraction"]) > 0
        assert float(diag["dropped_fraction"]) == pytest.approx(
            rdiag["dropped_fraction"], abs=1e-6
        )

    def test_ep_diagnostics_hlo_keeps_all_to_all_no_gather(self):
        # the comms contract survives the flag: diagnostics add [E]-sized
        # psums, never a gather of tokens or weights — pin + construction
        # live in the shared manifest
        hlo_contracts.verify("moe_apply_ep_diagnostics")

    def test_grads_unperturbed_by_diagnostics(self):
        cfg, params, x = _moe_setup(2)

        def loss_plain(p):
            y, aux = moe.moe_apply(p, x, cfg)
            return jnp.sum(y**2) + aux

        def loss_diag(p):
            y, aux, diag = moe.moe_apply(p, x, cfg, diagnostics=True)
            return jnp.sum(y**2) + aux

        g1 = jax.grad(loss_plain)(params)
        g2 = jax.grad(loss_diag)(params)
        for k in g1:
            np.testing.assert_allclose(
                np.asarray(g1[k]), np.asarray(g2[k]), atol=1e-6
            )


# ---------------------------------------------------------------------------
# Pipeline measured bubble vs the analytic
# ---------------------------------------------------------------------------


def _pipe_setup(n_stages, seed=0):
    mesh = create_mesh({"pipe": n_stages, "data": 8 // n_stages})
    params = {
        "w": jnp.asarray(
            np.random.default_rng(seed).normal(size=(n_stages, 8, 8)) * 0.1,
            jnp.float32,
        )
    }

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    return mesh, params, stage_fn


class TestPipelineBubble:
    @pytest.mark.parametrize("n_stages", [2, 4, 8])
    @pytest.mark.parametrize("m_per_stage", [1, 2, 3])
    def test_measured_bubble_matches_analytic(self, n_stages, m_per_stage):
        mesh, params, stage_fn = _pipe_setup(n_stages)
        m = m_per_stage * n_stages
        xs = jnp.asarray(
            np.random.default_rng(1).normal(size=(m, 4, 8)), jnp.float32
        )
        out, diag = pipeline.pipeline_apply(
            stage_fn, params, xs, mesh, diagnostics=True
        )
        ref = pipeline.pipeline_reference(stage_fn, params, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        analytic = (n_stages - 1) / (m + n_stages - 1)
        assert float(diag["bubble_fraction"]) == pytest.approx(
            analytic, abs=1e-6
        )
        assert float(diag["useful_ticks"]) == m
        assert float(diag["total_ticks"]) == m + n_stages - 1

    def test_ragged_stream_bubble_over_real_microbatches(self):
        mesh, params, stage_fn = _pipe_setup(4)
        xs = jnp.asarray(
            np.random.default_rng(2).normal(size=(7, 4, 8)), jnp.float32
        )
        out, diag = pipeline.pipeline_apply(
            stage_fn, params, xs, mesh, diagnostics=True
        )
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(pipeline.pipeline_reference(stage_fn, params, xs)),
            atol=1e-5,
        )
        # n_micro=7, S=4: analytic over the REAL stream
        assert float(diag["bubble_fraction"]) == pytest.approx(
            3 / 10, abs=1e-6
        )

    def test_diagnostics_hlo_stays_gather_free(self):
        # pin + construction live in the shared manifest
        hlo_contracts.verify("pipeline_diagnostics")

    def test_off_path_output_unchanged(self):
        mesh, params, stage_fn = _pipe_setup(4)
        xs = jnp.asarray(
            np.random.default_rng(3).normal(size=(8, 4, 8)), jnp.float32
        )
        on, _ = pipeline.pipeline_apply(
            stage_fn, params, xs, mesh, diagnostics=True
        )
        off = pipeline.pipeline_apply(stage_fn, params, xs, mesh)
        np.testing.assert_allclose(
            np.asarray(on), np.asarray(off), atol=1e-6
        )

    def test_grads_flow_through_diagnostics(self):
        mesh, params, stage_fn = _pipe_setup(4)
        xs = jnp.asarray(
            np.random.default_rng(4).normal(size=(8, 4, 8)), jnp.float32
        )

        def loss(p):
            out, diag = pipeline.pipeline_apply(
                stage_fn, p, xs, mesh, diagnostics=True
            )
            return jnp.sum(out**2)

        g = jax.grad(loss)(params)
        assert np.isfinite(np.asarray(g["w"])).all()
        assert np.abs(np.asarray(g["w"])).sum() > 0


# ---------------------------------------------------------------------------
# LM train_step diagnostics + fold into gauges
# ---------------------------------------------------------------------------


class TestLMDiagnostics:
    def test_moe_lm_step_returns_diag_and_folds(self):
        import optax

        mesh = create_mesh({"data": 8})
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16,
            moe_experts=4, moe_top_k=2,
        )
        params = lm.init_params(jax.random.key(0), cfg)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        toks = jnp.asarray(lm.make_synthetic_tokens(cfg, 8, seed=0))
        p2, o2, loss, diag = lm.train_step(
            params, opt, toks, cfg=cfg, tx=tx, mesh=mesh, data_axis="data",
            diagnostics=True,
        )
        # counts sum to n_layers * tokens * top_k (every layer routes the
        # full stream)
        t = 8 * 16
        assert float(diag["expert_tokens"].sum()) == 2 * t * 2
        m = Metrics()
        folded = _harness.fold_model_diagnostics(diag, metrics=m)
        assert m.gauge_value("moe.expert_imbalance") >= 1.0
        assert 0.0 <= m.gauge_value("moe.dropped_fraction") <= 1.0
        assert m.gauge_value("moe.gate_entropy") > 0
        assert set(folded) == {
            "moe.expert_imbalance", "moe.dropped_fraction", "moe.gate_entropy"
        }
        # loss identical to the plain step
        _, _, loss_plain = lm.train_step(
            params, opt, toks, cfg=cfg, tx=tx, mesh=mesh, data_axis="data",
        )
        assert float(loss) == pytest.approx(float(loss_plain), abs=1e-6)

    def test_pipeline_lm_step_reports_bubble(self):
        import optax

        mesh = create_mesh({"pipe": 4, "data": 2})
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            n_micro=8,
        )
        params = lm.init_params(jax.random.key(0), cfg)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        toks = jnp.asarray(lm.make_synthetic_tokens(cfg, 16, seed=0))
        _, _, loss, diag = lm.train_step(
            params, opt, toks, cfg=cfg, tx=tx, mesh=mesh, data_axis="data",
            pipe_axis="pipe", diagnostics=True,
        )
        # M=8, S=4 -> (S-1)/(M+S-1) = 3/11
        assert float(diag["bubble_fraction"]) == pytest.approx(
            3 / 11, abs=1e-6
        )
        m = Metrics()
        _harness.fold_model_diagnostics(diag, metrics=m)
        assert m.gauge_value("pipeline.bubble_fraction") == pytest.approx(
            3 / 11, abs=1e-4
        )

    def test_interleaved_lm_step_reports_v_bubble_and_folds(self):
        """V>1 diag carries virtual_stages and folds the interleaved
        number under its own gauge (pipeline.bubble_fraction_v) next to
        the shared pipeline.bubble_fraction."""
        import optax

        mesh = create_mesh({"pipe": 2, "data": 4})
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            n_micro=8, n_virtual=2,
        )
        params = lm.init_params(jax.random.key(0), cfg)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        toks = jnp.asarray(lm.make_synthetic_tokens(cfg, 32, seed=0))
        _, _, _, diag = lm.train_step(
            params, opt, toks, cfg=cfg, tx=tx, mesh=mesh, data_axis="data",
            pipe_axis="pipe", diagnostics=True,
        )
        # M=8, S=2, V=2 -> (S-1)/(V·M+S-1) = 1/17, below 1F1B's 1/9
        assert float(diag["bubble_fraction"]) == pytest.approx(
            1 / 17, abs=1e-6
        )
        assert float(diag["virtual_stages"]) == 2
        m = Metrics()
        folded = _harness.fold_model_diagnostics(diag, metrics=m)
        assert m.gauge_value("pipeline.bubble_fraction_v") == pytest.approx(
            1 / 17, abs=1e-4
        )
        assert m.gauge_value("pipeline.bubble_fraction") == pytest.approx(
            1 / 17, abs=1e-4
        )
        assert "pipeline.bubble_fraction_v" in folded

    def test_fold_none_and_empty_are_noops(self):
        m = Metrics()
        assert _harness.fold_model_diagnostics(None, metrics=m) == {}
        assert _harness.fold_model_diagnostics({}, metrics=m) == {}
        assert m.gauges() == {}

    def test_dimensionless_hists_never_render_as_milliseconds(self):
        # the folded diagnostics are FRACTIONS: quantiles_ms (the one
        # ms-renderer every pulse/bench/doctor line goes through) must
        # skip them — a dropped fraction of 0.02 printed as "20ms of
        # latency" on the fleet page would lie
        m = Metrics()
        m.observe("moe.dropped_fraction", 0.02)
        m.observe("pipeline.bubble_fraction", 0.18)
        m.observe("decode", 0.01)
        ms = telemetry.quantiles_ms(m.quantiles())
        assert "decode" in ms
        assert "moe.dropped_fraction" not in ms
        assert "pipeline.bubble_fraction" not in ms
        # ...and the federated latency summary excludes them too
        assert not telemetry.is_latency_hist("moe.gate_entropy")
        assert telemetry.is_latency_hist("train.step")

    def test_lm_compiled_memory_fields(self):
        # the MULTICHIP-partial helper: per-device compiled-memory bytes
        # from the same compiled handle as the HLO pins, backend-labeled
        import optax

        mesh = create_mesh({"data": 8})
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16
        )
        params = lm.init_params(jax.random.key(0), cfg)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        toks = jnp.asarray(lm.make_synthetic_tokens(cfg, 8, seed=0))
        import functools

        fn = functools.partial(
            lm.train_step, cfg=cfg, tx=tx, mesh=mesh, data_axis="data"
        )
        mem = compiled_memory_bytes(fn, params, opt, toks)
        assert mem["backend"] == "cpu"
        assert mem["argument_bytes"] > 0
        assert "temp_bytes" in mem


# ---------------------------------------------------------------------------
# Mixed-role aggregation: a trainer spool next to reader spools
# ---------------------------------------------------------------------------


def _write_trainer_spool(spool_dir, pid=101, steps=40, clock=lambda: 100.0):
    m = Metrics()
    for _ in range(steps):
        m.add("train.data_wait", records=1, seconds=0.001, latency=0.001)
        m.add("train.h2d", records=1, seconds=0.001, latency=0.001)
        m.add("train.compute", records=1, seconds=0.018, latency=0.018)
        m.add("train.step", records=1, seconds=0.02, latency=0.02)
        m.count("train.steps")
    m.gauge("train.share.data_wait", 0.05)
    m.gauge("train.share.h2d", 0.05)
    m.gauge("train.share.compute", 0.9)
    m.gauge("train.share.ckpt", 0.0)
    m.gauge("moe.expert_imbalance", 1.25)
    m.gauge("moe.dropped_fraction", 0.02)
    m.gauge("moe.gate_entropy", 1.1)
    import dataclasses

    ctx = dataclasses.replace(TraceContext.new(role="trainer"), pid=pid)
    sp = TelemetrySpool(
        str(spool_dir), metrics=m, context=ctx, clock=clock
    )
    sp.tick()
    return m, ctx


def _write_reader_spool(spool_dir, pid, decode_records, trace_id=None,
                        clock=lambda: 100.0):
    m = Metrics()
    m.add("decode", records=decode_records, nbytes=decode_records * 10,
          seconds=0.5, latency=0.01)
    m.gauge(telemetry.OCCUPANCY_GAUGE, 0.2)
    import dataclasses

    ctx = dataclasses.replace(TraceContext.new(role="reader"), pid=pid)
    if trace_id is not None:
        ctx = dataclasses.replace(ctx, trace_id=trace_id)
    sp = TelemetrySpool(str(spool_dir), metrics=m, context=ctx, clock=clock)
    sp.tick()
    return m, ctx


class TestMixedRoleAggregation:
    def test_trainer_aggregated_alongside_readers_exact_sums(self, tmp_path):
        spool = tmp_path / "spool"
        tm, tctx = _write_trainer_spool(spool, pid=101, steps=40)
        _write_reader_spool(spool, pid=102, decode_records=300,
                            trace_id=tctx.trace_id)
        _write_reader_spool(spool, pid=103, decode_records=500,
                            trace_id=tctx.trace_id)
        agg = TelemetryAggregator(str(spool), clock=lambda: 100.5)
        snap = agg.aggregate()
        assert len(snap.processes) == 3
        assert {p.role for p in snap.processes} == {"trainer", "reader"}
        # exact sums across roles
        assert snap.counters["train.steps"] == 40
        assert snap.stages["decode"][0] == 800
        assert snap.stages["train.compute"][0] == 40
        # role filter scopes exactly
        trainer_only = agg.aggregate(roles=["trainer"])
        assert len(trainer_only.processes) == 1
        assert trainer_only.counters["train.steps"] == 40
        assert "decode" not in trainer_only.stages
        readers_only = agg.aggregate(roles=["reader"])
        assert readers_only.stages["decode"][0] == 800
        assert "train.steps" not in readers_only.counters

    def test_role_labels_on_federated_page(self, tmp_path):
        spool = tmp_path / "spool"
        _, tctx = _write_trainer_spool(spool, pid=101)
        _write_reader_spool(spool, pid=102, decode_records=10,
                            trace_id=tctx.trace_id)
        agg = TelemetryAggregator(str(spool), clock=lambda: 100.5)
        page = agg.prometheus_text()
        assert 'role="trainer"' in page
        assert 'role="reader"' in page
        assert 'stage="train.compute"' in page

    def test_train_phase_shares_prefers_window_gauges(self, tmp_path):
        spool = tmp_path / "spool"
        _write_trainer_spool(spool, pid=101)
        snap = TelemetryAggregator(
            str(spool), clock=lambda: 100.5
        ).processes()[0]
        shares = fleet.train_phase_shares(snap)
        assert shares["compute"] == 0.9  # the gauge, not the stage ratio
        assert telemetry.training_verdict(shares) == "compute_bound"

    def test_train_phase_shares_falls_back_to_stage_seconds(self, tmp_path):
        spool = tmp_path / "spool"
        m = Metrics()
        m.add("train.data_wait", records=1, seconds=0.6, latency=0.6)
        m.add("train.compute", records=1, seconds=0.4, latency=0.4)
        sp = TelemetrySpool(
            str(spool), metrics=m, context=TraceContext.new(role="trainer"),
            clock=lambda: 1.0,
        )
        sp.tick()
        snap = TelemetryAggregator(
            str(spool), clock=lambda: 1.5
        ).processes()[0]
        shares = fleet.train_phase_shares(snap)
        assert shares["data_wait"] == pytest.approx(0.6)
        assert telemetry.training_verdict(shares) == "input_bound"

    def test_reader_snapshot_has_no_train_shares(self, tmp_path):
        spool = tmp_path / "spool"
        _write_reader_spool(spool, pid=102, decode_records=10)
        snap = TelemetryAggregator(
            str(spool), clock=lambda: 100.5
        ).processes()[0]
        assert fleet.train_phase_shares(snap) is None

    def test_doctor_fleet_shows_both_roles_and_trainer_verdict(self, tmp_path):
        spool = tmp_path / "spool"
        _, tctx = _write_trainer_spool(spool, pid=101)
        _write_reader_spool(spool, pid=102, decode_records=10,
                            trace_id=tctx.trace_id)
        res = subprocess.run(
            [sys.executable, DOCTOR, "fleet", str(spool),
             "--stale-after", "1e18"],
            capture_output=True, text=True,
        )
        assert res.returncode == 0, (res.stdout, res.stderr)
        lines = [json.loads(l) for l in res.stdout.splitlines() if l.strip()]
        procs = {l["role"]: l for l in lines if l["event"] == "proc"}
        assert set(procs) == {"trainer", "reader"}
        # the trainer's verdict is the TRAINING one, the reader's the
        # occupancy one
        assert procs["trainer"]["verdict"] == "compute_bound"
        assert procs["reader"]["verdict"] == "producer_bound"


# ---------------------------------------------------------------------------
# tfrecord_doctor train
# ---------------------------------------------------------------------------


class TestDoctorTrain:
    def _lines(self, res):
        return [json.loads(l) for l in res.stdout.splitlines() if l.strip()]

    def test_report_fields_and_exit_zero(self, tmp_path):
        spool = tmp_path / "spool"
        _, tctx = _write_trainer_spool(spool, pid=101, steps=40)
        _write_reader_spool(spool, pid=102, decode_records=10,
                            trace_id=tctx.trace_id)
        res = subprocess.run(
            [sys.executable, DOCTOR, "train", str(spool),
             "--stale-after", "1e18"],
            capture_output=True, text=True,
        )
        assert res.returncode == 0, (res.stdout, res.stderr)
        lines = self._lines(res)
        trainers = [l for l in lines if l["event"] == "trainer"]
        assert len(trainers) == 1  # the reader is not a trainer
        t = trainers[0]
        assert t["steps"] == 40
        assert t["verdict"] == "compute_bound"
        assert t["phase_shares"]["compute"] == 0.9
        assert t["phase_seconds"]["compute"] > 0
        assert t["step_p50_ms"] > 0 and t["step_p99_ms"] >= t["step_p50_ms"]
        assert t["moe"]["expert_imbalance"] == 1.25
        summary = [l for l in lines if l["event"] == "train"][0]
        assert summary["trainers"] == 1
        assert summary["steps"] == 40
        assert summary["verdict"] == "compute_bound"
        assert summary["phase_shares"]["compute"] > 0.8

    def test_no_trainers_exits_two(self, tmp_path):
        spool = tmp_path / "spool"
        _write_reader_spool(spool, pid=102, decode_records=10)
        res = subprocess.run(
            [sys.executable, DOCTOR, "train", str(spool),
             "--stale-after", "1e18"],
            capture_output=True, text=True,
        )
        assert res.returncode == 2
        err = self._lines(res)[0]
        assert err["event"] == "error"
        assert "no trainer spools" in err["error"]
        assert "reader" in err["error"]

    def test_empty_dir_exits_two(self, tmp_path):
        spool = tmp_path / "empty"
        spool.mkdir()
        res = subprocess.run(
            [sys.executable, DOCTOR, "train", str(spool)],
            capture_output=True, text=True,
        )
        assert res.returncode == 2
        assert "no spool files" in self._lines(res)[0]["error"]

    def test_custom_role_still_reported_via_train_stages(self, tmp_path):
        # a harness user with a custom telemetry_role still qualifies:
        # the train.* stages are the marker, not the label
        spool = tmp_path / "spool"
        m = Metrics()
        m.add("train.compute", records=1, seconds=1.0, latency=1.0)
        m.count("train.steps")
        TelemetrySpool(
            str(spool), metrics=m,
            context=TraceContext.new(role="my_custom_job"),
            clock=lambda: 1.0,
        ).tick()
        res = subprocess.run(
            [sys.executable, DOCTOR, "train", str(spool),
             "--stale-after", "1e18"],
            capture_output=True, text=True,
        )
        assert res.returncode == 0, (res.stdout, res.stderr)
        trainers = [
            l for l in self._lines(res) if l["event"] == "trainer"
        ]
        assert trainers and trainers[0]["role"] == "my_custom_job"


# ---------------------------------------------------------------------------
# --json document mode: one doc mirroring the text lines
# ---------------------------------------------------------------------------


def _strip_volatile(obj):
    """Remove wall-clock-derived fields (heartbeat age changes between two
    doctor invocations) so text-lines vs --json-doc compare equal."""
    if isinstance(obj, dict):
        return {
            k: _strip_volatile(v)
            for k, v in obj.items()
            if k != "heartbeat_age_s"
        }
    if isinstance(obj, list):
        return [_strip_volatile(v) for v in obj]
    return obj


class TestDoctorJson:
    def _roundtrip(self, argv):
        text = subprocess.run(
            [sys.executable, DOCTOR, *argv], capture_output=True, text=True
        )
        doc = subprocess.run(
            [sys.executable, DOCTOR, *argv, "--json"],
            capture_output=True, text=True,
        )
        assert doc.returncode == text.returncode, (doc.stdout, doc.stderr)
        lines = [
            json.loads(l) for l in text.stdout.splitlines() if l.strip()
        ]
        parsed = json.loads(doc.stdout)
        assert set(parsed) == {"events"}
        assert _strip_volatile(parsed["events"]) == _strip_volatile(lines)
        return text.returncode, parsed["events"]

    def test_fleet_roundtrip(self, tmp_path):
        spool = tmp_path / "spool"
        _, tctx = _write_trainer_spool(spool, pid=101)
        _write_reader_spool(spool, pid=102, decode_records=10,
                            trace_id=tctx.trace_id)
        rc, events = self._roundtrip(
            ["fleet", str(spool), "--stale-after", "1e18"]
        )
        assert rc == 0
        assert events[-1]["event"] == "fleet"

    def test_train_roundtrip(self, tmp_path):
        spool = tmp_path / "spool"
        _write_trainer_spool(spool, pid=101)
        rc, events = self._roundtrip(
            ["train", str(spool), "--stale-after", "1e18"]
        )
        assert rc == 0
        assert events[-1]["event"] == "train"

    def test_train_error_path_roundtrip_exit_two(self, tmp_path):
        spool = tmp_path / "empty"
        spool.mkdir()
        rc, events = self._roundtrip(["train", str(spool)])
        assert rc == 2
        assert events[0]["event"] == "error"

    def test_serve_status_roundtrip(self):
        from tpu_tfrecord import service

        d = service.ServiceDispatcher(lease_ttl_s=5.0).start()
        try:
            rc, events = self._roundtrip(["serve-status", d.addr])
            assert rc == 0
            assert events[-1]["event"] == "service"
        finally:
            d.stop()

    def test_serve_status_unreachable_roundtrip_exit_two(self):
        rc, events = self._roundtrip(
            ["serve-status", "127.0.0.1:1", "--timeout", "0.5"]
        )
        assert rc == 2
        assert events[0]["event"] == "error"


# ---------------------------------------------------------------------------
# Subprocess E2E: train_lm --spool lands final:true + doctor train reads it
# ---------------------------------------------------------------------------


class TestTrainLMSpoolE2E:
    def test_spooling_trainer_emits_final_and_doctor_reads_it(self, tmp_path):
        spool = tmp_path / "spool"
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
        res = subprocess.run(
            [sys.executable, TRAIN_LM, "--mesh", "dp", "--steps", "4",
             "--epochs", "1", "--save-every", "2",
             "--data-dir", str(tmp_path / "data"),
             "--ckpt-dir", str(tmp_path / "ckpt"),
             "--spool", str(spool), "--spool-interval", "0.2"],
            capture_output=True, text=True, env=env, timeout=600,
        )
        assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
        files = [
            n for n in os.listdir(spool) if n.endswith(fleet.SPOOL_SUFFIX)
        ]
        assert len(files) == 1
        snap = fleet.read_spool(str(spool / files[0]))
        assert snap is not None
        assert snap.final, "clean exit must land the final:true snapshot"
        assert snap.role == "trainer"
        assert snap.counters.get("train.steps", 0) >= 1
        assert "train.compute" in snap.stages
        # the doctor reads the same spool: exit 0 with a verdict
        doc = subprocess.run(
            [sys.executable, DOCTOR, "train", str(spool),
             "--stale-after", "1e18"],
            capture_output=True, text=True,
        )
        assert doc.returncode == 0, (doc.stdout, doc.stderr)
        lines = [
            json.loads(l) for l in doc.stdout.splitlines() if l.strip()
        ]
        summary = [l for l in lines if l["event"] == "train"][0]
        assert summary["verdict"] in (
            "input_bound", "compute_bound", "ckpt_bound"
        )
        trainer = [l for l in lines if l["event"] == "trainer"][0]
        assert trainer["finished"] is True
        assert trainer["alive"] is True
