"""Shared HLO-pin helpers: compile a function and assert which collectives
the backend actually emitted.

The model-parallel layer's contracts are COMMS contracts — "activations hop
by collective-permute", "EP dispatch is an all-to-all", "nothing gathers
the sharded stream" — and the only place those are real is the compiled
HLO. Every pin goes through `assert_hlo` so the idiom (lower -> compile ->
as_text -> grep) lives once, and through `per_device_argument_bytes` for
the memory-shape pins (what one device actually holds of the inputs).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax


def compiled(fn, *args, **kwargs):
    """The compiled executable of ``fn(*args)`` — the ONE handle both the
    HLO-text pins and the memory-shape pins read from. ``fn`` may already
    be jitted; sharded example args pin their layouts."""
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    return fn.lower(*args, **kwargs).compile()


def compiled_hlo(fn, *args, **kwargs) -> str:
    """Compiled (post-SPMD-partitioning) HLO text of ``fn(*args)``."""
    return compiled(fn, *args, **kwargs).as_text()


def assert_hlo(
    fn,
    args: Sequence,
    contains: Iterable[str] = (),
    absent: Iterable[str] = (),
) -> str:
    """Compile ``fn(*args)`` and assert substrings of the HLO text.

    ``contains``: ops that MUST appear (e.g. "collective-permute",
    "all-to-all"); ``absent``: ops that must NOT (e.g. "all-gather").
    Returns the HLO text for any further custom checks.
    """
    hlo = compiled_hlo(fn, *args)
    for op in contains:
        assert op in hlo, f"expected {op!r} in compiled HLO, not found"
    for op in absent:
        assert op not in hlo, f"forbidden {op!r} present in compiled HLO"
    return hlo


def per_device_argument_bytes(fn, *args) -> int:
    """Per-device bytes of ``fn``'s compiled arguments — what ONE device
    holds of the inputs (shards, not global tensors). This is the number
    the scale-shape pins compare as meshes and microbatch counts grow."""
    ma = compiled(fn, *args).memory_analysis()
    assert ma is not None, "backend reports no memory analysis"
    return int(ma.argument_size_in_bytes)


def compiled_memory_bytes(fn, *args) -> dict:
    """Per-device compiled-memory byte sizes from ``memory_analysis()``,
    labeled with the backend that compiled them — so a CPU-mesh number
    (the MULTICHIP partial) and the eventual real-device round land in
    the SAME fields (ROADMAP #4). Returns {} when the backend reports no
    memory analysis (some PJRT plugins)."""
    ma = compiled(fn, *args).memory_analysis()
    if ma is None:
        return {}
    out = {"backend": jax.default_backend()}
    for field in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        v = getattr(ma, field, None)
        if v is not None:
            out[field.replace("_size_in_bytes", "_bytes")] = int(v)
    return out
