"""Elastic, multi-tenant data service suite (ISSUE 12): the shared
BoundedClimber guard rails, FleetScaler decisions (grow on
producer_bound, drain on consumer_bound/idle, refill below the floor,
pending-spawn accounting, whipsaw immunity under an injected clock),
dispatcher drain semantics (lease hand-back, route exclusion, clean
goodbye, journal replay of draining/tenant state), tenant-keyed
multi-tenant leasing (fingerprint sharing, isolation, the two-job
zero-ground-truth-reads pin — local via cache counters and remote via
the Range server's file-GET counter), the serve-status doctor's tenant +
scaler lines, and the chaos acceptance run: a subprocess fleet that
grows, gracefully drains, and loses a victim to SIGKILL mid-drain, all
mid-epoch, with byte-identical consumer output."""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_tfrecord import elastic, fleet, service, telemetry
from tpu_tfrecord.autotune import BoundedClimber
from tpu_tfrecord.columnar import batch_to_rows
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import (
    ArrayType,
    LongType,
    StringType,
    StructField,
    StructType,
)

DOCTOR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "tfrecord_doctor.py",
)

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),
        StructField("arr", ArrayType(LongType())),
    ]
)
ROWS = [
    [i, None if i % 7 == 0 else f"v{i}" * (i % 3 + 1), list(range(i % 5))]
    for i in range(180)
]
PER_SHARD = 30  # 6 shards


@pytest.fixture(autouse=True)
def _reset_metrics():
    METRICS.reset()
    yield


@pytest.fixture
def data_dir(sandbox):
    out = str(sandbox / "ds")
    DatasetWriter(
        out, SCHEMA, mode="overwrite", max_records_per_file=PER_SHARD
    ).write_rows(ROWS)
    return out


def make_ds(data_dir, batch_size=8, **kw):
    return TFRecordDataset(
        data_dir, batch_size=batch_size, schema=SCHEMA,
        drop_remainder=False, num_epochs=1, **kw,
    )


def collect(data_dir, batch_size=8, hook=None, **kw):
    ds = make_ds(data_dir, batch_size=batch_size, **kw)
    got = []
    with ds.batches() as it:
        for b in it:
            got.extend(batch_to_rows(b, ds.schema))
            if hook is not None:
                hook(got)
    return got


@pytest.fixture
def local_rows(data_dir):
    return collect(data_dir)


def start_worker(dispatcher, **kw):
    w = service.DecodeWorker(dispatcher.addr, **kw).start()
    assert w.wait_registered(10), "worker failed to register"
    return w


def stage_records(name):
    return METRICS.raw_totals().get(name, (0, 0, 0, 0.0))[0]


def wait_for(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class FakeAggregator:
    """The scaler's test seam: a FleetSnapshot-shaped verdict source whose
    verdict and consumer-liveness are script-controlled."""

    def __init__(self, verdict="balanced", running=True):
        self.verdict = verdict
        self.running = running

    def aggregate(self, roles=None):
        procs = []
        if self.running:
            procs = [fleet.ProcessSnapshot(
                path="fake", host="h", pid=1, role="trainer", trace_id=None,
                heartbeat=time.time(), interval_s=1.0, seq=1,
                gauges={telemetry.OCCUPANCY_GAUGE: 0.1},
            )]
        return fleet.FleetSnapshot(
            processes=procs, alive=procs, dead=[], counters={}, stages={},
            hists={}, verdict=self.verdict, occupancy=None,
        )


# ---------------------------------------------------------------------------
# BoundedClimber — the shared whipsaw guard
# ---------------------------------------------------------------------------


class TestBoundedClimber:
    def test_hysteresis_requires_consecutive_same_verdict(self):
        c = BoundedClimber(hysteresis=3, cooldown_s=0.0, clock=lambda: 0.0)
        assert c.observe("producer_bound") is None
        assert c.observe("producer_bound") is None
        assert c.observe("producer_bound") == "producer_bound"

    def test_non_actionable_resets_streak(self):
        c = BoundedClimber(hysteresis=2, cooldown_s=0.0, clock=lambda: 0.0)
        assert c.observe("producer_bound") is None
        assert c.observe("balanced") is None
        assert c.observe("producer_bound") is None  # streak restarted
        assert c.observe("producer_bound") == "producer_bound"

    def test_verdict_flip_restarts_streak(self):
        c = BoundedClimber(hysteresis=2, cooldown_s=0.0, clock=lambda: 0.0)
        assert c.observe("producer_bound") is None
        assert c.observe("consumer_bound") is None
        assert c.observe("consumer_bound") == "consumer_bound"

    def test_cooldown_blocks_until_elapsed(self):
        now = [0.0]
        c = BoundedClimber(hysteresis=1, cooldown_s=10.0, clock=lambda: now[0])
        assert c.observe("producer_bound") == "producer_bound"
        c.acted()
        now[0] = 5.0
        assert c.observe("producer_bound") is None
        assert c.cooldown_remaining() == pytest.approx(5.0)
        now[0] = 10.0
        assert c.observe("producer_bound") == "producer_bound"

    def test_custom_actionable_set(self):
        c = BoundedClimber(
            hysteresis=1, cooldown_s=0.0, clock=lambda: 0.0,
            actionable=("producer_bound", "consumer_bound", "idle"),
        )
        assert c.observe("idle") == "idle"


# ---------------------------------------------------------------------------
# FleetScaler decisions
# ---------------------------------------------------------------------------


@pytest.fixture
def dispatcher():
    d = service.ServiceDispatcher(lease_ttl_s=1.0).start()
    yield d
    d.stop()


class TestScalerDecisions:
    def _scaler(self, d, spawn, agg, **pol):
        defaults = dict(hysteresis=1, cooldown_s=0.0, min_workers=1,
                        max_workers=4)
        defaults.update(pol)
        return elastic.FleetScaler(
            d, spawn, aggregator=agg,
            policy=elastic.ScalerPolicy(**defaults),
        )

    def test_below_min_refills_immediately(self, dispatcher):
        spawned = []

        def spawn():
            spawned.append(start_worker(dispatcher, drain_grace_s=0.1))

        s = self._scaler(dispatcher, spawn, FakeAggregator("balanced"))
        decision = s.step()
        assert decision == {
            "tick": 1, "action": "scale_up", "reason": "below_min",
            "workers": 0, "target": 1,
        }
        assert len(spawned) == 1
        assert METRICS.counter("elastic.scale_ups") == 1
        # the registered spawn retires the pending slot; at the floor no
        # further refill happens
        assert s.step() is None
        for w in spawned:
            w.stop()

    def test_producer_bound_grows_consumer_bound_needs_headroom(
        self, dispatcher
    ):
        workers = [start_worker(dispatcher, drain_grace_s=0.1)]

        def spawn():
            workers.append(start_worker(dispatcher, drain_grace_s=0.1))

        agg = FakeAggregator("producer_bound")
        s = self._scaler(dispatcher, spawn, agg, hysteresis=2)
        assert s.step() is None  # streak 1 < hysteresis
        d2 = s.step()
        assert d2 and d2["action"] == "scale_up" and d2["reason"] == "producer_bound"
        wait_for(lambda: len(dispatcher.status()["workers"]) == 2,
                 msg="second worker registration")
        for w in workers:
            w.stop()

    def test_whipsaw_alternating_verdicts_never_move(self, dispatcher):
        workers = [start_worker(dispatcher, drain_grace_s=0.1)]
        agg = FakeAggregator()
        s = self._scaler(dispatcher, lambda: None, agg, hysteresis=2)
        for i in range(10):
            agg.verdict = ("producer_bound", "consumer_bound")[i % 2]
            assert s.step() is None, "a flapping verdict moved the fleet"
        assert METRICS.counter("elastic.scale_ups") == 0
        assert METRICS.counter("elastic.scale_downs") == 0
        workers[0].stop()

    def test_cooldown_blocks_consecutive_moves_injected_clock(
        self, dispatcher
    ):
        now = [0.0]
        spawned = []
        workers = [start_worker(dispatcher, drain_grace_s=0.1)]
        agg = FakeAggregator("producer_bound")
        s = elastic.FleetScaler(
            dispatcher, lambda: spawned.append(now[0]),
            aggregator=agg, clock=lambda: now[0],
            policy=elastic.ScalerPolicy(
                hysteresis=1, cooldown_s=100.0, min_workers=1, max_workers=8
            ),
        )
        assert s.step()["action"] == "scale_up"
        now[0] = 50.0
        assert s.step() is None, "cooldown did not hold"
        now[0] = 100.0
        assert s.step()["action"] == "scale_up"
        assert len(spawned) == 2
        workers[0].stop()

    def test_pending_spawns_count_against_ceiling(self, dispatcher):
        now = [0.0]
        spawns = []
        workers = [start_worker(dispatcher, drain_grace_s=0.1)]
        agg = FakeAggregator("producer_bound")
        s = elastic.FleetScaler(
            dispatcher, lambda: spawns.append(now[0]),  # never registers
            aggregator=agg, clock=lambda: now[0],
            policy=elastic.ScalerPolicy(
                hysteresis=1, cooldown_s=0.0, min_workers=1, max_workers=3,
                pending_timeout_s=30.0,
            ),
        )
        assert s.step()["action"] == "scale_up"   # effective 1 -> 2
        assert s.step()["action"] == "scale_up"   # effective 2 -> 3
        assert s.step() is None, "pending spawns did not count against max"
        assert len(spawns) == 2
        # timed-out pendings stop counting (the exec died): retry allowed
        now[0] = 31.0
        assert s.step()["action"] == "scale_up"
        workers[0].stop()

    def test_idle_drains_to_min_and_status_surfaces(self, dispatcher):
        w1 = start_worker(dispatcher, worker_id="w-a", drain_grace_s=0.05)
        w2 = start_worker(dispatcher, worker_id="w-b", drain_grace_s=0.05)
        agg = FakeAggregator(running=False)  # no running consumer: idle
        s = self._scaler(dispatcher, lambda: None, agg, min_workers=1)
        decision = s.step()
        assert decision and decision["action"] == "scale_down"
        assert decision["reason"] == "idle"
        assert decision["victim"] == "w-b"  # deterministic: sorted()[-1]
        assert METRICS.counter("elastic.scale_downs") == 1
        # the victim finishes (nothing in flight), says goodbye, exits
        assert w2.drained.wait(10), "victim never drained"
        wait_for(
            lambda: [x["worker_id"] for x in dispatcher.status()["workers"]]
            == ["w-a"],
            msg="goodbye to remove the victim",
        )
        assert METRICS.counter("elastic.drains") == 1
        # at the floor: no further drain
        assert s.step() is None
        st = dispatcher.status()
        assert st["scaler"]["workers"] == 1
        assert st["scaler"]["last_decision"]["victim"] == "w-b"
        assert st["scaler"]["scale_downs"] == 1
        w1.stop()

    def test_spawn_failure_is_counted_not_fatal(self, dispatcher):
        workers = [start_worker(dispatcher, drain_grace_s=0.1)]

        def spawn():
            raise RuntimeError("exec failed")

        s = self._scaler(dispatcher, spawn, FakeAggregator("producer_bound"))
        assert s.step() is None
        assert METRICS.counter("elastic.spawn_errors") == 1
        assert METRICS.counter("elastic.scale_ups") == 0
        workers[0].stop()

    def test_unreadable_spool_never_drains_a_loaded_fleet(self, dispatcher):
        # an aggregator that RAISES (EACCES, EIO — not merely absent)
        # must be non-actionable: blindness is not idleness
        workers = [start_worker(dispatcher, worker_id=f"w-{i}",
                                drain_grace_s=0.1) for i in range(2)]

        class Broken:
            def aggregate(self, roles=None):
                raise PermissionError("spool dir unreadable")

        s = self._scaler(dispatcher, lambda: None, Broken())
        for _ in range(5):
            assert s.step() is None, "unreadable spool moved the fleet"
        assert METRICS.counter("elastic.scale_downs") == 0
        assert METRICS.counter("elastic.verdict_errors") == 5
        # a MISSING spool dir (no consumer ever spooled) IS idle: drain
        s2 = elastic.FleetScaler(
            dispatcher, lambda: None, spool_dir=str(dispatcher.addr) + "-none",
            policy=elastic.ScalerPolicy(hysteresis=1, cooldown_s=0.0,
                                        min_workers=1, max_workers=4),
        )
        s2.aggregator.spool_dir = "/nonexistent/tfr-spool"
        decision = s2.step()
        assert decision and decision["reason"] == "idle"
        for w in workers:
            w.stop()

    def test_scaler_thread_refills_from_zero(self, dispatcher):
        spawned = []

        def spawn():
            spawned.append(start_worker(dispatcher, drain_grace_s=0.1))

        s = elastic.FleetScaler(
            dispatcher, spawn, aggregator=FakeAggregator("balanced"),
            interval_s=0.05,
            policy=elastic.ScalerPolicy(min_workers=1, max_workers=2),
        ).start()
        try:
            wait_for(lambda: len(spawned) == 1, msg="thread refill")
        finally:
            s.stop()
            for w in spawned:
                w.stop()

    def test_roles_scope_reaches_the_aggregator(self, dispatcher):
        workers = [start_worker(dispatcher, drain_grace_s=0.1)]
        seen = []
        inner = FakeAggregator("balanced")

        class Agg:
            def aggregate(self, roles=None):
                seen.append(roles)
                return inner.aggregate()

        s = elastic.FleetScaler(
            dispatcher, lambda: None, aggregator=Agg(), roles=["trainer"],
            policy=elastic.ScalerPolicy(min_workers=1, max_workers=4),
        )
        s.step()
        assert seen == [["trainer"]]
        workers[0].stop()

    def test_ctor_needs_exactly_one_verdict_source(self, dispatcher):
        with pytest.raises(ValueError):
            elastic.FleetScaler(dispatcher, lambda: None)
        with pytest.raises(ValueError):
            elastic.FleetScaler(
                dispatcher, lambda: None, spool_dir="/tmp/x",
                aggregator=FakeAggregator(),
            )

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            elastic.ScalerPolicy(min_workers=0)
        with pytest.raises(ValueError):
            elastic.ScalerPolicy(min_workers=3, max_workers=2)
        with pytest.raises(ValueError):
            elastic.ScalerPolicy(hysteresis=0)


# ---------------------------------------------------------------------------
# Dispatcher drain semantics
# ---------------------------------------------------------------------------


def _route(d, shard_index, path, tenant="t0", exclude=()):
    return d._handle({
        "op": "route", "proto": service.PROTO_VERSION, "job": "j0",
        "tenant": tenant, "consumer": "c0", "path": path,
        "shard_index": shard_index, "exclude": list(exclude),
    })


class TestDrain:
    def test_drain_releases_leases_and_routes_around(self, dispatcher):
        w1 = start_worker(dispatcher, worker_id="w-a", drain_grace_s=5.0)
        w2 = start_worker(dispatcher, worker_id="w-b", drain_grace_s=5.0)
        # lease shard 0 onto whoever owns it
        first = _route(dispatcher, 0, "s0")
        owner = first["worker_id"]
        assert dispatcher.drain(owner) is True
        assert dispatcher.drain(owner) is False  # already draining
        assert dispatcher.drain("nope") is False
        assert METRICS.counter("elastic.drained_leases") == 1
        # the lease was handed back; re-route goes to the survivor and is
        # planned drift, never a lease_reassignment
        second = _route(dispatcher, 0, "s0")
        assert second["worker_id"] != owner
        assert dispatcher.status()["lease_reassignments"] == 0
        assert dispatcher.status()["draining"] == [owner]
        w1.stop()
        w2.stop()

    def test_all_draining_still_routes(self, dispatcher):
        w = start_worker(dispatcher, worker_id="w-a", drain_grace_s=30.0)
        assert dispatcher.drain("w-a")
        # availability beats drain purity when nothing else is alive
        reply = _route(dispatcher, 0, "s0")
        assert reply.get("ok") and reply["worker_id"] == "w-a"
        w.stop()

    def test_goodbye_unknown_worker_is_benign(self, dispatcher):
        reply = dispatcher._handle({
            "op": "goodbye", "proto": service.PROTO_VERSION,
            "worker_id": "ghost",
        })
        assert reply == {"ok": True, "known": False}
        assert METRICS.counter("elastic.drains") == 0

    def test_reregister_clears_drain_mark(self, dispatcher):
        w = start_worker(dispatcher, worker_id="w-a", drain_grace_s=30.0)
        assert dispatcher.drain("w-a")
        dispatcher._handle({
            "op": "register_worker", "proto": service.PROTO_VERSION,
            "worker_id": "w-a", "addr": w.addr, "pid": 1,
        })
        assert dispatcher.status()["draining"] == []
        w.stop()

    def test_journal_replay_restores_draining_and_tenants(self, tmp_path):
        journal = str(tmp_path / "journal.json")
        d = service.ServiceDispatcher(journal=journal, lease_ttl_s=5.0)
        try:
            for wid in ("w-a", "w-b"):
                d._handle({
                    "op": "register_worker", "proto": service.PROTO_VERSION,
                    "worker_id": wid, "addr": "127.0.0.1:1", "pid": 1,
                })
            _route(d, 0, "s0", tenant="t-shared")
            d._handle({
                "op": "shard_done", "proto": service.PROTO_VERSION,
                "job": "j0", "tenant": "t-shared", "consumer": "c0",
                "path": "s0", "worker_id": "w-a", "cached": True,
            })
            assert d.drain("w-b")
        finally:
            d.stop()
        d2 = service.ServiceDispatcher(journal=journal, lease_ttl_s=5.0)
        try:
            st = d2.status()
            assert st["draining"] == ["w-b"]
            t = st["tenants"]["t-shared"]
            assert t["consumers"] == 1 and t["jobs"] == 1
            assert t["shards_done"] == 1
            assert t["shared_cache_hits"] == 1 and t["completions"] == 1
        finally:
            d2.stop()


# ---------------------------------------------------------------------------
# Multi-tenant leasing + the shared warm cache
# ---------------------------------------------------------------------------


class TestMultiTenant:
    def test_tenant_digest_ignores_consumption_shape(self, data_dir):
        a = service.tenant_digest(make_ds(data_dir, batch_size=8))
        b = service.tenant_digest(make_ds(data_dir, batch_size=16, prefetch=7))
        c = service.tenant_digest(make_ds(data_dir, columns=["id"]))
        assert a == b
        assert a != c

    def test_same_fingerprint_shares_one_lease_table(
        self, dispatcher, data_dir, local_rows
    ):
        workers = [start_worker(dispatcher) for _ in range(2)]
        try:
            got8 = collect(data_dir, batch_size=8, service=dispatcher.addr,
                           service_deadline_ms=15000)
            got16 = collect(data_dir, batch_size=16, service=dispatcher.addr,
                            service_deadline_ms=15000)
            assert got8 == local_rows and got16 == local_rows
            st = dispatcher.status()
            assert len(st["tenants"]) == 1, st["tenants"]
            (tenant_info,) = st["tenants"].values()
            assert tenant_info["consumers"] == 2
            assert tenant_info["jobs"] == 2
            # the done-set is shared: 6 shards paid once FLEET-WIDE even
            # though two jobs each completed them
            assert tenant_info["shards_done"] == 6
            assert tenant_info["completions"] == 12
            assert st["shards_done"] == 6
            assert METRICS.counter("service.tenants") == 1
        finally:
            for w in workers:
                w.stop()

    def test_different_fingerprints_isolated(
        self, dispatcher, data_dir
    ):
        workers = [start_worker(dispatcher)]
        try:
            collect(data_dir, batch_size=8, service=dispatcher.addr,
                    service_deadline_ms=15000)
            collect(data_dir, batch_size=8, columns=["id"],
                    service=dispatcher.addr, service_deadline_ms=15000)
            st = dispatcher.status()
            assert len(st["tenants"]) == 2, st["tenants"]
            assert st["shards_done"] == 12  # nothing shared across tenants
            assert METRICS.counter("service.tenants") == 2
        finally:
            for w in workers:
                w.stop()

    def test_job2_zero_ground_truth_reads_local(
        self, dispatcher, data_dir, local_rows, tmp_path
    ):
        opts = TFRecordOptions.from_map(
            cache="auto", cache_dir=str(tmp_path / "cache")
        )
        w = service.DecodeWorker(dispatcher.addr, options=opts).start()
        assert w.wait_registered(10)
        try:
            got1 = collect(data_dir, batch_size=8, service=dispatcher.addr,
                           service_deadline_ms=15000)
            assert got1 == local_rows
            misses_before = METRICS.counter("cache.misses")
            hits_before = METRICS.counter("cache.hits")
            decode_before = stage_records("decode")
            got2 = collect(data_dir, batch_size=16, service=dispatcher.addr,
                           service_deadline_ms=15000)
            assert got2 == local_rows
            # job 2 is served ENTIRELY from the warm columnar cache: zero
            # ground-truth reads, pinned three ways
            assert METRICS.counter("cache.misses") == misses_before
            assert METRICS.counter("cache.hits") - hits_before == 6
            assert stage_records("decode") == decode_before
            assert METRICS.counter("service.cache_served") == 6
            assert METRICS.counter("service.shared_cache_hits") == 6
            (tenant_info,) = dispatcher.status()["tenants"].values()
            assert tenant_info["shared_cache_hits"] == 6
        finally:
            w.stop()

    def test_job2_zero_file_gets_remote(
        self, dispatcher, data_dir, local_rows, tmp_path, sandbox
    ):
        from tpu_tfrecord import httpfs

        opts = TFRecordOptions.from_map(
            cache="auto", cache_dir=str(tmp_path / "cache")
        )
        w = service.DecodeWorker(dispatcher.addr, options=opts).start()
        assert w.wait_registered(10)
        try:
            with httpfs.serve_directory(str(sandbox)) as srv:
                url = srv.url_for("ds")
                got1 = collect(url, batch_size=8, service=dispatcher.addr,
                               service_deadline_ms=15000)
                assert got1 == local_rows
                gets_after_job1 = srv.file_get_count
                assert gets_after_job1 > 0  # job 1 paid the link once
                got2 = collect(url, batch_size=16, service=dispatcher.addr,
                               service_deadline_ms=15000)
                assert got2 == local_rows
                # the PR 9 pin, now FLEET-wide: job 2 issues ZERO
                # ground-truth file GETs — the warm cache absorbed the
                # whole second job
                assert srv.file_get_count == gets_after_job1
                assert METRICS.counter("service.shared_cache_hits") == 6
        finally:
            w.stop()


# ---------------------------------------------------------------------------
# Aggregator role scoping (the scaler's verdict filter)
# ---------------------------------------------------------------------------


class TestAggregatorRoles:
    def test_roles_filter(self, tmp_path):
        spool = str(tmp_path / "spool")
        for pid, role in ((111, "trainer"), (222, "decode_worker")):
            ctx = dataclasses.replace(
                telemetry.TraceContext.new(role=role), pid=pid
            )
            sp = fleet.TelemetrySpool(spool, context=ctx)
            sp.tick()
        agg = fleet.TelemetryAggregator(spool, stale_after_s=3600.0)
        assert {p.role for p in agg.processes()} == {"trainer", "decode_worker"}
        only = agg.processes(roles=["trainer"])
        assert [p.role for p in only] == ["trainer"]
        snap = agg.aggregate(roles=["trainer"])
        assert [p.role for p in snap.processes] == ["trainer"]


# ---------------------------------------------------------------------------
# Options plumbing
# ---------------------------------------------------------------------------


class TestOptionsElastic:
    def test_round_trip_both_spellings(self):
        o = TFRecordOptions.from_map(
            elastic_min_workers=2, elastic_max_workers=6,
            elastic_interval_s=0.5,
        )
        assert (o.elastic_min_workers, o.elastic_max_workers,
                o.elastic_interval_s) == (2, 6, 0.5)
        o = TFRecordOptions.from_map(
            elasticMinWorkers="2", elasticMaxWorkers="6",
            elasticIntervalS="0.5",
        )
        assert (o.elastic_min_workers, o.elastic_max_workers,
                o.elastic_interval_s) == (2, 6, 0.5)

    def test_defaults(self):
        o = TFRecordOptions()
        assert o.elastic_min_workers == 1
        assert o.elastic_max_workers is None
        assert o.elastic_interval_s is None

    def test_validation_loud(self):
        with pytest.raises(ValueError):
            TFRecordOptions.from_map(elastic_min_workers=0)
        with pytest.raises(ValueError):
            TFRecordOptions.from_map(
                elastic_min_workers=4, elastic_max_workers=2
            )
        with pytest.raises(ValueError):
            TFRecordOptions.from_map(elastic_interval_s=0)


# ---------------------------------------------------------------------------
# serve-status doctor: tenant + scaler lines
# ---------------------------------------------------------------------------


class TestServeStatusElastic:
    def test_tenant_and_scaler_lines(self, dispatcher, data_dir, local_rows):
        w = start_worker(dispatcher, worker_id="w-a")
        s = elastic.FleetScaler(
            dispatcher, lambda: None, aggregator=FakeAggregator(),
            policy=elastic.ScalerPolicy(min_workers=1, max_workers=4),
        )
        s.step()
        try:
            got = collect(data_dir, service=dispatcher.addr,
                          service_deadline_ms=15000)
            assert got == local_rows
            doc = subprocess.run(
                [sys.executable, DOCTOR, "serve-status", dispatcher.addr],
                capture_output=True, text=True,
            )
            assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
            lines = [json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
            tenants = [l for l in lines if l.get("event") == "tenant"]
            assert len(tenants) == 1
            assert tenants[0]["consumers"] == 1
            assert tenants[0]["shards_done"] == 6
            assert tenants[0]["cache_hit_ratio"] == 0.0  # no cache configured
            (scaler_line,) = [l for l in lines if l.get("event") == "scaler"]
            assert scaler_line["workers"] == 1
            assert scaler_line["min_workers"] == 1
            (summary,) = [l for l in lines if l.get("event") == "service"]
            assert summary["tenants"] == 1
            assert summary["draining"] == []
        finally:
            w.stop()


# ---------------------------------------------------------------------------
# Worker CLI: --fault-plan + --drain-grace on a real subprocess
# ---------------------------------------------------------------------------


class TestWorkerCli:
    def test_subprocess_worker_with_fault_plan_serves_and_drains(
        self, dispatcher, data_dir, local_rows, tmp_path
    ):
        plan_path = str(tmp_path / "plan.json")
        with open(plan_path, "w") as fh:
            json.dump({
                "seed": 3,
                "rules": [{"op": "read", "kind": "stall", "path": "part-",
                           "times": 2, "stall_ms": 5}],
            }, fh)
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_tfrecord.service", "worker",
             "--dispatcher", dispatcher.addr, "--worker-id", "w-cli",
             "--drain-grace", "0.1", "--fault-plan", plan_path],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env,
        )
        try:
            ready = json.loads(p.stdout.readline())
            assert ready["worker_id"] == "w-cli"
            wait_for(
                lambda: any(w["alive"]
                            for w in dispatcher.status()["workers"]),
                msg="subprocess worker registration",
            )
            got = collect(data_dir, service=dispatcher.addr,
                          service_deadline_ms=15000)
            assert got == local_rows
            # drain it: the process must exit cleanly on its own
            assert dispatcher.drain("w-cli")
            assert p.wait(timeout=20) == 0
            wait_for(lambda: dispatcher.status()["workers"] == [],
                     msg="goodbye from the CLI worker")
            assert METRICS.counter("elastic.drains") == 1
        finally:
            if p.poll() is None:
                p.kill()
                p.wait()


# ---------------------------------------------------------------------------
# Bench: vs_previous regressions are a first-class verdict
# ---------------------------------------------------------------------------


class TestBenchRegressionVerdict:
    def test_regression_is_first_class_and_loud(self, monkeypatch, capsys):
        import bench

        monkeypatch.setattr(
            bench, "_load_previous_artifact",
            lambda: ("BENCH_r05.json", {"seq_host_value": 100.0}),
        )
        out = {"seq_host_value": 10.0}
        bench._attach_regression_verdict(out)
        assert out["regression_verdict"] == "regression"
        assert out["vs_previous"]["regressions"] == ["seq_host_value"]
        err = capsys.readouterr().err
        assert "REGRESSION" in err and "seq_host_value" in err

    def test_parity_append_survives_stripped_table(self, tmp_path):
        import bench

        parity = tmp_path / "PARITY.md"
        # header survived a hand edit, the table didn't: the appender
        # must rebuild the table, not die and cost the bench artifact
        parity.write_text(
            f"# P\n\n{bench._PARITY_SCALING_HEADER}\n\nprose only\n"
        )
        bench._append_parity_scaling_row(
            {1: 100.0, 2: 200.0, 4: 400.0}, path=str(parity)
        )
        content = parity.read_text()
        assert "| 100 | 200 | 400 | 2.00x | 4.00x |" in content
        # and a second append lands in the (rebuilt) table
        bench._append_parity_scaling_row(
            {1: 110.0, 2: 220.0, 4: 440.0}, path=str(parity)
        )
        assert "| 110 | 220 | 440 |" in parity.read_text()

    def test_parity_append_lands_below_separator(self, tmp_path):
        import bench

        parity = tmp_path / "PARITY.md"
        # table stripped to header + separator: the new row must land
        # BELOW the "|---|" separator, never between header and separator
        parity.write_text(
            f"{bench._PARITY_SCALING_HEADER}\n\n"
            "| round | date | 1w ex/s | 2w ex/s | 4w ex/s | 2w/1w | 4w/1w |\n"
            "|---|---|---|---|---|---|---|\n"
        )
        bench._append_parity_scaling_row(
            {1: 100.0, 2: 200.0, 4: 400.0}, path=str(parity)
        )
        lines = parity.read_text().splitlines()
        sep = next(i for i, l in enumerate(lines) if l.startswith("|---"))
        row = next(i for i, l in enumerate(lines) if "| 100 |" in l)
        assert row == sep + 1, lines

    def test_ok_and_no_previous_are_quiet(self, monkeypatch, capsys):
        import bench

        monkeypatch.setattr(bench, "_load_previous_artifact", lambda: None)
        out = {}
        bench._attach_regression_verdict(out)
        assert out["regression_verdict"] == "no_previous"
        monkeypatch.setattr(
            bench, "_load_previous_artifact",
            lambda: ("BENCH_r05.json", {"seq_host_value": 100.0}),
        )
        out = {"seq_host_value": 101.0}
        bench._attach_regression_verdict(out)
        assert out["regression_verdict"] == "ok"
        assert "REGRESSION" not in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Chaos acceptance: grow + graceful drain + SIGKILL mid-drain, mid-epoch
# ---------------------------------------------------------------------------


class TestResizeChaosAcceptance:
    def test_fleet_resize_mid_epoch_byte_identical(
        self, data_dir, local_rows
    ):
        d = service.ServiceDispatcher(lease_ttl_s=3.0).start()
        spawner = elastic.SubprocessSpawner(
            d.addr, ("--drain-grace", "0.2"),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        agg = FakeAggregator("balanced")
        scaler = elastic.FleetScaler(
            d, spawner, aggregator=agg,
            policy=elastic.ScalerPolicy(
                hysteresis=1, cooldown_s=0.0, min_workers=1, max_workers=3
            ),
        )
        try:
            spawner()
            spawner()
            wait_for(lambda: d.status()["alive"] >= 2, timeout=60,
                     msg="initial fleet registration")
            phases = {"grown": False, "drained": False, "killed": None}

            def hook(rows):
                if len(rows) >= 16 and not phases["grown"]:
                    # GROW mid-epoch: the scaler spawns worker 3
                    agg.verdict = "producer_bound"
                    assert scaler.step()["action"] == "scale_up"
                    wait_for(lambda: d.status()["alive"] >= 3, timeout=60,
                             msg="scaled-up worker registration")
                    phases["grown"] = True
                elif len(rows) >= 80 and not phases["drained"]:
                    # graceful DRAIN mid-epoch (no waiting here: the
                    # victim may be serving us right now, and its drain
                    # completes only once this very epoch stops needing
                    # it — asserted after the epoch)
                    agg.verdict = "consumer_bound"
                    decision = scaler.step()
                    assert decision["action"] == "scale_down"
                    phases["drained"] = decision["victim"]
                elif len(rows) >= 120 and phases["killed"] is None:
                    # second drain decision, victim SIGKILLed MID-DRAIN:
                    # it never gets to say goodbye
                    agg.verdict = "consumer_bound"
                    decision = scaler.step()
                    assert decision["action"] == "scale_down"
                    victim = decision["victim"]
                    pid = next(
                        w["pid"] for w in d.status()["workers"]
                        if w["worker_id"] == victim
                    )
                    os.kill(pid, signal.SIGKILL)
                    phases["killed"] = victim

            got = collect(data_dir, service=d.addr,
                          service_deadline_ms=15000, hook=hook)
            assert got == local_rows, "resize broke byte-identity"
            assert phases["grown"] and phases["drained"] and phases["killed"]
            assert phases["drained"] != phases["killed"]
            # exactly the expected elastic counters
            assert METRICS.counter("elastic.scale_ups") == 1
            assert METRICS.counter("elastic.scale_downs") == 2
            assert METRICS.counter("service.fallbacks") == 0
            # the graceful victim says goodbye once its streams finish...
            wait_for(
                lambda: phases["drained"] not in
                [w["worker_id"] for w in d.status()["workers"]],
                timeout=30, msg="graceful victim goodbye",
            )
            assert METRICS.counter("elastic.drains") == 1
            # ...the SIGKILLed one never does: it goes stale by heartbeat
            wait_for(
                lambda: any(
                    w["worker_id"] == phases["killed"] and not w["alive"]
                    for w in d.status()["workers"]
                ),
                timeout=30, msg="killed victim heartbeat expiry",
            )
            st = d.status()
            assert phases["killed"] in st["draining"]
        finally:
            spawner.reap()
            d.stop()
