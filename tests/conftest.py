"""Test fixtures.

Mirrors the reference's two-tier strategy (SURVEY.md §4): Tier 1 tests are
pure codec tests with no devices; Tier 2 tests fake a TPU pod with an
8-device CPU mesh (`--xla_force_host_platform_device_count=8`), the analog of
the reference's in-process Spark local mode (SharedSparkSessionSuite.scala).
"""

import os
import sys

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def sandbox(tmp_path):
    """Temp working dir, the analog of the reference's `tf-sandbox` fixture
    (SharedSparkSessionSuite.scala:29-43)."""
    d = tmp_path / "tf-sandbox"
    d.mkdir()
    return d
