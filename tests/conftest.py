"""Test fixtures.

Mirrors the reference's two-tier strategy (SURVEY.md §4): Tier 1 tests are
pure codec tests with no devices; Tier 2 tests fake a TPU pod with an
8-device CPU mesh (`--xla_force_host_platform_device_count=8`), the analog of
the reference's in-process Spark local mode (SharedSparkSessionSuite.scala).
"""

import os
import sys

# Must be set before the CPU backend is CREATED (not merely before jax is
# imported — the environment's sitecustomize may import jax at interpreter
# start, e.g. to register a TPU plugin). Backends initialize lazily, so
# forcing the platform through jax.config still works here.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def sandbox(tmp_path):
    """Temp working dir, the analog of the reference's `tf-sandbox` fixture
    (SharedSparkSessionSuite.scala:29-43)."""
    d = tmp_path / "tf-sandbox"
    d.mkdir()
    return d
