"""Hadoop codec breadth: snappy / lz4 / bzip2 (VERDICT r2 missing #2).

The reference forwards any codec class name to Hadoop
(DefaultSource.scala:95-102); these tests pin the native equivalents:
dependency-free raw-snappy and lz4-block codecs under Hadoop's
BlockCompressorStream framing, bzip2 via stdlib, wired through the same
codec registry as gzip/deflate/zstd.
"""

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import wire
from tpu_tfrecord.hadoop_codecs import (
    lz4_compress,
    lz4_decompress,
    snappy_compress,
    snappy_decompress,
)
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

SCHEMA = StructType([StructField("x", LongType()), StructField("s", StringType())])
ROWS = [[i, f"row{i}" * (i % 5 + 1)] for i in range(64)]


class TestRawSnappy:
    def test_literal_only_round_trip(self):
        for payload in (b"", b"a", b"hello world" * 100, bytes(range(256)) * 300):
            assert snappy_decompress(snappy_compress(payload)) == payload

    def test_spec_vector_with_copies(self):
        """Hand-built per the format spec: literal then a 1-byte-offset copy
        ('abcd' + copy(offset=4, len=4) -> 'abcdabcd')."""
        # varint len 8; literal tag len-1=3 -> 3<<2; 'abcd'; copy1 tag:
        # kind=1, len=4 -> bits (4-4)<<2 | 1; offset 4 -> high 3 bits 0,
        # low byte 4.
        blob = bytes([8, 3 << 2]) + b"abcd" + bytes([0x01, 4])
        assert snappy_decompress(blob) == b"abcdabcd"

    def test_spec_vector_overlapping_copy_rle(self):
        """offset < length: RLE semantics ('ab' + copy(offset=2, len=6) ->
        'ab' repeated)."""
        blob = bytes([8, 1 << 2]) + b"ab" + bytes([(6 - 4) << 2 | 0x01, 2])
        assert snappy_decompress(blob) == b"abababab"

    def test_spec_vector_two_byte_offset_copy(self):
        data = b"x" * 70 + b"PATTERN"
        # literal(77 bytes, needs 1 extra length byte) + copy2(len=7, off=7)
        blob = (
            bytes([8 + 69, (60) << 2, 76])
            + data
            + bytes([(7 - 1) << 2 | 0x02])
            + (7).to_bytes(2, "little")
        )
        # preamble: total 77+7=84
        blob = bytes([84]) + blob[1:]
        assert snappy_decompress(blob) == data + b"PATTERN"

    def test_corrupt_length_promise_raises(self):
        blob = bytes([9, 3 << 2]) + b"abcd"  # promises 9, delivers 4
        with pytest.raises(wire.TFRecordCorruptionError):
            snappy_decompress(blob)

    def test_bad_copy_offset_raises(self):
        blob = bytes([8, 3 << 2]) + b"abcd" + bytes([0x01, 200])  # offset 200 > 4
        with pytest.raises(wire.TFRecordCorruptionError):
            snappy_decompress(blob)


class TestRawLz4:
    def test_literal_only_round_trip(self):
        for payload in (b"", b"a", b"hello" * 1000, bytes(range(256)) * 100):
            assert lz4_decompress(lz4_compress(payload)) == payload

    def test_spec_vector_with_match(self):
        """token: 4 literals, match len 8 (4+4); offset 4 -> 'abcd' * 3."""
        blob = bytes([(4 << 4) | 4]) + b"abcd" + (4).to_bytes(2, "little")
        assert lz4_decompress(blob) == b"abcd" + b"abcdabcd"

    def test_extended_lengths(self):
        lit = bytes(range(256)) * 2  # 512 literals: 15 + 255 + 242
        blob = bytes([0xF0, 255, 512 - 15 - 255]) + lit
        assert lz4_decompress(blob) == lit

    def test_bad_offset_raises(self):
        blob = bytes([(4 << 4) | 4]) + b"abcd" + (9).to_bytes(2, "little")
        with pytest.raises(wire.TFRecordCorruptionError):
            lz4_decompress(blob)

    def test_native_size_guard_falls_back(self, monkeypatch):
        """Inputs past the native encoder's int32 match-table contract
        (>= 2 GiB) must skip the native path and still produce valid lz4
        (ADVICE: lz4 >= 2GiB guard). The threshold is shrunk so the guard
        is exercised without allocating 2 GiB; the fallback's literal-only
        output is recognizable by its 0xF0 full-literal token."""
        from tpu_tfrecord import hadoop_codecs

        payload = b"abcdefgh" * 1024  # compressible: native WOULD emit matches
        native_blob = lz4_compress(payload)
        monkeypatch.setattr(hadoop_codecs, "LZ4_NATIVE_MAX_BYTES", 16)
        guarded_blob = lz4_compress(payload)
        assert lz4_decompress(guarded_blob) == payload
        assert guarded_blob[0] == 0xF0  # literal-only fallback, not native
        if native_blob[0] != 0xF0:  # native available: guard changed dispatch
            assert guarded_blob != native_blob


@pytest.mark.parametrize("codec,ext", [
    ("snappy", ".snappy"), ("lz4", ".lz4"), ("bzip2", ".bz2"),
])
class TestCodecIntegration:
    def test_wire_round_trip_and_autodetect(self, sandbox, codec, ext):
        path = str(sandbox / f"w.tfrecord{ext}")
        records = [b"r1", b"r2" * 500, b"", b"r4" * 9000]
        wire.write_records(path, records, codec=codec)
        assert list(wire.read_records(path)) == records         # by extension
        assert list(wire.read_records(path, codec=codec)) == records

    def test_hadoop_class_name_alias(self, sandbox, codec, ext):
        cls = {
            "snappy": "org.apache.hadoop.io.compress.SnappyCodec",
            "lz4": "org.apache.hadoop.io.compress.Lz4Codec",
            "bzip2": "org.apache.hadoop.io.compress.BZip2Codec",
        }[codec]
        assert wire.normalize_codec(cls) == codec
        assert wire.codec_extension(codec) == ext
        assert wire.codec_from_path(f"part-0.tfrecord{ext}") == codec

    def test_dataset_round_trip(self, sandbox, codec, ext):
        out = str(sandbox / f"ds_{codec}")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite", codec=codec)
        shards = tfio.discover_shards(out)
        assert all(s.path.endswith(f".tfrecord{ext}") for s in shards)
        table = tfio.read(out, schema=SCHEMA)
        assert sorted(table.column("x")) == [r[0] for r in ROWS]

    def test_streaming_dataset_reads(self, sandbox, codec, ext):
        out = str(sandbox / f"sd_{codec}")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite", codec=codec)
        ds = TFRecordDataset(out, batch_size=16, schema=SCHEMA)
        got = []
        with ds.batches() as it:
            for cb in it:
                got.extend(cb["x"].values.tolist())
        assert sorted(got) == [r[0] for r in ROWS]

    def test_truncation_detected(self, sandbox, codec, ext):
        path = str(sandbox / f"t.tfrecord{ext}")
        wire.write_records(path, [b"abc" * 300] * 20, codec=codec)
        blob = open(path, "rb").read()
        open(path, "wb").write(blob[: len(blob) // 2])
        with pytest.raises(wire.TFRecordCorruptionError):
            list(wire.read_records(path))


class TestBlockFraming:
    def test_multi_block_write(self, sandbox):
        """Payload larger than the 256KB Hadoop block size spans blocks."""
        path = str(sandbox / "big.tfrecord.snappy")
        records = [bytes([i % 251]) * 4096 for i in range(200)]  # ~800KB
        wire.write_records(path, records, codec="snappy")
        assert list(wire.read_records(path)) == records
        # the stream really is multi-block: first block header says 256KB
        with open(path, "rb") as fh:
            first = int.from_bytes(fh.read(4), "big")
        assert first == 256 * 1024

    def test_unknown_codec_message_lists_all(self):
        with pytest.raises(ValueError, match="snappy.*lz4.*bzip2"):
            wire.normalize_codec("org.example.MadeUpCodec")


class TestNativeCodecParity:
    """The native snappy/lz4 decoders against the pure-Python oracles:
    byte-identical on valid element-dense streams (random literals +
    copies incl. overlapping RLE), and clean errors — never crashes — on
    mutated bytes."""

    native = pytest.importorskip("tpu_tfrecord._native")

    @pytest.fixture(autouse=True)
    def _need_native(self):
        if not self.native.available():
            pytest.skip("native lib unavailable")

    def _random_snappy(self, rng, n_elems=40):
        """A VALID raw-snappy stream built element by element."""
        from tpu_tfrecord.hadoop_codecs import _write_varint

        out = bytearray()
        body = bytearray()
        for _ in range(n_elems):
            if len(out) == 0 or rng.random() < 0.5:
                # short-form literal tag: len-1 must be < 60
                lit = bytes(rng.integers(0, 256, size=int(rng.integers(1, 60)),
                                         dtype=np.uint8))
                ln = len(lit) - 1
                body.append(ln << 2)
                body += lit
                out += lit
            else:
                length = int(rng.integers(4, 12))
                offset = int(rng.integers(1, min(len(out), 2000) + 1))
                body.append(((length - 1) << 2) | 0x02)
                body += offset.to_bytes(2, "little")
                start = len(out) - offset
                for i in range(length):
                    out.append(out[start + i])
        return bytes(_write_varint(len(out)) + body), bytes(out)

    def test_snappy_differential_fuzz(self):
        from tpu_tfrecord.hadoop_codecs import _snappy_decompress_py

        rng = np.random.default_rng(7)
        for _ in range(50):
            blob, want = self._random_snappy(rng)
            assert self.native.snappy_decompress(blob) == want
            assert _snappy_decompress_py(blob) == want

    def test_snappy_mutated_inputs_never_crash(self):
        from tpu_tfrecord.hadoop_codecs import _snappy_decompress_py

        rng = np.random.default_rng(8)
        blob, want = self._random_snappy(rng)
        for _ in range(300):
            mut = bytearray(blob)
            k = int(rng.integers(0, len(mut)))
            mut[k] = int(rng.integers(0, 256))
            mut = bytes(mut[: int(rng.integers(1, len(mut) + 1))])
            outcomes = []
            for fn in (self.native.snappy_decompress,
                       lambda b: _snappy_decompress_py(b)):
                try:
                    outcomes.append(fn(mut))
                except Exception:
                    outcomes.append("ERR")
            # native and oracle must AGREE: both decode to the same bytes
            # or both reject (a disagreement means one of them misparses)
            assert outcomes[0] == outcomes[1], mut.hex()

    def test_lz4_differential_and_mutations(self):
        from tpu_tfrecord.hadoop_codecs import _lz4_decompress_py, lz4_compress

        rng = np.random.default_rng(9)
        payload = bytes(rng.integers(0, 256, size=5000, dtype=np.uint8))
        blob = lz4_compress(payload)
        assert self.native.lz4_decompress(blob, len(payload)) == payload
        # hand-built two-sequence stream with extended literal AND match
        # lengths: seq1 = 256 literals (ext 241) + match(offset 8,
        # len 15+4+ext 3 = 22, overlapping -> RLE); seq2 (final) = 4
        # literals, no match
        lit = bytes(range(256))
        stream = bytes([0xFF, 256 - 15]) + lit \
            + (8).to_bytes(2, "little") + bytes([3]) \
            + bytes([4 << 4]) + lit[:4]
        want = _lz4_decompress_py(stream)
        assert self.native.lz4_decompress(stream, len(want)) == want
        for _ in range(300):
            mut = bytearray(stream)
            k = int(rng.integers(0, len(mut)))
            mut[k] = int(rng.integers(0, 256))
            mut = bytes(mut[: int(rng.integers(1, len(mut) + 1))])
            try:
                a = self.native.lz4_decompress(mut, None)
            except Exception:
                a = "ERR"
            try:
                b = _lz4_decompress_py(mut)
            except Exception:
                b = "ERR"
            assert a == b, mut.hex()

    def test_corrupt_length_varint_is_corruption_not_oom(self):
        """A corrupt preamble claiming terabytes must raise the codec
        corruption error BEFORE any allocation, not MemoryError."""
        from tpu_tfrecord.hadoop_codecs import snappy_decompress

        huge = b"\xff\xff\xff\xff\xff\x7f" + b"\x00" * 10  # claims ~2^42 B
        with pytest.raises(wire.TFRecordCorruptionError):
            snappy_decompress(huge)


class TestNativeCompressors:
    """Round-4 native ENCODERS (greedy hash matchers): snappy/lz4 writes
    must actually compress — dependency-free — and every output must decode
    bit-exactly through BOTH the native and the pure-Python (spec-oracle)
    decoders. Closes the VERDICT r3 'snappy write-side is literal-only'
    finding without needing python-snappy in any environment."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from tpu_tfrecord import _native

        if not _native.available():
            pytest.skip("native library unavailable")

    def test_compression_ratio_above_1_2_on_compressible(self):
        from tpu_tfrecord.hadoop_codecs import (
            _lz4_decompress_py,
            _snappy_decompress_py,
        )

        data = (b"click,1,user_984,item_123,cat_shoes|" * 8000)
        c = snappy_compress(data)
        assert len(data) / len(c) > 1.2, "snappy write-side must compress"
        assert snappy_decompress(c) == data
        assert _snappy_decompress_py(c) == data
        l = lz4_compress(data)
        assert len(data) / len(l) > 1.2, "lz4 write-side must compress"
        assert lz4_decompress(l, expected=len(data)) == data
        assert _lz4_decompress_py(l, expected=len(data)) == data

    def test_encoder_fuzz_round_trips_both_decoders(self):
        from tpu_tfrecord.hadoop_codecs import (
            _lz4_decompress_py,
            _snappy_decompress_py,
        )

        rng = np.random.default_rng(11)
        for trial in range(40):
            parts = []
            for _ in range(int(rng.integers(1, 8))):
                kind = int(rng.integers(0, 3))
                n = int(rng.integers(0, 5000))
                if kind == 0:
                    parts.append(rng.bytes(n))  # incompressible
                elif kind == 1:
                    parts.append(bytes([int(rng.integers(0, 256))]) * n)  # run
                else:
                    motif = rng.bytes(int(rng.integers(1, 40)) or 1)
                    parts.append(motif * (n // max(1, len(motif))))
            data = b"".join(parts)
            c = snappy_compress(data)
            assert snappy_decompress(c) == data, trial
            assert _snappy_decompress_py(c) == data, trial
            l = lz4_compress(data)
            assert lz4_decompress(l, expected=len(data)) == data, trial
            assert _lz4_decompress_py(l, expected=len(data)) == data, trial

    def test_cross_64k_block_boundary(self):
        # snappy fragments at 64KB: a motif straddling the boundary must
        # round-trip (no cross-block matches are emitted; decoders that
        # allow them still accept the stream)
        data = b"Z" * 65530 + b"boundary-motif" * 10 + b"Q" * 65530
        c = snappy_compress(data)
        assert snappy_decompress(c) == data

    def test_file_level_ratio_through_block_framing(self, sandbox):
        # End-to-end: dataset written with codec=snappy must be SMALLER on
        # disk than uncompressed (the r3 'parity in name only' gap), and
        # read back identically through the streaming dataset.
        import os

        rows = [[i, "abcdefgh" * 8] for i in range(4096)]
        plain = str(sandbox / "plain")
        comp = str(sandbox / "comp")
        tfio.write(rows, SCHEMA, plain)
        tfio.write(rows, SCHEMA, comp, codec="snappy")

        def total(d):
            return sum(
                os.path.getsize(os.path.join(d, f))
                for f in os.listdir(d)
                if not f.startswith("_")
            )

        assert total(plain) / total(comp) > 2.0
        back = tfio.read(comp, schema=SCHEMA).to_dicts()
        assert [[r["x"], r["s"]] for r in back] == rows
