"""Disaggregated data service suite (ISSUE 8): wire protocol integrity,
the seeded socket-fault seam, dispatcher leasing + journal replay,
byte-identical service reads, exactly-once delivery under worker death /
dispatcher restart / redelivery, graceful degradation to local reads,
checkpoint-resume interchange across the service boundary (both
directions, including past a reassigned shard), the serve-status doctor,
and the chaos acceptance run (K=3 worker subprocesses feeding 2
consumers, one worker SIGKILLed and the dispatcher killed+restarted
mid-epoch)."""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from tpu_tfrecord import service
from tpu_tfrecord import service_protocol as sp
from tpu_tfrecord.columnar import batch_to_rows, slice_batch
from tpu_tfrecord.faults import FaultPlan, FaultRule, InjectedFault, install_chaos
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.io.paths import interleave, interleave_owner
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import (
    ArrayType,
    LongType,
    StringType,
    StructField,
    StructType,
)

DOCTOR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "tfrecord_doctor.py",
)

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),  # nullable: exercises the mask
        StructField("arr", ArrayType(LongType())),  # ragged
    ]
)
# every 7th string null -> mask sections cross the wire too
ROWS = [
    [i, None if i % 7 == 0 else f"v{i}" * (i % 3 + 1), list(range(i % 5))]
    for i in range(180)
]
PER_SHARD = 30  # 6 shards


@pytest.fixture(autouse=True)
def _reset_metrics():
    METRICS.reset()
    yield


@pytest.fixture
def data_dir(sandbox):
    out = str(sandbox / "ds")
    DatasetWriter(
        out, SCHEMA, mode="overwrite", max_records_per_file=PER_SHARD
    ).write_rows(ROWS)
    return out


def make_ds(data_dir, state=None, **kw):
    return TFRecordDataset(
        data_dir, batch_size=8, schema=SCHEMA, drop_remainder=False,
        num_epochs=1, **kw,
    )


def collect(data_dir, state=None, n=None, **kw):
    """Rows from up to ``n`` batches (None = the whole epoch); with n set,
    also returns the iterator state at the pause point."""
    ds = make_ds(data_dir, **kw)
    got = []
    with ds.batches(state) as it:
        if n is None:
            for b in it:
                got.extend(batch_to_rows(b, ds.schema))
            return got
        for _ in range(n):
            got.extend(batch_to_rows(next(it), ds.schema))
        return got, it.state()


@pytest.fixture
def local_rows(data_dir):
    return collect(data_dir)


@pytest.fixture
def dispatcher():
    d = service.ServiceDispatcher(lease_ttl_s=5.0).start()
    yield d
    d.stop()


def start_workers(dispatcher, k, **kw):
    workers = [service.DecodeWorker(dispatcher.addr, **kw).start() for _ in range(k)]
    for w in workers:
        assert w.wait_registered(10), "worker failed to register"
    return workers


@pytest.fixture
def fleet(dispatcher):
    workers = start_workers(dispatcher, 2)
    yield dispatcher, workers
    for w in workers:
        w.stop()


# ---------------------------------------------------------------------------
# Wire protocol
# ---------------------------------------------------------------------------


class TestProtocol:
    def test_parse_addr(self):
        assert sp.parse_addr("h:1") == ("h", 1)
        assert sp.parse_addr("::1:80") == ("::1", 80)
        for bad in ("h", ":80", "h:"):
            with pytest.raises(ValueError):
                sp.parse_addr(bad)

    def test_frame_round_trip(self):
        a, b = socket.socketpair()
        try:
            sp.send_frame(a, b"hello world")
            assert sp.recv_frame(b, "peer") == b"hello world"
            sp.send_msg(a, {"op": "ping", "k": 1})
            assert sp.recv_msg(b, "peer") == {"op": "ping", "k": 1}
        finally:
            a.close()
            b.close()

    def test_clean_eof_at_boundary_is_none_elsewhere_loud(self):
        a, b = socket.socketpair()
        a.close()
        try:
            assert sp.recv_msg(b, "peer", allow_eof=True) is None
            with pytest.raises(sp.ProtocolError, match="short frame"):
                sp.recv_frame(b, "peer")  # allow_eof=False: EOF is a death
        finally:
            b.close()

    def test_mid_frame_close_is_short_frame(self):
        a, b = socket.socketpair()
        try:
            payload = b"x" * 64
            from tpu_tfrecord import wire

            a.sendall(struct.pack("<II", len(payload), wire.masked_crc32c(payload)))
            a.sendall(payload[:10])
            a.close()
            with pytest.raises(sp.ProtocolError, match="short frame"):
                sp.recv_frame(b, "peer")
        finally:
            b.close()

    def test_crc_mismatch_loud(self):
        a, b = socket.socketpair()
        try:
            payload = b"payload-bytes"
            a.sendall(struct.pack("<II", len(payload), 0xDEAD))
            a.sendall(payload)
            with pytest.raises(sp.ProtocolError, match="CRC mismatch"):
                sp.recv_frame(b, "peer")
        finally:
            a.close()
            b.close()

    def test_absurd_length_rejected_before_allocation(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<II", sp.MAX_CONTROL_FRAME + 1, 0))
            with pytest.raises(sp.ProtocolError, match="exceeds"):
                sp.recv_frame(b, "peer")
        finally:
            a.close()
            b.close()

    def test_non_object_message_loud(self):
        a, b = socket.socketpair()
        try:
            sp.send_frame(a, b"[1, 2]")
            with pytest.raises(sp.ProtocolError, match="malformed"):
                sp.recv_msg(b, "peer")
            sp.send_frame(a, b"\xff\xfe not json")
            with pytest.raises(sp.ProtocolError, match="malformed"):
                sp.recv_msg(b, "peer")
        finally:
            a.close()
            b.close()

    def _chunk_of(self, data_dir):
        ds = make_ds(data_dir)
        chunk = next(ds._decode_shard(0, 0, 0, 0))[0]
        return ds, chunk

    def _round_trip(self, ds, chunk, verify=True, corrupt=None):
        a, b = socket.socketpair()
        try:
            t = threading.Thread(target=sp.send_chunk, args=(a, chunk, 0, 0))
            t.start()
            header = sp.recv_msg(b, "peer")
            if corrupt is not None:
                corrupt(header)
            try:
                return sp.recv_chunk_body(
                    b, header, "peer", ds.chunk_dtypes().__getitem__, verify
                )
            finally:
                t.join()
        finally:
            a.close()
            b.close()

    def test_chunk_round_trip_identical_rows_and_order(self, data_dir):
        """Decoded rows AND column order survive the wire — order matters
        because downstream batch assembly is order-sensitive (regression:
        a sorted-by-name wire order permuted every non-alphabetical
        schema)."""
        ds, chunk = self._chunk_of(data_dir)
        got = self._round_trip(ds, chunk)
        assert list(got.columns) == list(chunk.columns)
        assert batch_to_rows(got, ds.schema) == batch_to_rows(chunk, ds.schema)

    def test_chunk_section_crc_verified(self, data_dir):
        ds, chunk = self._chunk_of(data_dir)

        def flip(header):
            header["cols"][0]["sections"][0]["crc"] ^= 1

        with pytest.raises(sp.ProtocolError, match="section CRC mismatch"):
            self._round_trip(ds, chunk, corrupt=flip)
        # verify=False skips the stamp check: the flip goes unnoticed
        got = self._round_trip(ds, chunk, verify=False, corrupt=flip)
        assert batch_to_rows(got, ds.schema) == batch_to_rows(chunk, ds.schema)

    def test_chunk_section_overrun_loud(self, data_dir):
        ds, chunk = self._chunk_of(data_dir)

        def grow(header):
            header["cols"][-1]["sections"][-1]["nbytes"] += 8

        with pytest.raises(sp.ProtocolError):
            self._round_trip(ds, chunk, corrupt=grow)


# ---------------------------------------------------------------------------
# Socket-fault seam (faults.FaultPlan connect/recv rules)
# ---------------------------------------------------------------------------


class TestSocketChaos:
    def test_connect_refused_rule(self):
        plan = FaultPlan([FaultRule(op="connect", kind="transient_error")])
        with pytest.raises(InjectedFault):
            plan.apply_socket("connect", "h:1")
        assert plan.ledger[0]["op"] == "connect"

    def test_recv_stall_uses_injected_sleep(self):
        slept = []
        plan = FaultPlan(
            [FaultRule(op="recv", kind="stall", stall_ms=250.0)],
            sleep=slept.append,
        )
        plan.apply_socket("recv", "h:1", size=64)
        assert slept == [0.25]

    def test_recv_short_read_caps_but_recv_loop_refills(self):
        """A capped recv returns a partial segment; _recv_exact must loop
        and still assemble the exact frame."""
        plan = FaultPlan(
            [FaultRule(op="recv", kind="short_read", cap_bytes=3, times=2)]
        )
        a, b = socket.socketpair()
        try:
            sp._CHAOS_PLAN = plan
            sp.send_msg(a, {"op": "ping", "pad": "x" * 200})
            assert sp.recv_msg(b, "peer") == {"op": "ping", "pad": "x" * 200}
        finally:
            sp._CHAOS_PLAN = None
            a.close()
            b.close()
        capped = [e for e in plan.ledger if e["kind"] == "short_read"]
        assert len(capped) == 2 and all(e["cap_bytes"] == 3 for e in capped)

    def test_recv_disconnect_closes_socket_and_raises(self):
        plan = FaultPlan([FaultRule(op="recv", kind="disconnect")])
        a, b = socket.socketpair()
        try:
            with pytest.raises(InjectedFault):
                plan.apply_socket("recv", "h:1", sock=b, size=16)
            # the local side observes a closed socket, like a real death
            with pytest.raises(OSError):
                b.recv(1)
        finally:
            a.close()
            b.close()

    def test_ledger_replayable(self):
        """Same plan JSON, same call sequence => byte-identical ledger —
        socket faults ride the SAME seeded, replayable machinery as file
        faults."""

        def run():
            plan = FaultPlan.from_json(
                {
                    "seed": 7,
                    "rules": [
                        {"op": "connect", "kind": "transient_error",
                         "probability": 0.5, "times": None},
                        {"op": "recv", "kind": "short_read", "cap_bytes": 9,
                         "ordinal": 2, "times": 3},
                    ],
                }
            )
            for i in range(10):
                try:
                    plan.apply_socket("connect", "h:1")
                except InjectedFault:
                    pass
                plan.apply_socket("recv", "h:1", size=100)
            return plan.ledger_json()

        first = run()
        assert first and first == run()

    def test_install_chaos_reaches_service_sockets(self, dispatcher, data_dir,
                                                   local_rows):
        """A seeded mid-stream disconnect on the consumer's recv of the
        worker chunk stream: the client reconnects, re-requests from its
        acked offset, and the epoch is STILL byte-identical — with the
        fault in the plan's ledger and the recovery in the counters.
        Workers bind a second loopback address so the rule's path
        substring targets EXACTLY the consumer->worker data stream (the
        dispatcher RPCs and worker heartbeats stay fault-free)."""
        d = dispatcher
        workers = start_workers(d, 2, host="127.1.0.1")
        plan = FaultPlan(
            [
                # ordinal deep enough to land mid-chunk-stream, times=1 so
                # the retry goes through clean
                FaultRule(op="recv", kind="disconnect", path="127.1.0.1",
                          ordinal=9, times=1),
            ]
        )
        try:
            with install_chaos(plan):
                got = collect(data_dir, service=d.addr, service_deadline_ms=2000)
        finally:
            for w in workers:
                w.stop()
        assert got == local_rows
        fired = [e for e in plan.ledger if e["kind"] == "disconnect"]
        assert len(fired) == 1 and fired[0]["op"] == "recv"
        assert METRICS.counter("service.reconnects") >= 1
        assert METRICS.counter("service.fallbacks") == 0


# ---------------------------------------------------------------------------
# Dispatcher: leasing, expiry, journal replay
# ---------------------------------------------------------------------------


def _route(d, shard_index, path=None, exclude=()):
    return d._handle(
        {
            "op": "route",
            "job": "j",
            "path": path or f"/data/shard-{shard_index}",
            "shard_index": shard_index,
            "exclude": list(exclude),
        }
    )


class TestDispatcher:
    def test_route_is_interleaved_over_alive_workers(self):
        now = [0.0]
        d = service.ServiceDispatcher(lease_ttl_s=5.0, clock=lambda: now[0])
        try:
            for i in range(3):
                d._handle({"op": "register_worker", "worker_id": f"w{i}",
                           "addr": f"h:{i}", "pid": i})
            wids = sorted(f"w{i}" for i in range(3))
            for s in range(6):
                r = _route(d, s)
                assert r["worker_id"] == wids[interleave_owner(s, 3)]
        finally:
            d.stop()

    def test_lease_expiry_and_reassignment_count(self):
        """A silent worker's lease expires at the TTL (injected clock) and
        its shard re-routes with the reassignment counted; a lease that
        merely MOVES because the fleet grew is rebalancing, not failure."""
        now = [0.0]
        d = service.ServiceDispatcher(lease_ttl_s=5.0, clock=lambda: now[0])
        try:
            d._handle({"op": "register_worker", "worker_id": "w0",
                       "addr": "h:0", "pid": 0})
            assert _route(d, 0)["worker_id"] == "w0"
            # fleet grows; shard 0 now interleaves to the other worker —
            # NOT a reassignment (w0 is alive and not excluded)
            d._handle({"op": "register_worker", "worker_id": "w1",
                       "addr": "h:1", "pid": 1})
            moved = _route(d, 1, path="/data/shard-0b")
            assert d.status()["lease_reassignments"] == 0
            # w0 goes silent past the TTL: its shard re-routes, counted
            now[0] = 6.0
            d._handle({"op": "heartbeat", "worker_id": "w1"})
            r = _route(d, 0)
            assert r["worker_id"] == "w1"
            st = d.status()
            assert st["lease_reassignments"] == 1
            assert [w["alive"] for w in st["workers"]] == [False, True]
            del moved
        finally:
            d.stop()

    def test_excluded_by_witness_counts_before_ttl(self):
        """A consumer that WATCHED its worker die excludes it on re-route;
        the reassignment counts immediately — no TTL wait."""
        now = [0.0]
        d = service.ServiceDispatcher(lease_ttl_s=5.0, clock=lambda: now[0])
        try:
            for i in range(2):
                d._handle({"op": "register_worker", "worker_id": f"w{i}",
                           "addr": f"h:{i}", "pid": i})
            first = _route(d, 0)["worker_id"]
            other = {"w0": "w1", "w1": "w0"}[first]
            r = _route(d, 0, exclude=[first])
            assert r["worker_id"] == other
            assert d.status()["lease_reassignments"] == 1
        finally:
            d.stop()

    def test_all_excluded_falls_back_to_alive(self):
        d = service.ServiceDispatcher(lease_ttl_s=5.0)
        try:
            d._handle({"op": "register_worker", "worker_id": "w0",
                       "addr": "h:0", "pid": 0})
            r = _route(d, 0, exclude=["w0"])
            assert r["worker_id"] == "w0"  # a flaky worker beats no worker
        finally:
            d.stop()

    def test_no_workers_is_an_error_reply(self):
        d = service.ServiceDispatcher(lease_ttl_s=5.0)
        try:
            assert _route(d, 0)["error"] == "no_workers"
        finally:
            d.stop()

    def test_proto_version_skew_rejected(self):
        d = service.ServiceDispatcher(lease_ttl_s=5.0)
        try:
            r = d._handle({"op": "route", "proto": 999})
            assert r["error"] == "proto_mismatch"
        finally:
            d.stop()

    def test_journal_replay_restores_assignment_state(self, tmp_path):
        """Kill the dispatcher, restart it from the journal: workers,
        leases, done set, reassignment count, and the trace identity all
        survive — the control plane forgets nothing but heartbeat
        freshness (which workers re-supply)."""
        journal = str(tmp_path / "journal.json")
        d = service.ServiceDispatcher(lease_ttl_s=5.0, journal=journal)
        try:
            for i in range(2):
                d._handle({"op": "register_worker", "worker_id": f"w{i}",
                           "addr": f"h:{i}", "pid": 100 + i})
            _route(d, 0)
            _route(d, 1, exclude=[_route(d, 1)["worker_id"]])
            d._handle({"op": "shard_done", "job": "j", "path": "/data/shard-0",
                       "worker_id": "w0"})
            before = d.status()
        finally:
            d.stop()
        d2 = service.ServiceDispatcher(lease_ttl_s=5.0, journal=journal)
        try:
            after = d2.status()
            for key in ("lease_reassignments", "shards_done", "active_leases",
                        "trace_id"):
                assert after[key] == before[key], key
            assert [w["worker_id"] for w in after["workers"]] == ["w0", "w1"]
            # replayed workers get one TTL of grace, then must re-heartbeat
            assert all(w["alive"] for w in after["workers"])
        finally:
            d2.stop()

    def test_unreadable_journal_is_loud(self, tmp_path):
        # an unreadable FILE (a directory at the journal path reads as
        # EISDIR) is loud — silently starting fresh would orphan every
        # lease the real journal records
        journal = str(tmp_path / "journal.json")
        os.mkdir(journal)
        with pytest.raises(RuntimeError, match="unreadable dispatcher journal"):
            service.ServiceDispatcher(journal=journal)

    def test_torn_journal_content_replays_consistent_prefix(self, tmp_path):
        # torn CONTENT is not an error since the HA PR: a crash mid-append
        # legitimately leaves a partial tail, and replay folds the newest
        # consistent prefix (here: nothing) instead of refusing to start
        journal = str(tmp_path / "journal.json")
        with open(journal, "w") as fh:
            fh.write("{torn")
        d = service.ServiceDispatcher(journal=journal)
        try:
            assert d.status()["workers"] == []
            assert d.accepting
        finally:
            d.stop()

    def test_shard_done_idempotent(self):
        d = service.ServiceDispatcher(lease_ttl_s=5.0)
        try:
            d._handle({"op": "register_worker", "worker_id": "w0",
                       "addr": "h:0", "pid": 0})
            _route(d, 0)
            for _ in range(2):
                d._handle({"op": "shard_done", "job": "j",
                           "path": "/data/shard-0", "worker_id": "w0"})
            assert d.status()["shards_done"] == 1
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# Service-backed reads: byte-identity, failure matrix, dedupe
# ---------------------------------------------------------------------------


class TestServiceRead:
    def test_rows_byte_identical_to_local(self, fleet, data_dir, local_rows):
        d, _ = fleet
        assert collect(data_dir, service=d.addr) == local_rows
        assert METRICS.counter("service.fallbacks") == 0
        assert METRICS.counter("service.chunks_recv") > 0

    def test_two_consumers_concurrently(self, fleet, data_dir, local_rows):
        d, _ = fleet
        results = {}

        def consume(k):
            results[k] = collect(data_dir, service=d.addr)

        threads = [threading.Thread(target=consume, args=(k,)) for k in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results[0] == local_rows and results[1] == local_rows

    def test_worker_death_mid_epoch_exactly_once(self, dispatcher, data_dir,
                                                 local_rows):
        """Kill the worker HOLDING the active lease mid-shard: the shard is
        re-routed exactly-once (witnessed exclusion, no TTL wait), the
        epoch completes byte-identical — nothing duplicated, nothing
        missing — and no fallback to local reads happened."""
        d = dispatcher
        workers = {w.worker_id: w for w in start_workers(d, 3)}
        try:
            ds = make_ds(data_dir, service=d.addr, service_deadline_ms=2000)
            got = []
            killed = False
            with ds.batches() as it:
                for b in it:
                    got.extend(batch_to_rows(b, ds.schema))
                    if not killed and len(got) >= 40:
                        # kill whichever worker holds an active lease right
                        # now (between-shards instants may have none — scan
                        # again at the next batch)
                        leases = {
                            w["worker_id"]: w["leases"]
                            for w in d.status()["workers"] if w["leases"]
                        }
                        if leases:
                            victim = next(iter(leases))
                            workers.pop(victim).stop()
                            killed = True
            assert killed, "no active lease ever observed"
            assert got == local_rows
            assert METRICS.counter("service.lease_reassignments") >= 1
            assert METRICS.counter("service.fallbacks") == 0
        finally:
            for w in workers.values():
                w.stop()

    def test_dispatcher_restart_mid_epoch(self, data_dir, local_rows, tmp_path):
        """Stop the dispatcher mid-epoch and restart it on the same port
        from its journal: workers re-register through their beat loop,
        the consumer rides its backoff through the outage, and the epoch
        completes byte-identical with no fallback."""
        journal = str(tmp_path / "journal.json")
        d = service.ServiceDispatcher(lease_ttl_s=5.0, journal=journal).start()
        port = int(d.addr.rsplit(":", 1)[1])
        workers = start_workers(d, 2)
        restarted = None
        try:
            ds = make_ds(data_dir, service=d.addr, service_deadline_ms=2000)
            got = []
            with ds.batches() as it:
                for b in it:
                    got.extend(batch_to_rows(b, ds.schema))
                    if restarted is None and len(got) >= 40:
                        d.stop()
                        restarted = service.ServiceDispatcher(
                            port=port, lease_ttl_s=5.0, journal=journal
                        ).start()
            assert restarted is not None
            assert got == local_rows
            assert METRICS.counter("service.fallbacks") == 0
        finally:
            for w in workers:
                w.stop()
            d.stop()
            if restarted is not None:
                restarted.stop()

    def test_unreachable_service_degrades_to_local(self, data_dir, local_rows):
        """No dispatcher at all: past the fallback budget the consumer
        reads the SAME shards locally — byte-identical rows, the
        degradation counted and logged."""
        got = collect(
            data_dir, service="127.0.0.1:1", service_deadline_ms=200,
            service_fallback_ms=250,
        )
        assert got == local_rows
        assert METRICS.counter("service.fallbacks") >= 1

    def test_fallback_none_never_degrades(self, data_dir):
        """service_fallback_ms=None = retry forever: the consumer must NOT
        silently read locally; it keeps trying until stopped."""
        ds = make_ds(
            data_dir, service="127.0.0.1:1", service_deadline_ms=100,
            service_fallback_ms=None,
        )
        it = ds.batches()
        t = threading.Thread(target=lambda: next(iter(it), None))
        t.start()
        t.join(timeout=1.0)
        try:
            assert t.is_alive(), "consumer fell back despite fallback=None"
            assert METRICS.counter("service.fallbacks") == 0
        finally:
            it.close()
            t.join(timeout=10)
            assert not t.is_alive()

    def test_spec_mismatch_is_loud_not_fallback(self, fleet, data_dir):
        """A consumer/worker disagreement about the dataset must raise,
        never be papered over by local fallback (divergent views of the
        data are a config bug, not a transport fault)."""
        d, _ = fleet
        ds = make_ds(data_dir, service=d.addr)
        client = service.ServiceClient(ds)
        client._spec = dict(client._spec, shards_digest="deadbeef00000000")
        try:
            with pytest.raises(service.ServiceSpecError, match="diverged"):
                list(client.shard_chunks(0, 0, 0, 0, threading.Event()))
        finally:
            client.close()

    def test_redelivered_prefix_dropped_not_double_counted(self, data_dir):
        """A fake worker redelivers: a full duplicate chunk AND a
        partially-overlapping chunk. The client's (shard, chunk-offset)
        dedupe drops the duplicate and slices the overlap — rows come out
        exactly once, in order."""
        ds = make_ds(data_dir)
        chunk0 = next(ds._decode_shard(0, 0, 0, 0))[0]
        rows0 = chunk0.num_rows
        assert rows0 >= 30
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        addr = sp.format_addr("127.0.0.1", srv.getsockname()[1])

        def fake_worker():
            conn, _ = srv.accept()
            with conn:
                assert sp.recv_msg(conn, "c")["op"] == "fetch"
                sp.send_chunk(conn, slice_batch(chunk0, 0, 10), 0, 0)
                # full duplicate: must be dropped whole
                sp.send_chunk(conn, slice_batch(chunk0, 0, 10), 0, 1)
                # partial overlap (rows 7..19; 7..9 already acked): only
                # the unseen suffix may come through
                sp.send_chunk(conn, slice_batch(chunk0, 7, 20), 7, 2)
                sp.send_chunk(conn, slice_batch(chunk0, 20, rows0), 20, 3)
                sp.send_msg(conn, {"op": "eof", "chunks": 4})

        t = threading.Thread(target=fake_worker)
        t.start()
        svc_ds = make_ds(data_dir, service="127.0.0.1:1")
        client = service.ServiceClient(svc_ds)
        try:
            out = list(
                client._fetch_shard(addr, ds.shards[0].path, 0, 0, 0,
                                    threading.Event())
            )
        finally:
            client.close()
            t.join()
            srv.close()
        got = [r for item in out for r in batch_to_rows(item[0], ds.schema)]
        assert got == batch_to_rows(chunk0, ds.schema)  # exactly once,
        # in order — no dup, no hole
        # positions stay contiguous: each chunk starts where the last ended
        pos = 0
        for chunk, _e, _p, start in out:
            assert start == pos
            pos += chunk.num_rows
        assert pos == rows0
        assert METRICS.counter("service.redelivered_dropped") == 2

    def test_worker_serves_from_columnar_cache(self, dispatcher, data_dir,
                                               local_rows, tmp_path):
        """A worker with the epoch cache enabled populates on the first
        epoch and serves from mmap on the second — same bytes on the
        consumer either way."""
        d = dispatcher
        cache_dir = str(tmp_path / "cache")
        opts = TFRecordOptions.from_map(cache="auto", cache_dir=cache_dir)
        workers = start_workers(d, 1, options=opts)
        try:
            first = collect(data_dir, service=d.addr)
            assert first == local_rows
            assert METRICS.counter("cache.misses") > 0
            second = collect(data_dir, service=d.addr)
            assert second == local_rows
            assert METRICS.counter("cache.hits") > 0
        finally:
            for w in workers:
                w.stop()


# ---------------------------------------------------------------------------
# Checkpoint/resume interchange across the service boundary
# ---------------------------------------------------------------------------


class TestResumeInterchange:
    def test_service_state_resumes_locally_and_back(self, fleet, data_dir,
                                                    local_rows):
        """IteratorState is chunk-source-agnostic: a state taken mid-epoch
        from a service-backed iterator resumes on a direct local reader,
        and vice versa — all four head+tail combinations reproduce the
        epoch byte-identically."""
        d, _ = fleet
        svc = dict(service=d.addr, service_deadline_ms=2000)
        head_svc, st_svc = collect(data_dir, n=5, **svc)
        head_loc, st_loc = collect(data_dir, n=5)
        assert head_svc == head_loc == local_rows[: len(head_svc)]
        # state equality modulo source, by construction
        assert st_svc.to_json() == st_loc.to_json()
        for head, st in ((head_svc, st_svc), (head_loc, st_loc)):
            assert head + collect(data_dir, state=st) == local_rows
            assert head + collect(data_dir, state=st, **svc) == local_rows

    def test_resume_past_reassigned_shard(self, dispatcher, data_dir,
                                          local_rows):
        """Kill the lease-holding worker mid-epoch, checkpoint AFTER the
        reassignment, then resume on a fresh service AND on a local
        reader: both tails complete the epoch byte-identically."""
        d = dispatcher
        workers = {w.worker_id: w for w in start_workers(d, 3)}
        try:
            ds = make_ds(data_dir, service=d.addr, service_deadline_ms=2000)
            head = []
            st = None
            killed = False
            with ds.batches() as it:
                for b in it:
                    head.extend(batch_to_rows(b, ds.schema))
                    if not killed and len(head) >= 40:
                        leases = {
                            w["worker_id"]: w["leases"]
                            for w in d.status()["workers"] if w["leases"]
                        }
                        victim = next(iter(leases))
                        workers.pop(victim).stop()
                        killed = True
                    elif killed and st is None and \
                            METRICS.counter("service.lease_reassignments"):
                        st = it.state()
                        break
            assert killed and st is not None, "reassignment never happened"
            tail_svc = collect(data_dir, state=st, service=d.addr,
                               service_deadline_ms=2000)
            tail_loc = collect(data_dir, state=st)
            assert head + tail_loc == local_rows
            assert tail_svc == tail_loc
        finally:
            for w in workers.values():
                w.stop()


# ---------------------------------------------------------------------------
# Options plumbing
# ---------------------------------------------------------------------------


class TestOptions:
    def test_round_trip_both_spellings(self):
        o = TFRecordOptions.from_map(
            service="h:1", serviceLeaseTtlS=3.0, service_deadline_ms=100,
            serviceFallbackMs=None,
        )
        assert o.service == "h:1"
        assert o.service_lease_ttl_s == 3.0
        assert o.service_deadline_ms == 100.0
        assert o.service_fallback_ms is None

    def test_defaults(self):
        o = TFRecordOptions()
        assert o.service is None
        assert o.service_lease_ttl_s == 10.0
        assert o.service_deadline_ms == 5000.0
        assert o.service_fallback_ms == 30000.0

    def test_validation_loud(self):
        with pytest.raises(ValueError, match="host:port"):
            TFRecordOptions.from_map(service="not-an-addr")
        with pytest.raises(ValueError, match="service_lease_ttl_s"):
            TFRecordOptions.from_map(service_lease_ttl_s=0)
        with pytest.raises(ValueError, match="service_deadline_ms"):
            TFRecordOptions.from_map(service_deadline_ms=-1)
        with pytest.raises(ValueError, match="service_fallback_ms"):
            TFRecordOptions.from_map(service_fallback_ms=-1)

    def test_autotune_disabled_under_service(self, fleet, data_dir):
        """Decode parallelism lives in the worker fleet: a service-backed
        iterator must not spin up a local pool controller."""
        d, _ = fleet
        ds = make_ds(data_dir, service=d.addr, autotune="on")
        with ds.batches() as it:
            next(it)
            assert it.autotune is None

    def test_interleave_is_one_owner(self):
        items = list(range(10))
        for count in (1, 2, 3):
            split = [interleave(items, s, count) for s in range(count)]
            assert sorted(sum(split, [])) == items
            for s, part in enumerate(split):
                for it_ in part:
                    assert interleave_owner(it_, count) == s
        with pytest.raises(ValueError):
            interleave(items, 2, 2)
        with pytest.raises(ValueError):
            interleave(items, 0, 0)


# ---------------------------------------------------------------------------
# serve-status doctor
# ---------------------------------------------------------------------------


class TestServeStatusDoctor:
    def test_report_and_exit_codes(self, fleet):
        d, workers = fleet
        proc = subprocess.run(
            [sys.executable, DOCTOR, "serve-status", d.addr],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr
        lines = [json.loads(l) for l in proc.stdout.splitlines()]
        by_event = {}
        for l in lines:
            by_event.setdefault(l["event"], []).append(l)
        assert len(by_event["worker"]) == len(workers)
        for w in by_event["worker"]:
            assert w["alive"] and w["heartbeat_age_s"] < 5.0
        (summary,) = by_event["service"]
        assert summary["workers"] == len(workers)
        assert summary["alive"] == len(workers)
        assert summary["trace_id"]

    def test_unreachable_exits_2(self):
        proc = subprocess.run(
            [sys.executable, DOCTOR, "serve-status", "127.0.0.1:1",
             "--timeout", "1"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 2
        assert json.loads(proc.stdout.splitlines()[0])["event"] == "error"


# ---------------------------------------------------------------------------
# The chaos acceptance run: subprocess workers, SIGKILL, dispatcher restart
# ---------------------------------------------------------------------------


def _spawn_worker_proc(dispatcher_addr):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_tfrecord.service", "worker",
         "--dispatcher", dispatcher_addr],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    ready = json.loads(proc.stdout.readline())
    assert ready["event"] == "ready"
    return proc, ready


class TestFailureHardening:
    """Pins for the review-driven hardening: length-field bounds, data-plane
    version skew, suspect aging, and liveness-vs-construction keepalives."""

    def test_chunk_body_length_bounds(self):
        """A chunk header announcing a negative, absurd, or non-numeric
        body length is a loud ProtocolError BEFORE any buffer allocation —
        never a bare ValueError that escapes the transport nets, never a
        huge bytearray."""
        for body in (-1, sp.MAX_CHUNK_BODY + 1, "nope"):
            header = {"op": "chunk", "start": 0, "rows": 0, "cols": [],
                      "body": body}
            with pytest.raises(sp.ProtocolError):
                sp.recv_chunk_body(None, header, "peer", {}.__getitem__)

    def test_worker_rejects_proto_skew_on_data_plane(self, dispatcher):
        """The worker's fetch loop rejects version skew as loudly as the
        dispatcher's control plane: a skewed consumer must never receive
        chunks whose section layout it would mis-parse."""
        w = service.DecodeWorker(dispatcher.addr).start()
        try:
            s = sp.connect(w.addr, timeout=5.0)
            try:
                s.settimeout(5.0)
                reply = sp.request(
                    s, w.addr, {"op": "fetch", "proto": 999, "spec": {},
                                "shard": "x"}
                )
                assert reply["kind"] == "proto_mismatch", reply
            finally:
                s.close()
        finally:
            w.stop()

    def test_route_reply_carries_dispatcher_ttl(self):
        """Consumers age suspects on the fleet's REAL reassignment clock:
        the route reply carries the dispatcher's lease TTL, so a mis-set
        local service_lease_ttl_s cannot desynchronize the client."""
        d = service.ServiceDispatcher(lease_ttl_s=7.5)
        try:
            d._handle({"op": "register_worker", "worker_id": "w0",
                       "addr": "h:0", "pid": 0})
            r = d._handle({"op": "route", "proto": service.PROTO_VERSION,
                           "job": "j", "path": "/p", "shard_index": 0,
                           "exclude": []})
            assert r["lease_ttl_s"] == 7.5
        finally:
            d.stop()

    def test_suspects_age_out_after_one_lease_ttl(self, data_dir):
        """One transient timeout must not exile a healthy worker for the
        client's lifetime: suspicion expires after one lease TTL — by then
        the dispatcher's own heartbeat accounting has caught a genuinely
        dead worker."""
        ds = make_ds(data_dir, service="127.0.0.1:1",
                     service_lease_ttl_s=5.0)
        client = service.ServiceClient(ds)
        now = [100.0]
        client._clock = lambda: now[0]
        client._suspects = {"w0": 100.0}
        assert client._live_suspects() == ["w0"]
        now[0] = 104.9
        assert client._live_suspects() == ["w0"]
        now[0] = 105.0
        assert client._live_suspects() == []
        assert client._suspects == {}

    def test_cold_construction_outlives_consumer_deadline(
        self, dispatcher, data_dir, local_rows, monkeypatch
    ):
        """A worker's first fetch pays dataset construction, which can
        exceed the consumer's per-op deadline on a loaded box: `building`
        keepalives make the deadline measure liveness, so a cold healthy
        worker costs zero reconnects and zero spurious reassignments."""
        orig = service.DecodeWorker._dataset_for

        def cold(self, spec):
            first = not self._datasets
            if first:
                time.sleep(1.0)  # >> the 400ms deadline below
            return orig(self, spec)

        monkeypatch.setattr(service.DecodeWorker, "_dataset_for", cold)
        w = service.DecodeWorker(dispatcher.addr).start()
        try:
            assert w.wait_registered(10)
            got = collect(data_dir, service=dispatcher.addr,
                          service_deadline_ms=400)
            assert got == local_rows
            assert METRICS.counter("service.reconnects") == 0
            assert dispatcher.status()["lease_reassignments"] == 0
        finally:
            w.stop()


class TestChaosAcceptance:
    def test_kill_worker_and_restart_dispatcher_mid_epoch(
        self, data_dir, local_rows, tmp_path
    ):
        """THE acceptance scenario (ISSUE 8): K=3 decode-worker
        subprocesses feed M=2 consumers; mid-epoch one worker is
        SIGKILLed (a real process death — no atexit, no socket
        shutdown) and the dispatcher is killed and restarted from its
        journal. Both consumers' epochs complete byte-identical to a
        direct local read — exactly-once, nothing duplicated, nothing
        missing, and none of it via local fallback."""
        journal = str(tmp_path / "journal.json")
        d = service.ServiceDispatcher(lease_ttl_s=10.0, journal=journal).start()
        port = int(d.addr.rsplit(":", 1)[1])
        addr = d.addr
        procs = []
        restarted = []
        state = {"d": d}
        try:
            for _ in range(3):
                procs.append(_spawn_worker_proc(addr))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(state["d"].status()["workers"]) == 3:
                    break
                time.sleep(0.05)
            assert len(state["d"].status()["workers"]) == 3

            chaos_done = threading.Event()
            gate = threading.Barrier(3, timeout=120)  # 2 consumers + chaos

            def consume(out):
                ds = make_ds(data_dir, service=addr, service_deadline_ms=3000)
                rows = []
                paused = False
                with ds.batches() as it:
                    for b in it:
                        rows.extend(batch_to_rows(b, ds.schema))
                        if len(rows) >= 40 and not paused:
                            paused = True
                            gate.wait()  # both consumers mid-epoch
                            chaos_done.wait()  # chaos runs while we hold
                out.extend(rows)

            def chaos():
                gate.wait()
                # SIGKILL a worker that holds an active lease right now
                leases = {
                    w["worker_id"]: w for w in state["d"].status()["workers"]
                    if w["leases"]
                }
                victim_id = next(iter(leases)) if leases else None
                for proc, ready in procs:
                    if victim_id is None or ready["worker_id"] == victim_id:
                        os.kill(proc.pid, signal.SIGKILL)
                        proc.wait()
                        break
                # kill + restart the dispatcher on the same port, same
                # journal — mid-epoch, while the SIGKILL is still fresh
                state["d"].stop()
                state["d"] = service.ServiceDispatcher(
                    port=port, lease_ttl_s=10.0, journal=journal
                ).start()
                restarted.append(state["d"])
                chaos_done.set()

            outs = [[], []]
            threads = [
                threading.Thread(target=consume, args=(outs[k],))
                for k in range(2)
            ]
            threads.append(threading.Thread(target=chaos))
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "acceptance run wedged"
            assert outs[0] == local_rows
            assert outs[1] == local_rows
            assert METRICS.counter("service.fallbacks") == 0
            assert METRICS.counter("service.reconnects") >= 1
        finally:
            for proc, _ in procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc, _ in procs:
                if proc.poll() is None:
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        proc.kill()
            state["d"].stop()
