"""Tier-2 tests for the TPU ingest slice on the 8-device CPU mesh:
dataset -> columnar batches -> dense host batches -> sharded jax.Array.

The "minimum end-to-end slice" of SURVEY.md §7.6: README-style schema,
round-trip into a sharded array on Mesh(('data',)), verified by value.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import tpu_tfrecord.io as tfio
from tpu_tfrecord.columnar import ColumnarDecoder
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    ArrayType,
    FloatType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import TFRecordSerializer, encode_row
from tpu_tfrecord.tpu import (
    assign_shards,
    batch_spec,
    create_mesh,
    data_sharding,
    DeviceIterator,
    HostPrefetcher,
    hash_bytes_column,
    host_batch_from_columnar,
    make_global_batch,
)

SCHEMA = StructType(
    [
        StructField("uid", LongType()),
        StructField("score", FloatType()),
        StructField("emb", ArrayType(FloatType())),
        StructField("cat", StringType()),
    ]
)


def write_dataset(sandbox, n=32):
    out = str(sandbox / "ingest")
    rows = [[i, i / 2.0, [float(i), float(i + 1), float(i + 2)], f"cat{i % 4}"] for i in range(n)]
    tfio.write(rows, SCHEMA, out, mode="overwrite")
    return out


class TestMesh:
    def test_create_default_mesh(self):
        mesh = create_mesh()
        assert mesh.shape["data"] == 8

    def test_create_2d_mesh(self):
        mesh = create_mesh({"data": -1, "model": 2})
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    def test_bad_mesh_rejected(self):
        with pytest.raises(ValueError):
            create_mesh({"data": 3})
        with pytest.raises(ValueError):
            create_mesh({"a": -1, "b": -1})

    def test_assign_shards_deterministic_interleave(self, sandbox):
        out = write_dataset(sandbox)
        shards = tfio.discover_shards(out)
        a = assign_shards(shards, process_index=0, process_count=2)
        b = assign_shards(shards, process_index=1, process_count=2)
        assert {s.path for s in a} | {s.path for s in b} == {s.path for s in shards}
        assert not ({s.path for s in a} & {s.path for s in b})


class TestHostBatch:
    def test_dense_host_batch(self, sandbox):
        out = write_dataset(sandbox, n=8)
        ds = TFRecordDataset(out, batch_size=8, schema=SCHEMA)
        with ds.batches() as it:
            cb = next(it)
        hb = host_batch_from_columnar(
            cb, ds.schema, pad_to={"emb": 4}, hash_buckets={"cat": 16}
        )
        assert hb["uid"].shape == (8,)
        assert hb["emb"].shape == (8, 4)
        np.testing.assert_allclose(hb["emb"][0], [0.0, 1.0, 2.0, 0.0])
        np.testing.assert_array_equal(hb["emb_len"], [3] * 8)
        assert hb["cat"].dtype == np.int32
        assert (hb["cat"] < 16).all() and (hb["cat"] >= 0).all()

    def test_hashing_is_deterministic(self):
        a = hash_bytes_column([b"x", b"y", b"x"], 1000)
        b = hash_bytes_column([b"x", b"y", b"x"], 1000)
        np.testing.assert_array_equal(a, b)
        assert a[0] == a[2]

    def test_batch_spec_matches_host_batch(self, sandbox):
        out = write_dataset(sandbox, n=8)
        ds = TFRecordDataset(out, batch_size=8, schema=SCHEMA)
        spec = batch_spec(ds.schema, 8, pad_to={"emb": 4}, hash_buckets={"cat": 16})
        with ds.batches() as it:
            hb = host_batch_from_columnar(
                next(it), ds.schema, pad_to={"emb": 4}, hash_buckets={"cat": 16}
            )
        assert set(spec) == set(hb)
        for name, s in spec.items():
            assert hb[name].shape == s.shape, name
            assert hb[name].dtype == s.dtype, name


class TestShardedIngest:
    def test_global_batch_sharded_on_data_axis(self, sandbox):
        out = write_dataset(sandbox, n=16)
        mesh = create_mesh()
        ds = TFRecordDataset(out, batch_size=16, schema=SCHEMA)
        with ds.batches() as it:
            hb = host_batch_from_columnar(
                next(it), ds.schema, pad_to={"emb": 4}, hash_buckets={"cat": 8}
            )
        gb = make_global_batch(hb, mesh)
        arr = gb["uid"]
        assert isinstance(arr, jax.Array)
        assert arr.shape == (16,)
        assert arr.sharding.spec == P("data")
        # every device holds 2 rows
        assert {s.data.shape for s in arr.addressable_shards} == {(2,)}
        np.testing.assert_array_equal(np.asarray(arr), np.asarray(hb["uid"]))
        assert gb["emb"].shape == (16, 4)
        assert gb["emb"].sharding.spec == P("data", None)

    def test_jit_consumes_sharded_batch(self, sandbox):
        """The aha slice: decoded records feed a jit computation over the mesh
        and come back correctly reduced."""
        out = write_dataset(sandbox, n=16)
        mesh = create_mesh()
        ds = TFRecordDataset(out, batch_size=16, schema=SCHEMA)
        with ds.batches() as it:
            hb = host_batch_from_columnar(next(it), ds.schema, pad_to={"emb": 3})
        gb = make_global_batch(hb, mesh)

        @jax.jit
        def step(emb, score):
            return (emb.sum(axis=1) * score).sum()

        got = step(gb["emb"], gb["score"])
        want = (hb["emb"].sum(axis=1) * hb["score"]).sum()
        np.testing.assert_allclose(float(got), float(want), rtol=1e-6)

    def test_device_iterator_double_buffers(self, sandbox):
        out = write_dataset(sandbox, n=32)
        mesh = create_mesh()
        ds = TFRecordDataset(out, batch_size=8, schema=SCHEMA)

        def host_batches():
            with ds.batches() as it:
                for cb in it:
                    yield host_batch_from_columnar(cb, ds.schema, pad_to={"emb": 3})

        count = 0
        seen_uids = []
        for gb in DeviceIterator(host_batches(), mesh):
            assert gb["uid"].sharding.spec == P("data")
            seen_uids.extend(np.asarray(gb["uid"]).tolist())
            count += 1
        assert count == 4
        assert sorted(seen_uids) == list(range(32))

    def test_device_iterator_transfer_thread_equivalent(self, sandbox):
        """transfer_thread=True must yield the same device batches in the
        same order as the inline path (the worker only moves WHERE the copy
        blocks, never what arrives)."""
        out = write_dataset(sandbox, n=32)
        mesh = create_mesh()
        ds = TFRecordDataset(out, batch_size=8, schema=SCHEMA)

        def host_batches():
            with ds.batches() as it:
                for cb in it:
                    yield host_batch_from_columnar(cb, ds.schema, pad_to={"emb": 3})

        with DeviceIterator(host_batches(), mesh, transfer_thread=True) as dev_it:
            seen = []
            for gb in dev_it:
                assert gb["uid"].sharding.spec == P("data")
                seen.extend(np.asarray(gb["uid"]).tolist())
        assert sorted(seen) == list(range(32))
        # exhausted: a further next() raises StopIteration, not a hang
        import pytest as _pytest

        with _pytest.raises(StopIteration):
            next(dev_it)

    def test_device_iterator_transfer_thread_propagates_errors(self):
        mesh = create_mesh()
        n = mesh.devices.size

        def bad_batches():
            yield {"x": np.arange(n, dtype=np.int32)}
            raise RuntimeError("producer exploded")

        with DeviceIterator(bad_batches(), mesh, transfer_thread=True) as dev_it:
            next(dev_it)
            import pytest as _pytest

            with _pytest.raises(RuntimeError, match="producer exploded"):
                next(dev_it)

    def test_device_iterator_transfer_thread_close_mid_stream(self):
        """close() while the producer is still running must unblock and
        join the worker; later next() raises StopIteration."""
        mesh = create_mesh()
        n = mesh.devices.size

        def endless():
            i = 0
            while True:
                yield {"x": np.full((n,), i, dtype=np.int32)}
                i += 1

        dev_it = DeviceIterator(endless(), mesh, transfer_thread=True)
        next(dev_it)
        dev_it.close()
        import pytest as _pytest

        with _pytest.raises(StopIteration):
            next(dev_it)
        assert not dev_it._pf._thread.is_alive()

    def test_device_iterator_rebuilds_shardings_on_ndim_change(self):
        """Regression (ADVICE r2): the sharding cache was keyed only on dict
        keys — an array whose RANK changes between batches must rebuild its
        NamedSharding, not reuse a stale wrong-rank PartitionSpec."""
        mesh = create_mesh()
        n = mesh.devices.size
        batches = [
            {"x": np.arange(2 * n, dtype=np.int32).reshape(2 * n)},
            {"x": np.ones((2 * n, 3), dtype=np.int32)},
            {"x": np.arange(2 * n, dtype=np.int32).reshape(2 * n)},
        ]
        shapes = [gb["x"].shape for gb in DeviceIterator(iter(batches), mesh)]
        assert shapes == [(2 * n,), (2 * n, 3), (2 * n,)]


class TestSequenceIngest:
    def test_ragged2_to_dense_device_array(self, sandbox):
        schema = StructType(
            [
                StructField("id", LongType()),
                StructField("frames", ArrayType(ArrayType(FloatType()))),
            ]
        )
        rows = [
            [0, [[1.0, 2.0], [3.0]]],
            [1, [[4.0, 5.0, 6.0]]],
            [2, [[7.0]]],
            [3, [[8.0], [9.0], [10.0]]],
        ] * 2
        out = str(sandbox / "seq")
        tfio.write(rows, schema, out, mode="overwrite", recordType="SequenceExample")
        mesh = create_mesh()
        ds = TFRecordDataset(
            out, batch_size=8, schema=schema, recordType="SequenceExample"
        )
        with ds.batches() as it:
            cb = next(it)
        hb = host_batch_from_columnar(cb, ds.schema, pad_to={"frames": (4, 4)})
        assert hb["frames"].shape == (8, 4, 4)
        gb = make_global_batch(hb, mesh)
        assert gb["frames"].shape == (8, 4, 4)
        assert gb["frames_len"].shape == (8,)
        row0 = np.asarray(gb["frames"])[0]
        np.testing.assert_allclose(row0[0, :2], [1.0, 2.0])
        np.testing.assert_allclose(row0[1, 0], 3.0)

    def test_cast_fused_pad_bf16(self, sandbox):
        """``cast`` emits frames in bf16 (fused native pad+cast, numpy
        fallback) with values equal to pad-then-astype, and batch_spec
        reflects the override."""
        import ml_dtypes

        from tpu_tfrecord.tpu.ingest import batch_spec

        schema = StructType(
            [
                StructField("id", LongType()),
                StructField("frames", ArrayType(ArrayType(FloatType()))),
            ]
        )
        rows = [
            [0, [[1.5, 2.25], [3.0]]],
            [1, [[4.0, 5.0, 6.0]]],
            [2, [[7.0]]],
            [3, [[8.0], [9.0], [10.0]]],
        ]
        out = str(sandbox / "seqcast")
        tfio.write(rows, schema, out, mode="overwrite", recordType="SequenceExample")
        ds = TFRecordDataset(
            out, batch_size=4, schema=schema, recordType="SequenceExample"
        )
        pad_to = {"frames": (4, 4)}
        cast = {"frames": ml_dtypes.bfloat16}
        with ds.batches() as it:
            cb = next(it)
        plain = host_batch_from_columnar(cb, ds.schema, pad_to=pad_to)
        casted = host_batch_from_columnar(cb, ds.schema, pad_to=pad_to, cast=cast)
        assert casted["frames"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(
            casted["frames"].astype(np.float32),
            plain["frames"].astype(ml_dtypes.bfloat16).astype(np.float32),
        )
        np.testing.assert_array_equal(casted["frames_len"], plain["frames_len"])
        np.testing.assert_array_equal(
            casted["frames_inner_len"], plain["frames_inner_len"]
        )
        spec = batch_spec(ds.schema, 4, pad_to=pad_to, cast=cast)
        assert spec["frames"].dtype == ml_dtypes.bfloat16
        assert spec["frames"].shape == (4, 4, 4)
        # typo'd cast key errors eagerly (mirrors validate_hash_buckets)
        with pytest.raises(ValueError, match="no castable data column"):
            host_batch_from_columnar(
                cb, ds.schema, pad_to=pad_to, cast={"frame": ml_dtypes.bfloat16}
            )
        with pytest.raises(ValueError, match="no castable data column"):
            batch_spec(ds.schema, 4, pad_to=pad_to, cast={"frame": ml_dtypes.bfloat16})
        # casting a pack-group member would be silently skipped on the
        # native pushed-down path — must refuse loudly instead
        with pytest.raises(ValueError, match="pack group"):
            host_batch_from_columnar(
                cb, ds.schema, pad_to=pad_to,
                cast={"id": np.float32}, pack={"g": ["id"]},
            )


def _heavy_step(scan_length):
    """A device step of tunable weight: matmul chain via lax.scan, seeded
    from the batch so nothing is constant-folded (~10ms per scan iteration
    on these CPU devices)."""
    w = jax.random.normal(jax.random.key(0), (384, 384), dtype=jnp.float32)

    @jax.jit
    def step(w, batch):
        first = next(iter(batch.values()))
        x = jnp.broadcast_to(first.sum().astype(jnp.float32), (384, 384)) * 1e-9 + w

        def body(c, _):
            return jnp.tanh(c @ w) * 0.1, ()

        c, _ = jax.lax.scan(body, x, (), length=scan_length)
        return c.sum()

    return w, step


def _measure_duty(dev_it, w, step, n_steps, warmup=2):
    from tpu_tfrecord.tracing import DutyCycle

    for _ in range(warmup):  # compile + cache warmup outside the measurement
        jax.block_until_ready(step(w, next(dev_it)))
    duty = DutyCycle()
    for _ in range(n_steps):
        with duty.wait():
            gb = next(dev_it)
        with duty.step():
            jax.block_until_ready(step(w, gb))
    return duty


class TestDutyCycleOverlap:
    """Machine-check of the BASELINE.md >=95% duty-cycle claim (VERDICT r2
    weak #2): in a regime where device step-time exceeds host batch-time BY
    CONSTRUCTION, the live pipeline must keep the consumer's input-wait
    under 5% of wall time. Red/green: if overlap machinery regresses
    (prefetch lost, transfer not dispatched early, decoder blocking the
    consumer), duty drops below 0.95 and this fails."""

    def test_full_pipeline_duty_exceeds_95(self, sandbox):
        out = write_dataset(sandbox, n=512)
        mesh = create_mesh()
        ds = TFRecordDataset(out, batch_size=64, schema=SCHEMA, num_epochs=None,
                             prefetch=4)

        def host_batches():
            with ds.batches() as it:
                for cb in it:
                    yield host_batch_from_columnar(cb, ds.schema,
                                                   pad_to={"emb": 3})

        with HostPrefetcher(host_batches()) as pf:
            duty = _measure_duty(DeviceIterator(pf, mesh), *_heavy_step(40),
                                 n_steps=6)
        assert duty.value() >= 0.95, (
            f"duty cycle {duty.value():.3f} < 0.95 "
            f"(busy={duty.busy_seconds:.3f}s wait={duty.wait_seconds:.3f}s)"
        )

    def test_host_prefetcher_hides_expensive_batch_assembly(self):
        """Sensitivity proof for the check above: with host batch production
        costing ~1/3 of a step (a stand-in for heavy pad/pack/hash work),
        the SERIALIZED pipeline measurably fails the 95% bar while the
        HostPrefetcher-overlapped one passes it — so a regression that
        silently serializes batch assembly turns this red."""
        import time

        mesh = create_mesh()
        n = mesh.devices.size
        w, step = _heavy_step(20)

        # Calibrate the producer cost to the MEASURED step time on this
        # machine (a hard-coded sleep breaks on faster hosts where the
        # producer could no longer keep up). The probe input must be
        # sharded exactly like the loop's batches: with an unsharded probe
        # the scan runs on one device instead of replicated on all 8, which
        # under-measures the step ~8x on this box. cost ~ step/2 keeps the
        # producer comfortably ahead overlapped, yet far over the 5% wait
        # budget serialized.
        probe = make_global_batch({"x": np.zeros((2 * n,), dtype=np.float32)},
                                  mesh)
        jax.block_until_ready(step(w, probe))  # compile
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(step(w, probe))
            times.append(time.perf_counter() - t0)
        cost = max(min(times) / 2, 0.002)

        def slow_batches(count=12):
            for i in range(count):
                time.sleep(cost)  # stand-in for pad/pack/hash numpy work
                yield {"x": np.full((2 * n,), i, dtype=np.float32)}
        serial = _measure_duty(DeviceIterator(slow_batches(), mesh), w, step,
                               n_steps=6)
        with HostPrefetcher(slow_batches()) as pf:
            overlap = _measure_duty(DeviceIterator(pf, mesh), w, step,
                                    n_steps=6)
        assert serial.value() < 0.95, (
            f"regime not sensitive: serialized duty {serial.value():.3f} "
            "already passes — raise the producer cost"
        )
        assert overlap.value() >= 0.95, (
            f"duty cycle {overlap.value():.3f} < 0.95 with HostPrefetcher "
            f"(busy={overlap.busy_seconds:.3f}s wait={overlap.wait_seconds:.3f}s; "
            f"serialized baseline {serial.value():.3f})"
        )

    def test_host_prefetcher_finite_stream_terminates(self):
        """Exhaustion must re-raise StopIteration on every subsequent
        next(), not block on the empty queue (the _DONE sentinel arrives
        exactly once)."""
        mesh = create_mesh()
        n = mesh.devices.size
        batches = [{"x": np.full((2 * n,), i, dtype=np.int32)} for i in range(3)]
        with HostPrefetcher(iter(batches)) as pf:
            got = [int(gb["x"][0]) for gb in DeviceIterator(pf, mesh)]
            assert got == [0, 1, 2]
            with pytest.raises(StopIteration):
                next(pf)
            with pytest.raises(StopIteration):
                next(pf)

    def test_host_prefetcher_propagates_producer_exception(self):
        def bad():
            yield {"x": np.zeros(8, dtype=np.int32)}
            raise RuntimeError("decode exploded")

        with HostPrefetcher(bad()) as pf:
            next(pf)
            with pytest.raises(RuntimeError, match="decode exploded"):
                next(pf)
            with pytest.raises(RuntimeError, match="decode exploded"):
                next(pf)

    def test_host_prefetcher_next_after_close_raises(self):
        """next() on a closed prefetcher raises StopIteration rather than
        blocking forever on a queue whose producer is gone."""
        def gen():
            for i in range(100):
                yield {"x": np.full(8, i, dtype=np.int32)}

        pf = HostPrefetcher(gen())
        next(pf)
        pf.close()
        with pytest.raises(StopIteration):
            next(pf)
