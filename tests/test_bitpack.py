"""Transfer bit-packing: host pack / device unpack round-trip.

No reference analog (the JVM rows never crossed a device link); this pins
the TPU-first transfer-packing layer used by the ingest bench: hashed
bucket indices packed to their significant bits on the host, unpacked
bit-exactly inside the consumer's jit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_tfrecord.tpu.bitpack import pack_bits, pack_mixed, packed_width, unpack_bits


@pytest.mark.parametrize("bits", [1, 3, 7, 13, 20, 24, 31, 32])
@pytest.mark.parametrize("n_cols", [1, 2, 26, 40])
def test_round_trip_random(bits, n_cols):
    rng = np.random.default_rng(bits * 100 + n_cols)
    vals = rng.integers(0, 1 << bits, size=(64, n_cols)).astype(np.int64)
    packed = pack_bits(vals, bits)
    assert packed.shape == (64, packed_width(n_cols, bits))
    assert packed.dtype == np.int32
    out = np.asarray(jax.jit(unpack_bits, static_argnums=(1, 2))(packed, n_cols, bits))
    np.testing.assert_array_equal(out, vals.astype(np.int32))


@pytest.mark.parametrize("bits", [5, 20, 27])
def test_all_ones_straddle(bits):
    # max values exercise every bit lane including cross-lane straddles
    vals = np.full((8, 33), (1 << bits) - 1, dtype=np.int64)
    out = np.asarray(unpack_bits(pack_bits(vals, bits), 33, bits))
    np.testing.assert_array_equal(out, vals.astype(np.int32))


def test_width_savings():
    # the motivating case: 26 cats at 20 bits -> 17 lanes instead of 26
    assert packed_width(26, 20) == 17
    assert packed_width(26, 32) == 26


def test_rejects_negative_and_bad_shape():
    with pytest.raises(ValueError, match="non-negative"):
        pack_bits(np.array([[-1, 2]], dtype=np.int64), 20)
    with pytest.raises(ValueError, match=r"\[B, C\]"):
        pack_bits(np.zeros(5, dtype=np.int32), 20)
    with pytest.raises(ValueError, match="bits"):
        packed_width(4, 0)


def test_bits32_passthrough_values():
    vals = np.array([[0, 1, (1 << 31) - 1]], dtype=np.int64)
    packed = pack_bits(vals, 32)
    np.testing.assert_array_equal(packed, vals.astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(unpack_bits(jnp.asarray(packed), 3, 32)), vals.astype(np.int32)
    )
    # [2**31, 2**32): bit pattern preserved, read back as int32 reinterpretation
    big = np.array([[3_000_000_000]], dtype=np.int64)
    out = pack_bits(big, 32)
    assert out[0, 0] == np.uint32(3_000_000_000).view(np.int32)
    # negatives rejected at every width, including 32
    with pytest.raises(ValueError, match="non-negative"):
        pack_bits(np.array([[-5]], dtype=np.int64), 32)


def test_unpack_under_sharding():
    """Unpack composes with the data-sharded global batch on the 8-dev mesh."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(0)
    vals = rng.integers(0, 1 << 20, size=(32, 26)).astype(np.int64)
    packed = pack_bits(vals, 20)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    gb = jax.device_put(packed, NamedSharding(mesh, P("data", None)))
    out = jax.jit(lambda p: unpack_bits(p, 26, 20))(gb)
    np.testing.assert_array_equal(np.asarray(out), vals.astype(np.int32))


@pytest.mark.parametrize("dtype", [np.int32, np.int64])
@pytest.mark.parametrize("bits", [1, 7, 20, 31, 32])
@pytest.mark.parametrize("keep,c", [(0, 26), (14, 26), (3, 1), (5, 0)])
def test_pack_mixed_equals_reference(dtype, bits, keep, c):
    """pack_mixed == concat + pack_bits; int32 input takes the native
    kernel (when built), int64 the numpy fallback — both bit-identical."""
    rng = np.random.default_rng(bits + keep)
    arr = np.concatenate(
        [
            rng.integers(0, 1 << 31, size=(37, keep)),
            rng.integers(0, min(1 << bits, 1 << 31), size=(37, c)),
        ],
        axis=1,
    ).astype(dtype)
    got = pack_mixed(arr, keep, bits)
    ref = np.concatenate(
        [arr[:, :keep].astype(np.int32), pack_bits(arr[:, keep:].astype(np.int64), bits)],
        axis=1,
    )
    np.testing.assert_array_equal(got, ref)
    # and the round trip through the device-side unpack
    if c:
        out = np.asarray(unpack_bits(got[:, keep:], c, bits))
        np.testing.assert_array_equal(
            out, (arr[:, keep:].astype(np.int64) & ((1 << bits) - 1)).astype(np.int32)
        )


@pytest.mark.parametrize("dtype", [np.int32, np.int64])
def test_pack_mixed_rejects_bad_args(dtype):
    arr = np.zeros((4, 6), dtype=dtype)
    with pytest.raises(ValueError, match="keep"):
        pack_mixed(arr, 7, 20)
    with pytest.raises(ValueError, match="non-negative"):
        # negative in a PACKED column — caught by the kernel's packing pass
        # (int32/native) or the fallback's scan (int64/numpy)
        bad = np.zeros((2, 3), dtype=dtype)
        bad[1, 2] = -1
        pack_mixed(bad, 1, 20)
    with pytest.raises(ValueError, match=r"\[B, C\]"):
        pack_mixed(np.zeros(3, dtype=np.int32), 0, 20)
    with pytest.raises(ValueError, match="bits"):
        pack_mixed(arr, 1, 0)  # validated before native dispatch
    with pytest.raises(ValueError, match="bits"):
        pack_mixed(arr, 1, 33)
    # negative values in KEEP lanes are fine (verbatim int32 transfer lanes)
    ok = np.full((2, 3), -7, dtype=dtype)
    out = pack_mixed(ok, 3, 20)
    np.testing.assert_array_equal(out, ok.astype(np.int32))


def test_bench_style_mixed_layout():
    """label+dense stay 32-bit, cats pack to 20 — the bench's [B,31] layout."""
    rng = np.random.default_rng(1)
    full = np.concatenate(
        [
            rng.integers(0, 2, size=(128, 1)),
            rng.integers(0, 1 << 31, size=(128, 13)),
            rng.integers(0, 1 << 20, size=(128, 26)),
        ],
        axis=1,
    ).astype(np.int64)
    wire_mat = np.concatenate(
        [full[:, :14].astype(np.int32), pack_bits(full[:, 14:], 20)], axis=1
    )
    assert wire_mat.shape == (128, 31)

    @jax.jit
    def consume(m):
        label = m[:, 0]
        dense = m[:, 1:14]
        cats = unpack_bits(m[:, 14:], 26, 20)
        return label, dense, cats

    label, dense, cats = consume(wire_mat)
    np.testing.assert_array_equal(np.asarray(label), full[:, 0].astype(np.int32))
    np.testing.assert_array_equal(np.asarray(dense), full[:, 1:14].astype(np.int32))
    np.testing.assert_array_equal(np.asarray(cats), full[:, 14:].astype(np.int32))
