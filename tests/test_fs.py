"""Pluggable-filesystem tests against fsspec's memory:// backend.

The reference reads/writes any Hadoop FileSystem (GCS/S3/HDFS) for free
(TFRecordOutputWriter.scala:19 CodecStreams, TFRecordFileReader.scala:24-32);
these pin the same pluggability through tpu_tfrecord.fs: full round trips,
save modes, partitionBy layout, codec streams, and the streaming dataset
reader, all on a non-local filesystem.
"""

import os
import uuid

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import fs as tfs
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.schema import (
    FloatType,
    LongType,
    StringType,
    StructField,
    StructType,
)

fsspec = pytest.importorskip("fsspec")

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("x", FloatType()),
        StructField("name", StringType()),
    ]
)
ROWS = [[i, i / 2.0, f"n{i}"] for i in range(20)]


@pytest.fixture
def mem_url():
    url = f"memory://fs-{uuid.uuid4().hex[:8]}"
    yield url
    mem = fsspec.filesystem("memory")
    try:
        mem.rm(url.split("://", 1)[1], recursive=True)
    except FileNotFoundError:
        pass


def test_filesystem_for_dispatch(tmp_path):
    assert isinstance(tfs.filesystem_for(str(tmp_path)), tfs.LocalFS)
    assert isinstance(tfs.filesystem_for("memory://x"), tfs.FsspecFS)
    assert not tfs.has_scheme("/plain/path")
    assert tfs.has_scheme("gs://bucket/key")


def test_round_trip_memory(mem_url):
    out = mem_url + "/ds"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite")
    assert tfio.has_success_marker(out)
    table = tfio.read(out, schema=SCHEMA)
    assert sorted(table.column("id")) == list(range(20))
    assert sorted(table.column("name"))[0] == "n0"


def test_schema_inference_memory(mem_url):
    out = mem_url + "/infer"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite")
    table = tfio.read(out)  # infers from the remote file bytes
    assert set(table.schema.names) == {"id", "x", "name"}


def test_save_modes_memory(mem_url):
    out = mem_url + "/modes"
    tfio.write(ROWS[:5], SCHEMA, out)
    with pytest.raises(FileExistsError):
        tfio.write(ROWS, SCHEMA, out, mode="error")
    # ignore: no-op
    tfio.write(ROWS, SCHEMA, out, mode="ignore")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 5
    # append adds
    tfio.write(ROWS[5:8], SCHEMA, out, mode="append")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 8
    # overwrite replaces
    tfio.write(ROWS[:3], SCHEMA, out, mode="overwrite")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 3


def test_partition_by_memory(mem_url):
    out = mem_url + "/pt"
    rows = [[i, float(i), f"g{i % 3}"] for i in range(9)]
    tfio.write(rows, SCHEMA, out, mode="overwrite", partition_by=["name"])
    fs = tfs.filesystem_for(out)
    entries = fs.listdir(out)
    assert sorted(e for e in entries if e.startswith("name=")) == [
        "name=g0",
        "name=g1",
        "name=g2",
    ]
    table = tfio.read(out)
    assert table.schema.names[-1] == "name"  # partition col appended
    assert sorted(table.column("id")) == list(range(9))


def test_gzip_codec_memory(mem_url):
    out = mem_url + "/gz"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite", codec="gzip")
    fs = tfs.filesystem_for(out)
    names = [n for n in fs.listdir(out) if n.endswith(".tfrecord.gz")]
    assert names, "gzip shard extension expected"
    table = tfio.read(out, schema=SCHEMA)
    assert sorted(table.column("id")) == list(range(20))


def test_streaming_dataset_memory(mem_url):
    out = mem_url + "/stream"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite")
    ds = TFRecordDataset(out, batch_size=8, schema=SCHEMA, drop_remainder=False)
    got = []
    with ds.batches() as it:
        for cb in it:
            got.extend(np.asarray(cb["id"].values).tolist())
    assert sorted(got) == list(range(20))


def test_glob_memory(mem_url):
    for sub in ("a", "b"):
        tfio.write(ROWS[:4], SCHEMA, mem_url + f"/glob/{sub}", mode="overwrite")
    table = tfio.read(mem_url + "/glob/*", schema=SCHEMA)
    assert len(table.rows) == 8


def test_walk_order_deterministic_memory(mem_url):
    """Directory recursion must be sorted (fsspec's own walk follows ls/dict
    order): every host must derive the SAME global shard order."""
    for sub in ["b", "a", "c"]:  # insertion order != sorted order
        tfio.write(ROWS[:2], SCHEMA, mem_url + f"/walk/{sub}", mode="overwrite")
    fs = tfs.filesystem_for(mem_url)
    seen = [p for p, _ in fs.walk_files(mem_url + "/walk", lambda n: not n.startswith("_"))]
    assert seen == sorted(seen)
    shards = tfio.discover_shards(mem_url + "/walk")
    assert [s.path for s in shards] == sorted(s.path for s in shards)


def test_local_walk_ignores_dir_symlink_cycles(tmp_path):
    """A symlink cycle inside the dataset must not hang discovery, and a
    symlink into the tree must not double-count shards (os.walk default)."""
    out = str(tmp_path / "ds")
    tfio.write([[1, 1.0, "a"]], SCHEMA, out, mode="overwrite")
    os.symlink(out, os.path.join(out, "loop"))
    shards = tfio.discover_shards(out)
    assert len(shards) == 1


def test_failed_write_leaves_no_partial_output_memory(mem_url):
    """A job that dies mid-write must leave NOTHING visible on the remote
    store: no data files, no _SUCCESS (the temp-dir commit protocol must
    hold on fsspec backends, not just local rename)."""
    out = mem_url + "/aborted"

    def exploding_rows():
        yield [1, 1.0, "a"]
        yield [2, 2.0, "b"]
        raise RuntimeError("upstream died")

    with pytest.raises(RuntimeError, match="upstream died"):
        tfio.write(exploding_rows(), SCHEMA, out, mode="error")
    fs = tfs.filesystem_for(out)
    if fs.exists(out):
        leftovers = [n for n in fs.listdir(out) if not n.startswith("_temporary")]
        assert leftovers == [], leftovers
    assert not tfio.has_success_marker(out)
    # and a retry with the same mode succeeds cleanly afterwards
    tfio.write(ROWS[:4], SCHEMA, out, mode="error")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 4


def test_scheme_errors_cleanly(monkeypatch):
    # unknown protocol should raise a clear error, not silently read nothing
    with pytest.raises(Exception):
        tfio.read("noproto42://bucket/x", schema=SCHEMA)
