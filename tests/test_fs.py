"""Pluggable-filesystem tests against fsspec's memory:// backend.

The reference reads/writes any Hadoop FileSystem (GCS/S3/HDFS) for free
(TFRecordOutputWriter.scala:19 CodecStreams, TFRecordFileReader.scala:24-32);
these pin the same pluggability through tpu_tfrecord.fs: full round trips,
save modes, partitionBy layout, codec streams, and the streaming dataset
reader, all on a non-local filesystem.
"""

import os
import uuid

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import fs as tfs
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.schema import (
    FloatType,
    LongType,
    StringType,
    StructField,
    StructType,
)

fsspec = pytest.importorskip("fsspec")

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("x", FloatType()),
        StructField("name", StringType()),
    ]
)
ROWS = [[i, i / 2.0, f"n{i}"] for i in range(20)]


@pytest.fixture
def mem_url():
    url = f"memory://fs-{uuid.uuid4().hex[:8]}"
    yield url
    mem = fsspec.filesystem("memory")
    try:
        mem.rm(url.split("://", 1)[1], recursive=True)
    except FileNotFoundError:
        pass


def test_filesystem_for_dispatch(tmp_path):
    assert isinstance(tfs.filesystem_for(str(tmp_path)), tfs.LocalFS)
    assert isinstance(tfs.filesystem_for("memory://x"), tfs.FsspecFS)
    assert not tfs.has_scheme("/plain/path")
    assert tfs.has_scheme("gs://bucket/key")


def test_round_trip_memory(mem_url):
    out = mem_url + "/ds"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite")
    assert tfio.has_success_marker(out)
    table = tfio.read(out, schema=SCHEMA)
    assert sorted(table.column("id")) == list(range(20))
    assert sorted(table.column("name"))[0] == "n0"


def test_schema_inference_memory(mem_url):
    out = mem_url + "/infer"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite")
    table = tfio.read(out)  # infers from the remote file bytes
    assert set(table.schema.names) == {"id", "x", "name"}


def test_save_modes_memory(mem_url):
    out = mem_url + "/modes"
    tfio.write(ROWS[:5], SCHEMA, out)
    with pytest.raises(FileExistsError):
        tfio.write(ROWS, SCHEMA, out, mode="error")
    # ignore: no-op
    tfio.write(ROWS, SCHEMA, out, mode="ignore")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 5
    # append adds
    tfio.write(ROWS[5:8], SCHEMA, out, mode="append")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 8
    # overwrite replaces
    tfio.write(ROWS[:3], SCHEMA, out, mode="overwrite")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 3


def test_partition_by_memory(mem_url):
    out = mem_url + "/pt"
    rows = [[i, float(i), f"g{i % 3}"] for i in range(9)]
    tfio.write(rows, SCHEMA, out, mode="overwrite", partition_by=["name"])
    fs = tfs.filesystem_for(out)
    entries = fs.listdir(out)
    assert sorted(e for e in entries if e.startswith("name=")) == [
        "name=g0",
        "name=g1",
        "name=g2",
    ]
    table = tfio.read(out)
    assert table.schema.names[-1] == "name"  # partition col appended
    assert sorted(table.column("id")) == list(range(9))


def test_gzip_codec_memory(mem_url):
    out = mem_url + "/gz"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite", codec="gzip")
    fs = tfs.filesystem_for(out)
    names = [n for n in fs.listdir(out) if n.endswith(".tfrecord.gz")]
    assert names, "gzip shard extension expected"
    table = tfio.read(out, schema=SCHEMA)
    assert sorted(table.column("id")) == list(range(20))


def test_streaming_dataset_memory(mem_url):
    out = mem_url + "/stream"
    tfio.write(ROWS, SCHEMA, out, mode="overwrite")
    ds = TFRecordDataset(out, batch_size=8, schema=SCHEMA, drop_remainder=False)
    got = []
    with ds.batches() as it:
        for cb in it:
            got.extend(np.asarray(cb["id"].values).tolist())
    assert sorted(got) == list(range(20))


def test_glob_memory(mem_url):
    for sub in ("a", "b"):
        tfio.write(ROWS[:4], SCHEMA, mem_url + f"/glob/{sub}", mode="overwrite")
    table = tfio.read(mem_url + "/glob/*", schema=SCHEMA)
    assert len(table.rows) == 8


def test_walk_order_deterministic_memory(mem_url):
    """Directory recursion must be sorted (fsspec's own walk follows ls/dict
    order): every host must derive the SAME global shard order."""
    for sub in ["b", "a", "c"]:  # insertion order != sorted order
        tfio.write(ROWS[:2], SCHEMA, mem_url + f"/walk/{sub}", mode="overwrite")
    fs = tfs.filesystem_for(mem_url)
    seen = [p for p, _ in fs.walk_files(mem_url + "/walk", lambda n: not n.startswith("_"))]
    assert seen == sorted(seen)
    shards = tfio.discover_shards(mem_url + "/walk")
    assert [s.path for s in shards] == sorted(s.path for s in shards)


def test_local_walk_ignores_dir_symlink_cycles(tmp_path):
    """A symlink cycle inside the dataset must not hang discovery, and a
    symlink into the tree must not double-count shards (os.walk default)."""
    out = str(tmp_path / "ds")
    tfio.write([[1, 1.0, "a"]], SCHEMA, out, mode="overwrite")
    os.symlink(out, os.path.join(out, "loop"))
    shards = tfio.discover_shards(out)
    assert len(shards) == 1


def test_failed_write_leaves_no_partial_output_memory(mem_url):
    """A job that dies mid-write must leave NOTHING visible on the remote
    store: no data files, no _SUCCESS (the temp-dir commit protocol must
    hold on fsspec backends, not just local rename)."""
    out = mem_url + "/aborted"

    def exploding_rows():
        yield [1, 1.0, "a"]
        yield [2, 2.0, "b"]
        raise RuntimeError("upstream died")

    with pytest.raises(RuntimeError, match="upstream died"):
        tfio.write(exploding_rows(), SCHEMA, out, mode="error")
    fs = tfs.filesystem_for(out)
    if fs.exists(out):
        leftovers = [n for n in fs.listdir(out) if not n.startswith("_temporary")]
        assert leftovers == [], leftovers
    assert not tfio.has_success_marker(out)
    # and a retry with the same mode succeeds cleanly afterwards
    tfio.write(ROWS[:4], SCHEMA, out, mode="error")
    assert len(tfio.read(out, schema=SCHEMA).rows) == 4


def test_scheme_errors_cleanly(monkeypatch):
    # unknown protocol should raise a clear error, not silently read nothing
    with pytest.raises(Exception):
        tfio.read("noproto42://bucket/x", schema=SCHEMA)


class TestIndependentReadHandles:
    """The explicit handle-capability flag (ISSUE 7 satellite, ROADMAP #3 /
    ADVICE #1): PrefetchReader may only run concurrent range fetches on a
    backend KNOWN to hand out one independent file object per open().
    Unknown backends default to the safe serialized path — slower, never
    silently corrupt — where the old protocol sniff defaulted them to the
    corrupting parallel path."""

    def _proto(self, proto):
        class _FS:
            protocol = proto

        return _FS()

    def test_known_object_stores_are_independent(self):
        for proto in ("s3", "gs", "gcs", "abfs", "http", "hdfs", "file"):
            assert tfs.independent_read_handles(self._proto(proto)), proto

    def test_memory_and_unknown_schemes_serialize(self):
        assert not tfs.independent_read_handles(self._proto("memory"))
        assert not tfs.independent_read_handles(self._proto("someproto42"))
        assert not tfs.independent_read_handles(object())  # no declaration
        assert not tfs.independent_read_handles(None)

    def test_multi_protocol_requires_all_known(self):
        assert tfs.independent_read_handles(self._proto(("gs", "gcs")))
        assert not tfs.independent_read_handles(self._proto(("gs", "weird")))

    def test_capability_flag_beats_protocol(self):
        # a wrapper/backend that KNOWS its handle semantics declares them,
        # overriding whatever the protocol classification would say
        class _IndependentUnknown:
            protocol = "someproto42"
            independent_read_handles = True

        class _SharedS3:
            protocol = "s3"
            independent_read_handles = False

        assert tfs.independent_read_handles(_IndependentUnknown())
        assert not tfs.independent_read_handles(_SharedS3())

    def test_walks_wrapper_chain(self):
        # FsspecFS/ChaosFS-style wrappers: the first declaration found
        # walking ._fs wins
        class _Inner:
            protocol = "s3"

        class _Wrapper:
            def __init__(self, inner):
                self._fs = inner

        assert tfs.independent_read_handles(_Wrapper(_Inner()))
        assert not tfs.independent_read_handles(_Wrapper(_Wrapper(object())))

        class _OptOutWrapper:
            # e.g. a caching wrapper that funnels every handle through one
            # shared buffer: declares, so the inner s3 is never consulted
            independent_read_handles = False

            def __init__(self, inner):
                self._fs = inner

        assert not tfs.independent_read_handles(_OptOutWrapper(_Inner()))

    def test_fsspec_memory_serializes_end_to_end(self, mem_url):
        # the real memory:// filesystem classifies as shared-handle
        mfs = tfs.filesystem_for(mem_url)
        assert not tfs.independent_read_handles(mfs)


class TestRemotePrefetch:
    """Block-pipelined remote readahead (VERDICT r4 item 3): N concurrent
    range fetches hide per-block link latency; a serial read pays it."""

    @staticmethod
    def _latency_fs(base_fs, per_read_s):
        """Wrap an FsspecFS so every read on every handle sleeps per_read_s
        first — a simulated high-RTT link whose handles, like a real object
        store's (and unlike fsspec memory://'s shared cursor), are
        INDEPENDENT and safe to use from concurrent fetch threads: each
        _SlowFile keeps its own position and serializes only the brief
        seek+read on the shared inner file, with the latency sleep outside
        the lock so concurrent range requests overlap like real GETs."""
        import threading
        import time as _time

        io_lock = threading.Lock()

        class _SlowFile:
            def __init__(self, inner):
                self._inner = inner
                self._pos = 0
                self._closed = False

            def seek(self, pos, whence=0):
                assert whence == 0
                self._pos = pos
                return pos

            def tell(self):
                return self._pos

            def read(self, size=-1):
                _time.sleep(per_read_s)  # the link RTT: outside the lock
                with io_lock:
                    self._inner.seek(self._pos)
                    data = self._inner.read(size)
                self._pos += len(data)
                return data

            def readinto(self, b):
                data = self.read(len(b))
                b[: len(data)] = data
                return len(data)

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                self.close()

            def close(self):
                self._closed = True

            @property
            def closed(self):
                return self._closed

        class _SlowFS:
            # each open() returns its own _SlowFile (own cursor): declare
            # the capability explicitly — "slowlink" is an unknown scheme,
            # which fs.independent_read_handles would otherwise serialize
            protocol = "slowlink"
            independent_read_handles = True

            def __init__(self, fs):
                self._fs = fs

            def open(self, path, mode):
                return _SlowFile(self._fs.open(path, mode))

            def __getattr__(self, name):
                return getattr(self._fs, name)

        return _SlowFS(base_fs)

    @pytest.mark.perf
    def test_prefetch_saturates_simulated_link(self, mem_url, monkeypatch):
        """With per-block latency L and depth D, a serial loop takes
        ~nblocks*L while the pipeline takes ~nblocks*L/D — assert a real
        win, and byte-exact equality with the serial read."""
        import time as _time

        nbytes = 24 << 20
        payload = bytes(np.random.default_rng(0).integers(0, 256, nbytes, np.uint8))
        path = mem_url + "/big.bin"
        fs = tfs.filesystem_for(path)
        with fs.open(path, "wb") as fh:
            fh.write(payload)
        monkeypatch.setenv("TFR_REMOTE_BLOCK_BYTES", str(2 << 20))
        monkeypatch.setenv("TFR_REMOTE_PREFETCH_DEPTH", "4")
        slow = self._latency_fs(fs, per_read_s=0.04)

        def drain(fh):
            # drain at the SAME granularity the link charges latency per
            # (one RTT per read call): 12 RTTs serial vs ceil(12/4) waves
            # pipelined — a 4x gap with real margin for per-block overhead
            out = []
            while True:
                chunk = fh.read(2 << 20)
                if not chunk:
                    return b"".join(out)
                out.append(chunk)

        t0 = _time.perf_counter()
        with slow.open(path, "rb") as fh:
            serial = drain(fh)
        t_serial = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        with tfs.open_for_read(slow, path) as fh:
            assert isinstance(fh, tfs.PrefetchReader)
            pipelined = drain(fh)
        t_pipe = _time.perf_counter() - t0
        assert pipelined == serial == payload
        # depth 4 should give ~4x; 1.8x is the regression bar (pool silently
        # degrading to serial)
        assert t_pipe < t_serial / 1.8, (t_serial, t_pipe)

    def test_dataset_read_uses_prefetch_and_matches(self, mem_url, monkeypatch):
        """End-to-end: a remote dataset big enough to engage the prefetcher
        decodes identically with pipelining on and off — and the pipelined
        leg PROVABLY routes through PrefetchReader (a block size above
        size/2 would silently fall back to the plain handle and compare two
        identical code paths)."""
        out = mem_url + "/ds"
        schema = StructType([StructField("x", LongType()), StructField("s", StringType())])
        rows = [[i, "v" * 64] for i in range(5000)]
        tfio.write(rows, schema, out, mode="overwrite")
        # ~0.6 MB shard: 128 KiB blocks satisfy open_for_read's
        # size >= 2*block engagement bar with blocks to spare
        monkeypatch.setenv("TFR_REMOTE_BLOCK_BYTES", str(128 << 10))
        built = []
        real_init = tfs.PrefetchReader.__init__
        monkeypatch.setattr(
            tfs.PrefetchReader,
            "__init__",
            lambda self, *a, **k: (built.append(1), real_init(self, *a, **k))[1],
        )

        def read_ids():
            ds = TFRecordDataset(out, batch_size=512, schema=schema,
                                 drop_remainder=False, use_mmap=False)
            got = []
            with ds.batches() as it:
                for cb in it:
                    got.extend(cb["x"].values.tolist())
            return got

        monkeypatch.setenv("TFR_REMOTE_PREFETCH_DEPTH", "4")
        with_prefetch = read_ids()
        assert built, "prefetcher never engaged — block bar not met?"
        monkeypatch.setenv("TFR_REMOTE_PREFETCH_DEPTH", "0")
        n_engaged = len(built)
        without = read_ids()
        assert len(built) == n_engaged, "depth=0 must disable the prefetcher"
        assert with_prefetch == without == list(range(5000))


def test_remote_gzip_streams_through_prefetcher(mem_url, monkeypatch):
    """A big compressed remote object: the codec wrapper must stream off
    PrefetchReader (raw block pipeline UNDER the gzip layer) and decode
    byte-identically to the plain handle."""
    import gzip

    path = mem_url + "/big.tfrecord.gz"
    fs = tfs.filesystem_for(path)
    rows = [[i, "pad" * 40] for i in range(40000)]
    schema = StructType([StructField("x", LongType()), StructField("s", StringType())])
    out = mem_url + "/gzds"
    tfio.write(rows, schema, out, mode="overwrite", codec="gzip")
    part = sorted(n for n in fs.listdir(out) if n.startswith("part-"))[0]
    size = fs.size(out + "/" + part)
    # block small enough that the object engages the prefetcher
    monkeypatch.setenv("TFR_REMOTE_BLOCK_BYTES", str(max(64 << 10, size // 8)))
    built = []
    real_init = tfs.PrefetchReader.__init__
    monkeypatch.setattr(
        tfs.PrefetchReader,
        "__init__",
        lambda self, *a, **k: (built.append(1), real_init(self, *a, **k))[1],
    )
    got = tfio.read(out, schema=schema)
    assert built, "gzip read did not engage the prefetcher"
    assert [r[0] for r in got.rows] == [r[0] for r in rows]
    assert got.rows[-1][1] == "pad" * 40
