"""Tier-2 tests: end-to-end dataset round-trips through the registered
'tfrecord' format — mirroring TFRecordIOSuite.scala plus the coverage gaps
SURVEY.md §4 lists (compression round-trip, multi-file read, inference
skipping empty files)."""

import decimal
import glob
import os

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import wire
from tpu_tfrecord.options import RecordType, TFRecordOptions
from tpu_tfrecord.registry import lookup_format
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)

SCHEMA = StructType(
    [
        StructField("id", IntegerType()),
        StructField("IntegerCol", IntegerType()),
        StructField("LongCol", LongType()),
        StructField("FloatCol", FloatType()),
        StructField("DoubleCol", DoubleType()),
        StructField("DecimalCol", DecimalType()),
        StructField("VectorCol", ArrayType(DoubleType())),
        StructField("StringCol", StringType()),
        StructField("BinaryCol", BinaryType()),
    ]
)

ROWS = [
    [11, 1, 23, 10.0, 14.0, decimal.Decimal("1.0"), [1.0, 2.0], "r1", b"\x01"],
    [21, 2, 24, 12.0, 15.0, decimal.Decimal("2.0"), [2.0, 2.0], "r2", b"\x02"],
    [31, 3, 25, 14.0, 16.0, decimal.Decimal("3.0"), [3.0, 2.0], "r3", b"\x03"],
]


def approx_row(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        if isinstance(w, decimal.Decimal):
            assert float(g) == pytest.approx(float(w), abs=1e-6)
        elif isinstance(w, float):
            assert g == pytest.approx(w, abs=1e-6)
        elif isinstance(w, list) and w and isinstance(w[0], float):
            assert g == pytest.approx(w, abs=1e-6)
        else:
            assert g == w


class TestExampleRoundTrip:
    """TFRecordIOSuite.scala:117-138."""

    def test_round_trip_with_user_schema(self, sandbox):
        out = str(sandbox / "example")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        table = tfio.read(out, schema=SCHEMA)
        assert table.schema == SCHEMA
        got = sorted(table.rows, key=lambda r: r[0])
        for g, w in zip(got, ROWS):
            approx_row(g, w)

    def test_round_trip_inferred_schema(self, sandbox):
        out = str(sandbox / "example2")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        table = tfio.read(out)
        # Inferred: Integer->long, Double/Decimal->float, Vector->array<float>
        m = {f.name: f.data_type for f in table.schema}
        assert m["id"] == LongType()
        assert m["DoubleCol"] == FloatType()
        assert m["VectorCol"] == ArrayType(FloatType())
        ids = sorted(table.column("id"))
        assert ids == [11, 21, 31]

    def test_success_marker_written(self, sandbox):
        out = str(sandbox / "marker")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        assert tfio.has_success_marker(out)

    def test_column_pruning(self, sandbox):
        out = str(sandbox / "prune")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        table = tfio.read(out, schema=SCHEMA, columns=["StringCol", "id"])
        assert table.schema.names == ["StringCol", "id"]
        assert sorted(table.rows) == [["r1", 11], ["r2", 21], ["r3", 31]]


class TestPartitionBy:
    """TFRecordIOSuite.scala:140-151 + README partitionBy example."""

    SCHEMA = StructType(
        [StructField("number", LongType()), StructField("word", StringType())]
    )
    ROWS = [[8, "bat"], [8, "abc"], [1, "xyz"], [2, "aaa"]]

    def test_layout_and_round_trip(self, sandbox):
        out = str(sandbox / "pt")
        tfio.write(self.ROWS, self.SCHEMA, out, mode="overwrite", partition_by=["number"])
        names = sorted(os.listdir(out))
        assert names == ["_SUCCESS", "number=1", "number=2", "number=8"]
        # partition column comes back (appended at the end) with long type
        table = tfio.read(out)
        assert table.schema.names == ["word", "number"]
        assert table.schema["number"].data_type == LongType()
        assert sorted(table.to_dicts(), key=lambda d: (d["number"], d["word"])) == [
            {"number": 1, "word": "xyz"},
            {"number": 2, "word": "aaa"},
            {"number": 8, "word": "abc"},
            {"number": 8, "word": "bat"},
        ]

    def test_multi_level_partitions(self, sandbox):
        schema = StructType(
            [
                StructField("date", StringType()),
                StructField("shard", LongType()),
                StructField("v", FloatType()),
            ]
        )
        rows = [["2026-01-01", 0, 1.0], ["2026-01-01", 1, 2.0], ["2026-01-02", 0, 3.0]]
        out = str(sandbox / "multi")
        tfio.write(rows, schema, out, mode="overwrite", partition_by=["date", "shard"])
        assert os.path.isdir(os.path.join(out, "date=2026-01-01", "shard=0"))
        table = tfio.read(out)
        assert table.schema.names == ["v", "date", "shard"]
        assert sorted(table.column("v")) == [1.0, 2.0, 3.0]

    def test_partition_value_escaping(self, sandbox):
        schema = StructType(
            [StructField("k", StringType()), StructField("v", LongType())]
        )
        rows = [["a/b:c", 1], [None, 2]]
        out = str(sandbox / "esc")
        tfio.write(rows, schema, out, mode="overwrite", partition_by=["k"])
        dirs = sorted(d for d in os.listdir(out) if d != "_SUCCESS")
        assert dirs == ["k=__HIVE_DEFAULT_PARTITION__", "k=a%2Fb%3Ac"]
        table = tfio.read(out)
        got = sorted(table.to_dicts(), key=lambda d: d["v"])
        assert got[0] == {"v": 1, "k": "a/b:c"}
        assert got[1] == {"v": 2, "k": None}

    def test_partition_column_not_written_to_records(self, sandbox):
        out = str(sandbox / "strip")
        tfio.write(self.ROWS, self.SCHEMA, out, mode="overwrite", partition_by=["number"])
        f = glob.glob(os.path.join(out, "number=8", "*.tfrecord"))[0]
        from tpu_tfrecord import proto

        recs = [proto.parse_example(r) for r in wire.read_records(f)]
        for r in recs:
            assert set(r.features) == {"word"}

    def test_all_columns_partition_rejected(self, sandbox):
        with pytest.raises(ValueError):
            tfio.write(
                [[1]],
                StructType([StructField("x", LongType())]),
                str(sandbox / "bad"),
                partition_by=["x"],
            )


class TestPartitionTypeInference:
    """Strict numeric classification: values Python's int()/float() accept
    but JVM parsing (the reference's substrate) rejects must stay strings."""

    def test_strict_long_and_double(self):
        from tpu_tfrecord.io.paths import infer_partition_type
        from tpu_tfrecord.schema import DoubleType as D, LongType as L, StringType as S

        assert infer_partition_type(["1", "-2", "+3"]) == L()
        assert infer_partition_type(["1", "2.5"]) == D()
        assert infer_partition_type(["1e3", ".5", "3.", "-1.5E-2"]) == D()
        # Java Long.parseLong does not trim; Double.parseDouble does and
        # accepts exact-case NaN/Infinity
        assert infer_partition_type([" 1", "1 ", " 1.5 "]) == D()
        assert infer_partition_type(["NaN", "Infinity", "-Infinity", "2.5"]) == D()
        for v in ["1_0", "inf", "nan", "infinity", "0x10", "1.0f", "", " "]:
            assert infer_partition_type([v]) == S(), v
        # one string value demotes the whole column
        assert infer_partition_type(["1", "1_0"]) == S()
        # None (HIVE default partition) does not affect classification
        assert infer_partition_type([None, "4"]) == L()


class TestStrictOptions:
    def test_unknown_option_raises_with_did_you_mean(self):
        from tpu_tfrecord.options import TFRecordOptions

        with pytest.raises(ValueError, match="verifyCrc"):
            TFRecordOptions.from_map({"verifyCRC": "true"})
        with pytest.raises(ValueError, match="codec"):
            TFRecordOptions.from_map({"codec_": "gzip"})
        with pytest.raises(ValueError, match="Unknown option"):
            TFRecordOptions.from_map({"utterly_bogus_key": 1})

    def test_unknown_option_raises_through_read_api(self, sandbox):
        schema = StructType([StructField("x", LongType())])
        out = str(sandbox / "strict")
        tfio.write([[1]], schema, out, mode="overwrite")
        with pytest.raises(ValueError, match="recordType"):
            tfio.read(out, recordtype="Example")  # typo'd case


class TestSequenceExampleRoundTrip:
    """TFRecordIOSuite.scala:153-167."""

    def test_round_trip(self, sandbox):
        schema = StructType(
            [
                StructField("id", LongType()),
                StructField("FloatArrayOfArray", ArrayType(ArrayType(FloatType()))),
                StructField("StrArrayOfArray", ArrayType(ArrayType(StringType()))),
            ]
        )
        rows = [
            [1, [[1.0, 2.0], [3.0]], [["a"], ["b", "c"]]],
            [2, [[5.0]], [["z"]]],
        ]
        out = str(sandbox / "seq")
        tfio.write(rows, schema, out, mode="overwrite", recordType="SequenceExample")
        table = tfio.read(out, schema=schema, recordType="SequenceExample")
        assert sorted(table.rows, key=lambda r: r[0]) == rows
        # inferred
        t2 = tfio.read(out, recordType="SequenceExample")
        m = {f.name: f.data_type for f in t2.schema}
        assert m["FloatArrayOfArray"] == ArrayType(ArrayType(FloatType()))


class TestByteArrayRoundTrip:
    """TFRecordIOSuite.scala:169-182."""

    def test_round_trip(self, sandbox):
        schema = StructType([StructField("byteArray", BinaryType())])
        rows = [[b"raw-1"], [b"\x00\xff"], [b""]]
        out = str(sandbox / "bytes")
        tfio.write(rows, schema, out, mode="overwrite", recordType="ByteArray")
        table = tfio.read(out, recordType="ByteArray")
        assert table.schema.names == ["byteArray"]
        assert sorted(table.column("byteArray")) == sorted(r[0] for r in rows)


class TestSaveModes:
    """TFRecordIOSuite.scala:184-237."""

    def test_overwrite_replaces(self, sandbox):
        out = str(sandbox / "ow")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        tfio.write(ROWS[:1], SCHEMA, out, mode="overwrite")
        assert len(tfio.read(out, schema=SCHEMA)) == 1

    def test_append_accumulates(self, sandbox):
        out = str(sandbox / "ap")
        tfio.write(ROWS, SCHEMA, out, mode="append")
        tfio.write(ROWS, SCHEMA, out, mode="append")
        assert len(tfio.read(out, schema=SCHEMA)) == 6

    def test_error_if_exists(self, sandbox):
        out = str(sandbox / "er")
        tfio.write(ROWS, SCHEMA, out)
        with pytest.raises(FileExistsError):
            tfio.write(ROWS, SCHEMA, out)  # default mode = error

    def test_ignore_leaves_files_untouched(self, sandbox):
        out = str(sandbox / "ig")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        files_before = {
            f: os.path.getmtime(os.path.join(out, f)) for f in os.listdir(out)
        }
        tfio.write(ROWS[:1], SCHEMA, out, mode="ignore")
        files_after = {
            f: os.path.getmtime(os.path.join(out, f)) for f in os.listdir(out)
        }
        assert files_before == files_after

    def test_unknown_mode_rejected(self, sandbox):
        with pytest.raises(ValueError):
            tfio.write(ROWS, SCHEMA, str(sandbox / "x"), mode="clobber")


class TestCompression:
    """Coverage gap in the reference: no codec round-trip test (SURVEY §4)."""

    @pytest.mark.parametrize("codec,ext", [("gzip", ".gz"), ("deflate", ".deflate")])
    def test_compressed_round_trip(self, sandbox, codec, ext):
        out = str(sandbox / f"comp-{codec}")
        files = tfio.write(ROWS, SCHEMA, out, mode="overwrite", codec=codec)
        assert all(f.endswith(".tfrecord" + ext) for f in files)
        table = tfio.read(out, schema=SCHEMA)  # codec inferred from extension
        assert len(table) == 3

    def test_hadoop_codec_class_name(self, sandbox):
        out = str(sandbox / "hadoopcodec")
        files = tfio.write(
            ROWS, SCHEMA, out, mode="overwrite",
            codec="org.apache.hadoop.io.compress.GzipCodec",
        )
        assert all(f.endswith(".tfrecord.gz") for f in files)


class TestMultiFileAndInference:
    """Coverage gaps: multi-file read; inference picks first non-empty file."""

    def test_multi_file_read_and_glob(self, sandbox):
        out1, out2 = str(sandbox / "m1"), str(sandbox / "m2")
        tfio.write(ROWS[:2], SCHEMA, out1, mode="overwrite")
        tfio.write(ROWS[2:], SCHEMA, out2, mode="overwrite")
        table = tfio.read([out1, out2], schema=SCHEMA)
        assert len(table) == 3
        table_glob = tfio.read(str(sandbox / "m*"), schema=SCHEMA)
        assert len(table_glob) == 3

    def test_inference_skips_empty_files(self, sandbox):
        out = str(sandbox / "withempty")
        os.makedirs(out)
        # an empty file sorts first
        open(os.path.join(out, "part-00000-aaa.tfrecord"), "wb").close()
        from tpu_tfrecord.serde import TFRecordSerializer, encode_row

        ser = TFRecordSerializer(SCHEMA)
        wire.write_records(
            os.path.join(out, "part-00001-bbb.tfrecord"),
            (encode_row(ser, RecordType.EXAMPLE, r) for r in ROWS),
        )
        table = tfio.read(out)
        assert len(table) == 3
        assert "id" in table.schema

    def test_no_input_files_raises(self, sandbox):
        with pytest.raises(FileNotFoundError):
            tfio.read(str(sandbox / "nope"))

    def test_empty_dir_inference_raises(self, sandbox):
        out = str(sandbox / "empty")
        os.makedirs(out)
        with pytest.raises(ValueError, match="infer schema"):
            tfio.read(out)

    def test_infer_schema_all_files_merges(self, sandbox):
        out = str(sandbox / "merge")
        s1 = StructType([StructField("x", LongType())])
        s2 = StructType([StructField("x", FloatType()), StructField("y", StringType())])
        tfio.write([[1]], s1, out, mode="append")
        tfio.write([[1.5, "a"]], s2, out, mode="append")
        r = tfio.reader(out)
        merged = r.infer_schema_all_files()
        m = {f.name: f.data_type for f in merged}
        assert m["x"] == FloatType()  # long+float -> float
        assert m["y"] == StringType()

    def test_infer_schema_all_files_parallel_equals_serial(self, sandbox):
        """Thread-pooled per-shard seqOp (the within-host analog of the
        reference's executor-parallel aggregate,
        TensorFlowInferSchema.scala:40-43) must produce the identical
        schema: partials merge in shard order, not completion order."""
        out = str(sandbox / "par")
        # heterogeneous shards exercise order-sensitive lattice merges
        shapes = [
            StructType([StructField("x", LongType())]),
            StructType([StructField("x", FloatType()), StructField("y", LongType())]),
            StructType([StructField("y", FloatType()), StructField("z", StringType())]),
            StructType([StructField("x", LongType()), StructField("z", StringType())]),
        ]
        rows = [[[1]], [[1.5, 2]], [[2.5, "s"]], [[7, "t"]]]
        for s, rws in zip(shapes, rows):
            tfio.write(rws, s, out, mode="append")
        r = tfio.reader(out)
        serial = r.infer_schema_all_files()
        for workers in (2, 8):
            assert r.infer_schema_all_files(num_workers=workers) == serial
        # single-process multihost entry: assign_shards keeps every shard,
        # the allgather degrades to identity, result identical (the real
        # >1-process leg runs in tests/test_multihost.py via the worker)
        assert r.infer_schema_multihost(num_workers=2) == serial

    @pytest.mark.perf
    @pytest.mark.skipif(
        (os.cpu_count() or 1) < 4,
        reason="needs >=4 cores to demonstrate inference scaling "
        "(runs on CI's multi-core runners; the TPU bench box has 1 core)",
    )
    def test_infer_schema_all_files_parallel_speedup(self, sandbox):
        """Wall-clock win on a multi-shard dataset (VERDICT r4 item 5).
        The per-shard seqOp is the native GIL-released wire walk, so a
        thread pool gives real scaling; shards are sized so per-shard work
        (~10ms native) dominates pool overhead."""
        import time as _time

        import numpy as np

        out = str(sandbox / "speed")
        schema = StructType(
            [StructField("a", LongType()), StructField("s", StringType())]
        )
        rng = np.random.default_rng(0)
        rows = [[int(v), "x" * 20] for v in rng.integers(0, 1 << 30, 40_000)]
        for _ in range(8):
            tfio.write(rows, schema, out, mode="append")
        r = tfio.reader(out)
        t0 = _time.perf_counter()
        serial = r.infer_schema_all_files()
        t_serial = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        parallel = r.infer_schema_all_files(num_workers=4)
        t_parallel = _time.perf_counter() - t0
        assert parallel == serial
        # conservative: any real pool on >=4 cores beats 1.3x easily; the
        # bar only needs to catch the pool silently degrading to serial
        assert t_parallel < t_serial / 1.3, (t_serial, t_parallel)


class TestRegistry:
    def test_lookup_format(self):
        ds = lookup_format("tfrecord")
        assert ds.short_name == "tfrecord"
        assert ds == lookup_format("TFRECORD")

    def test_unknown_format(self):
        with pytest.raises(ValueError):
            lookup_format("parquet-nope")


class TestSaveModeExistenceSemantics:
    """Spark parity: an existing-but-empty directory counts as 'exists' for
    error/ignore modes (path existence, not data-file presence)."""

    def test_error_on_empty_existing_dir(self, sandbox):
        out = str(sandbox / "emptydir")
        os.makedirs(out)
        with pytest.raises(FileExistsError):
            tfio.write(ROWS, SCHEMA, out)  # default ErrorIfExists

    def test_ignore_on_empty_existing_dir(self, sandbox):
        out = str(sandbox / "emptydir2")
        os.makedirs(out)
        assert tfio.write(ROWS, SCHEMA, out, mode="ignore") == []
        assert os.listdir(out) == []

    def test_overwrite_and_append_on_empty_dir_proceed(self, sandbox):
        out = str(sandbox / "emptydir3")
        os.makedirs(out)
        assert len(tfio.write(ROWS, SCHEMA, out, mode="overwrite")) > 0
        out2 = str(sandbox / "emptydir4")
        os.makedirs(out2)
        assert len(tfio.write(ROWS, SCHEMA, out2, mode="append")) > 0

    def test_failed_job_does_not_poison_retry(self, sandbox):
        """A failed first write must not leave an empty output dir that
        flips error/ignore semantics on retry (review regression)."""
        out = str(sandbox / "retry")

        def bad_rows():
            yield ROWS[0]
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            tfio.write(bad_rows(), SCHEMA, out)  # default mode=error
        assert not os.path.exists(out)
        # retry with fixed data now succeeds under the same mode
        assert len(tfio.write(ROWS, SCHEMA, out)) > 0

    def test_overwrite_preserves_other_jobs_temp(self, sandbox):
        """Overwrite clears data but must not delete another job's in-flight
        _temporary shards (review regression)."""
        out = str(sandbox / "owtemp")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        other = os.path.join(out, "_temporary", "other-job")
        os.makedirs(other)
        open(os.path.join(other, "inflight.tmp"), "wb").close()
        tfio.write(ROWS[:1], SCHEMA, out, mode="overwrite")
        assert os.path.exists(os.path.join(other, "inflight.tmp"))
        assert len(tfio.read(out, schema=SCHEMA)) == 1  # old data cleared


class TestUncoveredReadPaths:
    def test_inference_on_compressed_dataset(self, sandbox):
        out = str(sandbox / "gzinf")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite", codec="gzip")
        table = tfio.read(out)  # no schema: infer from .gz shards
        assert sorted(table.column("id")) == [11, 21, 31]

    def test_byte_array_with_partitions(self, sandbox):
        schema = StructType(
            [StructField("byteArray", BinaryType()), StructField("day", StringType())]
        )
        rows = [[b"p1", "a"], [b"p2", "b"]]
        out = str(sandbox / "bap")
        tfio.write(rows, schema, out, mode="overwrite", partition_by=["day"],
                   recordType="ByteArray")
        table = tfio.read(out, recordType="ByteArray")
        got = sorted(table.to_dicts(), key=lambda d: d["byteArray"])
        assert got == [{"byteArray": b"p1", "day": "a"}, {"byteArray": b"p2", "day": "b"}]

    def test_unknown_column_select_names_available(self, sandbox):
        out = str(sandbox / "badsel")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        with pytest.raises(ValueError, match="available"):
            tfio.read(out, schema=SCHEMA, columns=["id", "nope"])


class TestReadGuard:
    """read() materializes Python row lists — refuse huge datasets unless
    the caller opts in (VERDICT r2 weak #5)."""

    def test_limit_returns_head_and_closes_files(self, sandbox):
        from tpu_tfrecord.schema import LongType as LT

        schema = StructType([StructField("n", LT())])
        out = str(sandbox / "lim")
        tfio.write([[i] for i in range(50)], schema, out, mode="overwrite")
        table = tfio.read(out, schema=schema, limit=7)
        assert len(table) == 7
        assert tfio.read(out, schema=schema, limit=0).rows == []

    def test_oversized_dataset_refused_with_guidance(self, sandbox):
        out = str(sandbox / "big")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        with pytest.raises(ValueError, match="TFRecordDataset"):
            tfio.read(out, schema=SCHEMA, max_bytes=1)

    def test_limit_or_max_bytes_override_lifts_guard(self, sandbox):
        out = str(sandbox / "big2")
        tfio.write(ROWS, SCHEMA, out, mode="overwrite")
        assert len(tfio.read(out, schema=SCHEMA, max_bytes=1, limit=2)) == 2
        assert len(tfio.read(out, schema=SCHEMA, max_bytes=None)) == len(ROWS)
