"""Worker: distributed schema inference where THIS host's slice may be
corrupt. Proves the error-propagation contract of
DatasetReader.infer_schema_multihost: a local seqOp failure rides the
allgather instead of raising before it, so EVERY process raises the same
DistributedInferenceError (naming the failed process) rather than the
healthy peers hanging in the collective forever.

argv: coord num_procs pid data_dir
exit 7 = got the expected DistributedInferenceError; 1 = wrong outcome.
"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coord, num_procs, pid, data_dir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    )
    from tpu_tfrecord.tpu import distributed

    distributed.initialize(coord, num_procs, pid)

    import tpu_tfrecord.io as tfio
    from tpu_tfrecord.tpu.distributed import DistributedInferenceError

    try:
        schema = tfio.reader(data_dir).infer_schema_multihost(num_workers=2)
    except DistributedInferenceError as e:
        msg = str(e)
        # every process must see the SAME error, naming the corrupt slice's
        # owner (process 1 — the corrupt shard is second in sorted order)
        assert "process 1" in msg, msg
        assert "process 0" not in msg, msg
        print(f"pid {pid}: propagated ok: {msg}")
        sys.exit(7)
    print(f"pid {pid}: unexpectedly succeeded: {schema}")
    sys.exit(1)


if __name__ == "__main__":
    main()
