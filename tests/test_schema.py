"""Tier-1 tests for the schema model (StructType equivalent)."""

import numpy as np
import pytest

from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    NullType,
    StringType,
    StructField,
    StructType,
    numpy_dtype,
)


def full_schema():
    return StructType(
        [
            StructField("i", IntegerType(), False),
            StructField("l", LongType()),
            StructField("f", FloatType()),
            StructField("d", DoubleType()),
            StructField("dec", DecimalType()),
            StructField("s", StringType()),
            StructField("b", BinaryType()),
            StructField("al", ArrayType(LongType())),
            StructField("aas", ArrayType(ArrayType(StringType()))),
            StructField("n", NullType()),
        ]
    )


class TestStructType:
    def test_json_round_trip(self):
        schema = full_schema()
        assert StructType.from_json(schema.json()) == schema

    def test_field_lookup(self):
        schema = full_schema()
        assert schema.field_index("f") == 2
        assert schema["f"].data_type == FloatType()
        assert "f" in schema and "zzz" not in schema
        assert schema.names[0] == "i"

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError):
            StructType([StructField("x", LongType()), StructField("x", FloatType())])

    def test_equality_ignores_contains_null_like_reference_lattice(self):
        assert ArrayType(LongType(), True) == ArrayType(LongType(), False)
        assert ArrayType(LongType()) != ArrayType(FloatType())

    def test_add_select_drop(self):
        schema = StructType([StructField("a", LongType())])
        schema2 = schema.add("b", FloatType(), nullable=False)
        assert schema2.names == ["a", "b"]
        assert not schema2["b"].nullable
        assert schema2.select(["b"]).names == ["b"]
        assert schema2.drop(["a"]).names == ["b"]

    def test_decimal_identity(self):
        assert DecimalType() == DecimalType(10, 0)
        assert DecimalType(20, 2) != DecimalType()
        assert DecimalType(20, 2).simple_string() == "decimal(20,2)"

    def test_numpy_dtypes(self):
        assert numpy_dtype(IntegerType()) == np.int32
        assert numpy_dtype(LongType()) == np.int64
        assert numpy_dtype(FloatType()) == np.float32
        assert numpy_dtype(DoubleType()) == np.float64
        assert numpy_dtype(DecimalType()) == np.float64
        assert numpy_dtype(StringType()) is None
        assert numpy_dtype(ArrayType(FloatType())) == np.float32
