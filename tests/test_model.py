"""Tests for the flagship DLRM consumer + the driver entry points on the
8-device CPU mesh (dp x tp x sp shardings compile and execute)."""

import functools

import jax
import numpy as np
import optax
import pytest

from tpu_tfrecord.models import (
    DLRMConfig,
    forward,
    init_params,
    loss_fn,
    make_synthetic_batch,
    param_shardings,
    train_step,
)
from tpu_tfrecord.models.dlrm import batch_shardings
from tpu_tfrecord.tpu.mesh import create_mesh


class TestDLRM:
    def test_forward_shapes_and_dtype(self):
        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=4,
                         bottom_mlp=(8, 4), top_mlp=(8, 1))
        params = init_params(jax.random.key(0), cfg)
        batch = {k: jax.numpy.asarray(v) for k, v in make_synthetic_batch(cfg, 8).items()}
        logits = jax.jit(functools.partial(forward, cfg=cfg))(params, batch)
        assert logits.shape == (8,)
        assert logits.dtype == jax.numpy.float32

    def test_loss_decreases_under_training(self):
        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=4,
                         bottom_mlp=(8, 4), top_mlp=(8, 1))
        params = init_params(jax.random.key(1), cfg)
        batch = {k: jax.numpy.asarray(v) for k, v in make_synthetic_batch(cfg, 32).items()}
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        step = jax.jit(functools.partial(train_step, cfg=cfg, tx=tx))
        first = float(loss_fn(params, batch, cfg))
        for _ in range(20):
            params, opt_state, loss = step(params, opt_state, batch)
        assert float(loss) < first

    def test_sequence_tower(self):
        cfg = DLRMConfig(num_dense=2, num_categorical=2, vocab_size=8, embed_dim=4,
                         bottom_mlp=(4,), top_mlp=(4, 1), seq_len=6, seq_dim=3)
        params = init_params(jax.random.key(2), cfg)
        batch = {k: jax.numpy.asarray(v) for k, v in make_synthetic_batch(cfg, 4).items()}
        logits = forward(params, batch, cfg)
        assert logits.shape == (4,)
        # padding must not influence the pooled sequence features
        b2 = dict(batch)
        frames = np.asarray(batch["frames"]).copy()
        lens = np.asarray(batch["frames_len"])
        for i, l in enumerate(lens):
            frames[i, l:] = 999.0  # garbage in padded region
        b2["frames"] = jax.numpy.asarray(frames)
        logits2 = forward(params, b2, cfg)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2), rtol=2e-2)


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (32,)
        assert np.isfinite(np.asarray(out)).all()

    @pytest.mark.parametrize("n", [8, 4, 2, 1])
    def test_dryrun_multichip(self, n):
        import __graft_entry__ as ge

        ge.dryrun_multichip(n)


class TestShardedTrainStep:
    def test_tp_matches_replicated(self):
        """The tensor-parallel layout must compute the same loss as fully
        replicated params (collectives are inserted, not semantics changed)."""
        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=4,
                         bottom_mlp=(8, 4), top_mlp=(8, 1))
        params = init_params(jax.random.key(3), cfg)
        host = make_synthetic_batch(cfg, 16, seed=7)

        # replicated single-device loss
        batch1 = {k: jax.numpy.asarray(v) for k, v in host.items()}
        want = float(loss_fn(params, batch1, cfg))

        mesh = create_mesh({"data": 4, "model": 2})
        p_shard = param_shardings(mesh, params)
        sharded_params = jax.device_put(params, p_shard)
        b_shard = batch_shardings(mesh, host)
        batch = {
            k: jax.make_array_from_process_local_data(b_shard[k], v)
            for k, v in host.items()
        }
        got = float(jax.jit(functools.partial(loss_fn, cfg=cfg))(sharded_params, batch))
        assert got == pytest.approx(want, rel=2e-2)  # bf16 tolerance
