"""Tests for the flagship DLRM consumer + the driver entry points on the
8-device CPU mesh (dp x tp x sp shardings compile and execute)."""

import functools

import jax
import numpy as np
import optax
import pytest

from tpu_tfrecord.models import (
    DLRMConfig,
    forward,
    init_params,
    loss_fn,
    make_synthetic_batch,
    param_shardings,
    train_step,
)
from tpu_tfrecord.models.dlrm import batch_shardings
from tpu_tfrecord.tpu.mesh import create_mesh


class TestDLRM:
    def test_forward_shapes_and_dtype(self):
        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=4,
                         bottom_mlp=(8, 4), top_mlp=(8, 1))
        params = init_params(jax.random.key(0), cfg)
        batch = {k: jax.numpy.asarray(v) for k, v in make_synthetic_batch(cfg, 8).items()}
        logits = jax.jit(functools.partial(forward, cfg=cfg))(params, batch)
        assert logits.shape == (8,)
        assert logits.dtype == jax.numpy.float32

    def test_loss_decreases_under_training(self):
        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=4,
                         bottom_mlp=(8, 4), top_mlp=(8, 1))
        params = init_params(jax.random.key(1), cfg)
        batch = {k: jax.numpy.asarray(v) for k, v in make_synthetic_batch(cfg, 32).items()}
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        step = jax.jit(functools.partial(train_step, cfg=cfg, tx=tx))
        first = float(loss_fn(params, batch, cfg))
        for _ in range(20):
            params, opt_state, loss = step(params, opt_state, batch)
        assert float(loss) < first

    def test_sequence_tower(self):
        cfg = DLRMConfig(num_dense=2, num_categorical=2, vocab_size=8, embed_dim=4,
                         bottom_mlp=(4,), top_mlp=(4, 1), seq_len=6, seq_dim=3)
        params = init_params(jax.random.key(2), cfg)
        batch = {k: jax.numpy.asarray(v) for k, v in make_synthetic_batch(cfg, 4).items()}
        logits = forward(params, batch, cfg)
        assert logits.shape == (4,)
        # padding must not influence the pooled sequence features
        b2 = dict(batch)
        frames = np.asarray(batch["frames"]).copy()
        lens = np.asarray(batch["frames_len"])
        for i, l in enumerate(lens):
            frames[i, l:] = 999.0  # garbage in padded region
        b2["frames"] = jax.numpy.asarray(frames)
        logits2 = forward(params, b2, cfg)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2), rtol=2e-2)


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (32,)
        assert np.isfinite(np.asarray(out)).all()

    @pytest.mark.parametrize("n", [8, 4, 2, 1])
    def test_dryrun_multichip(self, n):
        import __graft_entry__ as ge

        ge.dryrun_multichip(n)


class TestSparseTrainStep:
    """sparse_train_step: embedding grads via gathered rows + scatter-add
    (no dense [F, V, D] gradient), row-wise AdaGrad on touched rows."""

    CFG = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=64, embed_dim=4,
                     bottom_mlp=(8, 4), top_mlp=(8, 1), dtype=jax.numpy.float32)

    @staticmethod
    def _dense_rowwise_adagrad_reference(params, opt_state, batch, cfg, tx,
                                         embed_lr=0.01, embed_eps=1e-8):
        """Oracle: full dense table gradient + row-wise AdaGrad applied
        densely. With dedup-first duplicate semantics (r4) this is exact for
        ANY index pattern — the dense gradient row IS the deduped sum
        (barring exact float cancellation making a touched row read zero)."""
        from tpu_tfrecord.models.dlrm import SparseEmbOptState

        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg)
        g_table = grads.pop("embeddings").astype(jax.numpy.float32)
        updates, dense_state = tx.update(
            grads, opt_state.dense, {k: v for k, v in params.items() if k != "embeddings"}
        )
        dense_params = jax.tree.map(
            lambda p, u: p + u,
            {k: v for k, v in params.items() if k != "embeddings"},
            updates,
        )
        touched = (g_table != 0).any(axis=-1)                       # [F, V]
        row_ms = (g_table * g_table).mean(axis=-1)                  # [F, V]
        accum = opt_state.accum + jax.numpy.where(touched, row_ms, 0.0)
        scale = embed_lr * jax.lax.rsqrt(accum + embed_eps)         # [F, V]
        table = params["embeddings"] - jax.numpy.where(
            touched[..., None], scale[..., None] * g_table, 0.0
        )
        return dict(dense_params, embeddings=table), SparseEmbOptState(dense_state, accum), loss

    def test_matches_dense_reference_without_duplicates(self):
        from tpu_tfrecord.models import sparse_opt_init, sparse_train_step

        cfg = self.CFG
        params = init_params(jax.random.key(5), cfg)
        host = make_synthetic_batch(cfg, 8, seed=11)
        # force DISTINCT indices per feature column (duplicate handling is
        # pinned separately below)
        rng = np.random.default_rng(3)
        for f in range(cfg.num_categorical):
            host["cat"][:, f] = rng.choice(cfg.vocab_size, size=8, replace=False)
        batch = {k: jax.numpy.asarray(v) for k, v in host.items()}
        tx = optax.sgd(1e-2)
        opt0 = sparse_opt_init(params, cfg, tx)

        got_p, got_s, got_l = jax.jit(
            functools.partial(sparse_train_step, cfg=cfg, tx=tx)
        )(params, opt0, batch)
        want_p, want_s, want_l = self._dense_rowwise_adagrad_reference(
            params, opt0, batch, cfg, tx
        )
        assert float(got_l) == pytest.approx(float(want_l), rel=1e-6)
        np.testing.assert_allclose(got_s.accum, want_s.accum, rtol=1e-5, atol=1e-8)
        np.testing.assert_allclose(
            got_p["embeddings"], want_p["embeddings"], rtol=1e-5, atol=1e-7
        )
        for (ga, wa) in zip(jax.tree.leaves(got_p["top"]), jax.tree.leaves(want_p["top"])):
            np.testing.assert_allclose(ga, wa, rtol=1e-5, atol=1e-7)

    def test_duplicate_indices_accumulate_exactly(self):
        from tpu_tfrecord.models import sparse_opt_init, sparse_train_step

        cfg = self.CFG
        params = init_params(jax.random.key(6), cfg)
        host = make_synthetic_batch(cfg, 6, seed=13)
        host["cat"][:] = 7  # every example hits the SAME row of every table
        batch = {k: jax.numpy.asarray(v) for k, v in host.items()}
        tx = optax.sgd(1e-2)
        opt0 = sparse_opt_init(params, cfg, tx)
        embed_lr, embed_eps = 0.01, 1e-8

        got_p, got_s, _ = jax.jit(
            functools.partial(sparse_train_step, cfg=cfg, tx=tx,
                              embed_lr=embed_lr, embed_eps=embed_eps)
        )(params, opt0, batch)

        # DEDUP-FIRST oracle (r4, matches dense row-wise AdaGrad / TF
        # IndexedSlices consumers): duplicates sum their row gradients
        # FIRST; the accumulator adds mean((sum g)^2) ONCE per unique row;
        # the scale from the post-accumulation value applies to the summed
        # gradient. The dense table gradient row IS the summed gradient.
        _, grads = jax.value_and_grad(loss_fn)(params, batch, cfg)
        g_table = np.asarray(grads["embeddings"], dtype=np.float32)

        for f in range(cfg.num_categorical):
            want_acc = float((g_table[f, 7] ** 2).mean())
            assert float(got_s.accum[f, 7]) == pytest.approx(want_acc, rel=1e-5)
            scale = embed_lr / np.sqrt(want_acc + embed_eps)
            want_row = np.asarray(params["embeddings"])[f, 7] - scale * g_table[f, 7]
            np.testing.assert_allclose(got_p["embeddings"][f, 7], want_row,
                                       rtol=1e-4, atol=1e-7)
            # untouched rows unchanged
            np.testing.assert_array_equal(
                got_p["embeddings"][f, 8], np.asarray(params["embeddings"])[f, 8]
            )

    def test_mixed_duplicate_group_sizes_match_dense_oracle(self):
        # Group sizes m VARY within one batch (indices drawn from a tiny
        # range): a bug wrong only when different-sized duplicate groups
        # coexist (e.g. a scale paired with the wrong group's m) passes
        # both the no-duplicates and the all-duplicates cases — this pins
        # the realistic skewed-index regime. Dedup-first semantics make the
        # dense row-wise AdaGrad oracle exact for ANY index pattern.
        from tpu_tfrecord.models import sparse_opt_init, sparse_train_step

        cfg = self.CFG
        params = init_params(jax.random.key(9), cfg)
        host = make_synthetic_batch(cfg, 64, seed=21)
        host["cat"] = np.random.default_rng(23).integers(
            0, 6, size=host["cat"].shape
        )  # ~10x duplication, uneven group sizes
        batch = {k: jax.numpy.asarray(v) for k, v in host.items()}
        tx = optax.sgd(1e-2)
        opt0 = sparse_opt_init(params, cfg, tx)
        got_p, got_s, got_l = jax.jit(
            functools.partial(sparse_train_step, cfg=cfg, tx=tx)
        )(params, opt0, batch)
        want_p, want_s, want_l = self._dense_rowwise_adagrad_reference(
            params, opt0, batch, cfg, tx
        )
        assert float(got_l) == pytest.approx(float(want_l), rel=1e-6)
        np.testing.assert_allclose(got_s.accum, want_s.accum, rtol=2e-5, atol=1e-9)
        np.testing.assert_allclose(
            got_p["embeddings"], want_p["embeddings"], rtol=2e-5, atol=1e-7
        )

    def test_pair_sort_path_matches_flat_keys(self, monkeypatch):
        """ADVICE close-out: for F*V > 2^31, flat int32 dedup keys would
        silently wrap (int64 is unavailable with x64 disabled), so the
        step switches to a lexicographic (f, v) pair sort. Both paths are
        stable sorts over the same total order, so the permutation — and
        therefore every update — is identical; pinned at test scale by
        shrinking the switch-over threshold."""
        from tpu_tfrecord.models import sparse_opt_init, sparse_train_step
        from tpu_tfrecord.models import dlrm as dlrm_mod

        # the sort seam itself, on skewed duplicate-heavy indices
        rng = np.random.default_rng(31)
        f_flat = jax.numpy.asarray(
            np.repeat(np.arange(3), 32).astype(np.int32)
        )
        v_flat = jax.numpy.asarray(rng.integers(0, 6, 96).astype(np.int32))
        flat = dlrm_mod._dedup_sort(f_flat, v_flat, 6, force_pairs=False)
        pairs = dlrm_mod._dedup_sort(f_flat, v_flat, 6, force_pairs=True)
        for got, want in zip(pairs, flat):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

        # and the full step end-to-end with the pair path forced
        cfg = self.CFG
        params = init_params(jax.random.key(12), cfg)
        host = make_synthetic_batch(cfg, 32, seed=33)
        host["cat"] = rng.integers(0, 6, size=host["cat"].shape)
        batch = {k: jax.numpy.asarray(v) for k, v in host.items()}
        tx = optax.sgd(1e-2)
        opt0 = sparse_opt_init(params, cfg, tx)
        step = functools.partial(sparse_train_step, cfg=cfg, tx=tx)
        want_p, want_s, want_l = jax.jit(step)(params, opt0, batch)
        monkeypatch.setattr(dlrm_mod, "_FLAT_KEY_MAX", 1)
        got_p, got_s, got_l = jax.jit(step)(params, opt0, batch)
        assert float(got_l) == pytest.approx(float(want_l), rel=1e-6)
        np.testing.assert_array_equal(
            np.asarray(got_s.accum), np.asarray(want_s.accum)
        )
        np.testing.assert_array_equal(
            np.asarray(got_p["embeddings"]), np.asarray(want_p["embeddings"])
        )

    def test_sharded_sparse_step_matches_single_device(self):
        from tpu_tfrecord.models import sparse_opt_init, sparse_train_step
        from tpu_tfrecord.models.dlrm import batch_shardings

        cfg = self.CFG
        params = init_params(jax.random.key(8), cfg)
        host = make_synthetic_batch(cfg, 16, seed=17)
        batch1 = {k: jax.numpy.asarray(v) for k, v in host.items()}
        tx = optax.sgd(1e-2)
        opt0 = sparse_opt_init(params, cfg, tx)
        want_p, _, want_l = jax.jit(
            functools.partial(sparse_train_step, cfg=cfg, tx=tx)
        )(params, opt0, batch1)

        mesh = create_mesh({"data": 4, "model": 2})
        p_shard = param_shardings(mesh, params)
        sharded_params = jax.device_put(params, p_shard)
        b_shard = batch_shardings(mesh, host)
        batch = {
            k: jax.make_array_from_process_local_data(b_shard[k], v)
            for k, v in host.items()
        }
        got_p, _, got_l = jax.jit(
            functools.partial(sparse_train_step, cfg=cfg, tx=tx)
        )(sharded_params, opt0, batch)
        assert float(got_l) == pytest.approx(float(want_l), rel=1e-5)
        np.testing.assert_allclose(
            np.asarray(got_p["embeddings"]), np.asarray(want_p["embeddings"]),
            rtol=1e-5, atol=1e-7,
        )


class TestShardedTrainStep:
    def test_tp_matches_replicated(self):
        """The tensor-parallel layout must compute the same loss as fully
        replicated params (collectives are inserted, not semantics changed)."""
        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=4,
                         bottom_mlp=(8, 4), top_mlp=(8, 1))
        params = init_params(jax.random.key(3), cfg)
        host = make_synthetic_batch(cfg, 16, seed=7)

        # replicated single-device loss
        batch1 = {k: jax.numpy.asarray(v) for k, v in host.items()}
        want = float(loss_fn(params, batch1, cfg))

        mesh = create_mesh({"data": 4, "model": 2})
        p_shard = param_shardings(mesh, params)
        sharded_params = jax.device_put(params, p_shard)
        b_shard = batch_shardings(mesh, host)
        batch = {
            k: jax.make_array_from_process_local_data(b_shard[k], v)
            for k, v in host.items()
        }
        got = float(jax.jit(functools.partial(loss_fn, cfg=cfg))(sharded_params, batch))
        assert got == pytest.approx(want, rel=2e-2)  # bf16 tolerance
