"""Pallas dot-interaction kernel vs the XLA reference (interpret mode on
CPU; the real-TPU compile/run is exercised by __graft_entry__ and bench)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_tfrecord.models.interaction import (
    dot_interaction,
    dot_interaction_pallas,
    dot_interaction_reference,
)


def make_emb(b=32, f=27, d=16, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(b, f, d)), dtype=dtype)


class TestDotInteraction:
    @pytest.mark.parametrize("b,f,d", [(32, 27, 16), (16, 4, 8), (64, 13, 32)])
    def test_kernel_matches_reference(self, b, f, d):
        emb = make_emb(b, f, d)
        want = dot_interaction_reference(emb)
        got = dot_interaction_pallas(emb, block_b=16, interpret=True)
        assert got.shape == (b, f * (f - 1) // 2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)

    def test_bf16(self):
        emb = make_emb(dtype=jnp.bfloat16)
        want = dot_interaction_reference(emb.astype(jnp.float32))
        got = dot_interaction_pallas(emb, block_b=32, interpret=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(want), rtol=3e-2, atol=3e-1
        )

    def test_non_divisible_batch_falls_back_to_gcd_tile(self):
        emb = make_emb(b=48)
        got = dot_interaction_pallas(emb, block_b=32, interpret=True)  # tile=gcd(48,32)=16
        want = dot_interaction_reference(emb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)

    def test_sub_sublane_tile_rejected_loudly(self):
        with pytest.raises(ValueError, match="pad the batch"):
            dot_interaction_pallas(make_emb(b=31), block_b=16, interpret=True)

    def test_gradient_through_pallas_branch(self):
        emb = make_emb(b=8, f=6, d=4)

        def loss_pallas(e):
            return (dot_interaction(e, True, 8, True) ** 2).sum()

        def loss_ref(e):
            return (dot_interaction_reference(e) ** 2).sum()

        g_p = jax.grad(loss_pallas)(emb)
        g_ref = jax.grad(loss_ref)(emb)
        np.testing.assert_allclose(np.asarray(g_p), np.asarray(g_ref), rtol=1e-4, atol=1e-5)

    def test_gradient_matches_reference(self):
        emb = make_emb(b=8, f=6, d=4)

        def loss_k(e):
            return (dot_interaction(e, False) ** 2).sum()

        def loss_ref(e):
            return (dot_interaction_reference(e) ** 2).sum()

        g_k = jax.grad(loss_k)(emb)
        g_ref = jax.grad(loss_ref)(emb)
        np.testing.assert_allclose(np.asarray(g_k), np.asarray(g_ref), rtol=1e-5)

    def test_dispatcher_cpu_uses_reference(self):
        emb = make_emb(b=8, f=5, d=4)
        got = dot_interaction(emb, None)  # cpu backend -> XLA reference
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(dot_interaction_reference(emb)), rtol=1e-6
        )

    def test_large_f_tiles_pair_dim(self):
        """P-tiled grid: F=64 gives P=2016 pairs, forcing multiple pair
        tiles (and padding) under a small block_p — results must still
        match the reference exactly (the pre-tiling kernel OOM'd VMEM
        here on real hardware)."""
        emb = make_emb(b=16, f=64, d=8)
        got = dot_interaction_pallas(emb, block_b=8, block_p=512, interpret=True)
        want = dot_interaction_reference(emb)
        assert got.shape == (16, 64 * 63 // 2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)

    def test_auto_block_b_shrink_preserves_divisibility(self):
        """b=20 with a huge D forces the VMEM-budget shrink; the shrink must
        land on a divisor of b or trailing rows silently vanish from the
        grid (regression: 20 -> 8 left rows 16-19 garbage)."""
        emb = make_emb(b=20, f=8, d=1024)
        got = dot_interaction_pallas(emb, block_b=20, interpret=True)
        want = dot_interaction_reference(emb)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-3, atol=1e-2
        )

    def test_auto_block_p_budgeted(self):
        # auto-sizing must pick a lane-multiple tile and still be exact
        emb = make_emb(b=16, f=40, d=32)
        got = dot_interaction_pallas(emb, block_b=8, interpret=True)
        want = dot_interaction_reference(emb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


class TestDLRMDotInteraction:
    def test_training_decreases_loss(self):
        import functools
        import optax
        from tpu_tfrecord.models import DLRMConfig, init_params, loss_fn, make_synthetic_batch, train_step

        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=4,
                         bottom_mlp=(8, 4), top_mlp=(8, 1), interaction="dot")
        params = init_params(jax.random.key(0), cfg)
        batch = {k: jnp.asarray(v) for k, v in make_synthetic_batch(cfg, 32).items()}
        import jax as _jax
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        step = _jax.jit(functools.partial(train_step, cfg=cfg, tx=tx))
        first = float(loss_fn(params, batch, cfg))
        for _ in range(15):
            params, opt_state, loss = step(params, opt_state, batch)
        assert float(loss) < first

    def test_mismatched_dims_rejected(self):
        from tpu_tfrecord.models import DLRMConfig, init_params

        cfg = DLRMConfig(num_dense=4, num_categorical=3, vocab_size=16, embed_dim=8,
                         bottom_mlp=(8, 4), top_mlp=(8, 1), interaction="dot")
        with pytest.raises(ValueError, match="bottom_mlp"):
            init_params(jax.random.key(0), cfg)


class TestDeviceTimeHarness:
    def test_measurement_harness_runs_and_loops_execute(self, monkeypatch):
        """tools/pallas_device_time.py smoke: the fori_loop carry makes K
        data-dependent applications that cannot collapse — the looped
        accumulator must equal K times one application's mean."""
        import sys, os
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
        from tools.pallas_device_time import _looped
        from tpu_tfrecord.models.interaction import dot_interaction_reference

        rng = np.random.default_rng(0)
        emb = jnp.asarray(rng.normal(size=(16, 8, 4)), dtype=jnp.float32)
        one = float(dot_interaction_reference(emb).mean())
        for k in (1, 3, 7):
            acc = float(_looped(dot_interaction_reference, k)(emb))
            # eps=1e-12 feedback leaves values numerically unchanged in f32
            assert acc == pytest.approx(k * one, rel=1e-5), k
