"""Chaos-matrix suite: deterministic fault injection x read mode x policy.

The stall-defense tentpole (ISSUE 3): FaultPlan/ChaosFS determinism, the
per-op deadline model (read_deadline_ms / open_deadline_ms), straggler
hedging (hedge_after_ms), the on_stall policy, the pipeline watchdog, the
RetryPolicy deadline-cap satellite, Metrics thread-safety, and the writer
heartbeat lease.

Stall timings: injected stalls are BOUNDED (plan.release() at teardown
frees any thread still blocked) and deadlines are tens of milliseconds, so
the whole suite costs seconds, not stall durations.
"""

import json
import os
import threading
import time

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import wire
from tpu_tfrecord.faults import (
    ChaosFS,
    FaultPlan,
    FaultRule,
    InjectedFault,
    install_chaos,
)
from tpu_tfrecord.io.dataset import IteratorState, TFRecordDataset
from tpu_tfrecord.metrics import METRICS, Metrics
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType
from tpu_tfrecord.stall import DeadlineError, GuardedReadStream, StallError

SCHEMA = StructType(
    [StructField("id", LongType(), nullable=False), StructField("s", StringType())]
)
ROWS = [[i, f"val{i}" * (i % 5 + 1)] for i in range(120)]
N_SHARDS = 4
PER_SHARD = len(ROWS) // N_SHARDS

# A permanent stall long enough that any test reaching it without defenses
# would hang past the outer guard; bounded so abandoned daemon threads die
# with the plan's release at teardown.
STALL_MS = 60_000


def _fast_retries(n):
    return RetryPolicy(max_retries=n, sleep=lambda _s: None)


@pytest.fixture(scope="module")
def dataset_dir(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("chaos") / "ds")
    for s in range(N_SHARDS):
        tfio.write(
            ROWS[s * PER_SHARD : (s + 1) * PER_SHARD],
            SCHEMA,
            out,
            mode="append" if s else "overwrite",
        )
    return out


def _shard_names(out):
    return sorted(n for n in os.listdir(out) if n.startswith("part-"))


def _shard_ids(path):
    """The id column of one shard file (ground truth via the wire layer)."""
    from tpu_tfrecord.serde import TFRecordDeserializer, decode_record
    from tpu_tfrecord.options import RecordType

    de = TFRecordDeserializer(SCHEMA)
    return [
        decode_record(de, RecordType.EXAMPLE, rec)[0]
        for rec in wire.read_records(path)
    ]


def _read_ids(out, state=None, max_batches=None, **kw):
    kw.setdefault("batch_size", 7)
    kw.setdefault("schema", SCHEMA)
    kw.setdefault("drop_remainder", False)
    ds = TFRecordDataset(out, **kw)
    got = []
    with ds.batches(state) as it:
        n = 0
        for cb in it:
            got.extend(cb["id"].values.tolist())
            n += 1
            if max_batches is not None and n >= max_batches:
                return got, it.state()
    return got, None


# Read-mode configurations: kwargs forcing each decode path, plus whether
# the native decoder must be detached (the pure-Python strict path).
MODES = {
    "strict": {"use_mmap": False, "_python": True},
    "fused": {"use_mmap": False},
    "mmap": {"use_mmap": True},
    "salvage": {"use_mmap": False, "on_corrupt": "skip_record"},
}


def _make_ds(out, mode, **kw):
    cfg = dict(MODES[mode])
    python_only = cfg.pop("_python", False)
    cfg.update(kw)
    cfg.setdefault("batch_size", 7)
    cfg.setdefault("schema", SCHEMA)
    cfg.setdefault("drop_remainder", False)
    ds = TFRecordDataset(out, **cfg)
    if python_only:
        ds._native_decoder = None  # force the two-pass Python strict path
    return ds


def _drain(ds, timeout=30):
    """Consume a dataset on a side thread under an outer deadlock guard:
    a stall bug here must FAIL the test, never hang the suite."""
    result = {}

    def run():
        try:
            got = []
            with ds.batches() as it:
                for cb in it:
                    got.extend(cb["id"].values.tolist())
            result["rows"] = got
        except BaseException as e:
            result["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "epoch hung: stall defense failed (outer guard)"
    return result


class TestFaultPlan:
    def test_json_round_trip(self):
        plan = FaultPlan(
            [
                FaultRule(op="read", kind="stall", path="p0", stall_ms=5.0),
                FaultRule(
                    op="open", kind="transient_error", ordinal=2, times=3,
                    probability=0.5,
                ),
            ],
            seed=7,
        )
        clone = FaultPlan.from_json(json.dumps(plan.to_json()))
        assert clone.to_json() == plan.to_json()
        assert clone.seed == 7

    def test_rule_validation(self):
        with pytest.raises(ValueError):
            FaultRule(op="nope", kind="stall", stall_ms=1.0)
        with pytest.raises(ValueError):
            FaultRule(op="read", kind="nope")
        with pytest.raises(ValueError):
            FaultRule(op="read", kind="stall", stall_ms=1.0, times=0)
        with pytest.raises(ValueError):
            FaultRule(op="read", kind="stall", stall_ms=1.0, probability=0.0)
        # cap 0 would be silent truncation (read(0) == b"" == EOF), and a
        # 0ms "stall" is a no-op: both are config mistakes, not scenarios
        with pytest.raises(ValueError):
            FaultRule(op="read", kind="short_read")
        with pytest.raises(ValueError):
            FaultRule(op="read", kind="stall")

    def test_ordinal_and_times(self):
        plan = FaultPlan(
            [FaultRule(op="read", kind="transient_error", ordinal=1, times=2)]
        )
        fired = [bool(plan.decide("read", "x")) for _ in range(5)]
        assert fired == [False, True, True, False, False]
        assert [e["ordinal"] for e in plan.ledger] == [1, 2]

    def test_probability_is_seed_deterministic(self):
        def ledger(seed):
            plan = FaultPlan(
                [
                    FaultRule(
                        op="read", kind="transient_error", times=None,
                        probability=0.5,
                    )
                ],
                seed=seed,
            )
            for _ in range(40):
                plan.decide("read", "x")
            return plan.ledger_json()

        assert ledger(3) == ledger(3)
        assert ledger(3) != ledger(4)  # 2^-40 flake odds: both draws equal

    def test_stall_uses_injectable_sleep(self):
        slept = []
        plan = FaultPlan(
            [FaultRule(op="read", kind="stall", stall_ms=2500.0)],
            sleep=slept.append,
        )
        plan.apply("read", "x", 100)
        assert slept == [2.5]  # no wall time: the seam took the stall


class TestChaosMatrix:
    """Fault kind x read mode x policy: the epoch either completes with
    the correct rows or raises, exactly per policy."""

    @pytest.mark.parametrize("mode", list(MODES))
    def test_no_faults_baseline(self, dataset_dir, mode):
        ds = _make_ds(dataset_dir, mode)
        result = _drain(ds)
        assert sorted(result["rows"]) == sorted(r[0] for r in ROWS)

    @pytest.mark.parametrize("mode", list(MODES))
    @pytest.mark.parametrize("policy", ["raise", "skip_shard"])
    def test_transient_error_retried_to_success(self, dataset_dir, mode, policy):
        """One injected transient error per shard + retries: every row
        arrives under every mode and policy (the fault heals)."""
        rules = [
            FaultRule(op="read", kind="transient_error", path=name, times=1)
            for name in _shard_names(dataset_dir)
        ]
        # mmap never read()s through the chaos file: fault its opens instead
        if mode == "mmap":
            rules = [
                FaultRule(
                    op="open", kind="transient_error", path=name, times=1
                )
                for name in _shard_names(dataset_dir)
            ]
        plan = FaultPlan(rules)
        ds = _make_ds(
            dataset_dir, mode, retry_policy=_fast_retries(3), on_stall=policy
        )
        with install_chaos(plan):
            result = _drain(ds)
        assert sorted(result["rows"]) == sorted(r[0] for r in ROWS)
        assert len(plan.ledger) == N_SHARDS  # every rule fired exactly once

    @pytest.mark.parametrize("mode", list(MODES))
    @pytest.mark.parametrize("policy", ["raise", "skip_shard"])
    def test_permanent_error_raises(self, dataset_dir, mode, policy):
        """A permanently erroring shard exhausts retries and raises under
        BOTH stall policies: on_stall covers stalls, not hard IO errors."""
        victim = _shard_names(dataset_dir)[1]
        op = "open" if mode == "mmap" else "read"
        plan = FaultPlan(
            [FaultRule(op=op, kind="permanent_error", path=victim, times=None)]
        )
        ds = _make_ds(
            dataset_dir, mode, retry_policy=_fast_retries(2), on_stall=policy
        )
        with install_chaos(plan):
            result = _drain(ds)
        assert isinstance(result["error"], InjectedFault)

    @pytest.mark.parametrize("mode", list(MODES))
    def test_short_reads_stream_correctly(self, dataset_dir, mode):
        """A 13-byte read cap must stream through every mode's refill
        logic, never misread as EOF/truncation."""
        if mode == "mmap":
            pytest.skip("mmap decodes from memory, not read() calls")
        plan = FaultPlan(
            [
                FaultRule(
                    op="read", kind="short_read", times=None, cap_bytes=13
                )
            ]
        )
        ds = _make_ds(dataset_dir, mode)
        with install_chaos(plan):
            result = _drain(ds, timeout=60)
        assert sorted(result["rows"]) == sorted(r[0] for r in ROWS)

    @pytest.mark.parametrize("mode", list(MODES))
    @pytest.mark.parametrize("policy", ["raise", "skip_shard"])
    def test_stall_per_policy(self, dataset_dir, mode, policy):
        """THE acceptance scenario: a shard whose read (open, for mmap)
        stalls 'forever' no longer hangs the epoch. Default policy raises
        within the configured deadline; skip_shard completes the epoch
        minus the stalled shard, counted in read.skipped_shards."""
        names = _shard_names(dataset_dir)
        victim = names[1]
        op = "open" if mode == "mmap" else "read"
        plan = FaultPlan(
            [
                FaultRule(
                    op=op, kind="stall", path=victim, times=None,
                    stall_ms=STALL_MS,
                )
            ]
        )
        METRICS.reset()
        ds = _make_ds(
            dataset_dir,
            mode,
            read_deadline_ms=150,
            open_deadline_ms=150,
            on_stall=policy,
        )
        try:
            with install_chaos(plan):
                result = _drain(ds)
            if policy == "raise":
                assert isinstance(result["error"], DeadlineError)
            else:
                victim_ids = set(_shard_ids(os.path.join(dataset_dir, victim)))
                expect = sorted(r[0] for r in ROWS if r[0] not in victim_ids)
                assert sorted(result["rows"]) == expect
                assert METRICS.counter("read.skipped_shards") == 1
            assert METRICS.counter("read.stalls") >= 1
            assert METRICS.counter("read.deadline_misses") >= 1
        finally:
            plan.release()


class TestChaosDeterminism:
    def _run(self, out, plan, checkpoint_at=None):
        """One tolerant epoch under ``plan``; optionally checkpoint after
        N batches and resume with a FRESH dataset + the same plan spec."""
        kw = dict(
            read_deadline_ms=150,
            on_stall="skip_shard",
            on_corrupt="skip_record",
            use_mmap=False,
            retry_policy=_fast_retries(1),
        )
        if checkpoint_at is None:
            rows, _ = _read_ids(out, **kw)
            return rows
        head, state = _read_ids(out, max_batches=checkpoint_at, **kw)
        resumed = FaultPlan.from_json(plan.to_json())
        with install_chaos(resumed):
            tail, _ = _read_ids(out, state=state, **kw)
        resumed.release()
        return head + tail

    def test_same_seed_same_ledger_and_rows(self, dataset_dir):
        """Same FaultPlan spec => byte-identical ledger and identical
        surviving row set across two full runs."""
        names = _shard_names(dataset_dir)
        spec = {
            "seed": 11,
            "rules": [
                {"op": "read", "kind": "stall", "path": names[2],
                 "ordinal": 0, "times": None, "stall_ms": STALL_MS},
                {"op": "read", "kind": "transient_error", "path": names[0],
                 "ordinal": 1, "times": 1},
            ],
        }
        runs = []
        for _ in range(2):
            plan = FaultPlan.from_json(spec)
            with install_chaos(plan):
                rows = self._run(dataset_dir, plan)
            plan.release()
            runs.append((rows, plan.ledger_json()))
        assert runs[0][0] == runs[1][0]
        assert runs[0][1] == runs[1][1]
        assert runs[0][1]  # the plan actually fired

    def test_determinism_across_checkpoint_resume(self, dataset_dir):
        """A checkpoint/resume boundary mid-epoch yields the same surviving
        row sequence as the uninterrupted run under the same plan spec."""
        names = _shard_names(dataset_dir)
        spec = {
            "seed": 5,
            "rules": [
                {"op": "read", "kind": "stall", "path": names[3],
                 "ordinal": 0, "times": None, "stall_ms": STALL_MS},
            ],
        }
        plan_a = FaultPlan.from_json(spec)
        with install_chaos(plan_a):
            full = self._run(dataset_dir, plan_a)
        plan_a.release()
        plan_b = FaultPlan.from_json(spec)
        with install_chaos(plan_b):
            resumed = self._run(dataset_dir, plan_b, checkpoint_at=5)
        plan_b.release()
        assert resumed == full


class TestHedgedReads:
    def test_hedge_win_is_byte_identical(self, dataset_dir):
        """Primary stalls once mid-shard; the hedge's backup read wins and
        the epoch's rows equal the fault-free run exactly."""
        baseline, _ = _read_ids(dataset_dir, use_mmap=False)
        victim = _shard_names(dataset_dir)[1]
        plan = FaultPlan(
            [
                FaultRule(
                    op="read", kind="stall", path=victim, ordinal=0, times=1,
                    stall_ms=STALL_MS,
                )
            ]
        )
        METRICS.reset()
        try:
            with install_chaos(plan):
                rows, _ = _read_ids(
                    dataset_dir, hedge_after_ms=50, use_mmap=False
                )
        finally:
            plan.release()
        assert rows == baseline
        assert METRICS.counter("read.hedges") >= 1
        assert METRICS.counter("read.hedge_wins") >= 1
        assert METRICS.counter("read.stalls") == 0  # hedge beat the stall

    def test_primary_win_is_byte_identical(self, dataset_dir):
        """No stall: the primary always wins, the hedge never launches,
        output matches the unguarded run."""
        baseline, _ = _read_ids(dataset_dir, use_mmap=False)
        METRICS.reset()
        rows, _ = _read_ids(dataset_dir, hedge_after_ms=10_000, use_mmap=False)
        assert rows == baseline
        assert METRICS.counter("read.hedges") == 0

    def test_guarded_stream_hedge_unit(self, tmp_path):
        """Unit-level: the backup side reads the same byte range, the
        stream's output is identical to the file, and the loser's handle
        is abandoned without corrupting the stream position."""
        path = str(tmp_path / "blob.bin")
        payload = bytes(range(256)) * 5000  # ~1.25 MB
        with open(path, "wb") as fh:
            fh.write(payload)
        release = threading.Event()
        state = {"opens": 0}

        class SlowFirstRead:
            """First read() of the FIRST handle blocks until released."""

            def __init__(self, fh, first):
                self._fh = fh
                self._first = first
                self._reads = 0

            def read(self, n=-1):
                self._reads += 1
                if self._first and self._reads == 1:
                    release.wait(30)
                return self._fh.read(n)

            def seek(self, pos):
                self._fh.seek(pos)

            def close(self):
                self._fh.close()

        def reopen(pos):
            state["opens"] += 1
            fh = SlowFirstRead(open(path, "rb"), first=False)
            fh.seek(pos)
            return fh

        m = Metrics()
        gs = GuardedReadStream(
            SlowFirstRead(open(path, "rb"), first=True),
            path,
            read_deadline=None,
            hedge_after=0.05,
            reopen=reopen,
            metrics=m,
            io_chunk=64 << 10,
        )
        try:
            out = gs.read(-1)
        finally:
            release.set()
            gs.close()
        assert out == payload
        assert state["opens"] == 1
        assert m.counter("read.hedges") == 1
        assert m.counter("read.hedge_wins") == 1


class TestHedgeBackupFailure:
    def test_failed_backup_does_not_shorten_primary_deadline(self, tmp_path):
        """A hedge whose BACKUP side errors must fall back to waiting on
        the merely-slow primary for the remaining read budget — not declare
        the primary stalled at hedge time."""
        path = str(tmp_path / "blob.bin")
        payload = os.urandom(128 << 10)
        with open(path, "wb") as fh:
            fh.write(payload)

        class SlowRead:
            """Every read takes 0.2s — slow, NOT stalled."""

            def __init__(self, fh):
                self._fh = fh

            def read(self, n=-1):
                time.sleep(0.2)
                return self._fh.read(n)

            def close(self):
                self._fh.close()

        def reopen(_pos):
            raise OSError("backup open refused")

        m = Metrics()
        gs = GuardedReadStream(
            SlowRead(open(path, "rb")),
            path,
            read_deadline=5.0,
            hedge_after=0.05,
            reopen=reopen,
            metrics=m,
            io_chunk=1 << 20,
        )
        try:
            out = gs.read(-1)
        finally:
            gs.close()
        assert out == payload  # the primary's bytes arrived intact
        assert m.counter("read.hedges") >= 1
        assert m.counter("read.hedge_wins") == 0
        assert m.counter("read.stalls") == 0  # no false stall declared


class TestWatchdog:
    def test_wedged_worker_skip_shard_completes(self, dataset_dir):
        """No deadline configured — only the watchdog stands between a
        wedged worker and an epoch that blocks forever. This test
        deadlocks without the watchdog (outer _drain guard enforces)."""
        victim = _shard_names(dataset_dir)[0]
        plan = FaultPlan(
            [
                FaultRule(
                    op="read", kind="stall", path=victim, times=None,
                    stall_ms=STALL_MS,
                )
            ]
        )
        METRICS.reset()
        ds = _make_ds(
            dataset_dir,
            "fused",
            num_workers=2,
            watchdog_timeout_ms=300,
            on_stall="skip_shard",
        )
        try:
            with install_chaos(plan):
                result = _drain(ds)
        finally:
            plan.release()
        victim_ids = set(_shard_ids(os.path.join(dataset_dir, victim)))
        expect = sorted(r[0] for r in ROWS if r[0] not in victim_ids)
        assert sorted(result["rows"]) == expect
        assert METRICS.counter("read.watchdog_restarts") >= 1
        assert METRICS.counter("read.stalls") >= 1
        assert METRICS.counter("read.skipped_shards") >= 1

    def test_wedged_worker_default_raises(self, dataset_dir):
        victim = _shard_names(dataset_dir)[0]
        plan = FaultPlan(
            [
                FaultRule(
                    op="read", kind="stall", path=victim, times=None,
                    stall_ms=STALL_MS,
                )
            ]
        )
        ds = _make_ds(
            dataset_dir, "fused", num_workers=2, watchdog_timeout_ms=300
        )
        try:
            with install_chaos(plan):
                result = _drain(ds)
        finally:
            plan.release()
        assert isinstance(result["error"], StallError)

    def test_no_watchdog_config_means_no_watchdog_thread(self, dataset_dir):
        """The default path spawns no watchdog and reads normally."""
        ds = _make_ds(dataset_dir, "fused", num_workers=2)
        result = _drain(ds)
        assert sorted(result["rows"]) == sorted(r[0] for r in ROWS)

    def test_backpressure_is_not_a_stall(self, tmp_path):
        """A SLOW CONSUMER must never trip the watchdog: workers blocked
        handing over chunks AND end sentinels (full job queues while the
        emitter waits on the prefetch queue) keep their heartbeat fresh —
        a done shard backpressured behind the emitter is healthy, never
        wedged. Shards here are >2 decode chunks, so the END put really
        blocks on the depth-2 job queue while the consumer dawdles."""
        long_schema = StructType([StructField("id", LongType(), nullable=False)])
        out = str(tmp_path / "bp")
        n = 4500  # > 2 * 2048-record chunks per shard => end-put blocks
        tfio.write([[i] for i in range(n)], long_schema, out, mode="overwrite")
        METRICS.reset()
        ds = TFRecordDataset(
            out, batch_size=512, schema=long_schema, drop_remainder=False,
            num_workers=2, prefetch=1, num_epochs=2,
            watchdog_timeout_ms=150, use_mmap=False,
        )
        got = []
        with ds.batches() as it:
            for cb in it:
                got.extend(cb["id"].values.tolist())
                time.sleep(0.08)  # consumer far slower than the decoders
        assert sorted(got) == sorted(list(range(n)) * 2)
        assert METRICS.counter("read.watchdog_restarts") == 0
        assert METRICS.counter("read.skipped_shards") == 0


class TestChaosFSWriteSide:
    def test_rename_race_is_absorbed_by_commit(self, tmp_path):
        """An injected landed-but-errored rename: PR 2's landed-rename
        detection plus write_retries absorbs it; output is complete."""
        out = str(tmp_path / "out")
        plan = FaultPlan(
            [FaultRule(op="rename", kind="rename_race", path="part-", times=1)]
        )
        with install_chaos(plan):
            tfio.write(
                ROWS[:10], SCHEMA, out, mode="overwrite", write_retries=2
            )
        assert len(plan.ledger) == 1
        table = tfio.read(out, schema=SCHEMA)
        assert sorted(table.column("id")) == list(range(10))

    def test_flaky_listing_raises(self, tmp_path, dataset_dir):
        plan = FaultPlan(
            [FaultRule(op="listdir", kind="flaky_listing", times=None)]
        )
        fs_obj = ChaosFS(__import__("tpu_tfrecord.fs", fromlist=["fs"]).LocalFS(), plan)
        with pytest.raises(InjectedFault):
            fs_obj.listdir(dataset_dir)
        with pytest.raises(InjectedFault):
            list(fs_obj.walk_files(dataset_dir, lambda n: True))


class TestRetryDeadlineCap:
    def test_backoff_capped_to_remaining_budget(self):
        """The deadline caps the next backoff sleep instead of refusing
        the retry: the policy never sleeps past its deadline but spends
        ALL of the budget it has (injectable clock proves it)."""
        t = [0.0]
        sleeps = []

        def clock():
            return t[0]

        def sleep(s):
            sleeps.append(s)
            t[0] += s

        pol = RetryPolicy(
            max_retries=10, base_delay=4.0, max_delay=4.0, jitter=False,
            deadline=10.0, sleep=sleep, clock=clock,
        )
        start = pol.clock()
        assert pol.pause(1, start)  # sleeps 4.0 (remaining 10)
        assert pol.pause(2, start)  # sleeps 4.0 (remaining 6)
        assert pol.pause(3, start)  # capped: sleeps the remaining 2.0
        assert not pol.pause(4, start)  # budget exhausted: no retry
        assert sleeps == [4.0, 4.0, 2.0]
        assert t[0] == 10.0  # never slept past the deadline

    def test_no_deadline_unchanged(self):
        sleeps = []
        pol = RetryPolicy(
            max_retries=2, base_delay=1.0, max_delay=8.0, jitter=False,
            sleep=sleeps.append, clock=lambda: 0.0,
        )
        assert pol.pause(1, 0.0) and pol.pause(2, 0.0)
        assert not pol.pause(3, 0.0)
        assert sleeps == [1.0, 2.0]


class TestMetricsThreadSafety:
    def test_concurrent_increments_lose_nothing(self):
        m = Metrics()
        n_threads, per_thread = 8, 2000
        start = threading.Barrier(n_threads)

        def bump():
            start.wait()
            for _ in range(per_thread):
                m.count("read.stalls")
                m.add("decode", records=1, nbytes=2, seconds=0.0)

        threads = [threading.Thread(target=bump) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert m.counter("read.stalls") == n_threads * per_thread
        st = m.stage("decode")
        assert st.records == n_threads * per_thread
        assert st.bytes == 2 * n_threads * per_thread
        assert st.batches == n_threads * per_thread


class TestWriterHeartbeatLease:
    def test_job_meta_carries_heartbeat(self, tmp_path):
        from tpu_tfrecord.io.writer import DatasetWriter, _JOB_MARKER, _WriteJob

        out = str(tmp_path / "hb")
        w = DatasetWriter(out, SCHEMA, mode="overwrite")
        assert w._prepare_output()
        job = _WriteJob(w, task_id=0)
        with open(os.path.join(job.temp_root, _JOB_MARKER)) as fh:
            meta = json.load(fh)
        assert meta["heartbeat"] >= meta["created"]
        # a forced re-stamp advances the heartbeat
        job._last_beat = 0.0
        time.sleep(0.01)
        job.heartbeat()
        with open(os.path.join(job.temp_root, _JOB_MARKER)) as fh:
            meta2 = json.load(fh)
        assert meta2["heartbeat"] > meta["heartbeat"]
        job.abort()

    def test_sweep_reclaims_stale_lease_cross_host(self, tmp_path):
        """A staging dir stamped by ANOTHER host whose heartbeat lease
        expired is swept (remote-FS orphan recovery); a fresh-lease foreign
        dir is left alone (may be a live writer)."""
        from tpu_tfrecord import fs as tfs
        from tpu_tfrecord.io import paths as p
        from tpu_tfrecord.io.writer import _JOB_MARKER, sweep_orphan_jobs

        out = str(tmp_path / "sweep")
        root = os.path.join(out, p.TEMP_PREFIX)
        stale = os.path.join(root, "deadjob")
        fresh = os.path.join(root, "livejob")
        os.makedirs(stale)
        os.makedirs(fresh)
        now = time.time()
        for d, beat in ((stale, now - 7200), (fresh, now)):
            with open(os.path.join(d, _JOB_MARKER), "w") as fh:
                json.dump(
                    {"pid": 999999, "host": "some-other-host",
                     "created": beat, "heartbeat": beat},
                    fh,
                )
        removed = sweep_orphan_jobs(tfs.LocalFS(), out, lease_ttl=3600)
        assert removed == [stale]
        assert not os.path.isdir(stale)
        assert os.path.isdir(fresh)

    def test_sweep_still_uses_local_dead_pid(self, tmp_path):
        """The PR 2 same-host dead-pid check still works even with a fresh
        heartbeat (a crashed job's last stamp can be recent)."""
        import socket

        from tpu_tfrecord import fs as tfs
        from tpu_tfrecord.io import paths as p
        from tpu_tfrecord.io.writer import _JOB_MARKER, sweep_orphan_jobs

        out = str(tmp_path / "sweep2")
        dead = os.path.join(out, p.TEMP_PREFIX, "crashed")
        os.makedirs(dead)
        now = time.time()
        with open(os.path.join(dead, _JOB_MARKER), "w") as fh:
            json.dump(
                {"pid": 999999999, "host": socket.gethostname(),
                 "created": now, "heartbeat": now},
                fh,
            )
        removed = sweep_orphan_jobs(tfs.LocalFS(), out)
        assert removed == [dead]


class TestGuardHygiene:
    def test_real_open_error_does_not_leak_worker_threads(self, tmp_path):
        """A genuine open failure (not a stall) under open_deadline_ms
        returns the pooled worker: repeated failures (a flaky store under
        retries) must not grow the thread count."""
        from tpu_tfrecord.stall import StallGuard

        guard = StallGuard(open_deadline=2.0)
        missing = str(tmp_path / "nope" / "missing.tfrecord")

        def boom():
            return open(missing, "rb")

        with pytest.raises(FileNotFoundError):
            guard.call_open(boom, missing)
        before = threading.active_count()
        for _ in range(25):
            with pytest.raises(FileNotFoundError):
                guard.call_open(boom, missing)
        assert threading.active_count() <= before + 1

    def test_row_api_shard_guards_share_the_process_pool(self, dataset_dir):
        """The row API builds one guard per ShardReader; guards share the
        process-wide worker pool, so reading many shards/epochs with stall
        options set keeps the thread count bounded instead of stranding
        idle workers per discarded guard."""
        before = threading.active_count()
        for _ in range(6):
            table = tfio.read(
                dataset_dir, schema=SCHEMA,
                read_deadline_ms=5000, open_deadline_ms=5000,
            )
            assert len(table.column("id")) == len(ROWS)
        from tpu_tfrecord.stall import _WorkerPool

        assert threading.active_count() <= before + _WorkerPool._MAX_IDLE

    def test_live_local_pid_vetoes_stale_lease_sweep(self, tmp_path):
        """A same-host writer whose pid is provably ALIVE is never swept,
        even when its heartbeat lease looks stale (marker re-stamps are
        best-effort and can silently fail while the job keeps writing)."""
        import socket

        from tpu_tfrecord import fs as tfs
        from tpu_tfrecord.io import paths as p
        from tpu_tfrecord.io.writer import _JOB_MARKER, sweep_orphan_jobs

        out = str(tmp_path / "live")
        live = os.path.join(out, p.TEMP_PREFIX, "livejob")
        os.makedirs(live)
        with open(os.path.join(live, _JOB_MARKER), "w") as fh:
            json.dump(
                {"pid": os.getpid(), "host": socket.gethostname(),
                 "created": 0.0, "heartbeat": 0.0},  # ancient lease
                fh,
            )
        removed = sweep_orphan_jobs(tfs.LocalFS(), out, lease_ttl=1.0)
        assert removed == []
        assert os.path.isdir(live)


class TestOptionsPlumbing:
    def test_stall_options_parse_and_validate(self):
        from tpu_tfrecord.options import TFRecordOptions

        o = TFRecordOptions.from_map(
            read_deadline_ms=250, openDeadlineMs=100, hedge_after_ms=50,
            on_stall="skip_shard", watchdogTimeoutMs=1000,
        )
        assert o.read_deadline_ms == 250
        assert o.open_deadline_ms == 100
        assert o.hedge_after_ms == 50
        assert o.on_stall == "skip_shard"
        assert o.watchdog_timeout_ms == 1000
        with pytest.raises(ValueError):
            TFRecordOptions.from_map(on_stall="retry")
        with pytest.raises(ValueError):
            TFRecordOptions.from_map(read_deadline_ms=0)

    def test_guard_from_options_none_by_default(self):
        from tpu_tfrecord.options import TFRecordOptions
        from tpu_tfrecord.stall import guard_from_options

        assert guard_from_options(TFRecordOptions()) is None
        g = guard_from_options(TFRecordOptions.from_map(read_deadline_ms=500))
        assert g is not None and g.read_deadline == 0.5


class TestDoctorSimulate:
    def test_simulate_replays_plan_and_reports_ledger(self, dataset_dir, tmp_path):
        import subprocess
        import sys

        victim = _shard_names(dataset_dir)[0]
        plan_path = str(tmp_path / "plan.json")
        with open(plan_path, "w") as fh:
            json.dump(
                {
                    "seed": 1,
                    "rules": [
                        {"op": "read", "kind": "transient_error",
                         "path": victim, "times": 1}
                    ],
                },
                fh,
            )
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tools",
                    "tfrecord_doctor.py",
                ),
                "--simulate",
                plan_path,
                os.path.join(dataset_dir, victim),
            ],
            capture_output=True,
            text=True,
        )
        lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
        ledger = [l for l in lines if l.get("event") == "fault"]
        errors = [l for l in lines if l.get("event") == "error"]
        assert ledger and ledger[0]["kind"] == "transient_error"
        assert errors  # the injected fault surfaced in the scan report
        assert out.returncode == 2

    def test_simulate_emits_ledger_even_when_expansion_fails(
        self, dataset_dir, tmp_path
    ):
        """A plan whose own listdir fault kills shard discovery still gets
        its ledger into the report — the ledger IS the repro artifact."""
        import subprocess
        import sys

        plan_path = str(tmp_path / "plan2.json")
        with open(plan_path, "w") as fh:
            json.dump(
                {"seed": 2,
                 "rules": [{"op": "listdir", "kind": "flaky_listing",
                            "times": None}]},
                fh,
            )
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tools",
                    "tfrecord_doctor.py",
                ),
                "--simulate", plan_path, dataset_dir,
            ],
            capture_output=True,
            text=True,
        )
        lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
        assert out.returncode == 2
        assert any(l.get("event") == "fault" for l in lines)

    def test_unreadable_plan_is_a_clean_error(self, dataset_dir, tmp_path):
        """A missing/bad --simulate plan keeps the CLI's line-JSON + exit-2
        contract instead of a raw traceback."""
        import subprocess
        import sys

        doctor = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "tfrecord_doctor.py",
        )
        for bad in ["/nonexistent/plan.json"]:
            out = subprocess.run(
                [sys.executable, doctor, "--simulate", bad, dataset_dir],
                capture_output=True, text=True,
            )
            lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
            assert out.returncode == 2
            assert any(l.get("event") == "error" for l in lines)
        bad_json = str(tmp_path / "bad.json")
        with open(bad_json, "w") as fh:
            fh.write("{not json")
        out = subprocess.run(
            [sys.executable, doctor, "--simulate", bad_json, dataset_dir],
            capture_output=True, text=True,
        )
        assert out.returncode == 2
        assert not out.stderr.strip()  # no traceback leaked
