"""Tests for the columnar batch decoder (the TPU hot path) — checked against
the row-oriented serde as its correctness oracle."""

import numpy as np
import pytest

from tpu_tfrecord.columnar import (
    ColumnarDecoder,
    bucket_boundaries,
    concat_batches,
    pad_ragged,
    pad_ragged2,
    slice_batch,
    take_rows,
)
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.proto import Example, Feature, FeatureList, SequenceExample, encode_example, encode_sequence_example
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import NullValueError, TFRecordSerializer, encode_row


class TestExampleDecoding:
    SCHEMA = StructType(
        [
            StructField("i", IntegerType()),
            StructField("l", LongType()),
            StructField("f", FloatType()),
            StructField("d", DoubleType()),
            StructField("s", StringType()),
            StructField("fv", ArrayType(FloatType())),
            StructField("lv", ArrayType(LongType())),
        ]
    )

    ROWS = [
        [1, 10, 0.5, 1.5, "a", [1.0, 2.0], [7]],
        [2, 20, 1.5, 2.5, "b", [3.0], [8, 9, 10]],
        [3, 30, 2.5, 3.5, "c", [], [11, 12]],
    ]

    def _records(self):
        ser = TFRecordSerializer(self.SCHEMA)
        return [encode_row(ser, RecordType.EXAMPLE, r) for r in self.ROWS]

    def test_scalar_columns(self):
        batch = ColumnarDecoder(self.SCHEMA).decode_batch(self._records())
        assert batch.num_rows == 3
        np.testing.assert_array_equal(batch["i"].values, np.array([1, 2, 3], np.int32))
        assert batch["i"].values.dtype == np.int32
        np.testing.assert_array_equal(batch["l"].values, [10, 20, 30])
        assert batch["l"].values.dtype == np.int64
        np.testing.assert_allclose(batch["f"].values, [0.5, 1.5, 2.5])
        assert batch["f"].values.dtype == np.float32
        # double comes off the wire as f32, widened to f64 column
        assert batch["d"].values.dtype == np.float64
        np.testing.assert_allclose(batch["d"].values, [1.5, 2.5, 3.5])
        assert batch["s"].blobs == [b"a", b"b", b"c"]

    def test_ragged_columns(self):
        batch = ColumnarDecoder(self.SCHEMA).decode_batch(self._records())
        fv = batch["fv"]
        np.testing.assert_array_equal(fv.offsets, [0, 2, 3, 3])
        np.testing.assert_allclose(fv.values, [1.0, 2.0, 3.0])
        lv = batch["lv"]
        np.testing.assert_array_equal(lv.offsets, [0, 1, 4, 6])
        np.testing.assert_array_equal(lv.values, [7, 8, 9, 10, 11, 12])

    def test_missing_nullable_masks(self):
        schema = StructType([StructField("x", LongType()), StructField("y", FloatType())])
        recs = [
            encode_example(Example(features={"x": Feature.int64_list([1])})),
            encode_example(
                Example(features={"x": Feature.int64_list([2]), "y": Feature.float_list([5.0])})
            ),
        ]
        batch = ColumnarDecoder(schema).decode_batch(recs)
        np.testing.assert_array_equal(batch["y"].mask, [False, True])
        np.testing.assert_allclose(batch["y"].values, [0.0, 5.0])

    def test_missing_non_nullable_raises(self):
        schema = StructType([StructField("x", LongType(), nullable=False)])
        recs = [encode_example(Example())]
        with pytest.raises(NullValueError):
            ColumnarDecoder(schema).decode_batch(recs)

    def test_kind_mismatch_raises(self):
        schema = StructType([StructField("x", FloatType())])
        recs = [encode_example(Example(features={"x": Feature.int64_list([1])}))]
        with pytest.raises(ValueError, match="does not match"):
            ColumnarDecoder(schema).decode_batch(recs)

    def test_extra_features_skipped(self):
        schema = StructType([StructField("x", LongType())])
        recs = [
            encode_example(
                Example(
                    features={
                        "x": Feature.int64_list([1]),
                        "junk": Feature.bytes_list([b"ignored"]),
                    }
                )
            )
        ]
        batch = ColumnarDecoder(schema).decode_batch(recs)
        np.testing.assert_array_equal(batch["x"].values, [1])

    def test_byte_array_passthrough(self):
        schema = StructType([StructField("byteArray", BinaryType())])
        batch = ColumnarDecoder(schema, RecordType.BYTE_ARRAY).decode_batch([b"a", b"bb"])
        assert batch["byteArray"].blobs == [b"a", b"bb"]


class TestSequenceExampleDecoding:
    SCHEMA = StructType(
        [
            StructField("id", LongType()),
            StructField("frames", ArrayType(ArrayType(FloatType()))),
        ]
    )

    def test_ragged2(self):
        ses = [
            SequenceExample(
                context={"id": Feature.int64_list([1])},
                feature_lists={
                    "frames": FeatureList(
                        [Feature.float_list([1.0, 2.0]), Feature.float_list([3.0])]
                    )
                },
            ),
            SequenceExample(
                context={"id": Feature.int64_list([2])},
                feature_lists={"frames": FeatureList([Feature.float_list([4.0, 5.0, 6.0])])},
            ),
        ]
        recs = [encode_sequence_example(se) for se in ses]
        batch = ColumnarDecoder(self.SCHEMA, RecordType.SEQUENCE_EXAMPLE).decode_batch(recs)
        fr = batch["frames"]
        np.testing.assert_array_equal(batch["id"].values, [1, 2])
        np.testing.assert_array_equal(fr.offsets, [0, 2, 3])  # rows -> inner lists
        np.testing.assert_array_equal(fr.inner_offsets, [0, 2, 3, 6])
        np.testing.assert_allclose(fr.values, [1.0, 2.0, 3.0, 4.0, 5.0, 6.0])

    def test_featurelist_of_scalars_as_ragged(self):
        schema = StructType([StructField("toks", ArrayType(LongType()))])
        se = SequenceExample(
            feature_lists={
                "toks": FeatureList([Feature.int64_list([5]), Feature.int64_list([6])])
            }
        )
        batch = ColumnarDecoder(schema, RecordType.SEQUENCE_EXAMPLE).decode_batch(
            [encode_sequence_example(se)]
        )
        np.testing.assert_array_equal(batch["toks"].offsets, [0, 2])
        np.testing.assert_array_equal(batch["toks"].values, [5, 6])


class TestPadding:
    def test_pad_ragged(self):
        values = np.array([1, 2, 3, 4, 5, 6], dtype=np.int64)
        offsets = np.array([0, 2, 2, 6])
        dense, lengths = pad_ragged(values, offsets, max_len=3, pad_value=-1)
        np.testing.assert_array_equal(
            dense, [[1, 2, -1], [-1, -1, -1], [3, 4, 5]]
        )
        np.testing.assert_array_equal(lengths, [2, 0, 3])  # truncated row 2

    def test_pad_ragged_auto_max(self):
        dense, lengths = pad_ragged(np.array([1.0, 2.0]), np.array([0, 1, 2]))
        assert dense.shape == (2, 1)

    def test_pad_ragged_empty(self):
        dense, lengths = pad_ragged(np.array([], dtype=np.float32), np.array([0]))
        assert dense.shape == (0, 0)

    def test_pad_ragged2(self):
        # 2 rows: [[1,2],[3]] and [[4,5,6]]
        values = np.array([1, 2, 3, 4, 5, 6], dtype=np.float32)
        inner = np.array([0, 2, 3, 6])
        splits = np.array([0, 2, 3])
        dense, outer_len, inner_len = pad_ragged2(values, inner, splits, 2, 3)
        assert dense.shape == (2, 2, 3)
        np.testing.assert_allclose(dense[0, 0], [1, 2, 0])
        np.testing.assert_allclose(dense[0, 1], [3, 0, 0])
        np.testing.assert_allclose(dense[1, 0], [4, 5, 6])
        np.testing.assert_array_equal(outer_len, [2, 1])
        np.testing.assert_array_equal(inner_len, [[2, 1], [3, 0]])

    @staticmethod
    def _pad_ragged2_loop(values, inner_offsets, row_splits, max_outer,
                          max_inner, pad_value=0):
        """Per-row reference (the pre-vectorization implementation) — the
        oracle the vectorized pad_ragged2 and the native fused kernel are
        pinned against."""
        outer_lengths = np.diff(row_splits)
        n = len(outer_lengths)
        dense = np.full((n, max_outer, max_inner), pad_value, dtype=values.dtype)
        inner_len = np.zeros((n, max_outer), dtype=np.int32)
        clipped = np.minimum(outer_lengths, max_outer).astype(np.int32)
        for i in range(n):
            for jo, j in enumerate(range(row_splits[i], row_splits[i] + clipped[i])):
                seg = values[inner_offsets[j] : inner_offsets[j + 1]][:max_inner]
                dense[i, jo, : len(seg)] = seg
                inner_len[i, jo] = len(seg)
        return dense, clipped, inner_len

    def test_pad_ragged2_vectorized_matches_loop_oracle(self):
        rng = np.random.default_rng(3)
        for trial in range(8):
            n = int(rng.integers(0, 40))
            outer = rng.integers(0, 7, n)
            splits = np.concatenate(([0], np.cumsum(outer))).astype(np.int64)
            inner_lens = rng.integers(0, 9, int(splits[-1]))
            inner = np.concatenate(([0], np.cumsum(inner_lens))).astype(np.int64)
            values = rng.normal(size=int(inner[-1])).astype(np.float32)
            lo = int(rng.integers(1, 9))
            li = int(rng.integers(1, 11))
            pad = float(rng.choice([0.0, -1.0]))
            got = pad_ragged2(values, inner, splits, lo, li, pad_value=pad)
            ref = self._pad_ragged2_loop(values, inner, splits, lo, li, pad_value=pad)
            for g, r in zip(got, ref):
                np.testing.assert_array_equal(g, r, err_msg=f"trial {trial}")

    def test_pad_ragged2_native_fused_matches_numpy(self):
        from tpu_tfrecord import _native

        if not _native.available():
            pytest.skip("native lib unavailable")
        import ml_dtypes

        rng = np.random.default_rng(5)
        outer = rng.integers(0, 7, 50)
        splits = np.concatenate(([0], np.cumsum(outer))).astype(np.int64)
        inner_lens = rng.integers(0, 9, int(splits[-1]))
        inner = np.concatenate(([0], np.cumsum(inner_lens))).astype(np.int64)
        values = rng.normal(size=int(inner[-1])).astype(np.float32)
        if len(values) >= 3:  # bf16 rounding + special values go through C++
            values[0] = np.nan
            values[1] = np.inf
            values[2] = np.float32(3.0000001)
        ref_dense, ref_ol, ref_il = pad_ragged2(values, inner, splits, 5, 7)
        got = _native.pad_ragged2_dense(values, inner, splits, 5, 7, None)
        assert got is not None
        np.testing.assert_array_equal(got[0], ref_dense)
        np.testing.assert_array_equal(got[1], ref_ol)
        np.testing.assert_array_equal(got[2], ref_il)
        # fused bf16 == pad-then-astype (round-to-nearest-even, NaN stays NaN)
        got_b = _native.pad_ragged2_dense(
            values, inner, splits, 5, 7, ml_dtypes.bfloat16
        )
        ref_b = ref_dense.astype(ml_dtypes.bfloat16)
        same = (got_b[0] == ref_b) | (
            np.isnan(got_b[0].astype(np.float32)) & np.isnan(ref_b.astype(np.float32))
        )
        assert same.all()
        # int64 source: i64 passthrough and i32 two's-complement truncation
        vi = rng.integers(-(2**40), 2**40, int(inner[-1])).astype(np.int64)
        ref_i, _, _ = pad_ragged2(vi, inner, splits, 5, 7)
        got_i64 = _native.pad_ragged2_dense(vi, inner, splits, 5, 7, np.int64)
        got_i32 = _native.pad_ragged2_dense(vi, inner, splits, 5, 7, np.int32)
        np.testing.assert_array_equal(got_i64[0], ref_i)
        np.testing.assert_array_equal(got_i32[0], ref_i.astype(np.int32))
        # non-zero pad_value is numpy-only: native reports unsupported
        assert (
            _native.pad_ragged2_dense(values, inner, splits, 5, 7, None, pad_value=-1)
            is None
        )

    def test_pad_ragged_native_fused_matches_numpy(self):
        from tpu_tfrecord import _native

        if not _native.available():
            pytest.skip("native lib unavailable")
        import ml_dtypes

        rng = np.random.default_rng(6)
        lens = rng.integers(0, 9, 64)
        offsets = np.concatenate(([0], np.cumsum(lens))).astype(np.int64)
        values = rng.normal(size=int(offsets[-1])).astype(np.float32)
        ref_dense, ref_len = pad_ragged(values, offsets, 5)
        got = _native.pad_ragged_dense(values, offsets, 5, None)
        assert got is not None
        np.testing.assert_array_equal(got[0], ref_dense)
        np.testing.assert_array_equal(got[1], ref_len)
        got_b = _native.pad_ragged_dense(values, offsets, 5, ml_dtypes.bfloat16)
        np.testing.assert_array_equal(
            got_b[0].astype(np.float32),
            ref_dense.astype(ml_dtypes.bfloat16).astype(np.float32),
        )

    def test_bucket_boundaries(self):
        bounds = bucket_boundaries([1, 2, 3, 4, 100], num_buckets=2)
        assert bounds[-1] == 100
        assert len(bounds) >= 1


class TestTakeRows:
    """take_rows == per-row slice+concat (the oracle) on every layout."""

    @staticmethod
    def _assert_batches_equal(got, ref):
        assert got.num_rows == ref.num_rows
        assert set(got.columns) == set(ref.columns)
        for name, g in got.columns.items():
            r = ref.columns[name]
            for attr in ("values", "offsets", "inner_offsets", "blob_offsets", "mask"):
                a, b = getattr(g, attr), getattr(r, attr)
                assert (a is None) == (b is None), (name, attr)
                if a is not None:
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            gb = None if g.blob is None else bytes(g.blob)
            rb = None if r.blob is None else bytes(r.blob)
            assert gb == rb, name

    @staticmethod
    def _example_batch():
        schema = StructType(
            [
                StructField("a", LongType()),
                StructField("s", StringType()),
                StructField("v", ArrayType(FloatType())),
            ]
        )
        rng = np.random.default_rng(0)
        dec = ColumnarDecoder(schema, RecordType.EXAMPLE)
        ser = TFRecordSerializer(schema)
        rows = []
        for i in range(97):
            rows.append(
                [
                    None if i % 7 == 0 else i,
                    None if i % 5 == 2 else f"s{i}" * (i % 3),
                    None
                    if i % 11 == 3
                    else [float(x) for x in rng.normal(size=i % 4)],
                ]
            )
        recs = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
        return dec.decode_batch(recs)

    def test_permutation_matches_oracle(self):
        batch = self._example_batch()
        rng = np.random.default_rng(1)
        idx = rng.permutation(batch.num_rows)
        got = take_rows(batch, idx)
        ref = concat_batches([slice_batch(batch, int(i), int(i) + 1) for i in idx])
        self._assert_batches_equal(got, ref)

    def test_repeats_and_subsets(self):
        batch = self._example_batch()
        rng = np.random.default_rng(2)
        idx = rng.integers(0, batch.num_rows, size=250)
        got = take_rows(batch, idx)
        ref = concat_batches([slice_batch(batch, int(i), int(i) + 1) for i in idx])
        self._assert_batches_equal(got, ref)

    def test_ragged2_sequence_example(self):
        schema = StructType([StructField("vv", ArrayType(ArrayType(LongType())))])
        dec = ColumnarDecoder(schema, RecordType.SEQUENCE_EXAMPLE)
        ser = TFRecordSerializer(schema)
        rng = np.random.default_rng(3)
        rows = [
            [[[int(x) for x in rng.integers(0, 9, rng.integers(0, 4))] for _ in range(rng.integers(0, 3))]]
            for _ in range(60)
        ]
        recs = [encode_row(ser, RecordType.SEQUENCE_EXAMPLE, r) for r in rows]
        batch = dec.decode_batch(recs)
        idx = rng.permutation(batch.num_rows)
        got = take_rows(batch, idx)
        ref = concat_batches([slice_batch(batch, int(i), int(i) + 1) for i in idx])
        self._assert_batches_equal(got, ref)

    def test_empty_indices_and_bounds(self):
        batch = self._example_batch()
        assert take_rows(batch, np.array([], dtype=np.int64)).num_rows == 0
        with pytest.raises(IndexError):
            take_rows(batch, [batch.num_rows])
        with pytest.raises(IndexError):
            take_rows(batch, [-1])
        with pytest.raises(ValueError):
            take_rows(batch, np.zeros((2, 2), dtype=np.int64))
        with pytest.raises(TypeError, match="boolean mask"):
            take_rows(batch, np.ones(batch.num_rows, dtype=bool))
