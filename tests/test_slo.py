"""Request tracing, exemplars, and the error-budget SLO engine (ISSUE 20).

The load-bearing pins:
  - EXEMPLARS: `Histogram.observe(..., exemplar=)` stores last-wins per
    bucket, survives state()/merge_state() round trips WITHOUT perturbing
    bucket counts (exemplar-carrying merges quantile-identically to
    exemplar-free), and exemplar-free snapshots serialize byte-identically
    to the pre-exemplar format (the "exemplars" key is simply absent).
  - BURN MATH: an alert fires only when BOTH windows of a pair burn at or
    above threshold (a stale spike never pages); windows anchor at the
    origin when they open before the ring (cumulative-from-zero honesty);
    out-of-order samples are dropped; the verdict flips healthy ->
    fast_burn under a shed storm on a purely fake clock.
  - SPOOL REPLAY: `fleet_samples` sums counters and bucket-exactly merges
    histograms per heartbeat across processes, scoped by trace id, and
    one malformed histogram state loses that stage for that process at
    that point — never the series.
  - DOCTOR: `tfrecord_doctor slo` --json round-trips the text lines on
    both the exit-0 (report, even when burning) and exit-2 (no spool /
    bad spec) paths; `merge-trace` accepts a DIRECTORY of traces.
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_tfrecord.metrics import Metrics
from tpu_tfrecord.slo import (
    DEFAULT_OBJECTIVES,
    DEFAULT_WINDOWS,
    BurnWindow,
    Objective,
    SloEngine,
    burn_rate,
    engine_from_spool,
    fleet_samples,
)
from tpu_tfrecord.telemetry import Histogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCTOR = os.path.join(REPO, "tools", "tfrecord_doctor.py")

#: bucket_index is an instance method (class-level layout), shared here
_bidx = Histogram().bucket_index


# ---------------------------------------------------------------------------
# Histogram exemplars
# ---------------------------------------------------------------------------


class TestHistogramExemplars:
    def test_observe_attaches_exemplar_to_the_value_bucket(self):
        h = Histogram()
        h.observe(0.25, exemplar=("t1", "s1"))
        idx = _bidx(0.25)
        assert h.exemplars[idx] == ("t1", "s1", 0.25)
        # untagged observations never create exemplars
        h.observe(0.5)
        assert len(h.exemplars) == 1

    def test_exemplar_at_tail_is_the_slow_request(self):
        h = Histogram()
        for _ in range(95):
            h.observe(0.010, exemplar=("tfast", "sfast"))
        for _ in range(5):
            h.observe(2.0, exemplar=("tslow", "sslow"))
        ex = h.exemplar_at(0.99)
        assert ex is not None
        assert ex["trace_id"] == "tslow" and ex["span_id"] == "sslow"
        assert ex["value"] == 2.0
        assert ex["bucket"] == _bidx(2.0)

    def test_exemplar_at_none_when_untagged(self):
        h = Histogram()
        h.observe(0.1)
        assert h.exemplar_at(0.99) is None
        assert Histogram().exemplar_at(0.99) is None

    def test_state_omits_exemplars_key_when_empty(self):
        """Byte compat: an exemplar-free histogram serializes exactly as
        it did before exemplars existed."""
        tagged, plain = Histogram(), Histogram()
        tagged.observe(0.1)
        plain.observe(0.1)
        assert "exemplars" not in plain.state()
        assert json.dumps(tagged.state(), sort_keys=True) == json.dumps(
            plain.state(), sort_keys=True
        )
        tagged.observe(0.2, exemplar=("t", "s"))
        assert "exemplars" in tagged.state()

    def test_merge_state_round_trips_exemplars_last_wins(self):
        a, b = Histogram(), Histogram()
        a.observe(0.1, exemplar=("ta", "sa"))
        b.observe(0.1, exemplar=("tb", "sb"))
        b.observe(3.0, exemplar=("tb2", "sb2"))
        merged = Histogram.from_states([a.state(), b.state()])
        idx = _bidx(0.1)
        # later state wins the shared bucket; b's tail bucket rides along
        assert merged.exemplars[idx] == ("tb", "sb", 0.1)
        assert merged.exemplars[_bidx(3.0)][0] == "tb2"
        # exemplars never perturb the merged counts/quantiles
        bare = Histogram.from_states(
            [{k: v for k, v in st.items() if k != "exemplars"}
             for st in (a.state(), b.state())]
        )
        assert merged.counts == bare.counts
        assert merged.count == bare.count == 3

    def test_merge_state_rejects_malformed_exemplars(self):
        h = Histogram()
        with pytest.raises(ValueError, match="exemplar bucket"):
            h.merge_state(
                {"buckets": {}, "count": 0, "total": 0.0,
                 "exemplars": {"99999": ["t", "s", 1.0]}}
            )
        with pytest.raises(TypeError, match="exemplars"):
            h.merge_state(
                {"buckets": {}, "count": 0, "total": 0.0, "exemplars": [1]}
            )

    def test_bucket_le_is_the_inclusive_upper_bound(self):
        for v in (1e-6, 0.001, 0.05, 0.25, 1.0, 30.0):
            idx = _bidx(v)
            assert v <= Histogram.bucket_le(idx) * (1 + 1e-12)
            if idx > 0:
                assert v > Histogram.bucket_le(idx - 1)


# ---------------------------------------------------------------------------
# Objectives
# ---------------------------------------------------------------------------


class TestObjective:
    def test_parse_round_trips_spec(self):
        a = Objective.parse("availability:0.999")
        assert (a.kind, a.target) == ("availability", 0.999)
        assert Objective.parse(a.spec) == a
        l = Objective.parse("latency:0.95:250")
        assert (l.kind, l.target, l.latency_ms) == ("latency", 0.95, 250.0)
        assert Objective.parse(l.spec) == l

    @pytest.mark.parametrize(
        "spec",
        ["availability", "availability:2", "latency:0.95", "bogus:0.9",
         "latency:0.95:abc", "latency:0.95:-1", ""],
    )
    def test_parse_rejects_bad_specs(self, spec):
        with pytest.raises(ValueError):
            Objective.parse(spec)

    def test_availability_bad_total_counts_sheds_and_misses(self):
        obj = Objective(kind="availability", target=0.999)
        counters = {
            "serve.requests": 97, "serve.rejected": 2,
            "serve.deadline_expired": 1,
        }
        assert obj.bad_total(counters, {}) == (3, 100)
        assert obj.bad_total({}, {}) == (0, 0)

    def test_latency_bad_total_is_bucket_exact_and_never_flatters(self):
        h = Histogram()
        for _ in range(9):
            h.observe(0.100)  # bucket upper bound well under 250 ms
        h.observe(1.0)
        obj = Objective(kind="latency", target=0.9, latency_ms=250.0)
        # accepts a live Histogram and its state() dict identically
        assert obj.bad_total({}, {"serve.latency": h}) == (1, 10)
        assert obj.bad_total({}, {"serve.latency": h.state()}) == (1, 10)
        # a value whose BUCKET straddles the target counts as bad: the
        # bucket's upper bound exceeds the limit, so it cannot be "good"
        edge = Histogram()
        edge.observe(0.249)
        bad, total = obj.bad_total({}, {"serve.latency": edge})
        assert total == 1
        assert bad == (
            0 if Histogram.bucket_le(_bidx(0.249)) <= 0.25
            else 1
        )

    def test_latency_bad_total_missing_stage_is_no_traffic(self):
        obj = Objective(kind="latency", target=0.95, latency_ms=250.0)
        assert obj.bad_total({}, {}) == (0, 0)


# ---------------------------------------------------------------------------
# Burn-rate engine
# ---------------------------------------------------------------------------

#: Seconds-scale copies of the default pair (same thresholds under pin).
FAST = BurnWindow("fast", long_s=60.0, short_s=5.0, threshold=14.4)
SLOW = BurnWindow("slow", long_s=360.0, short_s=30.0, threshold=6.0)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestSloEngine:
    def test_burn_rate_math(self):
        assert burn_rate(0, 0, 0.999) == 0.0  # idle window burns nothing
        assert burn_rate(1, 1000, 0.999) == pytest.approx(1.0)
        assert burn_rate(144, 10000, 0.999) == pytest.approx(14.4)

    def test_scaled_keeps_threshold(self):
        w = DEFAULT_WINDOWS[0].scaled(1.0 / 60.0)
        assert (w.long_s, w.short_s) == (60.0, 5.0)
        assert w.threshold == DEFAULT_WINDOWS[0].threshold == 14.4

    def test_no_data_verdict(self):
        eng = SloEngine(windows=(FAST, SLOW), clock=FakeClock())
        report = eng.evaluate()
        assert report["verdict"] == "no_data" and report["objectives"] == []

    def test_healthy_to_fast_burn_under_shed_storm(self):
        """THE flip: clean traffic reads healthy with a full budget; a
        shed storm inside both fast windows pages — all on a fake clock."""
        clock = FakeClock()
        eng = SloEngine(
            objectives=(Objective(kind="availability", target=0.999),),
            windows=(FAST, SLOW), clock=clock,
        )
        eng.observe({"serve.requests": 0}, ts=0.0)
        eng.observe({"serve.requests": 10000}, ts=100.0)
        healthy = eng.evaluate(now=100.0)
        assert healthy["verdict"] == "healthy"
        obj = healthy["objectives"][0]
        assert obj["budget_remaining"] == pytest.approx(1.0)
        assert not any(w["alerting"] for w in obj["windows"])
        # storm: 1000 sheds in two seconds
        eng.observe(
            {"serve.requests": 10000, "serve.rejected": 500}, ts=101.0
        )
        eng.observe(
            {"serve.requests": 10000, "serve.rejected": 1000}, ts=102.0
        )
        burning = eng.evaluate(now=102.0)
        assert burning["verdict"] == "fast_burn"
        obj = burning["objectives"][0]
        assert obj["verdict"] == "fast_burn"
        fast = next(w for w in obj["windows"] if w["name"] == "fast")
        assert fast["alerting"]
        assert fast["long_burn"] >= 14.4 and fast["short_burn"] >= 14.4
        assert obj["budget_remaining"] < 0  # budget overspent, not clamped

    def test_stale_spike_does_not_page(self):
        """Multi-window discipline: a storm that ended burns the LONG
        window but not the SHORT one — no alert (the classic reason for
        the pair)."""
        clock = FakeClock()
        eng = SloEngine(
            objectives=(Objective(kind="availability", target=0.999),),
            windows=(FAST,), clock=clock,
        )
        eng.observe({"serve.requests": 0}, ts=0.0)
        eng.observe(
            {"serve.requests": 500, "serve.rejected": 500}, ts=30.0
        )  # the old storm
        eng.observe(
            {"serve.requests": 1600, "serve.rejected": 500}, ts=52.0
        )  # clean recovery
        report = eng.evaluate(now=55.0)
        w = report["objectives"][0]["windows"][0]
        assert w["long_burn"] >= FAST.threshold  # storm visible long
        assert w["short_burn"] == 0.0           # but over short
        assert not w["alerting"]
        assert report["verdict"] == "healthy"

    def test_latency_objective_burns_from_histogram_states(self):
        clock = FakeClock()
        eng = SloEngine(
            objectives=(
                Objective(kind="latency", target=0.95, latency_ms=250.0),
            ),
            windows=(FAST, SLOW), clock=clock,
        )
        eng.observe({}, {}, ts=0.0)
        h = Histogram()
        for _ in range(100):
            h.observe(1.0)  # every request 4x over target
        eng.observe({}, {"serve.latency": h.state()}, ts=100.0)
        report = eng.evaluate(now=100.0)
        assert report["verdict"] == "fast_burn"
        assert report["objectives"][0]["bad"] == 100

    def test_out_of_order_sample_dropped(self):
        eng = SloEngine(
            objectives=(Objective(kind="availability", target=0.999),),
            windows=(FAST,), clock=FakeClock(),
        )
        eng.observe({"serve.requests": 100}, ts=10.0)
        eng.observe(
            {"serve.requests": 100, "serve.rejected": 999}, ts=5.0
        )  # stale replay: must not rewrite history
        report = eng.evaluate(now=10.0)
        assert report["objectives"][0]["bad"] == 0
        assert report["verdict"] == "healthy"

    def test_publish_lands_slo_gauges(self):
        clock = FakeClock()
        eng = SloEngine(windows=(FAST, SLOW), clock=clock)
        eng.observe({"serve.requests": 100}, ts=0.0)
        metrics = Metrics()
        report = eng.publish(metrics, now=0.0)
        gauges = metrics.gauges()
        for obj in DEFAULT_OBJECTIVES:
            assert f"slo.{obj.kind}.budget_remaining" in gauges
            assert f"slo.{obj.kind}.fast_burn" in gauges
            assert f"slo.{obj.kind}.slow_burn" in gauges
        assert report["verdict"] == "healthy"

    def test_engine_validation(self):
        with pytest.raises(ValueError, match="objective"):
            SloEngine(objectives=())
        with pytest.raises(ValueError, match="window"):
            SloEngine(windows=())


# ---------------------------------------------------------------------------
# Spool replay
# ---------------------------------------------------------------------------


def _spool_line(seq, heartbeat, counters, hists=None, trace="t" * 16, pid=1):
    return json.dumps({
        "event": "spool", "v": 1, "seq": seq, "ts": heartbeat,
        "interval_s": 1.0,
        "job": {
            "host": "h", "pid": pid, "role": "serve", "trace_id": trace,
            "heartbeat": heartbeat, "created": 0.0,
        },
        "counters": counters, "stages": {}, "gauges": {},
        "hists": hists or {},
    }, sort_keys=True)


def _write_spool(tmp_path, name, lines):
    path = tmp_path / f"{name}.spool.jsonl"
    path.write_text("\n".join(lines) + "\n")
    return path


class TestFleetSamples:
    def test_series_sums_each_process_newest_line_per_heartbeat(
        self, tmp_path
    ):
        _write_spool(tmp_path, "h-1", [
            _spool_line(1, 10.0, {"serve.requests": 5}, pid=1),
            _spool_line(2, 20.0, {"serve.requests": 10}, pid=1),
        ])
        _write_spool(tmp_path, "h-2", [
            _spool_line(1, 15.0, {"serve.requests": 7}, pid=2),
        ])
        series = fleet_samples(str(tmp_path))
        assert [ts for ts, _, _ in series] == [10.0, 15.0, 20.0]
        totals = [c.get("serve.requests", 0) for _, c, _ in series]
        assert totals == [5, 12, 17]  # cumulative per process, summed

    def test_trace_id_scopes_a_reused_dir(self, tmp_path):
        _write_spool(tmp_path, "h-1", [
            _spool_line(1, 10.0, {"serve.requests": 5}, trace="a" * 16),
        ])
        _write_spool(tmp_path, "h-2", [
            _spool_line(1, 11.0, {"serve.requests": 999}, trace="b" * 16,
                        pid=2),
        ])
        series = fleet_samples(str(tmp_path), trace_id="a" * 16)
        assert len(series) == 1
        assert series[0][1]["serve.requests"] == 5

    def test_bad_hist_state_loses_the_stage_never_the_series(self, tmp_path):
        good = Histogram()
        good.observe(0.1, exemplar=("t", "s"))
        _write_spool(tmp_path, "h-1", [
            _spool_line(1, 10.0, {}, hists={"serve.latency": good.state()}),
        ])
        _write_spool(tmp_path, "h-2", [
            _spool_line(
                1, 10.0, {"serve.requests": 3},
                hists={"serve.latency": {
                    "buckets": {}, "count": 0, "total": 0.0,
                    "layout": [1.0, 1.0, 7],  # version-skewed geometry
                }},
                pid=2,
            ),
        ])
        series = fleet_samples(str(tmp_path))
        assert len(series) == 1
        ts, counters, hists = series[0]
        assert counters["serve.requests"] == 3  # bad hist didn't drop proc
        assert hists["serve.latency"].count == 1  # good state merged
        # exemplars survive the spool round trip into the merged series
        assert hists["serve.latency"].exemplar_at(0.99)["trace_id"] == "t"

    def test_unreadable_dir_raises(self, tmp_path):
        with pytest.raises(OSError):
            fleet_samples(str(tmp_path / "missing"))

    def test_engine_from_spool_none_vs_engine(self, tmp_path):
        assert engine_from_spool(str(tmp_path)) is None  # no fleet != idle
        _write_spool(tmp_path, "h-1", [
            _spool_line(1, 0.0, {"serve.requests": 0}),
            _spool_line(
                2, 100.0, {"serve.requests": 100, "serve.rejected": 400}
            ),
        ])
        eng = engine_from_spool(
            str(tmp_path),
            objectives=(Objective(kind="availability", target=0.999),),
            windows=(FAST, SLOW),
        )
        assert eng is not None
        assert eng.evaluate(now=100.0)["verdict"] == "fast_burn"


# ---------------------------------------------------------------------------
# tfrecord_doctor slo / merge-trace (subprocess)
# ---------------------------------------------------------------------------


def _doctor(*argv):
    return subprocess.run(
        [sys.executable, DOCTOR, *argv],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


class TestDoctorSlo:
    def _storm_spool(self, tmp_path):
        _write_spool(tmp_path, "h-1", [
            _spool_line(1, 0.0, {"serve.requests": 0}),
            _spool_line(
                2, 100.0, {"serve.requests": 1000, "serve.rejected": 500}
            ),
        ])

    def test_json_mirrors_text_lines_and_flags_the_burn(self, tmp_path):
        self._storm_spool(tmp_path)
        args = (
            "slo", str(tmp_path), "--objective", "availability:0.999",
            "--now", "100",
        )
        text = _doctor(*args)
        assert text.returncode == 0, (text.stdout, text.stderr)
        lines = [json.loads(l) for l in text.stdout.strip().splitlines()]
        doc = _doctor(*args, "--json")
        assert doc.returncode == 0, (doc.stdout, doc.stderr)
        assert json.loads(doc.stdout)["events"] == lines  # the round trip
        objective, summary = lines
        assert objective["event"] == "objective"
        assert objective["objective"] == "availability:0.999"
        assert objective["bad"] == 500 and objective["total"] == 1500
        fast = next(
            w for w in objective["windows"] if w["name"] == "fast"
        )
        assert fast["alerting"] and fast["threshold"] == 14.4
        assert summary["event"] == "slo"
        assert summary["verdict"] == "fast_burn"  # a finding, exit 0

    def test_no_spool_snapshots_exits_2(self, tmp_path):
        proc = _doctor("slo", str(tmp_path), "--json")
        assert proc.returncode == 2
        events = json.loads(proc.stdout)["events"]
        assert events[-1]["event"] == "error"
        assert "no spool snapshots" in events[-1]["error"]

    def test_bad_objective_spec_exits_2(self, tmp_path):
        self._storm_spool(tmp_path)
        proc = _doctor("slo", str(tmp_path), "--objective", "bogus:0.9")
        assert proc.returncode == 2
        err = json.loads(proc.stdout.strip().splitlines()[-1])
        assert err["event"] == "error" and "bogus" in err["error"]

    def test_window_scale_shrinks_windows_not_thresholds(self, tmp_path):
        # the same storm viewed through 3600x-longer windows still anchors
        # at origin, so this just pins the flag parses and reports
        self._storm_spool(tmp_path)
        proc = _doctor(
            "slo", str(tmp_path), "--objective", "availability:0.999",
            "--window-scale", "0.01", "--now", "100",
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        objective = json.loads(proc.stdout.strip().splitlines()[0])
        assert {w["threshold"] for w in objective["windows"]} == {14.4, 6.0}


class TestMergeTraceDirectory:
    def test_directory_expands_to_sorted_trace_files(self, tmp_path):
        traces = tmp_path / "traces"
        traces.mkdir()
        for i, name in enumerate(["b.json", "a.json"]):
            (traces / name).write_text(json.dumps({
                "traceEvents": [{
                    "name": f"ev{i}", "ph": "X", "ts": 0, "dur": 1,
                    "pid": i, "tid": 0, "args": {},
                }],
            }))
        out = tmp_path / "merged.json"
        proc = _doctor("merge-trace", str(out), str(traces))
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        merged = json.loads(out.read_text())
        names = {e["name"] for e in merged["traceEvents"]}
        assert {"ev0", "ev1"} <= names
        final = json.loads(proc.stdout.strip().splitlines()[-1])
        assert final["inputs"] == 2

    def test_empty_directory_exits_2(self, tmp_path):
        empty = tmp_path / "traces"
        empty.mkdir()
        proc = _doctor("merge-trace", str(tmp_path / "out.json"), str(empty))
        assert proc.returncode == 2
        err = json.loads(proc.stdout.strip().splitlines()[-1])
        assert err["event"] == "error"
