"""Overload-proof serving tier (ISSUE 18): continuous batching with
admission control, request deadlines, and chaos-certified degradation.

The load-bearing pins:
  - BYTE PARITY: N concurrent clients through one server produce exactly
    the bytes of N sequential `LMStream` runs — including with a
    mid-generation client disconnect and a deadline expiry in the batch
    (the per-slot isolation property makes slot position and neighbors
    irrelevant; tests/test_pipeline_stream.py pins that half).
  - ADMISSION: the bounded queue sheds EXACTLY the over-admission excess
    (`serve.rejected`, Retry-After hint), never silently queues, and a
    deadline is enforced at admission AND at every tick — an expired
    in-flight request frees its slot immediately and is never served
    late.
  - DEGRADATION: `faults.py` op="serve" chaos (slow_client /
    client_disconnect / burst) rides the same replayable ledger; a
    SIGKILLed replica under the scaler drains through the survivor and
    the `min_workers` floor refills it.
  - CHECKPOINT: the serving-side checkpoint read routes through the
    manifest-last restore path — a generation killed mid-commit (parked
    with the ckpt-chaos seam) is invisible, never half-read.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from tpu_tfrecord import elastic, faults, telemetry
from tpu_tfrecord import service_protocol as sp
from tpu_tfrecord.metrics import METRICS, Metrics
from tpu_tfrecord.models import lm
from tpu_tfrecord.serving import (
    DeadlineExpired,
    ServeClient,
    ServePolicy,
    ServeRejected,
    ServeServer,
    ServingEngine,
    sequential_reference,
)
from tpu_tfrecord.tpu import create_mesh

CFG = lm.LMConfig(
    vocab_size=96, d_model=32, n_heads=2, n_layers=4,
    max_len=16, n_micro=4, n_virtual=1,
)
MB = 4


@pytest.fixture(scope="module")
def model():
    """One tiny seeded LM + 2-stage pipe mesh shared by the module (the
    compiled per-tick step is the expensive part)."""
    params = lm.init_params(jax.random.key(0), CFG)
    mesh = create_mesh({"pipe": 2}, jax.devices()[:2])
    return params, CFG, mesh


def _windows(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, CFG.vocab_size, size=CFG.max_len).astype(np.int32)
        for _ in range(n)
    ]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# Policy / verdict units
# ---------------------------------------------------------------------------


class TestServePolicy:
    def test_validation(self):
        with pytest.raises(ValueError, match="mb"):
            ServePolicy(mb=0)
        with pytest.raises(ValueError, match="max_queue"):
            ServePolicy(max_queue=0)
        with pytest.raises(ValueError, match="retry_after_s"):
            ServePolicy(retry_after_s=-1.0)

    def test_hint_scales_with_queue_pressure(self):
        pol = ServePolicy(mb=4, retry_after_s=0.1)
        assert pol.hint(0) == pytest.approx(0.1)
        assert pol.hint(8) > pol.hint(4) > pol.hint(0)


class TestServingVerdict:
    def test_no_data_is_unknown(self):
        assert telemetry.serving_verdict(None, 0, 250.0) == "unknown"

    def test_meeting_slo(self):
        assert telemetry.serving_verdict(100.0, 3, 250.0) == "meeting_slo"

    def test_missing_slo_with_full_queue_is_queue_bound(self):
        assert telemetry.serving_verdict(
            900.0, 8, 250.0, max_queue=16
        ) == "queue_bound"

    def test_missing_slo_with_empty_queue_is_compute_bound(self):
        assert telemetry.serving_verdict(
            900.0, 0, 250.0, max_queue=16
        ) == "compute_bound"


# ---------------------------------------------------------------------------
# Admission control (no engine thread: deterministic)
# ---------------------------------------------------------------------------


class TestAdmission:
    def _engine(self, model, **pol):
        params, cfg, mesh = model
        metrics = Metrics()
        clock = FakeClock()
        eng = ServingEngine(
            params, cfg, mesh,
            policy=ServePolicy(mb=MB, **pol), metrics=metrics, clock=clock,
        )
        return eng, metrics, clock

    def test_queue_full_shed_loudly_with_hint(self, model):
        eng, metrics, _ = self._engine(model, max_queue=3)
        ws = _windows(4, seed=1)
        for w in ws[:3]:
            eng.submit(w, 1)
        with pytest.raises(ServeRejected, match="queue full") as ei:
            eng.submit(ws[3], 1)
        assert ei.value.retry_after_s > 0
        assert metrics.counter("serve.rejected") == 1
        eng.stop()

    def test_draining_rejects_new_requests(self, model):
        eng, _, _ = self._engine(model)
        eng.drain()
        with pytest.raises(ServeRejected, match="draining"):
            eng.submit(_windows(1)[0], 1)

    def test_deadline_unmeetable_at_admission(self, model):
        eng, metrics, clock = self._engine(model)
        clock.advance(10.0)
        with pytest.raises(DeadlineExpired, match="admission"):
            eng.submit(_windows(1)[0], 1, deadline_s=0.0)
        assert metrics.counter("serve.deadline_expired") == 1
        eng.stop()

    def test_bad_request_shapes_rejected(self, model):
        eng, _, _ = self._engine(model)
        with pytest.raises(ValueError, match="window shape"):
            eng.submit(np.zeros(7, np.int32), 1)
        with pytest.raises(ValueError, match="n_new"):
            eng.submit(_windows(1)[0], 0)
        eng.stop()

    def test_overload_sheds_exactly_the_excess(self, model):
        """The chaos-acceptance half that needs no wall clock: a seeded
        burst of 10 against capacity 3 sheds exactly 7 (counted), every
        admitted request completes with the reference bytes, and ZERO
        admitted requests miss a deadline."""
        params, cfg, mesh = model
        eng, metrics, _ = self._engine(model, max_queue=3)
        ws = _windows(10, seed=2)
        admitted, shed = [], 0
        for w in ws:
            try:
                admitted.append((w, eng.submit(w, 2, deadline_s=60.0)))
            except ServeRejected:
                shed += 1
        assert len(admitted) == 3 and shed == 7
        assert metrics.counter("serve.rejected") == 7
        eng.run_until_idle()
        ref = sequential_reference(
            params, cfg, mesh, [(w, 2) for w, _ in admitted], MB
        )
        for (w, req), want in zip(admitted, ref):
            assert req.result(timeout=0) == want
        assert metrics.counter("serve.deadline_expired") == 0
        assert metrics.counter("serve.requests") == 3
        eng.stop()


# ---------------------------------------------------------------------------
# Engine byte parity: continuous batching == sequential runs
# ---------------------------------------------------------------------------


class TestEngineParity:
    def test_multiplexed_equals_sequential(self, model):
        """THE pin: mixed-length requests packed/refilled across ticks
        produce, bitwise, the tokens of one-at-a-time runs."""
        params, cfg, mesh = model
        metrics = Metrics()
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=32),
            metrics=metrics,
        )
        reqs = [(w, 1 + i % 3) for i, w in enumerate(_windows(7, seed=3))]
        handles = [eng.submit(w, n) for w, n in reqs]
        eng.run_until_idle()
        ref = sequential_reference(params, cfg, mesh, reqs, MB)
        for h, want in zip(handles, ref):
            assert h.result(timeout=0) == want
        assert metrics.counter("serve.requests") == 7
        eng.stop()

    def test_deadline_expiry_in_batch_frees_slot_without_perturbing(
        self, model
    ):
        """A deadline passing MID-GENERATION: the request is finished
        loudly (never served late), its slot frees on the next pack, and
        its neighbors' bytes are exactly the sequential reference."""
        params, cfg, mesh = model
        metrics = Metrics()
        clock = FakeClock()
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=32),
            metrics=metrics, clock=clock,
        )
        ws = _windows(4, seed=4)
        survivors = [eng.submit(w, 3) for w in ws[:3]]
        doomed = eng.submit(ws[3], 3, deadline_s=1.5)
        assert eng.step() == 4  # tick 1: all four get token 1
        clock.advance(2.0)      # the deadline passes while queued/continuing
        while eng.step() > 0:
            pass
        with pytest.raises(DeadlineExpired):
            doomed.result(timeout=0)
        assert len(doomed.out) < 3, "expired request must not be served late"
        assert metrics.counter("serve.deadline_expired") == 1
        ref = sequential_reference(
            params, cfg, mesh, [(w, 3) for w in ws[:3]], MB
        )
        for h, want in zip(survivors, ref):
            assert h.result(timeout=0) == want
        eng.stop()

    def test_cancel_frees_slot_without_perturbing(self, model):
        """Client abandonment (the engine half of a disconnect): cancel
        mid-generation, neighbors' bytes unchanged."""
        params, cfg, mesh = model
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=32),
            metrics=Metrics(),
        )
        ws = _windows(4, seed=5)
        keep = [eng.submit(w, 3) for w in ws[:3]]
        gone = eng.submit(ws[3], 3)
        assert eng.step() == 4
        eng.cancel(gone)
        eng.run_until_idle()
        with pytest.raises(ServeRejected, match="cancelled"):
            gone.result(timeout=0)
        ref = sequential_reference(
            params, cfg, mesh, [(w, 3) for w in ws[:3]], MB
        )
        for h, want in zip(keep, ref):
            assert h.result(timeout=0) == want
        eng.stop()


# ---------------------------------------------------------------------------
# Request-scoped tracing (ISSUE 20): root spans, children, exemplars
# ---------------------------------------------------------------------------


class TestRequestTracing:
    @pytest.fixture(autouse=True)
    def _recorder(self):
        """The span recorder is process-global: scrub it around every
        tracing test so neither direction leaks spans."""
        telemetry.RECORDER.clear()
        telemetry.enable()
        yield
        telemetry.disable()
        telemetry.RECORDER.clear()

    def test_one_root_span_per_admitted_request_exact_duration(self, model):
        """THE tracing pin: a seeded run shaped like the acceptance
        criterion (4 served requests, one cancel = client disconnect, one
        in-flight deadline expiry) yields EXACTLY one `serve.request`
        span per admitted request, with t0 = admission and duration =
        admission -> completion EXACTLY on the engine's fake clock; the
        latency histogram's tail exemplar resolves to one of those
        spans."""
        params, cfg, mesh = model
        metrics = Metrics()
        clock = FakeClock()
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=32),
            metrics=metrics, clock=clock,
        )
        ws = _windows(6, seed=20)
        finishes = {}

        def on_done(req):
            # the engine clock is frozen within a tick, so clock() here
            # IS the `now` _finish stamped into the span
            finishes[req.rid] = (req.status, clock())

        admitted = []
        for i, w in enumerate(ws[:4]):
            clock.advance(0.125)  # staggered admissions: distinct births
            admitted.append(eng.submit(w, 1 + i % 3, on_done=on_done))
        gone = eng.submit(ws[4], 3, on_done=on_done)
        doomed = eng.submit(ws[5], 3, deadline_s=1.5, on_done=on_done)
        admitted += [gone, doomed]
        eng.cancel(gone)        # disconnects before ever claiming a slot
        assert eng.step() == 4  # tick 1: the four serveable slots
        clock.advance(2.0)      # the in-flight deadline passes
        eng.run_until_idle()
        eng.stop()

        assert finishes[gone.rid][0] == "cancelled"
        assert finishes[doomed.rid][0] == "deadline_expired"
        spans = [
            s for s in telemetry.RECORDER.spans() if s[0] == "serve.request"
        ]
        assert len(spans) == len(admitted) == 6
        by_sid = {s[4]["span_id"]: s for s in spans}
        assert len(by_sid) == 6, "span ids must be unique per request"
        for req in admitted:
            _, t0_ns, dur_ns, _, attrs, ph = by_sid[req.span_id]
            status, done_t = finishes[req.rid]
            assert ph == "X"
            assert t0_ns == int(req.birth * 1e9)
            assert dur_ns == int((done_t - req.birth) * 1e9)
            assert attrs["status"] == status
            assert attrs["trace_id"] == req.trace_id
            assert attrs["rid"] == req.rid

        # every SERVED request carries a queue_wait child and >=1 tick
        # child parented under its span id; the refused two carry none
        children = {}
        for s in telemetry.RECORDER.spans():
            parent = (s[4] or {}).get("parent_span_id")
            if s[0] in ("serve.queue_wait", "serve.tick") and parent:
                children.setdefault(parent, set()).add(s[0])
        for req in admitted[:4]:
            assert children[req.span_id] == {
                "serve.queue_wait", "serve.tick"
            }
        assert gone.span_id not in children
        assert doomed.span_id not in children

        # the fleet-mergeable latency histogram's p99 exemplar names a
        # recorded request span, and its value is that span's duration
        merged = telemetry.Histogram.from_states(
            [metrics.hist_states()["serve.latency"]]
        )
        ex = merged.exemplar_at(0.99)
        assert ex is not None
        assert ex["span_id"] in by_sid
        root = by_sid[ex["span_id"]]
        assert root[4]["trace_id"] == ex["trace_id"]
        assert ex["value"] == pytest.approx(root[2] / 1e9)

    def test_admission_refusals_land_attributable_instants(self, model):
        """A refused request never gets a root span (it was never
        admitted) but its shed/expiry instant carries the trace identity,
        so it is still attributable in a merged timeline."""
        params, cfg, mesh = model
        clock = FakeClock()
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=1),
            metrics=Metrics(), clock=clock,
        )
        ws = _windows(3, seed=21)
        eng.submit(ws[0], 1)
        with pytest.raises(ServeRejected):
            eng.submit(ws[1], 1)
        with pytest.raises(DeadlineExpired):
            eng.submit(ws[2], 1, deadline_s=0.0)
        events = telemetry.RECORDER.spans()
        sheds = [s for s in events if s[0] == "serve.shed"]
        expiries = [s for s in events if s[0] == "serve.deadline_expired"]
        assert len(sheds) == 1
        assert sheds[0][5] == "i"
        assert sheds[0][4]["reason"] == "queue_full"
        assert sheds[0][4]["trace_id"] and sheds[0][4]["span_id"]
        assert len(expiries) == 1
        assert expiries[0][4]["at"] == "admission"
        eng.run_until_idle()
        requests = [
            s for s in telemetry.RECORDER.spans() if s[0] == "serve.request"
        ]
        assert len(requests) == 1  # only the admitted one
        eng.stop()

    def test_byte_parity_unchanged_with_tracing_enabled(self, model):
        """The serving parity pin holds verbatim with the recorder ON:
        tracing is observation, never perturbation."""
        params, cfg, mesh = model
        metrics = Metrics()
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=32),
            metrics=metrics,
        )
        reqs = [(w, 1 + i % 3) for i, w in enumerate(_windows(7, seed=3))]
        handles = [eng.submit(w, n) for w, n in reqs]
        eng.run_until_idle()
        ref = sequential_reference(params, cfg, mesh, reqs, MB)
        for h, want in zip(handles, ref):
            assert h.result(timeout=0) == want
        assert metrics.counter("serve.requests") == 7
        roots = [
            s for s in telemetry.RECORDER.spans() if s[0] == "serve.request"
        ]
        assert len(roots) == 7
        eng.stop()


# ---------------------------------------------------------------------------
# Socket tier: concurrent clients, disconnect chaos, drain
# ---------------------------------------------------------------------------


@pytest.fixture
def server(model):
    params, cfg, mesh = model
    metrics = Metrics()
    eng = ServingEngine(
        params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=32),
        metrics=metrics,
    )
    srv = ServeServer(eng, port=0).start()
    yield srv, metrics
    srv.stop()


class TestServeServer:
    def test_concurrent_clients_with_disconnect_byte_identical(
        self, model, server
    ):
        """The acceptance pin on the wire: 4 concurrent clients, one of
        them disconnecting mid-generation — the 3 survivors' bytes equal
        the sequential reference, the dropped slot frees (the engine
        drains to idle), and the loss is counted once."""
        params, cfg, mesh = model
        srv, metrics = server
        ws = _windows(4, seed=6)

        # the doomed client: raw socket, long request, hang up mid-run
        doomed = sp.connect(srv.addr, timeout=10.0)
        sp.send_msg(doomed, {
            "v": sp.PROTO_VERSION, "op": "generate", "req": 1,
            "tokens": ws[3].tolist(), "n_new": 500, "deadline_s": None,
        })
        deadline = time.monotonic() + 30
        while metrics.gauge_value("serve.in_flight", 0.0) < 1:
            assert time.monotonic() < deadline, "request never started"
            time.sleep(0.01)
        doomed.close()

        results: dict = {}

        def client(i):
            c = ServeClient([srv.addr])
            try:
                results[i] = c.generate(ws[i], n_new=3)
            finally:
                c.close()

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        ref = sequential_reference(
            params, cfg, mesh, [(w, 3) for w in ws[:3]], MB
        )
        for i in range(3):
            assert results[i] == ref[i], f"client {i} diverged"
        # the abandoned slot freed: the engine drains to idle
        deadline = time.monotonic() + 30
        while True:
            rep = srv.engine.report()
            if rep["queue_depth"] == 0 and rep["in_flight"] == 0:
                break
            assert time.monotonic() < deadline, rep
            time.sleep(0.05)
        assert metrics.counter("serve.disconnects") == 1

    def test_injected_disconnect_chaos_is_survivable(self, model):
        """faults.py op='serve' client_disconnect on the reply seam: the
        victim's connection drops (counted), the client's RetryPolicy
        resends, and every byte still matches the reference — chaos is
        invisible to correctness."""
        params, cfg, mesh = model
        metrics = Metrics()
        plan = faults.FaultPlan([
            faults.FaultRule(op="serve", kind="client_disconnect",
                             path="reply:", times=1),
        ])
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=32),
            metrics=metrics,
        )
        srv = ServeServer(eng, port=0, fault_plan=plan).start()
        try:
            ws = _windows(3, seed=7)
            results: dict = {}

            def client(i):
                c = ServeClient([srv.addr])
                try:
                    results[i] = c.generate(ws[i], n_new=2)
                finally:
                    c.close()

            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            ref = sequential_reference(
                params, cfg, mesh, [(w, 2) for w in ws], MB
            )
            for i in range(3):
                assert results[i] == ref[i]
            fired = [e for e in plan.ledger
                     if e["kind"] == "client_disconnect"]
            assert len(fired) == 1, "the injected disconnect never fired"
        finally:
            srv.stop()

    def test_status_reply_carries_report_fields(self, model, server):
        """The wire contract clients and the scaler read: status carries
        the full engine report (queue depth, verdict, counters)."""
        srv, _ = server
        sock = sp.connect(srv.addr, timeout=10.0)
        try:
            st = sp.request(sock, srv.addr, {
                "v": sp.PROTO_VERSION, "op": "status", "req": 1,
            })
            for key in ("queue_depth", "in_flight", "verdict", "mb",
                        "max_queue", "counters", "addr", "pid"):
                assert key in st, key
        finally:
            sock.close()

    def test_version_skew_rejected(self, model, server):
        srv, _ = server
        sock = sp.connect(srv.addr, timeout=10.0)
        try:
            rep = sp.request(sock, srv.addr, {
                "v": sp.PROTO_VERSION + 1, "op": "ping", "req": 1,
            })
            assert rep["ok"] is False and rep["error"] == "version_skew"
        finally:
            sock.close()

    def test_drain_finishes_in_flight_then_rejects(self, model, server):
        """Scale-down's goodbye: drain stops admission, finishes what was
        admitted, and flips the drained latch."""
        srv, _ = server
        c = ServeClient([srv.addr])
        try:
            w = _windows(1, seed=9)[0]
            got = c.generate(w, n_new=2)
            assert len(got) == 2
            rep = c.drain()
            assert rep["ok"] and rep["draining"]
            assert srv.drained.wait(30)
            with pytest.raises((ServeRejected, ConnectionError)):
                c.generate(w, n_new=1)
        finally:
            c.close()


class TestOverloadReply:
    def test_shed_reply_carries_retry_after(self, model):
        """One queue slot, no engine thread: the second concurrent
        generate is shed on the wire with 'overloaded' + a positive
        Retry-After hint (the client backoff floor)."""
        params, cfg, mesh = model
        eng = ServingEngine(
            params, cfg, mesh, policy=ServePolicy(mb=MB, max_queue=1),
            metrics=Metrics(),
        )
        srv = ServeServer(eng, port=0)
        srv._accept_thread = threading.Thread(
            target=srv._accept_loop, daemon=True
        )
        srv._accept_thread.start()
        try:
            w = _windows(1, seed=10)[0].tolist()
            s1 = sp.connect(srv.addr, timeout=10.0)
            sp.send_msg(s1, {
                "v": sp.PROTO_VERSION, "op": "generate", "req": 1,
                "tokens": w, "n_new": 1, "deadline_s": None,
            })
            s2 = sp.connect(srv.addr, timeout=10.0)
            deadline = time.monotonic() + 10
            while True:  # wait for req 1 to occupy the one queue slot
                if srv.engine.report()["queue_depth"] >= 1:
                    break
                assert time.monotonic() < deadline
                time.sleep(0.01)
            rep = sp.request(s2, srv.addr, {
                "v": sp.PROTO_VERSION, "op": "generate", "req": 2,
                "tokens": w, "n_new": 1, "deadline_s": None,
            })
            assert rep["ok"] is False and rep["error"] == "overloaded"
            assert rep["retry_after_s"] > 0
            s1.close()
            s2.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# op="serve" fault vocabulary
# ---------------------------------------------------------------------------


class TestServeFaults:
    def test_serve_kinds_require_serve_op(self):
        for kind in faults.SERVE_ONLY_KINDS:
            with pytest.raises(ValueError, match="op='serve'"):
                faults.FaultRule(op="read", kind=kind, stall_ms=5,
                                 burst_n=1)

    def test_serve_op_rejects_foreign_kinds(self):
        with pytest.raises(ValueError, match="op='serve' supports"):
            faults.FaultRule(op="serve", kind="short_read", cap_bytes=1)

    def test_slow_client_requires_stall(self):
        with pytest.raises(ValueError, match="stall_ms"):
            faults.FaultRule(op="serve", kind="slow_client")

    def test_burst_requires_n(self):
        with pytest.raises(ValueError, match="burst_n"):
            faults.FaultRule(op="serve", kind="burst")

    def test_apply_serve_slow_client_stalls_and_ledgers(self):
        slept = []
        plan = faults.FaultPlan(
            [faults.FaultRule(op="serve", kind="slow_client",
                              stall_ms=40.0)],
            sleep=slept.append,
        )
        assert plan.apply_serve("reply:127.0.0.1:5") == 0
        assert slept == [0.04]
        assert plan.ledger[0]["kind"] == "slow_client"
        assert plan.ledger[0]["stall_ms"] == 40.0

    def test_apply_serve_disconnect_closes_socket_and_raises(self):
        import socket as _socket

        a, b = _socket.socketpair()
        try:
            plan = faults.FaultPlan([
                faults.FaultRule(op="serve", kind="client_disconnect"),
            ])
            with pytest.raises(faults.InjectedFault):
                plan.apply_serve("recv:peer", sock=a)
            assert a.fileno() == -1, "socket must be closed"
        finally:
            for s in (a, b):
                try:
                    s.close()
                except OSError:
                    pass

    def test_apply_serve_burst_returns_extra_request_count(self):
        plan = faults.FaultPlan([
            faults.FaultRule(op="serve", kind="burst", burst_n=5),
        ])
        assert plan.apply_serve("admit") == 5
        assert plan.apply_serve("admit") == 0  # times=1: fired out
        assert plan.ledger[0]["kind"] == "burst"

    def test_round_trips_through_json(self):
        plan = faults.FaultPlan([
            faults.FaultRule(op="serve", kind="slow_client", stall_ms=10,
                             path="reply:"),
            faults.FaultRule(op="serve", kind="burst", burst_n=3),
        ], seed=7)
        again = faults.FaultPlan.from_json(json.dumps(plan.to_json()))
        assert again.to_json() == plan.to_json()


# ---------------------------------------------------------------------------
# ServingScaler: queue_bound grows, idle drains, SIGKILL refills
# ---------------------------------------------------------------------------


class _FakeFleet:
    """In-memory replicas for the scaler state machine: spawn() mints an
    address; statuses are scripted per test."""

    def __init__(self):
        self.n = 0
        self.load = {}  # addr -> status dict overrides
        self.dead = set()
        self.draining = set()

    def spawn(self):
        self.n += 1
        addr = f"127.0.0.1:{9000 + self.n}"
        self.load[addr] = {}
        return addr

    def status(self, addr):
        if addr in self.dead:
            raise ConnectionError("SIGKILLed")
        base = {
            "queue_depth": 0, "in_flight": 0, "p99_ms": 50.0,
            "slo_p99_ms": 250.0, "max_queue": 16, "completed": 0,
            "draining": addr in self.draining,
        }
        base.update(self.load.get(addr, {}))
        return base

    def drain(self, addr):
        self.draining.add(addr)
        return {"ok": True, "draining": True}


def _scaler(fleet, **pol):
    clock = FakeClock()
    s = elastic.ServingScaler(
        fleet.spawn,
        policy=elastic.ScalerPolicy(
            min_workers=1, max_workers=4, hysteresis=2, cooldown_s=1.0,
            **pol,
        ),
        status_fn=fleet.status, drain_fn=fleet.drain, clock=clock,
    )
    return s, clock


class TestServingScaler:
    def test_grows_on_queue_bound_and_drains_on_idle(self):
        fleet = _FakeFleet()
        s, clock = _scaler(fleet)
        assert s.step()["reason"] == "below_min"  # empty fleet -> floor
        addr = s.replicas[0]
        # sustained overload: full queue + missed SLO -> queue_bound
        fleet.load[addr] = {
            "queue_depth": 12, "p99_ms": 900.0, "completed": 10,
        }
        grew = None
        for _ in range(6):
            clock.advance(2.0)
            fleet.load[addr]["completed"] += 5  # not idle
            grew = s.step()
            if grew:
                break
        assert grew and grew["action"] == "scale_up"
        assert grew["reason"] == "queue_bound"
        assert len(s.replicas) == 2
        # load vanishes: empty queues + zero completions -> idle -> drain
        for a in s.replicas:
            fleet.load[a] = {"queue_depth": 0, "completed": 50}
        shrank = None
        for _ in range(8):
            clock.advance(2.0)
            shrank = s.step() or shrank
        assert shrank and shrank["action"] == "scale_down"
        assert shrank["reason"] == "idle"
        assert fleet.draining, "the victim never got the drain RPC"

    def test_drained_replica_death_is_a_clean_goodbye(self):
        fleet = _FakeFleet()
        s, clock = _scaler(fleet)
        s.step()
        victim = fleet.spawn()
        s.replicas.append(victim)
        fleet.drain(victim)
        s._draining.add(victim)
        before = METRICS.counter("elastic.drains")
        lost = METRICS.counter("elastic.replicas_lost")
        fleet.dead.add(victim)  # drained replica exits on its own
        clock.advance(2.0)
        s.step()
        assert victim not in s.replicas
        assert METRICS.counter("elastic.drains") == before + 1
        assert METRICS.counter("elastic.replicas_lost") == lost

    def test_sigkill_refills_below_floor_bypassing_climber(self):
        """An UNDRAINED death is a kill: counted `elastic.replicas_lost`
        and refilled on the very next tick (no hysteresis wait)."""
        fleet = _FakeFleet()
        s, clock = _scaler(fleet)
        s.step()
        victim = s.replicas[0]
        lost = METRICS.counter("elastic.replicas_lost")
        fleet.dead.add(victim)
        clock.advance(2.0)
        decision = s.step()
        assert METRICS.counter("elastic.replicas_lost") == lost + 1
        assert decision is not None and decision["reason"] == "below_min"
        assert len(s.replicas) == 1 and s.replicas[0] != victim


# ---------------------------------------------------------------------------
# Subprocess chaos: SIGKILLed replica drains through the survivor
# ---------------------------------------------------------------------------


def _replica_env():
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }


@pytest.mark.slow
class TestReplicaKillChaos:
    def test_sigkill_drains_through_survivor_and_scaler_refills(
        self, tmp_path
    ):
        """The acceptance scenario end-to-end with real processes: two
        seeded replicas, one SIGKILLed mid-fleet — the client walks the
        member list so its requests drain through the survivor with the
        reference bytes, and the scaler's next tick counts the loss and
        refills the floor."""
        spawner = elastic.ServingReplicaSpawner(
            extra_args=(
                "--stages", "1", "--layers", "2", "--d-model", "16",
                "--heads", "2", "--mb", "2", "--seed", "5",
            ),
            env=_replica_env(),
        )
        scaler = elastic.ServingScaler(
            spawner,
            policy=elastic.ScalerPolicy(
                min_workers=2, max_workers=3, hysteresis=2, cooldown_s=0.0,
            ),
        )
        try:
            scaler.step()  # below_min: 1st replica
            scaler.step()  # below_min: 2nd replica
            assert len(scaler.replicas) == 2
            addrs = list(scaler.replicas)

            cfg = lm.LMConfig(
                vocab_size=96, d_model=16, n_heads=2, n_layers=2,
                max_len=16, n_micro=2, n_virtual=1,
            )
            params = lm.init_params(jax.random.key(5), cfg)
            mesh = create_mesh({"pipe": 1}, jax.devices()[:1])
            ws = _windows(3, seed=11)
            ref = sequential_reference(
                params, cfg, mesh, [(w, 2) for w in ws], 2
            )

            c = ServeClient(addrs)
            try:
                assert c.generate(ws[0], 2) == ref[0]
                # SIGKILL the replica the client is currently pinned to:
                # the next request MUST rotate to the survivor
                victim_addr = c.addr
                victim = next(
                    p for p, a in zip(spawner.procs, addrs)
                    if a == victim_addr
                )
                os.kill(victim.pid, signal.SIGKILL)
                victim.wait(timeout=30)
                assert c.generate(ws[1], 2) == ref[1]
                assert c.generate(ws[2], 2) == ref[2]
            finally:
                c.close()

            lost = METRICS.counter("elastic.replicas_lost")
            decision = scaler.step()  # census the corpse, refill the floor
            assert METRICS.counter("elastic.replicas_lost") == lost + 1
            assert decision is not None and decision["reason"] == "below_min"
            assert len(scaler.replicas) == 2
            assert victim_addr not in scaler.replicas
        finally:
            scaler.stop()
            spawner.reap()


# ---------------------------------------------------------------------------
# Checkpoint chaos pin: serving load never half-reads a generation
# ---------------------------------------------------------------------------

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
EXAMPLES_DIR = os.path.join(os.path.dirname(TESTS_DIR), "examples")


@pytest.mark.slow
class TestServeCheckpointChaosPin:
    def test_load_skips_generation_killed_mid_commit(self, tmp_path):
        """Park the LMCheckpoint writer at pre_manifest on generation 8
        (generation 4 complete), SIGKILL it there, then run serve_lm's
        `load_checkpoint` against the wreckage: it must serve generation
        4 — the newest COMPLETE one — and never touch the manifest-less
        gen-8 carcass."""
        import ckpt_chaos_worker as worker

        d = str(tmp_path / "ckpt")
        mark = str(tmp_path / "mark")
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "TFR_CKPT_CHAOS_STAGE": "pre_manifest",
            "TFR_CKPT_CHAOS_MARK": mark,
            "TFR_CKPT_CHAOS_SKIP": "1",
        }
        p = subprocess.Popen(
            [sys.executable, os.path.join(TESTS_DIR, "ckpt_chaos_worker.py"),
             "lm", d, "--steps", "12", "--save-every", "4"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        try:
            deadline = time.time() + 120
            while not os.path.exists(mark):
                if p.poll() is not None:
                    out, err = p.communicate()
                    raise AssertionError(
                        f"worker exited before parking:\n{out}\n{err}"
                    )
                assert time.time() < deadline, "worker never parked"
                time.sleep(0.02)
        finally:
            if p.poll() is None:
                os.kill(p.pid, signal.SIGKILL)
            p.wait()

        # the wreckage the serving tier must survive: gen-4 complete,
        # gen-8 present but manifest-less (killed mid-commit)
        gens = sorted(n for n in os.listdir(d) if n.startswith("gen-"))
        assert "gen-00000004" in gens and "gen-00000008" in gens
        assert not os.path.exists(
            os.path.join(d, "gen-00000008", "MANIFEST.json")
        )

        sys.path.insert(0, EXAMPLES_DIR)
        try:
            import serve_lm
        finally:
            sys.path.remove(EXAMPLES_DIR)
        step, state = serve_lm.load_checkpoint(d, worker._init_state())
        assert step == 4, f"served step {step}, not the complete gen 4"
        # the restored bytes are exactly the step-4 state, not a blend
        want = worker._init_state()
        for s in range(1, 5):
            want = worker._update(want, s)
        assert worker._digest(
            {k: np.asarray(v) for k, v in state.items()}
        ) == worker._digest(want)
