"""Cluster flight recorder tests (ISSUE 7): trace-context propagation,
telemetry spool + aggregation, trace merging, and the fleet doctor.

Tier 1 (no devices). Unit tests drive private Metrics/TelemetrySpool
instances with injected clocks; the integration tests spawn real
subprocesses (tests/fleet_worker.py) that read concurrently while
spooling into one directory, then check the aggregated picture against
the per-process ground truth EXACTLY — sums, histogram buckets, labels,
liveness.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from tpu_tfrecord import fleet, telemetry
from tpu_tfrecord.fleet import (
    TelemetryAggregator,
    TelemetrySpool,
    read_spool,
)
from tpu_tfrecord.metrics import METRICS, Metrics
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType
from tpu_tfrecord.telemetry import (
    Histogram,
    TraceContext,
    atomic_write_bytes,
    merge_chrome_traces,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fleet_worker.py")
DOCTOR = os.path.join(REPO, "tools", "tfrecord_doctor.py")

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),
    ]
)


def write_dataset(path, n_shards=3, rows_per_shard=40):
    import tpu_tfrecord.io as tfio

    for s in range(n_shards):
        tfio.write(
            [[i, f"s{i}"] for i in range(s * rows_per_shard, (s + 1) * rows_per_shard)],
            SCHEMA,
            str(path),
            mode="append" if s else "overwrite",
        )
    return str(path)


@pytest.fixture(autouse=True)
def _clean_process_globals():
    """The trace context and metrics registry are process-global; every
    test starts and ends with both pristine so identity assertions are
    order-independent."""
    telemetry.disable()
    telemetry.RECORDER.clear()
    telemetry.RECORDER.context = None
    METRICS.reset()
    yield
    telemetry.disable()
    telemetry.RECORDER.clear()
    telemetry.RECORDER.context = None
    METRICS.reset()


# ---------------------------------------------------------------------------
# TraceContext
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_new_stamps_identity(self):
        ctx = TraceContext.new(role="dispatcher")
        assert ctx.trace_id and ctx.span_id and ctx.trace_id != ctx.span_id
        assert ctx.parent_span_id is None
        assert ctx.role == "dispatcher"
        assert ctx.pid == os.getpid()
        assert ctx.host
        assert ctx.label() == f"dispatcher@{ctx.host}:{ctx.pid}"

    def test_child_shares_trace_not_identity(self):
        root = TraceContext.new()
        child = root.child("decode_worker")
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        assert child.span_id != root.span_id
        # host/pid are the CHILD's to stamp at adoption
        assert child.host == "" and child.pid == 0

    def test_json_round_trip(self):
        ctx = TraceContext.new(role="trainer")
        assert TraceContext.from_json(json.loads(json.dumps(ctx.to_json()))) == ctx
        # unknown keys from a newer writer are ignored, not fatal
        obj = dict(ctx.to_json(), future_field=1)
        assert TraceContext.from_json(obj) == ctx

    def test_adopt_restamps_host_pid(self):
        foreign = TraceContext(
            trace_id="t" * 16, span_id="s" * 16, host="elsewhere", pid=1
        )
        adopted = telemetry.adopt(foreign)
        assert adopted.trace_id == foreign.trace_id
        assert adopted.pid == os.getpid()
        assert adopted.host != "elsewhere"
        assert telemetry.current_context() is adopted

    def test_current_context_is_sticky(self):
        a = telemetry.current_context()
        assert telemetry.current_context() is a

    def test_adopt_from_env_joins_parent_trace(self):
        parent = TraceContext.new(role="parent")
        ctx = telemetry.adopt_from_env(role="worker", environ=parent.to_env())
        assert ctx.trace_id == parent.trace_id
        assert ctx.parent_span_id == parent.span_id
        assert ctx.span_id != parent.span_id
        assert ctx.role == "worker"
        assert ctx.pid == os.getpid()

    def test_adopt_from_env_without_or_bad_payload_is_fresh_root(self):
        ctx = telemetry.adopt_from_env(environ={})
        assert ctx.parent_span_id is None
        telemetry.RECORDER.context = None
        ctx2 = telemetry.adopt_from_env(
            environ={telemetry.TRACE_CONTEXT_ENV: "{not json"}
        )
        assert ctx2.parent_span_id is None
        assert ctx2.trace_id != ctx.trace_id
        # valid JSON that is not an object is just as malformed: a worker
        # calling adopt_from_env unconditionally must never crash on it
        for payload in ("null", "[1, 2]", '"x"', "42"):
            telemetry.RECORDER.context = None
            ctx3 = telemetry.adopt_from_env(
                environ={telemetry.TRACE_CONTEXT_ENV: payload}
            )
            assert ctx3.parent_span_id is None, payload


# ---------------------------------------------------------------------------
# Histogram state export / exact merge
# ---------------------------------------------------------------------------


class TestHistogramMerge:
    def _observations(self, seed, n):
        import random

        rng = random.Random(seed)
        out = []
        for _ in range(n):
            # span the bucket range: sub-floor, micro, milli, multi-second
            out.append(rng.choice([5e-8, 1e-6, 1e-4, 3e-3, 0.05, 1.7]) *
                       (1.0 + rng.random()))
        return out

    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
    def test_merged_equals_concatenated_exactly(self, seed):
        """The property the whole aggregation story rests on: K per-process
        histograms merged bucket-wise are IDENTICAL (bucket counts, count,
        min/max — not approximately, exactly) to one histogram fed the
        concatenated observations, so cluster quantiles are real."""
        import random

        rng = random.Random(seed * 1000 + 7)
        obs = self._observations(seed, 400)
        parts = [Histogram() for _ in range(3)]
        reference = Histogram()
        for v in obs:
            parts[rng.randrange(3)].observe(v)
            reference.observe(v)
        merged = Histogram.from_states(
            [json.loads(json.dumps(p.state())) for p in parts]
        )
        assert merged.counts == reference.counts  # exact bucket equality
        assert merged.count == reference.count
        assert merged.min == reference.min
        assert merged.max == reference.max
        assert merged.total == pytest.approx(reference.total)
        mq, rq = merged.quantiles(), reference.quantiles()
        assert mq.pop("mean_s") == pytest.approx(rq.pop("mean_s"))
        assert mq == rq

    def test_state_is_sparse_and_json_safe(self):
        h = Histogram()
        h.observe(0.001)
        h.observe(0.001)
        st = json.loads(json.dumps(h.state()))
        assert st["count"] == 2
        assert sum(int(c) for c in st["buckets"].values()) == 2
        assert len(st["buckets"]) == 1  # sparse: only touched buckets

    def test_empty_states_merge_to_empty(self):
        merged = Histogram.from_states([Histogram().state()] * 3)
        assert merged.count == 0
        assert merged.quantiles() == {}

    def test_layout_mismatch_raises(self):
        h = Histogram()
        bad = Histogram().state()
        bad["layout"] = [1e-7, 0.5, 72]
        with pytest.raises(ValueError, match="layout"):
            h.merge_state(bad)

    def test_bucket_index_out_of_range_raises(self):
        # a negative index would silently wrap into the tail bucket and
        # corrupt the cluster quantiles instead of flagging the bad spool
        h = Histogram()
        bad = Histogram().state()
        bad["buckets"] = {"-3": 2}
        with pytest.raises(ValueError, match="out of range"):
            h.merge_state(bad)
        bad["buckets"] = {"1000000": 1}
        with pytest.raises(ValueError, match="out of range"):
            h.merge_state(bad)
        assert h.count == 0

    def test_non_mapping_state_raises(self):
        h = Histogram()
        with pytest.raises(TypeError, match="mapping"):
            h.merge_state([1, 2, 3])
        bad = Histogram().state()
        bad["buckets"] = [4]
        with pytest.raises(TypeError, match="mapping"):
            h.merge_state(bad)


# ---------------------------------------------------------------------------
# Atomic artifact writes
# ---------------------------------------------------------------------------


class TestAtomicWrite:
    def test_write_and_no_tmp_residue(self, tmp_path):
        path = tmp_path / "x.json"
        atomic_write_bytes(str(path), b"abc")
        atomic_write_bytes(str(path), b"defg")  # overwrite is atomic too
        assert path.read_bytes() == b"defg"
        assert os.listdir(tmp_path) == ["x.json"]

    def test_failed_write_leaves_previous_file(self, tmp_path, monkeypatch):
        path = tmp_path / "x.json"
        atomic_write_bytes(str(path), b"good")
        real_replace = os.replace

        def boom(src, dst):
            raise OSError("disk gone")

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            atomic_write_bytes(str(path), b"bad")
        monkeypatch.setattr(os, "replace", real_replace)
        assert path.read_bytes() == b"good"
        assert os.listdir(tmp_path) == ["x.json"]  # tmp cleaned up

    def test_save_chrome_trace_is_atomic(self, tmp_path, monkeypatch):
        rec = telemetry.SpanRecorder(enabled=True)
        with rec.span("decode"):
            pass
        out = tmp_path / "trace.json"
        rec.save_chrome_trace(str(out))
        assert json.load(open(out))["traceEvents"]

        def boom(src, dst):
            raise OSError("crash mid-export")

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            rec.save_chrome_trace(str(out))
        monkeypatch.undo()
        # the previous complete export survives, no torn file
        assert json.load(open(out))["traceEvents"]
        assert os.listdir(tmp_path) == ["trace.json"]


# ---------------------------------------------------------------------------
# Trace merging
# ---------------------------------------------------------------------------


def _fake_trace(path, ctx, span_name, pid=None):
    rec = telemetry.SpanRecorder(enabled=True)
    rec.context = ctx
    with rec.span(span_name):
        pass
    doc = rec.to_chrome_trace()
    if pid is not None:  # simulate another host reusing a pid number
        for ev in doc["traceEvents"]:
            ev["pid"] = pid
        doc["traceContext"] = dict(doc["traceContext"], pid=pid)
    atomic_write_bytes(str(path), json.dumps(doc).encode())
    return doc


class TestMergeChromeTraces:
    def test_merge_keeps_one_named_track_per_process(self, tmp_path):
        ctxs = [
            TraceContext(
                trace_id="t" * 16, span_id=f"s{i}" * 4, role=f"r{i}",
                host="hostA", pid=1000 + i,
            )
            for i in range(3)
        ]
        paths = []
        for i, ctx in enumerate(ctxs):
            p = tmp_path / f"p{i}.json"
            _fake_trace(p, ctx, f"decode{i}", pid=ctx.pid)
            paths.append(str(p))
        out = tmp_path / "merged.json"
        merged = merge_chrome_traces(str(out), paths)
        doc = json.load(open(out))  # valid JSON on disk
        assert doc == json.loads(json.dumps(merged))
        pids = {e["pid"] for e in doc["traceEvents"]}
        assert len(pids) == 3
        named = {
            e["pid"]: e["args"]["name"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert set(named) == pids  # every pid track is labeled
        assert named[1001] == "r1@hostA:1001"
        # all three files' spans survived
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"decode0", "decode1", "decode2"} <= names

    def test_pid_collision_across_hosts_remapped(self, tmp_path):
        a = TraceContext(trace_id="t" * 16, span_id="a" * 8, role="w",
                         host="hostA", pid=7)
        b = TraceContext(trace_id="t" * 16, span_id="b" * 8, role="w",
                         host="hostB", pid=7)
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        _fake_trace(pa, a, "spanA", pid=7)
        _fake_trace(pb, b, "spanB", pid=7)
        merged = merge_chrome_traces(
            str(tmp_path / "m.json"), [str(pa), str(pb)]
        )
        ev_a = [e for e in merged["traceEvents"] if e["name"] == "spanA"][0]
        ev_b = [e for e in merged["traceEvents"] if e["name"] == "spanB"][0]
        assert ev_a["pid"] != ev_b["pid"]  # tracks never interleave
        labels = {
            e["args"]["name"]
            for e in merged["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert {"w@hostA:7", "w@hostB:7"} <= labels

    def test_contextless_file_gets_synthesized_label(self, tmp_path):
        raw = {"traceEvents": [
            {"name": "x", "ph": "X", "ts": 0, "dur": 1, "pid": 3, "tid": 1}
        ]}
        p = tmp_path / "legacy.json"
        p.write_text(json.dumps(raw))
        merged = merge_chrome_traces(str(tmp_path / "m.json"), [str(p)])
        meta = [
            e for e in merged["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        ]
        assert meta and meta[0]["args"]["name"] == "legacy.json"

    def test_malformed_input_raises_not_drops(self, tmp_path):
        good = tmp_path / "good.json"
        good.write_text(json.dumps({"traceEvents": []}))
        bad = tmp_path / "bad.json"
        bad.write_text("{torn")
        with pytest.raises(ValueError, match="bad.json"):
            merge_chrome_traces(str(tmp_path / "m.json"), [str(good), str(bad)])
        notatrace = tmp_path / "list.json"
        notatrace.write_text("[1, 2]")
        with pytest.raises(ValueError, match="list.json"):
            merge_chrome_traces(str(tmp_path / "m.json"), [str(notatrace)])
        with pytest.raises(OSError):
            merge_chrome_traces(
                str(tmp_path / "m.json"), [str(tmp_path / "missing.json")]
            )


# ---------------------------------------------------------------------------
# Telemetry spool
# ---------------------------------------------------------------------------


def _spool(tmp_path, metrics, clock, interval=1.0, role="reader", pid=None,
           host="testhost"):
    ctx = TraceContext(
        trace_id="t" * 16, span_id=os.urandom(4).hex(), role=role,
        host=host, pid=os.getpid() if pid is None else pid,
    )
    return TelemetrySpool(
        str(tmp_path), role=role, interval_s=interval, metrics=metrics,
        context=ctx, clock=clock,
    )


class TestSpool:
    def test_tick_writes_newest_cumulative_snapshot(self, tmp_path):
        m = Metrics()
        now = [100.0]
        sp = _spool(tmp_path, m, lambda: now[0], interval=0.5)
        m.add("decode", records=10, nbytes=64, seconds=0.25, latency=0.25)
        sp.tick()
        m.add("decode", records=5, nbytes=32, seconds=0.1, latency=0.1)
        m.gauge("prefetch.occupancy", 0.5)
        now[0] = 101.0
        sp.tick()
        snap = read_spool(sp.path)
        assert snap is not None
        assert snap.lines == 2 and snap.skipped_lines == 0
        assert snap.seq == 2  # newest line wins
        assert snap.stages["decode"][0] == 15  # cumulative, not delta
        assert snap.stages["decode"][1] == 96
        assert snap.gauges["prefetch.occupancy"] == 0.5
        assert snap.heartbeat == 101.0
        assert snap.role == "reader" and snap.host == "testhost"
        assert snap.hists["decode"]["count"] == 2
        assert m.counter("fleet.spool_writes") == 2

    def test_counters_and_stages_partition(self, tmp_path):
        # pure counters (no bytes/seconds) land in `counters`, timed
        # stages in `stages` — the aggregator sums them separately
        m = Metrics()
        m.count("read.stalls", 3)
        m.add("decode", records=4, seconds=0.2)
        sp = _spool(tmp_path, m, lambda: 1.0)
        snap_line = sp.snapshot()
        assert snap_line["counters"] == {"read.stalls": 3}
        assert list(snap_line["stages"]) == ["decode"]

    def test_torn_line_skipped_not_fatal(self, tmp_path):
        m = Metrics()
        m.add("decode", records=7, seconds=0.1)
        sp = _spool(tmp_path, m, lambda: 5.0)
        sp.tick()
        with open(sp.path, "ab") as fh:
            fh.write(b'{"event": "spool", "tor')  # simulated torn append
        snap = read_spool(sp.path)
        assert snap is not None
        assert snap.skipped_lines == 1
        assert snap.stages["decode"][0] == 7

    def test_no_valid_lines_returns_none(self, tmp_path):
        p = tmp_path / f"x{fleet.SPOOL_SUFFIX}"
        p.write_text("garbage\n{also: torn\n")
        assert read_spool(str(p)) is None
        assert read_spool(str(tmp_path / "missing")) is None

    def test_history_bounded(self, tmp_path):
        m = Metrics()
        sp = TelemetrySpool(
            str(tmp_path), interval_s=1.0, metrics=m,
            context=TraceContext.new(), max_lines=4, clock=lambda: 1.0,
        )
        for _ in range(10):
            sp.tick()
        with open(sp.path) as fh:
            lines = [l for l in fh.read().splitlines() if l.strip()]
        assert len(lines) == 4
        assert json.loads(lines[-1])["seq"] == 10

    def test_tick_never_raises(self, tmp_path, monkeypatch):
        m = Metrics()
        sp = _spool(tmp_path, m, lambda: 1.0)

        def boom(path, data):
            raise OSError("spool dir vanished")

        monkeypatch.setattr(fleet, "atomic_write_bytes", boom)
        sp.tick()  # must not raise: spooling is telemetry
        assert m.counter("fleet.spool_errors") == 1

    def test_thread_ticks_and_final_snapshot(self, tmp_path):
        m = Metrics()
        m.add("decode", records=1, seconds=0.01)
        sp = TelemetrySpool(
            str(tmp_path), interval_s=0.05, metrics=m,
            context=TraceContext.new(role="reader"),
        )
        sp.start()
        deadline = time.time() + 5.0
        while time.time() < deadline:
            snap = read_spool(sp.path)
            if snap is not None and snap.seq >= 2:
                break
            time.sleep(0.02)
        m.add("decode", records=9, seconds=0.01)
        sp.stop(final=True)
        sp.stop(final=True)  # idempotent
        snap = read_spool(sp.path)
        assert snap.stages["decode"][0] == 10  # final tick caught the tail

    def test_default_role_keeps_adopted_context_role(self, tmp_path):
        # a worker that adopted role="decode_worker" (adopt_from_env /
        # adopt_shared_trace_context) must not have it clobbered by the
        # spool when telemetry_role is unset (options.py documents the
        # default as "the current trace-context role")
        telemetry.adopt(TraceContext.new(role="decode_worker"))
        sp = TelemetrySpool(str(tmp_path), metrics=Metrics())
        assert sp.context.role == "decode_worker"
        assert telemetry.current_context().role == "decode_worker"
        # an explicit role still re-adopts — that's the option's job
        sp2 = TelemetrySpool(str(tmp_path), role="trainer", metrics=Metrics())
        assert sp2.context.role == "trainer"

    def test_acquire_release_refcount(self, tmp_path):
        d = str(tmp_path / "spool")
        a = fleet.acquire_spool(d, interval_s=60.0)
        b = fleet.acquire_spool(d, interval_s=60.0)
        assert a is b  # one spool per (process, dir)
        fleet.release_spool(d)
        assert not a._stop.is_set()  # still referenced
        fleet.release_spool(d)
        assert a._stop.is_set()
        assert read_spool(a.path) is not None  # final snapshot landed
        fleet.release_spool(d)  # unmatched release ignored

    def test_remote_scheme_spool_dir_rejected(self, tmp_path):
        # abspath would silently mangle "gs://bucket/spool" into a private
        # local dir on every host: workers look healthy, aggregator finds
        # an empty fleet — reject loudly at both ends instead
        with pytest.raises(ValueError, match="local path"):
            fleet.TelemetrySpool("gs://bucket/spool", metrics=Metrics())
        with pytest.raises(ValueError, match="local path"):
            fleet.acquire_spool("s3://bucket/spool", interval_s=60.0)
        with pytest.raises(ValueError, match="local path"):
            fleet.TelemetryAggregator("gs://bucket/spool")

    def test_snapshot_follows_late_adopted_context(self, tmp_path):
        # adopt_shared_trace_context may run AFTER the spooling iterator
        # is constructed — later snapshots must stamp the shared trace id,
        # or trace_id-scoped aggregation silently drops the process
        m = Metrics()
        m.add("decode", records=1, seconds=0.1)
        sp = TelemetrySpool(str(tmp_path), metrics=m, clock=lambda: 1.0)
        early = sp.snapshot()
        shared = telemetry.adopt(
            TraceContext.new(role="worker").with_role("worker")
        )
        assert early["job"]["trace_id"] != shared.trace_id
        late = sp.snapshot()
        assert late["job"]["trace_id"] == shared.trace_id
        assert late["job"]["role"] == "worker"
        # an explicitly injected context stays pinned (test seam)
        pinned = _spool(tmp_path, m, lambda: 1.0)
        telemetry.adopt(TraceContext.new(role="other"))
        assert pinned.snapshot()["job"]["trace_id"] == "t" * 16


# ---------------------------------------------------------------------------
# Aggregator
# ---------------------------------------------------------------------------


def _write_process(tmp_path, clock, pid, role="reader", decode=(10, 100, 0.5),
                   counters=(), latencies=(), occupancy=None, interval=1.0):
    m = Metrics()
    m.add("decode", records=decode[0], nbytes=decode[1], seconds=decode[2])
    for name, v in counters:
        m.count(name, v)
    for lat in latencies:
        m.observe("decode", lat)
    if occupancy is not None:
        m.gauge(telemetry.OCCUPANCY_GAUGE, occupancy)
    sp = _spool(tmp_path, m, clock, interval=interval, role=role, pid=pid)
    sp.tick()
    return m


class TestAggregator:
    def test_counters_and_stages_sum_exactly(self, tmp_path):
        now = [50.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(tmp_path, clock, pid=1, decode=(10, 100, 0.5),
                       counters=[("read.stalls", 3)])
        _write_process(tmp_path, clock, pid=2, decode=(20, 300, 1.5),
                       counters=[("read.stalls", 4), ("read.hedges", 1)])
        _write_process(tmp_path, clock, pid=3, decode=(5, 50, 0.25))
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert len(snap.processes) == 3 and not snap.dead
        assert snap.counters["read.stalls"] == 7
        assert snap.counters["read.hedges"] == 1
        # fleet.spool_writes is itself spooled (each process wrote once...
        # but the tick that WROTE the line ran before the counter bumped,
        # so the newest landed line says 0 until the next tick)
        assert snap.stages["decode"][0] == 35
        assert snap.stages["decode"][1] == 450
        assert snap.stages["decode"][3] == pytest.approx(2.25)

    def test_histograms_merge_bucket_exactly(self, tmp_path):
        import random

        rng = random.Random(11)
        now = [10.0]
        clock = lambda: now[0]  # noqa: E731
        all_obs = []
        for pid in (1, 2, 3):
            obs = [rng.uniform(1e-5, 2.0) for _ in range(100)]
            all_obs.extend(obs)
            _write_process(tmp_path, clock, pid=pid, latencies=obs)
        reference = Histogram()
        for v in all_obs:
            reference.observe(v)
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert snap.hists["decode"].counts == reference.counts
        mq, rq = snap.quantiles()["decode"], reference.quantiles()
        assert mq.pop("mean_s") == pytest.approx(rq.pop("mean_s"))
        assert mq == rq

    def test_stale_heartbeat_flags_dead(self, tmp_path):
        """Liveness with an injected clock: a process is alive through
        2x its own declared interval and dead one tick past it."""
        now = [1000.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(tmp_path, clock, pid=1, interval=1.0)
        now[0] = 1001.0
        _write_process(tmp_path, clock, pid=2, interval=1.0)
        agg = TelemetryAggregator(str(tmp_path), clock=clock)
        snap = agg.aggregate()
        assert not snap.dead  # ages 1.0 and 0.0: both within 2x interval
        now[0] = 1002.0  # pid 1's age is exactly the 2.0 bar: still alive
        snap = agg.aggregate()
        assert not snap.dead
        now[0] = 1002.5  # pid 1 at 2.5 > 2.0: dead; pid 2 at 1.5: alive
        snap = agg.aggregate()
        assert [p.pid for p in snap.dead] == [1]
        assert [p.pid for p in snap.alive] == [2]
        # a dead process's totals still count — they happened
        assert snap.stages["decode"][0] == 20
        # explicit override beats the per-process default
        snap = TelemetryAggregator(
            str(tmp_path), stale_after_s=10.0, clock=clock
        ).aggregate()
        assert not snap.dead

    def test_cluster_verdict_from_alive_occupancy(self, tmp_path):
        now = [10.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(tmp_path, clock, pid=1, occupancy=0.9)
        _write_process(tmp_path, clock, pid=2, occupancy=0.8)
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert snap.occupancy == pytest.approx(0.85)
        assert snap.verdict == "consumer_bound"
        # a dead process's occupancy must not poison the verdict
        now[0] = 100.0
        _write_process(tmp_path, clock, pid=3, occupancy=0.0)
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert [p.pid for p in snap.alive] == [3]
        assert snap.occupancy == pytest.approx(0.0)
        assert snap.verdict == "producer_bound"

    def test_corrupt_hist_state_loses_stage_not_fleet(self, tmp_path):
        # one process spooled histogram states with a foreign bucket
        # layout (version skew) or garbage indices: its buckets are
        # dropped, its counters still sum, the fleet picture survives —
        # and the doctor reports instead of dying with a traceback
        now = [10.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(tmp_path, clock, pid=1, latencies=[0.01, 0.02])
        _write_process(tmp_path, clock, pid=2, latencies=[0.03])
        spool_file = os.path.join(tmp_path, f"testhost-2{fleet.SPOOL_SUFFIX}")
        obj = json.loads(open(spool_file).read().splitlines()[-1])
        obj["hists"]["decode"]["layout"] = [1e-7, 0.5, 72]
        with open(spool_file, "w") as fh:
            fh.write(json.dumps(obj) + "\n")
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert len(snap.processes) == 2
        assert snap.hists["decode"].count == 2  # pid 1's buckets only
        assert snap.stages["decode"][0] == 20  # counters unaffected
        proc = subprocess.run(
            [sys.executable, DOCTOR, "fleet", str(tmp_path),
             "--stale-after", "3600"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)

    def test_empty_dir_and_unreadable_dir(self, tmp_path):
        snap = TelemetryAggregator(str(tmp_path), clock=lambda: 0.0).aggregate()
        assert snap.processes == [] and snap.verdict == "unknown"
        with pytest.raises(OSError):
            TelemetryAggregator(
                str(tmp_path / "missing"), clock=lambda: 0.0
            ).processes()

    def test_federated_page_parses_with_official_parser(self, tmp_path):
        parser = pytest.importorskip("prometheus_client.parser")
        now = [10.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(tmp_path, clock, pid=1, role="reader",
                       decode=(10, 100, 0.5), latencies=[0.01, 0.02],
                       occupancy=0.4, counters=[("read.stalls", 2)])
        _write_process(tmp_path, clock, pid=2, role="trainer",
                       decode=(20, 200, 1.0), latencies=[0.03])
        agg = TelemetryAggregator(str(tmp_path), clock=clock)
        families = {
            f.name: f
            for f in parser.text_string_to_metric_families(agg.prometheus_text())
        }
        up = families["tfrecord_process_up"]
        by_pid = {s.labels["pid"]: s for s in up.samples}
        assert set(by_pid) == {"1", "2"}
        assert by_pid["1"].labels["role"] == "reader"
        assert by_pid["1"].labels["host"] == "testhost"
        assert all(s.value == 1.0 for s in up.samples)
        recs = families["tfrecord_stage_records"]
        decode = {
            s.labels["pid"]: s.value
            for s in recs.samples
            if s.labels["stage"] == "decode"
        }
        assert decode == {"1": 10.0, "2": 20.0}  # per-process, sum in PromQL
        stalls = [
            s for s in recs.samples if s.labels["stage"] == "read.stalls"
        ]
        assert stalls and stalls[0].value == 2.0
        lat = families["tfrecord_fleet_latency_seconds"]
        cnt = [s for s in lat.samples if s.name.endswith("_count")]
        assert cnt and cnt[0].value == 3.0  # cluster-exact merged histogram

    def test_federated_http_endpoint(self, tmp_path):
        import urllib.request

        now = [10.0]
        _write_process(tmp_path, lambda: now[0], pid=1)
        agg = TelemetryAggregator(str(tmp_path), clock=lambda: now[0])
        server = agg.serve(0)
        try:
            host, port = telemetry.exporter_address(0)
            assert port == server.server_address[1]
            body = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5
            ).read().decode()
            assert "tfrecord_process_up" in body
        finally:
            telemetry.shutdown_exporter(0)

    def test_serve_refuses_port_already_serving_other_kind(self, tmp_path):
        # the per-port table must not hand a fleet caller the PROCESS
        # exporter's server: scrapes would succeed while fleet liveness
        # families silently never appear
        _write_process(tmp_path, lambda: 10.0, pid=1)
        exporter = telemetry.ensure_exporter(0, metrics=Metrics())
        assert exporter is not None
        try:
            agg = TelemetryAggregator(str(tmp_path), clock=lambda: 10.0)
            assert agg.serve(0) is None  # collision: failure is visible
        finally:
            telemetry.shutdown_exporter(0)

    def test_clean_shutdown_never_flagged_dead(self, tmp_path):
        """A final (stop()) snapshot marks the process FINISHED: however
        stale its heartbeat gets, it stays out of the dead list — a
        completed job must not read as a mass kill. A process with no
        final marker at the same staleness goes dead."""
        now = [1000.0]
        clock = lambda: now[0]  # noqa: E731
        m1 = Metrics()
        m1.add("decode", records=10, nbytes=100, seconds=0.5)
        sp1 = _spool(tmp_path, m1, clock, interval=1.0, pid=1)
        sp1.tick()
        sp1.stop(final=True)  # clean goodbye
        m2 = Metrics()
        m2.add("decode", records=20, nbytes=200, seconds=1.0)
        _spool(tmp_path, m2, clock, interval=1.0, pid=2).tick()  # no goodbye
        now[0] = 2000.0  # both heartbeats ancient
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert [p.pid for p in snap.alive] == [1]
        assert snap.alive[0].final
        assert [p.pid for p in snap.dead] == [2]
        # finished totals still count
        assert snap.stages["decode"][0] == 30

    def test_finished_process_occupancy_excluded_while_any_run(self, tmp_path):
        """A finished process's frozen exit occupancy must not dilute the
        live verdict — but with NOTHING running, the fleet is a
        post-mortem and the exit states are the right evidence."""
        now = [100.0]
        clock = lambda: now[0]  # noqa: E731
        m1 = Metrics()
        m1.add("decode", records=1, nbytes=1, seconds=0.1)
        m1.gauge(telemetry.OCCUPANCY_GAUGE, 1.0)
        sp1 = _spool(tmp_path, m1, clock, pid=1)
        sp1.tick()
        sp1.stop(final=True)  # finished at occupancy 1.0
        m2 = Metrics()
        m2.add("decode", records=1, nbytes=1, seconds=0.1)
        m2.gauge(telemetry.OCCUPANCY_GAUGE, 0.1)
        sp2 = _spool(tmp_path, m2, clock, pid=2)
        sp2.tick()  # still running, starved
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert snap.occupancy == pytest.approx(0.1)
        assert snap.verdict == "producer_bound"
        sp2.stop(final=True)  # now everything finished: post-mortem mean
        snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert snap.occupancy == pytest.approx(0.55)

    def test_trace_id_scopes_reused_spool_dir(self, tmp_path):
        """A reused spool dir holds a previous run's files; the trace_id
        filter merges one run only."""
        now = [10.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(tmp_path, clock, pid=1)  # trace id "t"*16
        stale = json.dumps({
            "event": "spool", "v": 1, "seq": 7, "ts": 1.0, "interval_s": 1.0,
            "job": {"host": "old", "pid": 1, "role": "r",
                    "heartbeat": 1.0, "trace_id": "previousrun00000"},
            "counters": {}, "stages": {"decode": [99, 0, 0, 1.0]},
            "gauges": {}, "hists": {},
        })
        (tmp_path / f"old-1{fleet.SPOOL_SUFFIX}").write_text(stale + "\n")
        unscoped = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
        assert unscoped.stages["decode"][0] == 109  # mixed: disclosure only
        scoped = TelemetryAggregator(
            str(tmp_path), clock=clock, trace_id="t" * 16
        ).aggregate()
        assert [p.pid for p in scoped.processes] == [1]
        assert scoped.stages["decode"][0] == 10

    def test_doctor_names_unmatched_trace_id_filter(self, tmp_path):
        # a typo'd/stale --trace-id against a dir FULL of spool files must
        # not claim "no spool files found" — that sends the operator to
        # debug a missing directory instead of the filter
        _write_process(tmp_path, lambda: 10.0, pid=1)  # trace id "t"*16
        proc = subprocess.run(
            [sys.executable, DOCTOR, "fleet", str(tmp_path),
             "--trace-id", "nosuchtrace00000"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 2
        err = json.loads(proc.stdout.strip().splitlines()[-1])
        assert "nosuchtrace00000" in err["error"]
        assert err["spool_files"] == 1
        assert err["trace_ids_present"] == ["t" * 16]

    def test_snapshot_carries_spool_start_for_wall_throughput(self, tmp_path):
        """`created` (spool start, writer's clock) survives the round
        trip: heartbeat - created is the wall window the doctor divides
        records by (busy seconds sum across threads and would understate
        parallel workers)."""
        now = [100.0]
        m = Metrics()
        m.add("decode", records=50, nbytes=0, seconds=7.5)  # busy > wall
        sp = _spool(tmp_path, m, lambda: now[0])
        now[0] = 105.0
        sp.tick()
        snap = read_spool(sp.path)
        assert snap.created == pytest.approx(100.0)
        assert snap.heartbeat == pytest.approx(105.0)
        assert snap.heartbeat - snap.created == pytest.approx(5.0)
        # the epoch sticks to the METRICS REGISTRY, not the spool
        # instance: a release + re-acquire over the same (cumulative)
        # registry keeps the original window instead of restarting it
        # under lifetime totals and overstating the rate
        sp.stop(final=True)
        now[0] = 200.0
        sp2 = _spool(tmp_path, m, lambda: now[0])
        sp2.tick()
        snap = read_spool(sp2.path)
        assert snap.created == pytest.approx(100.0)
        # a registry reset restarts the window with the totals
        m.reset()
        now[0] = 300.0
        sp3 = _spool(tmp_path, m, lambda: now[0])
        sp3.tick()
        assert read_spool(sp3.path).created == pytest.approx(300.0)

    def test_malformed_line_skipped_not_fatal(self, tmp_path):
        """A line that parses as JSON but fails field coercion (version
        skew, hand edits) loses that LINE, not the file and not the fleet:
        the newest remaining valid line wins and aggregation proceeds."""
        now = [10.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(tmp_path, clock, pid=1, decode=(10, 100, 0.5))
        bad_file = tmp_path / f"evil-9{fleet.SPOOL_SUFFIX}"
        good = json.dumps({
            "event": "spool", "v": 1, "seq": 1, "ts": 10.0,
            "interval_s": 1.0,
            "job": {"host": "h", "pid": 9, "role": "r", "heartbeat": 10.0},
            "counters": {}, "stages": {"decode": [5, 50, 0, 0.25]},
            "gauges": {}, "hists": {},
        })
        for bad in (
            '{"event": "spool", "seq": 2, "job": {"pid": "abc"}}',
            '{"event": "spool", "seq": 3, "job": {"heartbeat": "x"}}',
            '{"event": "spool", "seq": 4, "stages": {"decode": [1]}}',
            '{"event": "spool", "seq": 5, "counters": {"c": "NaNope"}}',
        ):
            bad_file.write_text(good + "\n" + bad + "\n")
            snap = TelemetryAggregator(str(tmp_path), clock=clock).aggregate()
            assert {p.pid for p in snap.processes} == {1, 9}, bad
            nine = [p for p in snap.processes if p.pid == 9][0]
            assert nine.seq == 1 and nine.skipped_lines == 1, bad
            assert snap.stages["decode"][0] == 15, bad

    def test_label_values_escaped_on_federated_page(self, tmp_path):
        """role/host are user strings: quotes/backslashes/newlines must be
        escaped so the page still parses with the official parser."""
        parser = pytest.importorskip("prometheus_client.parser")
        now = [10.0]
        clock = lambda: now[0]  # noqa: E731
        _write_process(
            tmp_path, clock, pid=1, role='w"1\\x\ny',
        )
        agg = TelemetryAggregator(str(tmp_path), clock=clock)
        families = {
            f.name: f
            for f in parser.text_string_to_metric_families(agg.prometheus_text())
        }
        up = families["tfrecord_process_up"]
        assert up.samples[0].labels["role"] == 'w"1\\x\ny'

    def test_acquire_spool_mismatched_join_warns(self, tmp_path, caplog):
        """Joining an existing spool dir with a different role/interval
        keeps the existing spool's settings and says so."""
        import logging

        from tpu_tfrecord.fleet import acquire_spool, release_spool

        d = str(tmp_path / "sp")
        acquire_spool(d, role="a", interval_s=30.0)
        try:
            with caplog.at_level(logging.WARNING, logger="tpu_tfrecord"):
                sp = acquire_spool(d, role="b", interval_s=0.5)
            assert sp.interval_s == 30.0 and sp.context.role == "a"
            msgs = " ".join(r.message for r in caplog.records)
            assert "interval" in msgs and "role" in msgs
        finally:
            release_spool(d)
            release_spool(d)


# ---------------------------------------------------------------------------
# Options plumbing
# ---------------------------------------------------------------------------


class TestOptions:
    def test_defaults_off(self):
        from tpu_tfrecord.options import TFRecordOptions

        o = TFRecordOptions.from_map()
        assert o.telemetry_spool_dir is None
        assert o.spool_interval_s is None
        assert o.telemetry_role is None

    def test_parsing_and_validation(self, tmp_path):
        from tpu_tfrecord.options import TFRecordOptions

        o = TFRecordOptions.from_map(
            telemetry_spool_dir=str(tmp_path), spool_interval_s=0.5,
            telemetry_role="decode_worker",
        )
        assert o.telemetry_spool_dir == str(tmp_path)
        assert o.spool_interval_s == 0.5
        assert o.telemetry_role == "decode_worker"
        camel = TFRecordOptions.from_map(
            telemetrySpoolDir=str(tmp_path), spoolIntervalS="2",
            telemetryRole="t",
        )
        assert camel.spool_interval_s == 2.0
        with pytest.raises(ValueError, match="spool_interval_s"):
            TFRecordOptions.from_map(spool_interval_s=0)
        with pytest.raises(ValueError, match="telemetry_role"):
            TFRecordOptions.from_map(telemetry_role="")

    def test_dataset_scheme_spool_dir_rejected(self, sandbox):
        # the iterator must not abspath "gs://..." before the spool's
        # scheme guard sees it — that would silently spool into a local
        # '<cwd>/gs:/bucket/spool' dir instead of raising
        from tpu_tfrecord.io.dataset import TFRecordDataset

        data = write_dataset(sandbox / "ds", n_shards=1, rows_per_shard=4)
        ds = TFRecordDataset(
            data, batch_size=4, schema=SCHEMA, num_epochs=1,
            drop_remainder=False, telemetry_spool_dir="gs://bucket/spool",
        )
        with pytest.raises(ValueError, match="local path"):
            with ds.batches():
                pass
        assert not fleet._SPOOLS

    def test_dataset_spools_while_iterating(self, sandbox, tmp_path):
        from tpu_tfrecord.io.dataset import TFRecordDataset

        data = write_dataset(sandbox / "ds", n_shards=2, rows_per_shard=30)
        spool_dir = str(tmp_path / "spool")
        ds = TFRecordDataset(
            data, batch_size=16, schema=SCHEMA, num_epochs=1,
            drop_remainder=False, telemetry_spool_dir=spool_dir,
            spool_interval_s=0.05, telemetry_role="reader",
        )
        rows = 0
        with ds.batches() as it:
            for cb in it:
                rows += cb.num_rows
        assert rows == 60
        # the iterator's close released the refcount: final snapshot landed
        snaps = TelemetryAggregator(spool_dir, clock=time.time).processes()
        assert len(snaps) == 1
        assert snaps[0].role == "reader"
        assert snaps[0].stages["decode"][0] == 60
        assert not fleet._SPOOLS  # registry drained


# ---------------------------------------------------------------------------
# Multi-process integration (the acceptance test)
# ---------------------------------------------------------------------------


def _spawn_worker(data, spool_dir, env, role="reader", trace_out=None,
                  linger=0.0, interval=0.1):
    cmd = [
        sys.executable, WORKER, data, spool_dir,
        "--role", role, "--epochs", "2", "--batch-size", "16",
        "--interval", str(interval),
    ]
    if trace_out:
        cmd += ["--trace-out", trace_out]
    if linger:
        cmd += ["--linger", str(linger)]
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
    )


class TestFleetIntegration:
    def test_three_workers_aggregate_exactly(self, sandbox, tmp_path):
        """K=3 subprocesses read concurrently while spooling into one dir:
        the aggregated decode count equals the per-process sum EXACTLY,
        every process carries the parent's trace id, the federated page
        parses with per-process labels, the fleet doctor exits 0, and the
        merged Chrome trace has one named track per pid."""
        data = write_dataset(sandbox / "ds", n_shards=3, rows_per_shard=40)
        spool_dir = str(tmp_path / "spool")
        parent_ctx = TraceContext.new(role="test_parent")
        env = {
            **os.environ, "JAX_PLATFORMS": "cpu", **parent_ctx.to_env(),
        }
        traces = [str(tmp_path / f"trace-{i}.json") for i in range(3)]
        procs = [
            _spawn_worker(data, spool_dir, env, role=f"reader{i}",
                          trace_out=traces[i])
            for i in range(3)
        ]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, (out, err)
            outs.append(json.loads(out.splitlines()[-1]))

        # every worker read the whole dataset twice
        assert all(o["rows"] == 240 for o in outs)
        # trace propagation: all three joined the parent's trace
        assert {o["trace_id"] for o in outs} == {parent_ctx.trace_id}
        assert {o["parent_span_id"] for o in outs} == {parent_ctx.span_id}

        # exact aggregation: merged decode records == sum of per-process
        agg = TelemetryAggregator(spool_dir)
        snap = agg.aggregate()
        assert len(snap.processes) == 3
        expected = sum(o["decode_records"] for o in outs)
        assert snap.stages["decode"][0] == expected == 720
        roles = sorted(p.role for p in snap.processes)
        assert roles == ["reader0", "reader1", "reader2"]
        assert sorted(p.pid for p in snap.processes) == sorted(
            o["pid"] for o in outs
        )

        # federated page parses with the official parser, labeled per pid
        parser = pytest.importorskip("prometheus_client.parser")
        families = {
            f.name: f
            for f in parser.text_string_to_metric_families(agg.prometheus_text())
        }
        recs = families["tfrecord_stage_records"]
        decode = {
            int(s.labels["pid"]): s.value
            for s in recs.samples
            if s.labels["stage"] == "decode"
        }
        assert decode == {o["pid"]: float(o["decode_records"]) for o in outs}

        # fleet doctor: exit 0 with per-proc lines and a cluster verdict
        proc = subprocess.run(
            [sys.executable, DOCTOR, "fleet", spool_dir,
             "--stale-after", "3600"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
        proc_lines = [l for l in lines if l["event"] == "proc"]
        (fleet_line,) = [l for l in lines if l["event"] == "fleet"]
        assert len(proc_lines) == 3
        assert all(l["alive"] for l in proc_lines)
        assert all(l["records_per_sec"] for l in proc_lines)
        assert fleet_line["stages"]["decode"]["records"] == 720
        assert fleet_line["alive"] == 3 and fleet_line["dead"] == []
        assert fleet_line["verdict"] in (
            "producer_bound", "consumer_bound", "balanced", "unknown"
        )
        assert fleet_line["trace_ids"] == [parent_ctx.trace_id]

        # merged timeline: valid trace-event JSON, 3 named pid tracks
        merged_path = str(tmp_path / "merged.json")
        proc = subprocess.run(
            [sys.executable, DOCTOR, "merge-trace", merged_path] + traces,
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        summary = json.loads(proc.stdout.splitlines()[-1])
        assert summary["event"] == "merged_trace" and summary["pids"] >= 3
        doc = json.load(open(merged_path))
        pids = {
            e["pid"] for e in doc["traceEvents"] if e.get("ph") != "M"
        }
        assert pids == {o["pid"] for o in outs}
        named = {
            e["pid"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert pids <= named  # one named track per pid
        assert any(e["name"] == "decode" for e in doc["traceEvents"])

    def test_killed_worker_flagged_stale(self, sandbox, tmp_path):
        """SIGKILL a demonstrably-alive worker: the aggregator flags it
        dead once its heartbeat age passes the staleness bar (2x its
        declared interval), and the doctor reports it in the dead list."""
        data = write_dataset(sandbox / "ds", n_shards=1, rows_per_shard=20)
        spool_dir = str(tmp_path / "spool")
        os.makedirs(spool_dir)  # don't race the worker's own makedirs
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        interval = 0.2
        p = _spawn_worker(data, spool_dir, env, role="victim",
                          linger=120.0, interval=interval)
        try:
            agg = TelemetryAggregator(spool_dir)
            deadline = time.time() + 120.0
            alive_seen = False
            while time.time() < deadline:
                snap = agg.aggregate()
                if snap.alive and snap.alive[0].stages.get("decode"):
                    alive_seen = True
                    break
                time.sleep(0.05)
            assert alive_seen, (p.poll(), p.stderr.read() if p.poll() else "")
            p.kill()
            p.wait(timeout=30)
            # dead within ~one heartbeat interval past the 2x bar
            deadline = time.time() + 10 * interval
            flagged = None
            while time.time() < deadline:
                snap = agg.aggregate()
                if snap.dead:
                    flagged = snap.dead[0]
                    break
                time.sleep(interval / 4)
            assert flagged is not None, "killed worker never flagged stale"
            assert flagged.role == "victim"
            # its totals still count after death
            assert snap.stages["decode"][0] == flagged.stages["decode"][0]
            proc = subprocess.run(
                [sys.executable, DOCTOR, "fleet", spool_dir],
                capture_output=True, text=True, env=env,
            )
            assert proc.returncode == 0
            lines = [
                json.loads(l) for l in proc.stdout.splitlines() if l.strip()
            ]
            (fleet_line,) = [l for l in lines if l["event"] == "fleet"]
            assert fleet_line["dead"] and fleet_line["dead"][0]["role"] == "victim"
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
