"""Tests for pipeline features: multi-worker decode, shard shuffle, retries."""

import os

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord.io.dataset import IteratorState, TFRecordDataset
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.schema import FloatType, LongType, StructField, StructType


def _fast_retries(n, sleep=None):
    """Retry policy for tests: real retry semantics, no wall-clock sleeping
    (``sleep`` hooks let fault tests repair the file 'during' the backoff)."""
    return RetryPolicy(max_retries=n, sleep=sleep or (lambda _s: None))

SCHEMA = StructType([StructField("uid", LongType()), StructField("v", FloatType())])


def write_shards(sandbox, num_shards=6, rows_per_shard=7):
    out = str(sandbox / "pf")
    uid = 0
    for s in range(num_shards):
        tfio.write(
            [[uid + i, float(uid + i)] for i in range(rows_per_shard)],
            SCHEMA,
            out,
            mode="append",
        )
        uid += rows_per_shard
    return out


def collect_uids(ds, state=None):
    uids = []
    with ds.batches(state) as it:
        for b in it:
            uids.extend(b["uid"].values.tolist())
    return uids


class TestMultiWorker:
    @pytest.mark.parametrize("workers", [2, 4])
    def test_identical_to_sequential(self, sandbox, workers):
        out = write_shards(sandbox)
        seq = collect_uids(TFRecordDataset(out, batch_size=5, schema=SCHEMA))
        par = collect_uids(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA, num_workers=workers)
        )
        assert par == seq  # exact order, not just same multiset

    def test_parallel_resume(self, sandbox):
        out = write_shards(sandbox)
        ds = TFRecordDataset(out, batch_size=5, schema=SCHEMA, num_workers=3)
        with ds.batches() as it:
            first = next(it)["uid"].values.tolist()
            st = it.state()
        rest = collect_uids(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA, num_workers=3), st
        )
        seq_all = collect_uids(TFRecordDataset(out, batch_size=5, schema=SCHEMA))
        assert first + rest == seq_all

    @pytest.mark.skipif(
        (os.cpu_count() or 1) < 4,
        reason="needs >=4 cores to demonstrate decode scaling "
        "(runs on CI's multi-core runners; the TPU bench box has 1 core)",
    )
    def test_num_workers_scales_wall_clock(self, tmp_path):
        """N-worker decode must beat 1-worker wall-clock on a multi-core
        host — the native decoder releases the GIL, so shard decode is real
        thread parallelism. Generous threshold (1.4x at 4 workers) to stay
        CI-stable."""
        import time

        from tpu_tfrecord import _native

        if not _native.available():
            pytest.skip("needs the native decoder (GIL-released decode)")
        schema = StructType(
            [StructField("uid", LongType())]
            + [StructField(f"I{i}", LongType()) for i in range(12)]
        )
        out = str(tmp_path / "scale")
        rng = np.random.default_rng(0)
        for s in range(8):
            rows = [
                [int(v) for v in rng.integers(0, 1 << 30, size=13)]
                for _ in range(4000)
            ]
            tfio.write(rows, schema, out, mode="append")

        def run(workers: int) -> float:
            ds = TFRecordDataset(
                out, batch_size=4000, schema=schema, num_workers=workers
            )
            with ds.batches() as it:
                next(it)  # warm (file cache, lazy init)
                t0 = time.perf_counter()
                n = 0
                for b in it:
                    n += b.num_rows
                dt = time.perf_counter() - t0
            assert n >= 8 * 4000 - 2 * 4000
            return dt

        t1 = min(run(1), run(1))
        t4 = min(run(4), run(4))
        assert t4 < t1 / 1.4, (
            f"4-worker decode ({t4:.3f}s) not faster than 1-worker "
            f"({t1:.3f}s) on a {os.cpu_count()}-core host"
        )

    def test_parallel_error_propagates(self, sandbox):
        out = write_shards(sandbox, num_shards=2)
        f = sorted(
            os.path.join(out, x) for x in os.listdir(out) if x.endswith(".tfrecord")
        )[1]
        raw = bytearray(open(f, "rb").read())
        raw[20] ^= 0xFF
        open(f, "wb").write(bytes(raw))
        ds = TFRecordDataset(out, batch_size=4, schema=SCHEMA, num_workers=2)
        with pytest.raises(Exception):
            collect_uids(ds)


from tpu_tfrecord import _native as _native_mod


@pytest.mark.skipif(
    not _native_mod.available(),
    reason="mmap fast path requires the native fused decoder",
)
class TestMmapPath:
    def test_mmap_and_buffered_paths_agree(self, sandbox):
        """Local uncompressed shards default to the mmap fast path; it must
        be indistinguishable from the buffered path (order, values, resume
        positions)."""
        out = write_shards(sandbox, num_shards=3, rows_per_shard=11)
        mm = TFRecordDataset(out, batch_size=7, schema=SCHEMA, drop_remainder=False)
        buf = TFRecordDataset(
            out, batch_size=7, schema=SCHEMA, drop_remainder=False, use_mmap=False
        )
        assert collect_uids(mm) == collect_uids(buf)
        # mid-stream state from one path resumes identically on the other
        it = mm.batches()
        next(it)
        st = it.state()
        it.close()
        assert collect_uids(
            TFRecordDataset(
                out, batch_size=7, schema=SCHEMA, drop_remainder=False, use_mmap=False
            ),
            st,
        ) == collect_uids(
            TFRecordDataset(out, batch_size=7, schema=SCHEMA, drop_remainder=False),
            st,
        )

    def test_mmap_transient_open_error_retried(self, sandbox, monkeypatch):
        """The mmap path opens files via its own seam (_open_local);
        a transient OSError there must be retried like the buffered path."""
        out = write_shards(sandbox, num_shards=1)
        calls = {"n": 0}
        import tpu_tfrecord.io.dataset as dsmod

        real_open = dsmod._open_local

        def flaky(path, mode):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient blip")
            return real_open(path, mode)

        monkeypatch.setattr(dsmod, "_open_local", flaky)
        ds = TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                             retry_policy=_fast_retries(2))
        assert len(collect_uids(ds)) == 7
        assert calls["n"] == 2

    def test_mmap_mid_shard_retry_no_duplicates(self, sandbox, monkeypatch):
        """Corruption past the first chunk: the retry must resume after the
        records already emitted — no duplicates, no holes (mmap path)."""
        out = write_shards(sandbox, num_shards=1, rows_per_shard=3000)
        f = [os.path.join(out, x) for x in os.listdir(out) if x.endswith(".tfrecord")][0]
        good = open(f, "rb").read()
        bad = bytearray(good)
        bad[-10] ^= 0x55  # corrupt the LAST record (second decode chunk)
        open(f, "wb").write(bytes(bad))

        def repair(_seconds):
            open(f, "wb").write(good)

        ds = TFRecordDataset(
            out, batch_size=2048, schema=SCHEMA, drop_remainder=False,
            retry_policy=_fast_retries(2, sleep=repair),
        )
        uids = collect_uids(ds)
        assert uids == list(range(3000))  # exactly once each, in order

    def test_mmap_bogus_length_within_file_raises(self, sandbox):
        """verify_crc=False + a corrupt length field whose bogus value still
        FITS in the remaining file: must raise max_record_bytes corruption,
        never swallow the remaining records as one giant 'record'."""
        import struct

        from tpu_tfrecord import wire

        out = write_shards(sandbox, num_shards=1, rows_per_shard=200)
        f = [os.path.join(out, x) for x in os.listdir(out) if x.endswith(".tfrecord")][0]
        raw = bytearray(open(f, "rb").read())
        struct.pack_into("<Q", raw, 0, len(raw) // 2)  # bogus but in-bounds
        open(f, "wb").write(bytes(raw))
        ds = TFRecordDataset(
            out, batch_size=10, schema=SCHEMA, verify_crc=False,
            max_record_bytes=1024,
        )
        with pytest.raises(wire.TFRecordCorruptionError, match="max_record_bytes"):
            collect_uids(ds)

    def test_mmap_truncated_shard_raises(self, sandbox):
        out = write_shards(sandbox, num_shards=1, rows_per_shard=20)
        f = [os.path.join(out, x) for x in os.listdir(out) if x.endswith(".tfrecord")][0]
        blob = open(f, "rb").read()
        open(f, "wb").write(blob[: len(blob) - 7])
        from tpu_tfrecord import wire

        ds = TFRecordDataset(out, batch_size=4, schema=SCHEMA)
        with pytest.raises(wire.TFRecordCorruptionError, match="truncated"):
            collect_uids(ds)


class TestShuffle:
    def test_shuffle_is_permutation_and_seeded(self, sandbox):
        out = write_shards(sandbox)
        base = collect_uids(
            TFRecordDataset(out, batch_size=7, schema=SCHEMA, drop_remainder=False)
        )
        s1 = collect_uids(
            TFRecordDataset(out, batch_size=7, schema=SCHEMA, shuffle=True, seed=1,
                            drop_remainder=False)
        )
        s1b = collect_uids(
            TFRecordDataset(out, batch_size=7, schema=SCHEMA, shuffle=True, seed=1,
                            drop_remainder=False)
        )
        s2 = collect_uids(
            TFRecordDataset(out, batch_size=7, schema=SCHEMA, shuffle=True, seed=2,
                            drop_remainder=False)
        )
        assert sorted(s1) == sorted(base)
        assert s1 == s1b           # deterministic for a seed
        assert s1 != base or s2 != base  # actually shuffles

    def test_epochs_reshuffle(self, sandbox):
        out = write_shards(sandbox)
        ds = TFRecordDataset(out, batch_size=42, schema=SCHEMA, shuffle=True, seed=3,
                             num_epochs=2, drop_remainder=False)
        uids = collect_uids(ds)
        e1, e2 = uids[:42], uids[42:]
        assert sorted(e1) == sorted(e2)
        assert e1 != e2  # different epoch permutation

    def test_shuffled_resume_matches_uninterrupted(self, sandbox):
        out = write_shards(sandbox)
        full = collect_uids(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA, shuffle=True, seed=7)
        )
        ds = TFRecordDataset(out, batch_size=5, schema=SCHEMA, shuffle=True, seed=7)
        with ds.batches() as it:
            first = next(it)["uid"].values.tolist()
            st = it.state()
        rest = collect_uids(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA, shuffle=True, seed=7), st
        )
        assert first + rest == full

    def test_shuffle_with_workers(self, sandbox):
        out = write_shards(sandbox)
        a = collect_uids(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA, shuffle=True, seed=5)
        )
        b = collect_uids(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA, shuffle=True, seed=5,
                            num_workers=3)
        )
        assert a == b


class TestRetries:
    def test_transient_io_error_retried(self, sandbox, monkeypatch):
        out = write_shards(sandbox, num_shards=1)
        # use_mmap=False: stream-level fault injection targets the buffered path
        ds = TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                             retry_policy=_fast_retries(2),
                             drop_remainder=False, use_mmap=False)
        real_open = __import__("tpu_tfrecord.wire", fromlist=["wire"]).open_compressed
        calls = {"n": 0}

        def flaky(path, mode, codec):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient network blip")
            return real_open(path, mode, codec)

        monkeypatch.setattr("tpu_tfrecord.wire.open_compressed", flaky)
        uids = collect_uids(ds)
        assert len(uids) == 7
        assert calls["n"] == 2

    def test_exhausted_retries_raise(self, sandbox, monkeypatch):
        out = write_shards(sandbox, num_shards=1)
        ds = TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                             retry_policy=_fast_retries(1), use_mmap=False)

        def always_fail(path, mode, codec):
            raise OSError("gone")

        monkeypatch.setattr("tpu_tfrecord.wire.open_compressed", always_fail)
        with pytest.raises(OSError):
            collect_uids(ds)


class TestAbandonedIterator:
    def test_threads_exit_after_gc_without_close(self, sandbox):
        """Review regression: dropping an iterator without close() must not
        leak pipeline threads or pin shard buffers forever."""
        import gc
        import threading
        import time as _time

        out = write_shards(sandbox, num_shards=6, rows_per_shard=20)
        before = threading.active_count()
        ds = TFRecordDataset(out, batch_size=5, schema=SCHEMA, num_workers=3,
                             num_epochs=None)
        it = ds.batches()
        next(it)  # pipeline running
        assert threading.active_count() > before
        del it
        gc.collect()
        deadline = _time.time() + 5
        while threading.active_count() > before and _time.time() < deadline:
            _time.sleep(0.1)
        assert threading.active_count() <= before + 1  # poll-loop grace


class TestTracing:
    def test_trace_and_duty_cycle(self):
        from tpu_tfrecord.tracing import DutyCycle, trace
        import time as _t

        with trace("host-region"):
            pass
        d = DutyCycle()
        with d.wait():
            _t.sleep(0.01)
        with d.step():
            _t.sleep(0.03)
        # assert the arithmetic, not OS scheduler timing
        assert d.busy_seconds > 0 and d.wait_seconds > 0
        assert d.value() == pytest.approx(
            d.busy_seconds / (d.busy_seconds + d.wait_seconds)
        )
        assert DutyCycle().value() is None


class TestHashBucketsValidation:
    def test_bad_hash_buckets_raise(self, sandbox):
        from tpu_tfrecord.schema import StringType

        schema = StructType([StructField("c", StringType()), StructField("x", LongType())])
        out = str(sandbox / "hv")
        tfio.write([["a", 1]], schema, out, mode="overwrite")
        with pytest.raises(ValueError, match="no such data column"):
            TFRecordDataset(out, batch_size=1, schema=schema, hash_buckets={"nope": 8})
        with pytest.raises(ValueError, match="string/binary"):
            TFRecordDataset(out, batch_size=1, schema=schema, hash_buckets={"x": 8})
        with pytest.raises(ValueError, match="positive"):
            TFRecordDataset(out, batch_size=1, schema=schema, hash_buckets={"c": 0})


class TestSlabStreaming:
    def test_tiny_slabs_identical_to_whole_shard(self, sandbox):
        """Force many slabs per shard (slab smaller than one record frame
        included): stream must be identical to the default path."""
        out = write_shards(sandbox, num_shards=3, rows_per_shard=25)
        ref = collect_uids(TFRecordDataset(out, batch_size=10, schema=SCHEMA))
        for slab in (17, 64, 300):
            got = collect_uids(
                TFRecordDataset(out, batch_size=10, schema=SCHEMA, slab_bytes=slab)
            )
            assert got == ref, f"slab_bytes={slab}"

    def test_tiny_slabs_resume(self, sandbox):
        out = write_shards(sandbox, num_shards=2, rows_per_shard=30)
        ds = TFRecordDataset(out, batch_size=8, schema=SCHEMA, slab_bytes=50)
        with ds.batches() as it:
            first = next(it)["uid"].values.tolist()
            st = it.state()
        rest = collect_uids(
            TFRecordDataset(out, batch_size=8, schema=SCHEMA, slab_bytes=50), st
        )
        ref = collect_uids(TFRecordDataset(out, batch_size=8, schema=SCHEMA))
        assert first + rest == ref

    def test_truncated_tail_detected(self, sandbox):
        from tpu_tfrecord.wire import TFRecordCorruptionError

        out = write_shards(sandbox, num_shards=1, rows_per_shard=5)
        f = [os.path.join(out, x) for x in os.listdir(out) if x.endswith(".tfrecord")][0]
        raw = open(f, "rb").read()
        open(f, "wb").write(raw[:-3])
        ds = TFRecordDataset(out, batch_size=1, schema=SCHEMA, slab_bytes=64,
                             drop_remainder=False)
        with pytest.raises(TFRecordCorruptionError):
            collect_uids(ds)

    def test_bogus_length_bounded_not_buffered(self, sandbox):
        """A corrupt length field with verify_crc=False must raise promptly
        via max_record_bytes, not buffer the rest of the shard."""
        import struct

        from tpu_tfrecord.wire import TFRecordCorruptionError

        out = write_shards(sandbox, num_shards=1, rows_per_shard=50)
        f = [os.path.join(out, x) for x in os.listdir(out) if x.endswith(".tfrecord")][0]
        raw = bytearray(open(f, "rb").read())
        # overwrite the FIRST record's length with a huge value
        struct.pack_into("<Q", raw, 0, 1 << 60)
        open(f, "wb").write(bytes(raw))
        ds = TFRecordDataset(out, batch_size=10, schema=SCHEMA, slab_bytes=64,
                             verify_crc=False, max_record_bytes=1 << 20)
        with pytest.raises(TFRecordCorruptionError, match="max_record_bytes"):
            collect_uids(ds)

    def test_gzip_slab_streaming(self, sandbox):
        out = str(sandbox / "gz")
        rows = [[i, float(i)] for i in range(40)]
        tfio.write(rows, SCHEMA, out, mode="overwrite", codec="gzip")
        got = collect_uids(
            TFRecordDataset(out, batch_size=10, schema=SCHEMA, slab_bytes=100)
        )
        ref = collect_uids(TFRecordDataset(out, batch_size=10, schema=SCHEMA))
        assert got == ref

    def test_mid_shard_retry_no_duplicates(self, sandbox, monkeypatch):
        """IO error mid-shard: retry must resume after the already-emitted
        records, not duplicate them."""
        out = write_shards(sandbox, num_shards=1, rows_per_shard=60)
        real_open = __import__("tpu_tfrecord.wire", fromlist=["wire"]).open_compressed
        state = {"opens": 0}

        class FlakyFile:
            def __init__(self, fh):
                self._fh = fh
                self._reads = 0

            def read(self, n=-1):
                self._reads += 1
                if state["opens"] == 1 and self._reads == 3:
                    raise OSError("mid-shard blip")
                return self._fh.read(n)

            def close(self):
                self._fh.close()

            def __enter__(self):
                return self

            def __exit__(self, *a):
                self.close()

        def flaky(path, mode, codec):
            state["opens"] += 1
            return FlakyFile(real_open(path, mode, codec))

        monkeypatch.setattr("tpu_tfrecord.wire.open_compressed", flaky)
        # use_mmap=False: stream-level fault injection targets the buffered
        # path (the mmap fast path opens files directly; see use_mmap doc)
        ds = TFRecordDataset(out, batch_size=10, schema=SCHEMA, slab_bytes=200,
                             retry_policy=_fast_retries(2),
                             drop_remainder=False, use_mmap=False)
        uids = collect_uids(ds)
        assert uids == list(range(60))
        assert state["opens"] >= 2  # retried
