"""Tier-1 tests for schema inference, mirroring InferSchemaSuite.scala."""

import os

import pytest

from tpu_tfrecord import infer, proto
from tpu_tfrecord.infer import SchemaInferenceError, infer_schema, merge_type_maps
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.proto import Example, Feature, FeatureList, SequenceExample
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    FloatType,
    LongType,
    NullType,
    StringType,
    StructField,
    StructType,
)

long_feature = Feature.int64_list([2**31 + 10])
float_feature = Feature.float_list([10.0])
str_feature = Feature.bytes_list([b"r1"])
long_list = Feature.int64_list([-2, 20])
float_list = Feature.float_list([2.5, 7.0])
str_list = Feature.bytes_list([b"r1", b"r2"])
empty_float_list = Feature(proto.FLOAT_LIST, [])


class TestExampleInference:
    """InferSchemaSuite.scala:39-81."""

    def test_infer_from_examples(self):
        example1 = Example(
            features={
                "LongFeature": long_feature,
                "FloatFeature": float_feature,
                "StrFeature": str_feature,
                "LongList": long_feature,
                "FloatList": float_feature,
                "StrList": str_feature,
                "MixedTypeList": long_list,
            }
        )
        example2 = Example(
            features={
                "StrFeature": str_feature,
                "LongList": long_list,
                "FloatList": float_list,
                "StrList": str_list,
                "MixedTypeList": float_list,
            }
        )
        schema = infer_schema([example1, example2], RecordType.EXAMPLE)
        m = {f.name: f.data_type for f in schema}
        assert len(schema) == 7
        assert m["LongFeature"] == LongType()
        assert m["FloatFeature"] == FloatType()
        assert m["StrFeature"] == StringType()
        assert m["LongList"] == ArrayType(LongType())
        assert m["FloatList"] == ArrayType(FloatType())
        assert m["StrList"] == ArrayType(StringType())
        # long+float lists promote to Array(Float)
        assert m["MixedTypeList"] == ArrayType(FloatType())

    def test_infer_from_serialized_bytes(self):
        ex = Example(features={"a": long_feature})
        schema = infer_schema([proto.encode_example(ex)], RecordType.EXAMPLE)
        assert {f.name: f.data_type for f in schema} == {"a": LongType()}

    def test_scalar_string_promotion(self):
        # long scalar + string scalar -> String (precedence 3 > 1)
        e1 = Example(features={"x": long_feature})
        e2 = Example(features={"x": str_feature})
        schema = infer_schema([e1, e2], RecordType.EXAMPLE)
        assert schema["x"].data_type == StringType()


class TestSequenceExampleInference:
    """InferSchemaSuite.scala:83-140."""

    def test_infer_from_sequence_examples(self):
        se1 = SequenceExample(
            context={"FloatFeature": float_feature},
            feature_lists={
                "LongListOfLists": FeatureList([long_feature, long_list]),
                "FloatListOfLists": FeatureList([float_feature, float_list]),
                "StringListOfLists": FeatureList([str_feature]),
                "MixedListOfLists": FeatureList([float_feature, str_list]),
            },
        )
        se2 = SequenceExample(
            feature_lists={
                "LongListOfLists": FeatureList([long_list]),
                "FloatListOfLists": FeatureList([float_feature]),
                "StringListOfLists": FeatureList([str_feature]),
                "MixedListOfLists": FeatureList([long_feature, str_feature]),
            },
        )
        schema = infer_schema([se1, se2], RecordType.SEQUENCE_EXAMPLE)
        m = {f.name: f.data_type for f in schema}
        assert len(schema) == 5
        assert m["FloatFeature"] == FloatType()
        assert m["LongListOfLists"] == ArrayType(ArrayType(LongType()))
        assert m["FloatListOfLists"] == ArrayType(ArrayType(FloatType()))
        assert m["StringListOfLists"] == ArrayType(ArrayType(StringType()))
        assert m["MixedListOfLists"] == ArrayType(ArrayType(StringType()))

    def test_empty_feature_yields_null_type(self):
        """InferSchemaSuite.scala:142-155."""
        se = SequenceExample(context={"emptyFloatFeature": empty_float_list})
        schema = infer_schema([se], RecordType.SEQUENCE_EXAMPLE)
        assert len(schema) == 1
        assert schema["emptyFloatFeature"].data_type == NullType()

    def test_empty_then_concrete_promotes(self):
        se1 = SequenceExample(context={"x": empty_float_list})
        se2 = SequenceExample(context={"x": float_feature})
        schema = infer_schema([se1, se2], RecordType.SEQUENCE_EXAMPLE)
        assert schema["x"].data_type == FloatType()


class TestMergeAndErrors:
    def test_unsupported_record_type_raises(self):
        with pytest.raises((SchemaInferenceError, ValueError)):
            infer_schema([b"\x00"], "Bogus")

    def test_byte_array_schema(self):
        schema = infer_schema([], RecordType.BYTE_ARRAY)
        assert schema.names == ["byteArray"]
        assert schema["byteArray"].data_type == BinaryType()

    def test_merge_type_maps_union_and_promotion(self):
        """The distributed combOp (TensorFlowInferSchema.scala:120-127)."""
        a = {"x": LongType(), "y": ArrayType(LongType()), "only_a": StringType()}
        b = {"x": FloatType(), "y": ArrayType(FloatType()), "only_b": None}
        merged = merge_type_maps(a, b)
        assert merged["x"] == FloatType()
        assert merged["y"] == ArrayType(FloatType())
        assert merged["only_a"] == StringType()
        assert merged["only_b"] is None

    def test_infer_sample_limit(self):
        e1 = Example(features={"x": long_feature})
        e2 = Example(features={"x": str_feature})
        schema = infer_schema([e1, e2], RecordType.EXAMPLE, limit=1)
        assert schema["x"].data_type == LongType()

    def test_wrong_message_type_raises(self):
        with pytest.raises(SchemaInferenceError):
            infer_schema([SequenceExample()], RecordType.EXAMPLE)


class TestMergeAlgebra:
    """The distributed combOp must be commutative and associative — hosts
    fold partial maps in different groupings; determinism depends on it."""

    TYPES = [
        None,
        LongType(),
        FloatType(),
        StringType(),
        ArrayType(LongType()),
        ArrayType(FloatType()),
        ArrayType(StringType()),
        ArrayType(ArrayType(LongType())),
        ArrayType(ArrayType(FloatType())),
        ArrayType(ArrayType(StringType())),
    ]

    def random_map(self, rng):
        return {
            f"f{i}": self.TYPES[int(rng.integers(0, len(self.TYPES)))]
            for i in range(int(rng.integers(0, 6)))
        }

    def test_commutative(self):
        import numpy as np

        rng = np.random.default_rng(0)
        for _ in range(200):
            a, b = self.random_map(rng), self.random_map(rng)
            assert merge_type_maps(a, b) == merge_type_maps(b, a)

    def test_associative(self):
        import numpy as np

        rng = np.random.default_rng(1)
        for _ in range(200):
            a, b, c = (self.random_map(rng) for _ in range(3))
            left = merge_type_maps(merge_type_maps(a, b), c)
            right = merge_type_maps(a, merge_type_maps(b, c))
            assert left == right

    def test_idempotent(self):
        import numpy as np

        rng = np.random.default_rng(2)
        for _ in range(50):
            a = self.random_map(rng)
            assert merge_type_maps(a, a) == a


class TestNativeInferOracle:
    """The native wire-walk inference seqOp (tfr_infer_batch) must match the
    Python oracle exactly — clean maps AND error class/record — over
    adversarial wire layouts: duplicate map keys (last-wins masking a
    kind-unset error), repeated kind fields (merge vs replace), packed and
    unpacked encodings, split features segments, empty lists/FeatureLists."""

    @staticmethod
    def _varint(v: int) -> bytes:
        out = b""
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out += bytes([b | 0x80])
            else:
                return out + bytes([b])

    @classmethod
    def _tag(cls, f: int, w: int) -> bytes:
        return cls._varint((f << 3) | w)

    @classmethod
    def _ld(cls, f: int, payload: bytes) -> bytes:
        return cls._tag(f, 2) + cls._varint(len(payload)) + payload

    def _rand_feature(self, rng) -> bytes:
        import numpy as np

        if rng.random() < 0.08:
            return b""  # kind unset -> SchemaInferenceError unless masked
        segs = b""
        for _ in range(rng.choice([1, 1, 1, 2])):
            kind = rng.choice([1, 2, 3])
            n = rng.choice([0, 0, 1, 1, 1, 2, 5])
            if kind == 1:
                inner = b"".join(
                    self._ld(1, bytes(rng.randrange(256) for _ in range(rng.randrange(4))))
                    for _ in range(n)
                )
            elif kind == 2:
                if rng.random() < 0.5:
                    inner = self._ld(1, np.arange(n, dtype="<f4").tobytes())
                else:
                    inner = b"".join(
                        self._tag(1, 5) + np.float32(i).tobytes() for i in range(n)
                    )
            else:
                if rng.random() < 0.5:
                    inner = self._ld(
                        1, b"".join(self._varint(rng.randrange(1 << 40)) for _ in range(n))
                    )
                else:
                    inner = b"".join(
                        self._tag(1, 0) + self._varint(rng.randrange(1 << 40))
                        for _ in range(n)
                    )
            segs += self._ld(kind, inner)
        return segs

    def _rand_example(self, rng) -> bytes:
        names = ["a", "b", "c", "dup", "dup", "x" * 30]
        rng.shuffle(names)
        entries = b""
        for nm in names[: rng.randrange(1, 6)]:
            entry = self._ld(1, nm.encode())
            if rng.random() < 0.95:
                entry += self._ld(2, self._rand_feature(rng))
            entries += self._ld(1, entry)
        out = self._ld(1, entries)
        if rng.random() < 0.3:
            # second features segment: dict.update merge semantics
            out += self._ld(
                1, self._ld(1, self._ld(1, b"late") + self._ld(2, self._rand_feature(rng)))
            )
        return out

    def _rand_seq_example(self, rng) -> bytes:
        out = self._rand_example(rng)  # context shares the map layout
        fl = b""
        for nm in ["s1", "s2", "dupfl", "dupfl"][: rng.randrange(0, 4)]:
            inner = b"".join(
                self._ld(1, self._rand_feature(rng)) for _ in range(rng.randrange(0, 4))
            )
            fl += self._ld(1, self._ld(1, nm.encode()) + self._ld(2, inner))
        return out + (self._ld(2, fl) if fl else b"")

    def _run_case(self, records, record_type):
        import numpy as np

        from tpu_tfrecord import _native
        from tpu_tfrecord.infer import infer_from_records, type_map_from_precedences
        from tpu_tfrecord.proto import ProtoDecodeError

        try:
            oracle, oracle_exc = infer_from_records(iter(records), record_type), None
        except (SchemaInferenceError, ProtoDecodeError) as e:
            oracle, oracle_exc = None, type(e).__name__
        buf = b"".join(records)
        offsets = np.cumsum([0] + [len(r) for r in records[:-1]]).astype(np.uint64)
        lengths = np.array([len(r) for r in records], np.uint64)
        try:
            with _native.InferScanner(record_type) as sc:
                k = len(records) // 2  # two updates: exercise accumulation
                sc.update(buf, offsets[:k], lengths[:k])
                sc.update(buf, offsets[k:], lengths[k:])
                native, native_exc = type_map_from_precedences(sc.result()), None
        except (SchemaInferenceError, ProtoDecodeError) as e:
            native, native_exc = None, type(e).__name__
        assert oracle_exc == native_exc, (oracle_exc, native_exc)
        assert oracle == native

    def test_differential_example(self):
        import random

        from tpu_tfrecord import _native

        if not _native.available():
            pytest.skip("native lib unavailable")
        rng = random.Random(7)
        for _ in range(400):
            self._run_case(
                [self._rand_example(rng) for _ in range(rng.randrange(1, 8))],
                RecordType.EXAMPLE,
            )

    def test_differential_sequence_example(self):
        import random

        from tpu_tfrecord import _native

        if not _native.available():
            pytest.skip("native lib unavailable")
        rng = random.Random(8)
        for _ in range(400):
            self._run_case(
                [self._rand_seq_example(rng) for _ in range(rng.randrange(1, 8))],
                RecordType.SEQUENCE_EXAMPLE,
            )

    def test_limit_skips_corruption_past_sample(self, tmp_path):
        """With inferSampleLimit=N, corruption AFTER the N sampled records
        must not fail inference — the limit is pushed into the span scan so
        trailing bytes are never framed or CRC-checked, matching the lazy
        per-record oracle (code-review r5 finding)."""
        import numpy as np

        import tpu_tfrecord.io as tfio
        from tpu_tfrecord import _native, wire

        if not _native.available():
            pytest.skip("native lib unavailable")
        out = tmp_path / "corrupt"
        schema = StructType([StructField("a", LongType())])
        tfio.write([[i] for i in range(50)], schema, str(out), mode="overwrite")
        shard = next(p for p in os.listdir(out) if p.startswith("part-"))
        path = out / shard
        data = bytearray(path.read_bytes())
        data[-6] ^= 0xFF  # corrupt the last record's payload (CRC mismatch)
        path.write_bytes(bytes(data))
        # full inference sees the corruption
        with pytest.raises(wire.TFRecordCorruptionError):
            tfio.reader(str(out)).schema()
        # sampled inference stops before it
        r = tfio.reader(str(out), inferSampleLimit=10)
        assert [f.name for f in r.schema()] == ["a"]
        np.testing.assert_array_equal(
            [row[0] for row in tfio.read(str(out), schema=schema, limit=10).rows],
            list(range(10)),
        )

    def test_span_stream_limit_contract_pure_python(self, tmp_path, monkeypatch):
        """scan_spans_stream's pure-Python leg honors max_records the same
        way the native leg does: bytes past the sampled records are never
        framed or CRC-checked, even within an already-read slab
        (code-review r5 finding — the fallback used to frame the whole slab
        first and so raised on corruption past the limit)."""
        from tpu_tfrecord import _native, wire
        from tpu_tfrecord.io.reader import scan_spans_stream

        path = tmp_path / "x.tfrecord"
        wire.write_records(str(path), [b"payload-%02d" % i for i in range(20)])
        data = bytearray(path.read_bytes())
        data[-3] ^= 0xFF  # corrupt the LAST record's payload (CRC mismatch)
        path.write_bytes(bytes(data))

        def spans(max_records):
            out = []
            for buf, offs, lens in scan_spans_stream(
                str(path), True, max_records=max_records
            ):
                out.extend(
                    bytes(buf[int(o) : int(o) + int(l)])
                    for o, l in zip(offs, lens)
                )
            return out

        for native_on in (True, False):
            if native_on and not _native.available():
                continue
            monkeypatch.setattr(_native, "available", lambda v=native_on: v)
            got = spans(max_records=5)
            assert got == [b"payload-%02d" % i for i in range(5)], native_on
            with pytest.raises(wire.TFRecordCorruptionError):
                spans(max_records=None)

    def test_reader_native_path_matches_oracle_with_limit(self, tmp_path):
        """DatasetReader._shard_type_map (native) == infer_from_records
        (oracle) including infer_sample_limit truncation."""
        import numpy as np

        import tpu_tfrecord.io as tfio
        from tpu_tfrecord import _native, wire
        from tpu_tfrecord.infer import infer_from_records

        if not _native.available():
            pytest.skip("native lib unavailable")
        out = str(tmp_path / "ds")
        schema = StructType(
            [StructField("a", LongType()), StructField("v", ArrayType(FloatType()))]
        )
        rng = np.random.default_rng(3)
        rows = [
            [int(rng.integers(0, 100)), [float(x) for x in rng.normal(size=rng.integers(1, 4))]]
            for _ in range(200)
        ]
        tfio.write(rows, schema, out, mode="overwrite")
        for limit in (None, 1, 7, 200, 10_000):
            r = tfio.reader(out, inferSampleLimit=limit) if limit else tfio.reader(out)
            sh = r.shards[0]
            native = r._shard_type_map(sh)
            oracle = infer_from_records(
                wire.read_records(sh.path), RecordType.EXAMPLE, limit=limit
            )
            assert native == oracle, limit


class TestSpanStreamFuzz:
    def test_slab_and_limit_sweep_matches_oracle(self, tmp_path, monkeypatch):
        """scan_spans_stream must yield the identical record sequence for
        EVERY (slab size, max_records, leg) combination — tiny slabs force
        partial-frame tail carries to interact with the record limit, the
        newest shared seam between the dataset and inference paths."""
        import random

        from tpu_tfrecord import _native, wire
        from tpu_tfrecord.io.reader import scan_spans_stream

        rng = random.Random(11)
        payloads = [
            bytes(rng.randrange(256) for _ in range(rng.choice([0, 1, 7, 40, 300])))
            for _ in range(57)
        ]
        path = tmp_path / "fuzz.tfrecord"
        wire.write_records(str(path), payloads)

        def collect(slab, limit):
            got = []
            for buf, offs, lens in scan_spans_stream(
                str(path), True, slab_bytes=slab, max_records=limit
            ):
                got.extend(
                    bytes(buf[int(o) : int(o) + int(l)])
                    for o, l in zip(offs, lens)
                )
            return got

        legs = [True, False] if _native.available() else [False]
        for native_on in legs:
            monkeypatch.setattr(_native, "available", lambda v=native_on: v)
            for slab in (17, 64, 333, 1 << 20):
                for limit in (None, 0, 1, 5, 56, 57, 500):
                    want = payloads if limit is None else payloads[:limit]
                    assert collect(slab, limit) == want, (native_on, slab, limit)
