"""Tier-1 tests for schema inference, mirroring InferSchemaSuite.scala."""

import pytest

from tpu_tfrecord import infer, proto
from tpu_tfrecord.infer import SchemaInferenceError, infer_schema, merge_type_maps
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.proto import Example, Feature, FeatureList, SequenceExample
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    FloatType,
    LongType,
    NullType,
    StringType,
)

long_feature = Feature.int64_list([2**31 + 10])
float_feature = Feature.float_list([10.0])
str_feature = Feature.bytes_list([b"r1"])
long_list = Feature.int64_list([-2, 20])
float_list = Feature.float_list([2.5, 7.0])
str_list = Feature.bytes_list([b"r1", b"r2"])
empty_float_list = Feature(proto.FLOAT_LIST, [])


class TestExampleInference:
    """InferSchemaSuite.scala:39-81."""

    def test_infer_from_examples(self):
        example1 = Example(
            features={
                "LongFeature": long_feature,
                "FloatFeature": float_feature,
                "StrFeature": str_feature,
                "LongList": long_feature,
                "FloatList": float_feature,
                "StrList": str_feature,
                "MixedTypeList": long_list,
            }
        )
        example2 = Example(
            features={
                "StrFeature": str_feature,
                "LongList": long_list,
                "FloatList": float_list,
                "StrList": str_list,
                "MixedTypeList": float_list,
            }
        )
        schema = infer_schema([example1, example2], RecordType.EXAMPLE)
        m = {f.name: f.data_type for f in schema}
        assert len(schema) == 7
        assert m["LongFeature"] == LongType()
        assert m["FloatFeature"] == FloatType()
        assert m["StrFeature"] == StringType()
        assert m["LongList"] == ArrayType(LongType())
        assert m["FloatList"] == ArrayType(FloatType())
        assert m["StrList"] == ArrayType(StringType())
        # long+float lists promote to Array(Float)
        assert m["MixedTypeList"] == ArrayType(FloatType())

    def test_infer_from_serialized_bytes(self):
        ex = Example(features={"a": long_feature})
        schema = infer_schema([proto.encode_example(ex)], RecordType.EXAMPLE)
        assert {f.name: f.data_type for f in schema} == {"a": LongType()}

    def test_scalar_string_promotion(self):
        # long scalar + string scalar -> String (precedence 3 > 1)
        e1 = Example(features={"x": long_feature})
        e2 = Example(features={"x": str_feature})
        schema = infer_schema([e1, e2], RecordType.EXAMPLE)
        assert schema["x"].data_type == StringType()


class TestSequenceExampleInference:
    """InferSchemaSuite.scala:83-140."""

    def test_infer_from_sequence_examples(self):
        se1 = SequenceExample(
            context={"FloatFeature": float_feature},
            feature_lists={
                "LongListOfLists": FeatureList([long_feature, long_list]),
                "FloatListOfLists": FeatureList([float_feature, float_list]),
                "StringListOfLists": FeatureList([str_feature]),
                "MixedListOfLists": FeatureList([float_feature, str_list]),
            },
        )
        se2 = SequenceExample(
            feature_lists={
                "LongListOfLists": FeatureList([long_list]),
                "FloatListOfLists": FeatureList([float_feature]),
                "StringListOfLists": FeatureList([str_feature]),
                "MixedListOfLists": FeatureList([long_feature, str_feature]),
            },
        )
        schema = infer_schema([se1, se2], RecordType.SEQUENCE_EXAMPLE)
        m = {f.name: f.data_type for f in schema}
        assert len(schema) == 5
        assert m["FloatFeature"] == FloatType()
        assert m["LongListOfLists"] == ArrayType(ArrayType(LongType()))
        assert m["FloatListOfLists"] == ArrayType(ArrayType(FloatType()))
        assert m["StringListOfLists"] == ArrayType(ArrayType(StringType()))
        assert m["MixedListOfLists"] == ArrayType(ArrayType(StringType()))

    def test_empty_feature_yields_null_type(self):
        """InferSchemaSuite.scala:142-155."""
        se = SequenceExample(context={"emptyFloatFeature": empty_float_list})
        schema = infer_schema([se], RecordType.SEQUENCE_EXAMPLE)
        assert len(schema) == 1
        assert schema["emptyFloatFeature"].data_type == NullType()

    def test_empty_then_concrete_promotes(self):
        se1 = SequenceExample(context={"x": empty_float_list})
        se2 = SequenceExample(context={"x": float_feature})
        schema = infer_schema([se1, se2], RecordType.SEQUENCE_EXAMPLE)
        assert schema["x"].data_type == FloatType()


class TestMergeAndErrors:
    def test_unsupported_record_type_raises(self):
        with pytest.raises((SchemaInferenceError, ValueError)):
            infer_schema([b"\x00"], "Bogus")

    def test_byte_array_schema(self):
        schema = infer_schema([], RecordType.BYTE_ARRAY)
        assert schema.names == ["byteArray"]
        assert schema["byteArray"].data_type == BinaryType()

    def test_merge_type_maps_union_and_promotion(self):
        """The distributed combOp (TensorFlowInferSchema.scala:120-127)."""
        a = {"x": LongType(), "y": ArrayType(LongType()), "only_a": StringType()}
        b = {"x": FloatType(), "y": ArrayType(FloatType()), "only_b": None}
        merged = merge_type_maps(a, b)
        assert merged["x"] == FloatType()
        assert merged["y"] == ArrayType(FloatType())
        assert merged["only_a"] == StringType()
        assert merged["only_b"] is None

    def test_infer_sample_limit(self):
        e1 = Example(features={"x": long_feature})
        e2 = Example(features={"x": str_feature})
        schema = infer_schema([e1, e2], RecordType.EXAMPLE, limit=1)
        assert schema["x"].data_type == LongType()

    def test_wrong_message_type_raises(self):
        with pytest.raises(SchemaInferenceError):
            infer_schema([SequenceExample()], RecordType.EXAMPLE)


class TestMergeAlgebra:
    """The distributed combOp must be commutative and associative — hosts
    fold partial maps in different groupings; determinism depends on it."""

    TYPES = [
        None,
        LongType(),
        FloatType(),
        StringType(),
        ArrayType(LongType()),
        ArrayType(FloatType()),
        ArrayType(StringType()),
        ArrayType(ArrayType(LongType())),
        ArrayType(ArrayType(FloatType())),
        ArrayType(ArrayType(StringType())),
    ]

    def random_map(self, rng):
        return {
            f"f{i}": self.TYPES[int(rng.integers(0, len(self.TYPES)))]
            for i in range(int(rng.integers(0, 6)))
        }

    def test_commutative(self):
        import numpy as np

        rng = np.random.default_rng(0)
        for _ in range(200):
            a, b = self.random_map(rng), self.random_map(rng)
            assert merge_type_maps(a, b) == merge_type_maps(b, a)

    def test_associative(self):
        import numpy as np

        rng = np.random.default_rng(1)
        for _ in range(200):
            a, b, c = (self.random_map(rng) for _ in range(3))
            left = merge_type_maps(merge_type_maps(a, b), c)
            right = merge_type_maps(a, merge_type_maps(b, c))
            assert left == right

    def test_idempotent(self):
        import numpy as np

        rng = np.random.default_rng(2)
        for _ in range(50):
            a = self.random_map(rng)
            assert merge_type_maps(a, a) == a
