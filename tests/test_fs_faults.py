"""Remote-filesystem fault injection (VERDICT r2 missing #3 / next-step #5).

The local tests prove read_retries, truncation detection, and the atomic
write-job abort against injected LOCAL faults; these prove the same
contracts on the REMOTE path by wrapping the fsspec file objects the real
read/write code opens: transient mid-read errors, permanently flaky
streams, object-store-style short reads, slow reads, and upload-on-close
failures. The reference inherits all of this from Hadoop FS semantics
(TFRecordFileReader.scala:24-32, TFRecordOutputWriter.scala:19).
"""

import importlib.util
import uuid

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import fs as tfs, wire
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType


def _fast_retries(n):
    """Real retry semantics, injected no-op sleep: no wall-clock cost."""
    return RetryPolicy(max_retries=n, sleep=lambda _s: None)

fsspec = pytest.importorskip("fsspec")

SCHEMA = StructType(
    [StructField("id", LongType(), nullable=False), StructField("s", StringType())]
)
ROWS = [[i, f"val{i}" * (i % 4 + 1)] for i in range(60)]


@pytest.fixture
def mem_url():
    url = f"memory://faults-{uuid.uuid4().hex[:8]}"
    yield url
    mem = fsspec.filesystem("memory")
    try:
        mem.rm(url.split("://", 1)[1], recursive=True)
    except FileNotFoundError:
        pass


class _FaultyFile:
    """Wraps an fsspec file: optional per-read byte cap (object-store short
    reads), a one-shot OSError raised mid-stream after N bytes, and an
    OSError from close() on write streams (failed upload flush)."""

    def __init__(self, inner, plan, path):
        self._inner = inner
        self._plan = plan
        self._path = path
        self._read_bytes = 0

    def _maybe_fail(self):
        remaining = self._plan.read_faults.get(self._path, 0)
        if remaining and self._read_bytes >= self._plan.fail_after_bytes:
            self._plan.read_faults[self._path] = remaining - 1
            raise OSError(f"injected transient read error on {self._path}")

    def read(self, size=-1):
        self._maybe_fail()
        if self._plan.short_read_cap and size is not None and size > 0:
            size = min(size, self._plan.short_read_cap)
        data = self._inner.read(size)
        self._read_bytes += len(data)
        return data

    def readinto(self, b):
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def write(self, data):
        return self._inner.write(data)

    def close(self):
        if self._plan.close_faults and not self._inner.closed and \
                "w" in getattr(self._inner, "mode", "w"):
            if any(k in self._path for k in self._plan.close_faults):
                # a LOST upload: the inner file is never closed, so the
                # fsspec buffer is never committed to the store — the
                # object does not exist afterwards (the real object-store
                # failure mode; abort must cope with a missing file)
                raise OSError(f"injected upload failure on close: {self._path}")
        if not self._inner.closed:
            self._inner.close()

    @property
    def closed(self):
        return self._inner.closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _FaultPlan:
    def __init__(self):
        self.read_faults = {}       # full path -> remaining one-shot errors
        self.fail_after_bytes = 0   # bytes served before an armed error fires
        self.short_read_cap = 0     # 0 = off
        self.close_faults = set()   # path substrings whose close() fails


@pytest.fixture
def faulty_fs(monkeypatch):
    plan = _FaultPlan()
    orig = tfs.FsspecFS.open

    def open_(self, path, mode):
        return _FaultyFile(orig(self, path, mode), plan, path)

    monkeypatch.setattr(tfs.FsspecFS, "open", open_)
    return plan


def _write_remote(mem_url, n_shards=3):
    out = mem_url + "/ds"
    per = len(ROWS) // n_shards
    for s in range(n_shards):
        tfio.write(ROWS[s * per : (s + 1) * per], SCHEMA, out,
                   mode="append" if s else "overwrite")
    return out


def _read_all_ids(out, **kw):
    ds = TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                         drop_remainder=False, **kw)
    got = []
    with ds.batches() as it:
        for cb in it:
            got.extend(cb["id"].values.tolist())
    return got


class TestRemoteReadFaults:
    def test_transient_error_retries_without_dups_or_holes(self, mem_url, faulty_fs):
        out = _write_remote(mem_url)
        shards = [s.path for s in tfio.discover_shards(out)]
        faulty_fs.fail_after_bytes = 100  # mid-stream, not on open
        faulty_fs.read_faults = {p: 1 for p in shards}  # one failure each
        got = _read_all_ids(out, retry_policy=_fast_retries(2))
        assert sorted(got) == sorted(r[0] for r in ROWS)
        assert all(v == 0 for v in faulty_fs.read_faults.values())  # all fired

    def test_retries_exhausted_raises(self, mem_url, faulty_fs):
        # fail_after_bytes=0: EVERY read of the flaky shard errors before
        # serving a byte, so no attempt makes progress and the retry
        # budget must exhaust. (With progress between firings the remote
        # stream now legitimately HEALS by resuming at the consumed
        # offset — pinned in tests/test_http_remote.py — so a
        # progress-permitting fault no longer exhausts anything.)
        out = _write_remote(mem_url)
        shards = [s.path for s in tfio.discover_shards(out)]
        faulty_fs.fail_after_bytes = 0
        faulty_fs.read_faults = {shards[0]: 1000}  # permanently flaky
        with pytest.raises(OSError, match="injected transient"):
            _read_all_ids(out, retry_policy=_fast_retries(2))

    def test_short_and_slow_reads_stream_correctly(self, mem_url, faulty_fs):
        """Object-store-style short reads (every read capped at 7 bytes)
        must stream through the slab carry logic, never misread as EOF."""
        out = _write_remote(mem_url)
        faulty_fs.short_read_cap = 7
        got = _read_all_ids(out)
        assert sorted(got) == sorted(r[0] for r in ROWS)
        # and the row-level reader path
        table = tfio.read(out, schema=SCHEMA)
        assert sorted(table.column("id")) == sorted(r[0] for r in ROWS)

    @pytest.mark.parametrize("codec", [
        "gzip", "deflate",
        pytest.param("zstd", marks=pytest.mark.skipif(
            importlib.util.find_spec("zstandard") is None,
            reason="optional zstandard package not installed",
        )),
        "snappy", "lz4", "bzip2",
    ])
    def test_short_reads_through_codec_streams(self, mem_url, faulty_fs, codec):
        """Every codec's framing reader must loop over short reads (3-byte
        cap: even the 4-byte Hadoop block headers split) instead of
        misreporting a valid remote file as truncated."""
        out = mem_url + f"/short_{codec}"
        tfio.write(ROWS[:20], SCHEMA, out, mode="overwrite", codec=codec)
        faulty_fs.short_read_cap = 3
        table = tfio.read(out, schema=SCHEMA)
        assert sorted(table.column("id")) == list(range(20))

    def test_remote_truncation_detected(self, mem_url, faulty_fs):
        out = _write_remote(mem_url, n_shards=1)
        shard = tfio.discover_shards(out)[0].path
        mem = fsspec.filesystem("memory")
        key = shard.split("://", 1)[1]
        blob = mem.cat_file(key)
        mem.pipe_file(key, blob[: len(blob) - 5])
        with pytest.raises(wire.TFRecordCorruptionError):
            _read_all_ids(out)

    def test_remote_gzip_truncation_detected(self, mem_url, faulty_fs):
        out = mem_url + "/gz"
        tfio.write(ROWS[:20], SCHEMA, out, mode="overwrite", codec="gzip")
        shard = tfio.discover_shards(out)[0].path
        mem = fsspec.filesystem("memory")
        key = shard.split("://", 1)[1]
        blob = mem.cat_file(key)
        mem.pipe_file(key, blob[: len(blob) // 2])
        with pytest.raises((wire.TFRecordCorruptionError, OSError, EOFError)):
            _read_all_ids(out)


class TestRemoteWriteFaults:
    def test_upload_on_close_failure_aborts_cleanly(self, mem_url, faulty_fs):
        """A part-file whose close() fails (object-store upload flush) must
        surface the error AND leave nothing visible: no data files, no
        _SUCCESS; a later retry succeeds."""
        out = mem_url + "/aborted"
        faulty_fs.close_faults = {"part-"}
        with pytest.raises(OSError, match="injected upload failure"):
            tfio.write(ROWS[:10], SCHEMA, out, mode="error")
        fs = tfs.filesystem_for(out)
        if fs.exists(out):
            visible = [n for n in fs.listdir(out) if not n.startswith("_temporary")]
            assert visible == [], visible
        assert not tfio.has_success_marker(out)
        faulty_fs.close_faults = set()
        tfio.write(ROWS[:10], SCHEMA, out, mode="error")
        assert sorted(tfio.read(out, schema=SCHEMA).column("id")) == list(range(10))

    def test_upload_failure_leaves_no_object_behind(self, mem_url, faulty_fs):
        """The injected close() failure models a LOST upload: the temp part
        file must not exist on the store afterwards (abort must cope with
        deleting files that never materialized)."""
        out = mem_url + "/lost"
        faulty_fs.close_faults = {"part-"}
        with pytest.raises(OSError, match="injected upload failure"):
            tfio.write(ROWS[:5], SCHEMA, out, mode="error")
        mem = fsspec.filesystem("memory")
        key = out.split("://", 1)[1]
        if mem.exists(key):
            found = [p for p in mem.find(key) if "part-" in p]
            assert found == [], found

    def test_success_marker_write_failure_propagates(self, mem_url, monkeypatch):
        """The _SUCCESS marker is created via FsspecFS.touch (not open):
        a failed marker upload must surface, never report success."""
        out = mem_url + "/marker"
        orig_touch = tfs.FsspecFS.touch

        def touch_(self, path):
            if "_SUCCESS" in path:
                raise OSError(f"injected marker upload failure: {path}")
            return orig_touch(self, path)

        monkeypatch.setattr(tfs.FsspecFS, "touch", touch_)
        with pytest.raises(OSError, match="injected marker upload"):
            tfio.write(ROWS[:4], SCHEMA, out, mode="error")
        assert not tfio.has_success_marker(out)
