"""Subprocess worker for the kill -9 mid-populate test
(tests/test_http_remote.py): stream a remote HTTP dataset with the
columnar epoch cache populating, printing one line per batch so the
parent can SIGKILL this process while a cache entry is mid-append.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("url")
    ap.add_argument("cache_dir")
    ap.add_argument("--batch-size", type=int, default=256)
    args = ap.parse_args()

    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.schema import (
        LongType, StringType, StructField, StructType,
    )

    schema = StructType([
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),
    ])
    ds = TFRecordDataset(
        args.url, batch_size=args.batch_size, schema=schema,
        drop_remainder=False, cache="auto", cache_dir=args.cache_dir,
    )
    n = 0
    with ds.batches() as it:
        for cb in it:
            n += cb.num_rows
            print(f"batch rows={n}", flush=True)
    print(f"done rows={n}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
