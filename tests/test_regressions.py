"""Regression tests for review findings: job-temp isolation, rollover
atomicity, partition-column materialization, iterator termination, strict
padding, local batch sizing."""

import os

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import (
    ArrayType,
    FloatType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.tpu import create_mesh, host_batch_from_columnar
from tpu_tfrecord.tpu.mesh import local_batch_size

SCHEMA = StructType([StructField("uid", LongType()), StructField("tag", StringType())])


class TestWriterAtomicity:
    def test_failed_job_leaves_no_final_files(self, sandbox):
        """Rollover shards must NOT appear in the output dir if the job fails."""
        out = str(sandbox / "fail")

        def rows():
            for i in range(25):
                yield [i, "t"]
            raise RuntimeError("mid-job failure")

        w = DatasetWriter(out, SCHEMA, TFRecordOptions(), mode="overwrite",
                          max_records_per_file=10)
        with pytest.raises(RuntimeError, match="mid-job"):
            w.write_rows(rows())
        data_files = [
            f for f in os.listdir(out) if not f.startswith("_")
        ] if os.path.isdir(out) else []
        assert data_files == []
        assert not tfio.has_success_marker(out)

    def test_rollover_commits_all_at_end(self, sandbox):
        out = str(sandbox / "roll")
        w = DatasetWriter(out, SCHEMA, TFRecordOptions(), mode="overwrite",
                          max_records_per_file=10)
        files = w.write_rows([[i, "t"] for i in range(25)])
        assert len(files) == 3
        assert len(tfio.read(out, schema=SCHEMA)) == 25

    def test_other_jobs_temp_dir_survives(self, sandbox):
        """Completing one job must not clobber another job's in-flight temp."""
        out = str(sandbox / "concurrent")
        os.makedirs(os.path.join(out, "_temporary", "other-job"))
        open(os.path.join(out, "_temporary", "other-job", "in-flight.tmp"), "wb").close()
        w = DatasetWriter(out, SCHEMA, TFRecordOptions(), mode="append")
        w.write_rows([[1, "a"]])
        assert os.path.exists(
            os.path.join(out, "_temporary", "other-job", "in-flight.tmp")
        )


class TestPartitionColumnsInBatches:
    def test_requested_partition_column_materialized(self, sandbox):
        out = str(sandbox / "pds")
        rows = [[i, "a" if i < 4 else "b"] for i in range(8)]
        schema = StructType([StructField("uid", LongType()), StructField("day", StringType())])
        tfio.write(rows, schema, out, mode="overwrite", partition_by=["day"])
        ds = TFRecordDataset(out, batch_size=8, drop_remainder=False,
                             columns=["uid", "day"])
        with ds.batches() as it:
            b = next(it)
        assert "day" in b.columns
        uid = b["uid"].values
        day = [blob.decode() for blob in b["day"].blobs]
        for u, d in zip(uid.tolist(), day):
            assert d == ("a" if u < 4 else "b")

    def test_numeric_partition_column(self, sandbox):
        out = str(sandbox / "npds")
        schema = StructType([StructField("v", FloatType()), StructField("shard", LongType())])
        tfio.write([[0.5, 3], [1.5, 7]], schema, out, mode="overwrite",
                   partition_by=["shard"])
        ds = TFRecordDataset(out, batch_size=2, drop_remainder=False)
        with ds.batches() as it:
            b = next(it)
        assert set(b.columns) == {"v", "shard"}
        assert b["shard"].values.dtype == np.int64
        assert sorted(b["shard"].values.tolist()) == [3, 7]


class TestIteratorTermination:
    def test_next_after_exhaustion_raises_stopiteration(self, sandbox):
        out = str(sandbox / "term")
        tfio.write([[i, "t"] for i in range(4)], SCHEMA, out, mode="overwrite")
        ds = TFRecordDataset(out, batch_size=2, schema=SCHEMA)
        it = ds.batches()
        list(it)
        with pytest.raises(StopIteration):
            next(it)
        with pytest.raises(StopIteration):
            next(it)
        it.close()

    def test_producer_error_re_raised_every_time(self, sandbox):
        out = str(sandbox / "err")
        tfio.write([[1, "t"]], SCHEMA, out, mode="overwrite")
        # corrupt the shard
        f = [p for p in os.listdir(out) if p.endswith(".tfrecord")][0]
        path = os.path.join(out, f)
        raw = bytearray(open(path, "rb").read())
        raw[14] ^= 0xFF
        open(path, "wb").write(bytes(raw))
        ds = TFRecordDataset(out, batch_size=1, schema=SCHEMA)
        it = ds.batches()
        with pytest.raises(Exception):
            next(it)
        with pytest.raises(Exception):
            next(it)  # must not hang
        it.close()


class TestStrictPadding:
    def test_missing_pad_to_raises(self, sandbox):
        schema = StructType([StructField("emb", ArrayType(FloatType()))])
        out = str(sandbox / "pad")
        tfio.write([[[1.0, 2.0]]], schema, out, mode="overwrite")
        ds = TFRecordDataset(out, batch_size=1, schema=schema, drop_remainder=False)
        with ds.batches() as it:
            cb = next(it)
        with pytest.raises(ValueError, match="pad_to"):
            host_batch_from_columnar(cb, ds.schema)


class TestLocalBatchSize:
    def test_rejects_non_divisible_process_count(self):
        mesh = create_mesh({"data": 2, "model": 4})
        # single process: divisible by axis and by process count (1)
        assert local_batch_size(2, mesh) == 2
        with pytest.raises(ValueError):
            local_batch_size(3, mesh)
