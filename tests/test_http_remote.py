"""Real-network remote ingestion that survives a hostile link (ISSUE 9).

Every test here reads over a REAL TCP connection: a threaded stdlib Range
server (tpu_tfrecord.httpfs.serve_directory) fronts a local dataset, and a
seeded FaultPlan fires faults at the server side of the socket — RST
mid-body, truncated bodies, 503/429 with Retry-After, stalls, trickles,
and lying Content-Range headers — while client-side ``connect`` rules
model connection-refused. The contracts pinned:

- recoverable faults heal (RetryPolicy; PrefetchReader block fetches
  resume from the exact byte offset) with rows BYTE-IDENTICAL to a local
  read — zero fallback-to-wrong-data;
- a lying server (wrong Content-Range) is a LOUD BadContentRangeError,
  never silently shifted records;
- the fault ledger is replayable (same plan + same access pattern =>
  identical ledger);
- cold remote shards stream straight into the columnar cache (the link
  is paid once per epoch), and a SIGKILLed consumer mid-populate resumes
  with the cache either valid or bypassed — never wrong;
- PrefetchReader.close() leaves no live fetch thread (ADVICE r5 #2).
"""

import os
import signal
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import fs as tfs
from tpu_tfrecord import httpfs
from tpu_tfrecord.faults import FaultPlan, FaultRule, install_chaos
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.schema import (
    LongType,
    StringType,
    StructField,
    StructType,
)

SCHEMA = StructType([
    StructField("id", LongType(), nullable=False),
    StructField("s", StringType()),
])

N_SHARDS = 3
# big enough that a 64 KiB TFR_REMOTE_BLOCK_BYTES engages PrefetchReader
# (size >= 2 * block) in the matrix's prefetch mode: ~140 KiB per shard
ROWS_PER_SHARD = 1200


def _fast_retries(n, **kw):
    return RetryPolicy(max_retries=n, sleep=lambda _s: None, **kw)


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """(server, dataset url, local dataset dir, sorted shard names)."""
    root = tmp_path_factory.mktemp("httpds")
    out = os.path.join(str(root), "ds")
    for s in range(N_SHARDS):
        tfio.write(
            [[i, f"val{i}" + "x" * (80 + i % 40)]
             for i in range(s * ROWS_PER_SHARD, (s + 1) * ROWS_PER_SHARD)],
            SCHEMA, out, mode="append" if s else "overwrite",
        )
    names = sorted(n for n in os.listdir(out) if n.startswith("part-"))
    with httpfs.serve_directory(str(root)) as srv:
        yield srv, srv.url_for("ds"), out, names
        srv.set_plan(None)


@pytest.fixture
def clean_plan(served):
    srv = served[0]
    srv.set_plan(None)
    yield srv
    srv.set_plan(None)


def read_ids(source, **kw):
    kw.setdefault("batch_size", 16)
    kw.setdefault("drop_remainder", False)
    ds = TFRecordDataset(source, schema=SCHEMA, **kw)
    got = []
    with ds.batches() as it:
        for cb in it:
            got.extend(cb["id"].values.tolist())
    return got


@pytest.fixture(scope="module")
def local_ids(served):
    _, _, out, _ = served
    ids = read_ids(out)
    assert sorted(ids) == list(range(N_SHARDS * ROWS_PER_SHARD))
    return ids


class TestHttpFS:
    def test_dispatch_and_capability(self, served):
        _, url, _, _ = served
        fsys = tfs.filesystem_for(url)
        assert isinstance(fsys, httpfs.HttpFS)
        # every open() is its own connection: concurrent block fetches OK
        assert tfs.independent_read_handles(fsys)

    def test_discovery_matches_local(self, served):
        _, url, out, names = served
        remote = tfio.discover_shards(url)
        local = tfio.discover_shards(out)
        assert [s.path.rsplit("/", 1)[-1] for s in remote] == names
        assert [s.size for s in remote] == [s.size for s in local]

    def test_info_carries_freshness_stamps(self, served):
        srv, url, out, names = served
        fsys = tfs.filesystem_for(url)
        info = fsys.info(f"{url}/{names[0]}")
        assert info["size"] == os.path.getsize(os.path.join(out, names[0]))
        assert "mtime" in info and "ETag" in info

    def test_read_only_is_loud(self, served):
        _, url, _, _ = served
        fsys = tfs.filesystem_for(url)
        with pytest.raises(OSError, match="read-only"):
            fsys.open(url + "/x", "wb")
        with pytest.raises(OSError, match="read-only"):
            fsys.rename(url + "/a", url + "/b")
        with pytest.raises(OSError, match="read-only"):
            fsys.makedirs(url + "/d")

    def test_range_reads_and_eof(self, served):
        srv, url, out, names = served
        path = os.path.join(out, names[0])
        payload = open(path, "rb").read()
        fsys = tfs.filesystem_for(url)
        with fsys.open(f"{url}/{names[0]}", "rb") as fh:
            assert fh.read(64) == payload[:64]
            fh.seek(len(payload) // 2)
            assert fh.read(128) == payload[len(payload) // 2:][:128]
            fh.seek(len(payload) + 10)
            assert fh.read(8) == b""  # past EOF: clean empty, not an error

    def test_clean_epoch_byte_identical(self, served, local_ids, clean_plan):
        _, url, _, _ = served
        assert read_ids(url) == local_ids

    def test_row_reader_over_http(self, served, clean_plan):
        _, url, _, _ = served
        table = tfio.read(url, schema=SCHEMA)
        assert sorted(table.column("id")) == list(
            range(N_SHARDS * ROWS_PER_SHARD)
        )

    def test_redirected_reads_follow_like_metadata(self, served, local_ids,
                                                   clean_plan):
        """A CDN-offload-shaped 302: discovery already follows redirects;
        the DATA read must too, or the epoch dies on a server the
        metadata layer explicitly supports."""
        srv, url, out, names = served
        red = srv.url_for(f"redirect/ds/{names[0]}")
        fsys = tfs.filesystem_for(red)
        assert fsys.size(red) == os.path.getsize(os.path.join(out, names[0]))
        payload = open(os.path.join(out, names[0]), "rb").read()
        with fsys.open(red, "rb") as fh:
            fh.seek(1000)
            assert fh.read(64) == payload[1000:1064]
        # and a whole dataset through the redirecting prefix
        assert read_ids(srv.url_for("redirect/ds")) == local_ids

    def test_small_object_reads_self_heal_below_prefetch_bar(
        self, served, clean_plan,
    ):
        """Objects below the PrefetchReader engagement bar get the SAME
        self-healing contract: a plain handle that reopens and resumes at
        the exact consumed offset (review fix — the retry policy used to
        be silently dropped for small shards)."""
        srv, url, out, names = served
        shard_url = f"{url}/{names[0]}"
        payload = open(os.path.join(out, names[0]), "rb").read()
        plan = FaultPlan([
            FaultRule(op="http", kind="truncated_body", path=names[0],
                      cap_bytes=512, times=1),
        ])
        srv.set_plan(plan)
        METRICS.reset()
        fsys = tfs.filesystem_for(shard_url)
        # default 8 MiB block: far below the bar -> RetryingReadStream
        fh = tfs.open_for_read(fsys, shard_url,
                               retry_policy=_fast_retries(2))
        assert isinstance(fh, tfs.RetryingReadStream)
        with fh:
            assert fh.read() == payload
        assert METRICS.counter("remote.fetch_retries") == 1
        # exact-offset resume: the reopened request was keyed at byte 512
        assert ("http", f"/ds/{names[0]}@512") in plan._calls, \
            sorted(plan._calls)

    def test_http_rules_reject_unexecutable_kinds(self):
        """An op='http' rule with a kind the Range server's dispatch does
        not execute would be LEDGERED as fired while the object serves
        clean — refused at construction instead."""
        for kind in ("short_read", "disconnect", "flaky_listing",
                     "rename_race"):
            with pytest.raises(ValueError, match="http"):
                FaultRule(op="http", kind=kind,
                          **({"cap_bytes": 8} if kind == "short_read" else {}))
        # the generic kinds the server DOES execute stay legal
        FaultRule(op="http", kind="stall", stall_ms=5)
        FaultRule(op="http", kind="transient_error")

    def test_autoindex_redirecting_dir_is_not_a_file(self, tmp_path):
        """A generic autoindex server 301s 'ds' -> 'ds/' and serves an
        HTML listing: isfile must say False (isdir True), or the doctor
        scans the listing page as TFRecord bytes."""
        import http.server as _hs
        import threading as _th

        class _Autoindex(_hs.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _respond(self):
                if self.path == "/ds":
                    self.send_response(301)
                    self.send_header("Location", "/ds/")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return None
                if self.path == "/ds/":
                    body = b'<html><a href="shard.tfrecord">s</a></html>'
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    return body
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return None

            def do_HEAD(self):  # noqa: N802
                self._respond()

            def do_GET(self):  # noqa: N802
                body = self._respond()
                if body:
                    self.wfile.write(body)

        httpd = _hs.ThreadingHTTPServer(("127.0.0.1", 0), _Autoindex)
        t = _th.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/ds"
            fsys = httpfs.HttpFS()
            assert not fsys.isfile(url)
            assert fsys.isdir(url)
            assert fsys.listdir(url) == ["shard.tfrecord"]
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_open_fault_during_retry_spends_budget_not_escapes(
        self, served, clean_plan,
    ):
        """A transient fault at REOPEN time (inside the self-healing
        stream's retry) must consume the same budget as a read fault,
        not abort the stream with retries left."""
        srv, url, out, names = served
        payload = open(os.path.join(out, names[0]), "rb").read()
        plan = FaultPlan([
            FaultRule(op="http", kind="truncated_body", path=names[0],
                      cap_bytes=256, times=1),
            # the RETRY's reopen (new connection) is refused once
            FaultRule(op="connect", kind="transient_error", ordinal=1,
                      times=1),
        ])
        srv.set_plan(plan)
        METRICS.reset()
        shard_url = f"{url}/{names[0]}"
        with install_chaos(plan):
            fsys = tfs.filesystem_for(shard_url)
            with tfs.open_for_read(fsys, shard_url,
                                   retry_policy=_fast_retries(3)) as fh:
                got = fh.read()
        assert got == payload
        assert METRICS.counter("remote.fetch_retries") == 2
        kinds = sorted(e["kind"] for e in plan.ledger)
        assert kinds == ["transient_error", "truncated_body"], kinds

    def test_real_connection_refused_is_prompt_oserror(self, tmp_path):
        # a dead port: the OS itself refuses — the realest fault there is
        with httpfs.serve_directory(str(tmp_path)) as srv:
            dead_url = srv.url_for("nothing.bin")
        t0 = time.monotonic()
        with pytest.raises(OSError):
            with httpfs.HttpFS().open(dead_url, "rb") as fh:
                fh.read(1)
        assert time.monotonic() - t0 < 5.0


# -- the fault-kind x read-mode matrix --------------------------------------
#
# Modes share one contract: recoverable faults + retries => rows
# byte-identical to local; the fault provably fired (ledger non-empty).

READ_MODES = {
    "strict": {},
    "salvage": {"on_corrupt": "skip_record"},
    "prefetch": {},  # PrefetchReader engaged via small block env
    "cached": {"cache": "auto"},
}


def _fault_rules(kind, names):
    """Rules for one fault kind against the first two shards."""
    if kind == "refused":
        # client-side: the first two read-time connects are refused
        return [FaultRule(op="connect", kind="transient_error", times=2)]
    if kind == "reset":
        return [FaultRule(op="http", kind="reset", path=names[0],
                          cap_bytes=64, times=1),
                FaultRule(op="http", kind="reset", path=names[1],
                          cap_bytes=256, times=1)]
    if kind == "truncated":
        return [FaultRule(op="http", kind="truncated_body", path=names[0],
                          cap_bytes=100, times=1)]
    if kind == "status_503":
        return [FaultRule(op="http", kind="http_error", path=names[0],
                          status=503, retry_after_s=0.001, times=1),
                FaultRule(op="http", kind="http_error", path=names[1],
                          status=429, retry_after_s=0.001, times=1)]
    if kind == "stall":
        # bounded server-side stall: the client rides it out (the
        # deadline/hedge legs are pinned separately below)
        return [FaultRule(op="http", kind="stall", path=names[0],
                          stall_ms=120, times=1)]
    if kind == "trickle":
        return [FaultRule(op="http", kind="trickle", path=names[0],
                          stall_ms=1, cap_bytes=512, times=1)]
    if kind == "bad_content_range":
        return [FaultRule(op="http", kind="bad_content_range", path=names[0],
                          shift_bytes=32, times=1)]
    raise AssertionError(kind)


FAULT_KINDS = [
    "refused", "reset", "truncated", "status_503", "stall", "trickle",
    "bad_content_range",
]


class TestFaultMatrix:
    @pytest.mark.parametrize("mode", sorted(READ_MODES))
    @pytest.mark.parametrize("kind", FAULT_KINDS)
    def test_fault_heals_byte_identical(
        self, served, local_ids, clean_plan, monkeypatch, tmp_path,
        kind, mode,
    ):
        srv, url, _, names = served
        plan = FaultPlan(_fault_rules(kind, names), seed=11)
        kw = dict(READ_MODES[mode])
        if mode == "prefetch":
            # engage the block pipeline: 64 KiB blocks against ~140 KiB
            # shards, 4 fetches in flight on independent connections
            monkeypatch.setenv("TFR_REMOTE_BLOCK_BYTES", str(64 << 10))
            monkeypatch.setenv("TFR_REMOTE_PREFETCH_DEPTH", "4")
        if mode == "cached":
            kw.update(cache_dir=str(tmp_path / f"cache-{kind}"))
        METRICS.reset()
        srv.set_plan(plan)
        if kind == "refused":
            # construct BEFORE chaos so discovery connects are clean; the
            # refused connects then hit the read path deterministically
            ds = TFRecordDataset(
                url, batch_size=16, schema=SCHEMA, drop_remainder=False,
                retry_policy=_fast_retries(4), **kw,
            )
            got = []
            with install_chaos(plan):
                with ds.batches() as it:
                    for cb in it:
                        got.extend(cb["id"].values.tolist())
        else:
            got = read_ids(url, retry_policy=_fast_retries(4), **kw)
        assert got == local_ids, f"{kind} x {mode}: rows differ from local"
        assert plan.ledger, f"{kind} x {mode}: fault never fired"
        if kind == "bad_content_range":
            # the lie was DETECTED (counter), not absorbed as shifted data
            assert METRICS.counter("remote.bad_range") >= 1

    def test_bad_content_range_without_retries_is_loud(
        self, served, local_ids, clean_plan,
    ):
        srv, url, _, names = served
        srv.set_plan(FaultPlan([
            FaultRule(op="http", kind="bad_content_range", path=names[0],
                      shift_bytes=32, times=None),
        ]))
        METRICS.reset()
        with pytest.raises(OSError):
            read_ids(url)
        assert METRICS.counter("remote.bad_range") >= 1

    def test_permanent_fault_exhausts_retries_loudly(
        self, served, clean_plan,
    ):
        srv, url, _, names = served
        srv.set_plan(FaultPlan([
            FaultRule(op="http", kind="http_error", path=names[0],
                      status=503, times=None),
        ]))
        with pytest.raises(OSError):
            read_ids(url, retry_policy=_fast_retries(2))

    def test_ledger_replay_deterministic(self, served, local_ids, clean_plan):
        """Same plan JSON + same access pattern => byte-identical ledger
        (sequential reads: no prefetch concurrency in this leg)."""
        srv, url, _, names = served
        spec = FaultPlan([
            FaultRule(op="http", kind="truncated_body", path=names[0],
                      cap_bytes=128, times=1),
            FaultRule(op="http", kind="http_error", path=names[1],
                      status=503, times=1),
            FaultRule(op="http", kind="stall", path=names[2],
                      stall_ms=10, times=1),
        ], seed=5).to_json()
        ledgers = []
        for _ in range(2):
            plan = FaultPlan.from_json(spec)
            srv.set_plan(plan)
            assert read_ids(url, retry_policy=_fast_retries(3)) == local_ids
            ledgers.append(plan.ledger_json())
        assert ledgers[0] == ledgers[1]
        assert ledgers[0].count("\n") == 2  # 3 events, one per shard


class TestBlockSelfHeal:
    """PrefetchReader block fetches retry + resume from the exact byte
    offset — the tentpole's self-healing contract, on a big object."""

    @pytest.fixture()
    def big(self, tmp_path):
        payload = bytes(
            np.random.default_rng(7).integers(0, 256, 1 << 20, np.uint8)
        )
        name = f"big-{uuid.uuid4().hex[:6]}.bin"
        (tmp_path / name).write_bytes(payload)
        with httpfs.serve_directory(str(tmp_path)) as srv:
            yield srv, srv.url_for(name), payload

    def _prefetch_open(self, url, policy, monkeypatch, depth=4):
        monkeypatch.setenv("TFR_REMOTE_BLOCK_BYTES", str(128 << 10))
        monkeypatch.setenv("TFR_REMOTE_PREFETCH_DEPTH", str(depth))
        fsys = tfs.filesystem_for(url)
        fh = tfs.open_for_read(fsys, url, retry_policy=policy)
        assert isinstance(fh, tfs.PrefetchReader)
        return fh

    def test_reset_mid_block_resumes_exact_offset(self, big, monkeypatch):
        srv, url, payload = big
        plan = FaultPlan([
            # RST two different blocks mid-body
            FaultRule(op="http", kind="reset", path="@131072",
                      cap_bytes=1000, times=1),
            FaultRule(op="http", kind="reset", path="@524288",
                      cap_bytes=5000, times=1),
        ], seed=3)
        srv.set_plan(plan)
        METRICS.reset()
        with self._prefetch_open(url, _fast_retries(3), monkeypatch) as fh:
            got = fh.read()
        assert got == payload
        assert METRICS.counter("remote.fetch_retries") >= 2
        assert len(plan.ledger) == 2

    def test_truncated_block_resumes(self, big, monkeypatch):
        srv, url, payload = big
        plan = FaultPlan([
            FaultRule(op="http", kind="truncated_body", path="@262144",
                      cap_bytes=4096, times=1),
        ], seed=3)
        srv.set_plan(plan)
        METRICS.reset()
        with self._prefetch_open(url, _fast_retries(2), monkeypatch) as fh:
            got = fh.read()
        assert got == payload
        assert METRICS.counter("remote.fetch_retries") == 1
        # truncation is a clean FIN: exactly cap_bytes were delivered, so
        # the retry re-ranged from the EXACT byte the body broke off at —
        # the server saw a request keyed at block_start + 4096
        assert ("http", "/" + url.rsplit("/", 1)[-1] + "@266240") in plan._calls, \
            sorted(plan._calls)

    def test_retry_after_is_honored_through_sleep_seam(self, big, monkeypatch):
        srv, url, payload = big
        slept = []
        policy = RetryPolicy(max_retries=2, sleep=slept.append)
        plan = FaultPlan([
            FaultRule(op="http", kind="http_error", path="@0",
                      status=429, retry_after_s=0.25, times=1),
        ])
        srv.set_plan(plan)
        with self._prefetch_open(url, policy, monkeypatch) as fh:
            got = fh.read()
        assert got == payload
        assert 0.25 in slept, slept  # the server's hint, not just backoff

    def test_retry_after_is_bounded_by_cap_and_deadline(self, big,
                                                        monkeypatch):
        """A hostile Retry-After (86400s) must not park the reader: the
        hint is clamped to the sanity cap AND the policy's remaining
        wall-clock deadline — pause() promises never to sleep past the
        deadline, and the hint cannot smuggle that promise away."""
        srv, url, payload = big
        slept = []
        clock = {"t": 0.0}
        policy = RetryPolicy(
            max_retries=3, deadline=5.0, jitter=False, base_delay=0.0,
            sleep=slept.append, clock=lambda: clock["t"],
        )
        plan = FaultPlan([
            FaultRule(op="http", kind="http_error", path="@0",
                      status=429, retry_after_s=86400, times=1),
        ])
        srv.set_plan(plan)
        with self._prefetch_open(url, policy, monkeypatch) as fh:
            got = fh.read()
        assert got == payload
        assert slept and max(slept) <= 5.0, slept

    def test_budget_exhausted_raises(self, big, monkeypatch):
        srv, url, _ = big
        srv.set_plan(FaultPlan([
            FaultRule(op="http", kind="reset", path="@0",
                      cap_bytes=100, times=None),
        ]))
        with self._prefetch_open(url, _fast_retries(1), monkeypatch) as fh:
            with pytest.raises(OSError):
                fh.read()

    def test_close_leaves_no_live_fetch_threads(self, big, monkeypatch):
        """ADVICE r5 #2: close() must WAIT for in-flight fetch threads —
        they hold live backend handles that race tempdir cleanup."""
        srv, url, payload = big
        with self._prefetch_open(url, None, monkeypatch) as fh:
            assert fh.read(1024) == payload[:1024]
        # bounded-wait close has returned: no fetch worker may survive it
        alive = [t.name for t in threading.enumerate()
                 if t.name.startswith("tfr-prefetch") and t.is_alive()]
        assert alive == [], alive

    def test_close_waits_for_inflight_fetch(self, big, monkeypatch):
        """A fetch actually in flight at close() time completes (or is
        joined) before close returns — not abandoned holding a handle."""
        srv, url, payload = big
        srv.set_latency(0.05)  # every request answers late: fetches in flight
        try:
            fh = self._prefetch_open(url, None, monkeypatch)
            assert fh.read(1) == payload[:1]
            fh.close()  # blocks (bounded) on the in-flight block fetches
            alive = [t.name for t in threading.enumerate()
                     if t.name.startswith("tfr-prefetch") and t.is_alive()]
            assert alive == [], alive
        finally:
            srv.set_latency(0.0)


class TestStallGuardOverRealSockets:
    """The existing deadline/hedge machinery reading through real
    connections: a server that goes quiet mid-body is detected and
    survived on a live socket, not a wrapped file object."""

    def test_read_deadline_converts_server_stall(
        self, served, local_ids, clean_plan,
    ):
        srv, url, _, names = served
        plan = FaultPlan([
            FaultRule(op="http", kind="stall", path=names[0],
                      stall_ms=60_000, times=1),
        ])
        srv.set_plan(plan)
        METRICS.reset()
        try:
            got = read_ids(
                url, read_deadline_ms=200, retry_policy=_fast_retries(2),
            )
        finally:
            plan.release()
        assert got == local_ids
        assert METRICS.counter("read.deadline_misses") >= 1

    def test_hedge_wins_against_stalled_connection(
        self, served, local_ids, clean_plan,
    ):
        srv, url, _, names = served
        plan = FaultPlan([
            FaultRule(op="http", kind="stall", path=names[0] + "@0",
                      stall_ms=60_000, times=1),
        ])
        srv.set_plan(plan)
        METRICS.reset()
        try:
            got = read_ids(url, hedge_after_ms=150)
        finally:
            plan.release()
        assert got == local_ids
        assert METRICS.counter("read.hedges") >= 1
        assert METRICS.counter("read.hedge_wins") >= 1


class TestRemoteIntoCache:
    """remote -> CachePopulator -> mmap: the link is paid once per epoch."""

    def test_link_paid_once_per_epoch(self, served, local_ids, clean_plan,
                                      tmp_path):
        srv, url, _, _ = served
        cdir = str(tmp_path / "cache")
        METRICS.reset()
        ep1 = read_ids(url, cache="auto", cache_dir=cdir)
        assert ep1 == local_ids
        gets_after_populate = srv.file_get_count
        ep2 = read_ids(url, cache="auto", cache_dir=cdir)
        assert ep2 == local_ids
        assert METRICS.counter("cache.hits") >= N_SHARDS
        # epoch 2 issued ZERO file GETs: served from the local mmap cache
        # (dir-index GETs and HEADs are metadata, not the link being
        # re-paid for shard bytes)
        assert srv.file_get_count == gets_after_populate

    def test_faulted_populate_still_commits_valid_entries(
        self, served, local_ids, clean_plan, tmp_path,
    ):
        """A transient link fault DURING the populating epoch heals via
        retries and the committed entries still serve byte-identical
        rows."""
        srv, url, _, names = served
        cdir = str(tmp_path / "cache")
        plan = FaultPlan([
            FaultRule(op="http", kind="reset", path=names[1],
                      cap_bytes=64, times=1),
        ])
        srv.set_plan(plan)
        METRICS.reset()
        ep1 = read_ids(url, cache="auto", cache_dir=cdir,
                       retry_policy=_fast_retries(3))
        assert ep1 == local_ids and plan.ledger
        srv.set_plan(None)
        ep2 = read_ids(url, cache="auto", cache_dir=cdir)
        assert ep2 == local_ids
        assert METRICS.counter("cache.hits") >= N_SHARDS

    def test_kill9_mid_populate_then_resume_never_wrong(
        self, tmp_path,
    ):
        """Chaos acceptance: SIGKILL the consumer process mid-populate,
        then read again from the same cache dir — rows byte-identical,
        cache either valid or bypassed+repopulated, never wrong."""
        root = tmp_path / "killds"
        out = os.path.join(str(root), "ds")
        n = 3000
        for s in range(3):
            tfio.write(
                [[i, f"v{i}"] for i in range(s * n, (s + 1) * n)],
                SCHEMA, out, mode="append" if s else "overwrite",
            )
        local = read_ids(out, batch_size=256)
        cdir = str(tmp_path / "cache")
        with httpfs.serve_directory(str(root)) as srv:
            url = srv.url_for("ds")
            proc = subprocess.Popen(
                [sys.executable,
                 os.path.join(os.path.dirname(__file__),
                              "http_cache_worker.py"),
                 url, cdir, "--batch-size", "256"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            line = proc.stdout.readline()  # first batch: populate underway
            assert line.startswith("batch"), (line, proc.stderr.read())
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()
            # resume in-process against the SAME cache dir: whatever state
            # the kill left (partial staging, committed entries, nothing)
            # must yield ground-truth rows
            METRICS.reset()
            got = read_ids(url, batch_size=256, cache="auto", cache_dir=cdir)
            assert got == local, "post-kill rows differ from ground truth"
            # and a further epoch serves cache hits with identical rows
            METRICS.reset()
            again = read_ids(url, batch_size=256, cache="auto",
                             cache_dir=cdir)
            assert again == local
            assert METRICS.counter("cache.hits") >= 3


class TestChaosAcceptance:
    def test_mixed_hostile_epoch_byte_identical_and_replayable(
        self, served, local_ids, clean_plan,
    ):
        """THE acceptance leg: one epoch under a seeded plan mixing
        resets, stalls, truncations, and 503s completes byte-identical to
        local with zero corrupt rows, and the ledger is replayable."""
        srv, url, _, names = served
        spec = FaultPlan([
            FaultRule(op="http", kind="reset", path=names[0],
                      cap_bytes=200, times=1),
            FaultRule(op="http", kind="stall", path=names[0],
                      stall_ms=50, times=1),
            FaultRule(op="http", kind="truncated_body", path=names[1],
                      cap_bytes=150, times=1),
            FaultRule(op="http", kind="http_error", path=names[2],
                      status=503, retry_after_s=0.001, times=1),
            FaultRule(op="http", kind="http_error", path=names[2],
                      status=429, retry_after_s=0.001, ordinal=1, times=1),
        ], seed=42).to_json()
        ledgers = []
        for _ in range(2):
            plan = FaultPlan.from_json(spec)
            srv.set_plan(plan)
            METRICS.reset()
            got = read_ids(url, retry_policy=_fast_retries(4))
            assert got == local_ids, "hostile epoch rows differ from local"
            assert METRICS.counter("read.corrupt_records") == 0
            ledgers.append(plan.ledger_json())
        assert ledgers[0] == ledgers[1], "ledger not replayable"
        import json as _json

        fired = sorted(
            _json.loads(line)["kind"] for line in ledgers[0].splitlines()
        )
        assert fired == sorted([
            "reset", "stall", "truncated_body", "http_error", "http_error",
        ]), fired


class TestDoctorOverHttp:
    def test_doctor_scan_accepts_http_sources(self, served, clean_plan):
        _, url, _, names = served
        doc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "tfrecord_doctor.py"),
             f"{url}/{names[0]}"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
        import json as _json

        lines = [_json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
        summary = [l for l in lines if l.get("event") == "summary"][0]
        assert summary["records"] == ROWS_PER_SHARD
        assert summary["corrupt_events"] == 0

    def test_doctor_scan_http_dataset_dir(self, served, clean_plan):
        _, url, _, _ = served
        doc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "tfrecord_doctor.py"), url],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert doc.returncode == 0, (doc.returncode, doc.stdout, doc.stderr)
        import json as _json

        lines = [_json.loads(l) for l in doc.stdout.splitlines() if l.strip()]
        summaries = [l for l in lines if l.get("event") == "summary"]
        assert len(summaries) == N_SHARDS
        assert sum(s["records"] for s in summaries) == N_SHARDS * ROWS_PER_SHARD
