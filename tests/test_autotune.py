"""Closed-loop autotuning (ISSUE 6): the flight recorder drives the knobs.

Four layers:

- controller units: hysteresis, cooldown, per-knob clamps, threshold
  derivation from observed p99s, readahead retargeting — all with an
  injected clock and synthetic pulse payloads (no pipeline).
- live pool machinery: the resizable prefetch queue, worker-pool
  accounting, and mid-epoch grow/shrink with byte-identical output and
  checkpoint/resume interchangeability (the guarantees a resize must
  preserve).
- stall-guard integration: controller-updated thresholds are picked up by
  live guarded streams.
- the throttled-decode chaos acceptance test: with every read stalled by
  injected sleeps, ``autotune="on"`` starting from deliberately-wrong
  knobs recovers >= 90% of the hand-tuned fixed-knob throughput, with row
  output byte-identical to the fixed-knob run.
"""

import os
import time

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import telemetry
from tpu_tfrecord.autotune import (
    AutotuneController,
    AutotunePolicy,
    PipelineControl,
)
from tpu_tfrecord.io.dataset import TFRecordDataset, _ResizableQueue
from tpu_tfrecord.metrics import Metrics
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType
from tpu_tfrecord.stall import StallGuard


SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),
    ]
)


def write_dataset(base, n_shards=6, rows_per_shard=40) -> str:
    out = os.path.join(str(base), "ds")
    for s in range(n_shards):
        rows = [
            [i, f"row-{i}"]
            for i in range(s * rows_per_shard, (s + 1) * rows_per_shard)
        ]
        tfio.write(rows, SCHEMA, out, mode="append" if s else "overwrite")
    return out


def read_all(ds) -> list:
    with ds.batches() as it:
        return [r for b in it for r in b["id"].values.tolist()]


# ---------------------------------------------------------------------------
# Resizable prefetch queue
# ---------------------------------------------------------------------------


class TestResizableQueue:
    def test_grow_wakes_blocked_putter(self):
        import threading

        q = _ResizableQueue(maxsize=1)
        q.put(1)
        done = threading.Event()

        def putter():
            q.put(2)  # blocks until resize
            done.set()

        t = threading.Thread(target=putter, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not done.is_set()
        q.resize(2)
        assert done.wait(1.0)
        assert q.get() == 1 and q.get() == 2

    def test_shrink_blocks_new_puts_until_drained(self):
        q = _ResizableQueue(maxsize=4)
        for i in range(3):
            q.put(i)
        q.resize(1)
        with pytest.raises(Exception):
            q.put(99, timeout=0.05)
        # existing items are never dropped
        assert [q.get() for _ in range(3)] == [0, 1, 2]
        q.put(99, timeout=0.5)

    def test_resize_floor_is_one(self):
        q = _ResizableQueue(maxsize=4)
        q.resize(0)
        assert q.maxsize == 1


# ---------------------------------------------------------------------------
# PipelineControl accounting
# ---------------------------------------------------------------------------


class TestPipelineControl:
    def test_set_workers_clamps_and_spawns(self):
        spawned = []
        c = PipelineControl(workers=2, max_workers=4)
        c.bind_spawn(lambda: spawned.append(1))
        assert len(spawned) == 2  # brought up to initial target
        assert c.set_workers(99) == 4  # clamped to the ceiling
        assert len(spawned) == 4
        assert c.set_workers(0) == 1  # clamped to the floor
        # shrink spawns nothing; surplus workers retire via should_exit
        assert len(spawned) == 4

    def test_exit_permits_match_surplus_exactly(self):
        c = PipelineControl(workers=4, max_workers=8)
        c.bind_spawn(lambda: None)
        c.set_workers(2)
        # exactly alive - target workers get an exit permit
        permits = [c.should_exit() for _ in range(4)]
        assert permits.count(True) == 2
        for p in permits:
            if p:
                c.note_exit(permitted=True)
        # books balanced: no further exits allowed at target
        assert not c.should_exit()

    def test_grow_after_shrink_respawns(self):
        spawned = []
        c = PipelineControl(workers=3, max_workers=8)
        c.bind_spawn(lambda: spawned.append(1))
        c.set_workers(1)
        assert c.should_exit() and c.should_exit()
        c.note_exit(permitted=True)
        c.note_exit(permitted=True)
        before = len(spawned)
        c.set_workers(3)
        assert len(spawned) - before == 2

    def test_prefetch_and_readahead_without_queue_or_dataset(self):
        c = PipelineControl(workers=1)
        assert c.prefetch is None
        assert c.set_prefetch(5) == 5 and c.prefetch == 5
        assert c.set_readahead_bytes(8 << 20) == 8 << 20
        assert c.readahead_bytes == 8 << 20


# ---------------------------------------------------------------------------
# Controller units (injected clock, synthetic payloads)
# ---------------------------------------------------------------------------


def payload(verdict="unknown", stages=None, quantiles=None, gauges=None):
    return {
        "event": "pulse",
        "verdict": verdict,
        "stages": stages or {},
        "quantiles": quantiles or {},
        "gauges": gauges or {},
        "counters": {},
    }


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_controller(workers=1, policy=None, guard=None, queue=None, **ctrl_kw):
    clock = FakeClock()
    control = PipelineControl(workers=workers, max_workers=8, queue=queue,
                              guard=guard)
    ctl = AutotuneController(
        control,
        interval_s=1.0,
        policy=policy or AutotunePolicy(hysteresis=2, cooldown_s=2.0),
        metrics=Metrics(),
        clock=clock,
        **ctrl_kw,
    )
    return ctl, control, clock


class TestControllerPool:
    def test_hysteresis_requires_consecutive_verdicts(self):
        ctl, control, clock = make_controller()
        ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 1  # one tick is not a trend
        clock.t += 10
        ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 2  # second consecutive tick moves

    def test_balanced_resets_streak(self):
        ctl, control, clock = make_controller()
        ctl.on_pulse(payload("producer_bound"))
        ctl.on_pulse(payload("balanced"))
        clock.t += 10
        ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 1  # streak restarted

    def test_whipsaw_verdicts_never_move_the_pool(self):
        ctl, control, clock = make_controller()
        for i in range(10):
            clock.t += 10  # cooldown is never the limiter here
            ctl.on_pulse(
                payload("producer_bound" if i % 2 else "consumer_bound")
            )
        assert control.workers == 1
        assert ctl.log == []

    def test_cooldown_limits_move_rate(self):
        ctl, control, clock = make_controller(
            policy=AutotunePolicy(hysteresis=1, cooldown_s=5.0)
        )
        ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 2
        clock.t += 1.0  # inside the cooldown window
        ctl.on_pulse(payload("producer_bound"))
        ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 2
        clock.t += 10.0
        ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 3

    def test_consumer_bound_shrinks_to_floor_only(self):
        ctl, control, clock = make_controller(
            workers=2, policy=AutotunePolicy(hysteresis=1, cooldown_s=0.0,
                                             min_workers=1)
        )
        for _ in range(5):
            clock.t += 1
            ctl.on_pulse(payload("consumer_bound"))
        assert control.workers == 1  # clamped at min_workers, never 0

    def test_grow_clamps_at_max_workers(self):
        ctl, control, clock = make_controller(
            policy=AutotunePolicy(hysteresis=1, cooldown_s=0.0, max_workers=3)
        )
        for _ in range(8):
            clock.t += 1
            ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 3

    def test_prefetch_tracks_pool(self):
        q = _ResizableQueue(maxsize=1)
        ctl, control, clock = make_controller(
            policy=AutotunePolicy(hysteresis=1, cooldown_s=0.0), queue=q
        )
        clock.t += 1
        ctl.on_pulse(payload("producer_bound"))
        assert control.workers == 2
        assert q.maxsize == 4  # workers + 2

    def test_decisions_logged_and_counted(self):
        ctl, control, clock = make_controller(
            policy=AutotunePolicy(hysteresis=1, cooldown_s=0.0)
        )
        clock.t += 1
        out = ctl.on_pulse(payload("producer_bound"))
        assert out["autotune"]["workers"] == 2
        assert out["autotune"]["adjusted"][0]["knob"] == "workers"
        assert ctl.log[0]["reason"] == "producer_bound"
        assert ctl.metrics.counter("autotune.adjustments") >= 1
        assert ctl.metrics.gauge_value("autotune.workers") == 2.0


class TestControllerThresholds:
    def q(self, stage, p99_ms, count=100):
        return {stage: {"p50_ms": p99_ms / 2, "p90_ms": p99_ms,
                        "p99_ms": p99_ms, "count": count}}

    def test_hedge_derived_from_read_p99(self):
        guard = StallGuard()
        ctl, control, clock = make_controller(guard=guard)
        ctl.on_pulse(payload(quantiles=self.q("read.io", 50.0)))
        assert guard.hedge_after == pytest.approx(0.2)  # 4 x 50ms

    def test_hedge_floor_clamp(self):
        guard = StallGuard()
        ctl, control, clock = make_controller(guard=guard)
        ctl.on_pulse(payload(quantiles=self.q("read.io", 1.0)))
        assert guard.hedge_after == pytest.approx(0.1)  # min_hedge_ms

    def test_deadlines_adapted_but_never_introduced(self):
        guard = StallGuard()  # user configured NO deadlines
        ctl, control, clock = make_controller(guard=guard)
        ctl.on_pulse(
            payload(quantiles={**self.q("read.io", 500.0),
                               **self.q("read.open", 500.0)})
        )
        assert guard.read_deadline is None
        assert guard.open_deadline is None
        guard2 = StallGuard(read_deadline=1.0, open_deadline=1.0)
        ctl2, _, _ = make_controller(guard=guard2)
        ctl2.on_pulse(
            payload(quantiles={**self.q("read.io", 500.0),
                               **self.q("read.open", 400.0)})
        )
        assert guard2.read_deadline == pytest.approx(10.0)  # 20 x 500ms
        assert guard2.open_deadline == pytest.approx(8.0)

    def test_threshold_band_suppresses_twitch(self):
        guard = StallGuard(hedge_after=0.2)
        ctl, control, clock = make_controller(guard=guard)
        # derived 4 x 55ms = 220ms: within 25% of the current 200ms
        ctl.on_pulse(payload(quantiles=self.q("read.io", 55.0)))
        assert guard.hedge_after == pytest.approx(0.2)
        assert ctl.log == []

    def test_min_latency_samples_gate(self):
        guard = StallGuard()
        ctl, control, clock = make_controller(guard=guard)
        ctl.on_pulse(payload(quantiles=self.q("read.io", 50.0, count=3)))
        assert guard.hedge_after is None  # too few observations to trust

    def test_deadline_ceiling_clamp(self):
        guard = StallGuard(read_deadline=1.0)
        ctl, control, clock = make_controller(guard=guard)
        ctl.on_pulse(payload(quantiles=self.q("read.io", 60_000.0)))
        assert guard.read_deadline == pytest.approx(120.0)  # max_deadline_ms


class TestControllerReadahead:
    def test_retarget_to_bandwidth_horizon(self):
        ctl, control, clock = make_controller()
        control.set_readahead_bytes(64 << 20)
        # 100 MB/s observed -> 0.5s horizon -> 50 MB: within the 50% band
        ctl.on_pulse(payload(stages={"read.io": {"bytes_per_sec": 100e6}}))
        assert control.readahead_bytes == 64 << 20
        # 400 MB/s -> ~191 MiB: beyond the band, retargets
        ctl.on_pulse(payload(stages={"read.io": {"bytes_per_sec": 400e6}}))
        assert control.readahead_bytes == int(round(400e6 * 0.5 / (1 << 20))) << 20

    def test_clamped_to_policy_range(self):
        ctl, control, clock = make_controller()
        control.set_readahead_bytes(64 << 20)
        ctl.on_pulse(payload(stages={"read.io": {"bytes_per_sec": 10e9}}))
        assert control.readahead_bytes == 256 << 20  # max_readahead_mb
        ctl.on_pulse(payload(stages={"read.io": {"bytes_per_sec": 1e6}}))
        assert control.readahead_bytes == 8 << 20  # min_readahead_mb

    def test_disabled_readahead_stays_disabled(self):
        ctl, control, clock = make_controller()
        control.set_readahead_bytes(0)
        ctl.on_pulse(payload(stages={"read.io": {"bytes_per_sec": 400e6}}))
        assert control.readahead_bytes == 0


# ---------------------------------------------------------------------------
# Pulse observer plumbing
# ---------------------------------------------------------------------------


class TestPulseObserver:
    def test_observer_fields_merged_into_emitted_line(self):
        from tpu_tfrecord.telemetry import Pulse

        lines = []
        pulse = Pulse(60.0, metrics=Metrics(), emit=lines.append)
        pulse.add_observer(lambda p: {"autotune": {"workers": 3}})
        pulse.tick()
        assert lines[0]["autotune"] == {"workers": 3}

    def test_observer_exception_never_breaks_the_tick(self):
        from tpu_tfrecord.telemetry import Pulse

        lines = []
        pulse = Pulse(60.0, metrics=Metrics(), emit=lines.append)

        def bad(_p):
            raise RuntimeError("observer bug")

        pulse.add_observer(bad)
        pulse.tick()
        assert lines and lines[0]["event"] == "pulse"


# ---------------------------------------------------------------------------
# Live pool resize: determinism + checkpoint/resume
# ---------------------------------------------------------------------------


class TestLivePoolResize:
    def test_rows_identical_across_mid_epoch_resizes(self, tmp_path):
        out = write_dataset(tmp_path)
        baseline = read_all(
            TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                            drop_remainder=False)
        )
        ds = TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                             drop_remainder=False, autotune="on",
                             autotune_interval_s=300.0)
        got = []
        it = ds.batches()
        with it:
            for i, b in enumerate(it):
                if i == 1:
                    it._control.set_workers(4)
                    it._control.set_prefetch(8)
                if i == 10:
                    it._control.set_workers(1)
                    it._control.set_prefetch(2)
                got.extend(b["id"].values.tolist())
        assert got == baseline

    def test_checkpoint_resume_across_resize(self, tmp_path):
        out = write_dataset(tmp_path)
        baseline = read_all(
            TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                            drop_remainder=False)
        )
        ds = TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                             drop_remainder=False, autotune="on",
                             autotune_interval_s=300.0)
        it = ds.batches()
        head = []
        for i, b in enumerate(it):
            if i == 2:
                it._control.set_workers(3)  # resize BEFORE the checkpoint
            head.extend(b["id"].values.tolist())
            if i == 5:
                break
        state = it.state()
        it.close()
        # resume into a DIFFERENT starting worker count, autotune still on
        ds2 = TFRecordDataset(out, batch_size=7, schema=SCHEMA,
                              drop_remainder=False, num_workers=2,
                              autotune="on", autotune_interval_s=300.0)
        tail = []
        it2 = ds2.batches(state)
        with it2:
            for i, b in enumerate(it2):
                if i == 1:
                    it2._control.set_workers(4)  # and resize mid-resume too
                tail.extend(b["id"].values.tolist())
        assert head + tail == baseline

    def test_single_worker_autotune_path_matches_sequential(self, tmp_path):
        out = write_dataset(tmp_path, n_shards=3)
        baseline = read_all(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA,
                            drop_remainder=False)
        )
        got = read_all(
            TFRecordDataset(out, batch_size=5, schema=SCHEMA,
                            drop_remainder=False, autotune="on",
                            autotune_interval_s=300.0)
        )
        assert got == baseline

    def test_iterator_exposes_controller_only_when_on(self, tmp_path):
        out = write_dataset(tmp_path, n_shards=2)
        ds = TFRecordDataset(out, batch_size=5, schema=SCHEMA)
        with ds.batches() as it:
            assert it.autotune is None and it._control is None
        ds2 = TFRecordDataset(out, batch_size=5, schema=SCHEMA,
                              autotune="on", autotune_interval_s=300.0)
        with ds2.batches() as it2:
            assert it2.autotune is not None
            assert it2._control.guard is ds2._stall_guard
            assert ds2._stall_guard is not None  # created for autotune


# ---------------------------------------------------------------------------
# Stall-guard live thresholds
# ---------------------------------------------------------------------------


class TestLiveThresholds:
    def test_guarded_stream_reads_thresholds_through_guard(self, tmp_path):
        import io

        from tpu_tfrecord.stall import GuardedReadStream

        guard = StallGuard(read_deadline=60.0)
        stream = GuardedReadStream(
            io.BytesIO(b"x" * 1024), "mem", read_deadline=60.0,
            hedge_after=None, reopen=lambda pos: io.BytesIO(b"x" * 1024),
            guard=guard,
        )
        assert stream._deadline == 60.0
        guard.update_thresholds(read_deadline_ms=125.0, hedge_after_ms=250.0)
        assert stream._deadline == pytest.approx(0.125)
        assert stream._hedge_after == pytest.approx(0.25)
        stream.close()

    def test_update_thresholds_units_and_partial(self):
        guard = StallGuard(read_deadline=1.0)
        guard.update_thresholds(hedge_after_ms=500.0)
        assert guard.read_deadline == 1.0  # untouched
        assert guard.hedge_after == pytest.approx(0.5)
        guard.update_thresholds(read_deadline_ms=2000.0,
                                open_deadline_ms=3000.0)
        assert guard.read_deadline == pytest.approx(2.0)
        assert guard.open_deadline == pytest.approx(3.0)


# ---------------------------------------------------------------------------
# Options plumbing
# ---------------------------------------------------------------------------


class TestOptionsPlumbing:
    def test_parse_and_defaults(self):
        opts = TFRecordOptions.from_map()
        assert opts.autotune == "off" and opts.autotune_interval_s is None
        opts = TFRecordOptions.from_map(
            autotune="on", autotune_interval_s="0.5"
        )
        assert opts.autotune == "on"
        assert opts.autotune_interval_s == 0.5

    def test_validation(self):
        with pytest.raises(ValueError, match="autotune must be"):
            TFRecordOptions.from_map(autotune="sometimes")
        with pytest.raises(ValueError, match="autotune_interval_s"):
            TFRecordOptions.from_map(autotune_interval_s=0)

    def test_unknown_key_suggestion(self):
        with pytest.raises(ValueError, match="autotune"):
            TFRecordOptions.from_map(autotunee="on")


# ---------------------------------------------------------------------------
# Doctor `tune` subcommand
# ---------------------------------------------------------------------------


class TestDoctorTune:
    def test_tune_emits_knobs_and_exits_zero(self, tmp_path):
        import json
        import subprocess
        import sys

        out = write_dataset(tmp_path, n_shards=3)
        doctor = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "tfrecord_doctor.py",
        )
        res = subprocess.run(
            [sys.executable, doctor, "tune", out, "--seconds", "0.6",
             "--interval", "0.1", "--batch-size", "16"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert res.returncode == 0, (res.returncode, res.stdout, res.stderr)
        lines = [json.loads(l) for l in res.stdout.splitlines() if l.strip()]
        final = [l for l in lines if l.get("event") == "tune"]
        assert final and "knobs" in final[0]
        assert final[0]["knobs"]["workers"] >= 1
        assert final[0]["rows"] > 0

    def test_tune_unreadable_dataset_exits_two(self, tmp_path):
        import subprocess
        import sys

        doctor = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "tfrecord_doctor.py",
        )
        res = subprocess.run(
            [sys.executable, doctor, "tune", str(tmp_path / "nope")],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert res.returncode == 2


# ---------------------------------------------------------------------------
# The acceptance test: throttled decode, controller recovers throughput
# ---------------------------------------------------------------------------


class TestThrottledDecodeChaos:
    """Every shard read pays an injected 30ms sleep (GIL released, like a
    real slow store), so throughput scales with decode-pool parallelism.

    Two tiers (ISSUE 13 satellite — the wall-clock throughput ratio was a
    pre-existing flake on the shared 2-vCPU box, where a loaded co-tenant
    can slow EITHER leg arbitrarily and no fixed ratio holds):

    - tier 1 (``test_autotune_grows_and_stays_deterministic``): every
      assertion is counter-based and deterministic — the controller must
      GROW the pool under throttle (its own decision log proves it) and
      rows must be byte-identical to the hand-tuned run. No wall-clock
      bar, so no interference flake.
    - ``slow`` (``test_autotune_recovers_hand_tuned_throughput``): the
      original >= 90%-of-hand-tuned throughput ratio, kept as the
      convergence-quality bar for runs that opt into perf assertions.
    """

    def _run(self, out, epochs, **ds_kw):
        from tpu_tfrecord.faults import FaultPlan, FaultRule, install_chaos

        plan = FaultPlan(
            [FaultRule(op="read", kind="stall", path="part-", times=None,
                       stall_ms=30.0)],
            seed=7,
        )
        ds = TFRecordDataset(
            out, batch_size=20, schema=SCHEMA, drop_remainder=False,
            num_epochs=epochs, use_mmap=False, **ds_kw,
        )
        rows = []
        epoch_times = []
        with install_chaos(plan):
            t0 = time.perf_counter()
            rows_seen = 0
            with ds.batches() as it:
                tuner = it.autotune
                for b in it:
                    rows.extend(b["id"].values.tolist())
                    rows_seen += b.num_rows
                    if rows_seen >= 240:  # one epoch of 6 shards x 40 rows
                        epoch_times.append(time.perf_counter() - t0)
                        t0 = time.perf_counter()
                        rows_seen = 0
        plan.release()
        return rows, epoch_times, tuner

    def test_autotune_grows_and_stays_deterministic(self, tmp_path):
        """Tier-1 half: deterministic counter-based assertions only."""
        out = write_dataset(tmp_path, n_shards=6, rows_per_shard=40)
        fixed_rows, _, _ = self._run(out, 4, num_workers=4, prefetch=4)
        tuned_rows, _, tuner = self._run(
            out, 4, num_workers=1, prefetch=1,
            autotune="on", autotune_interval_s=0.1,
        )
        # determinism across every pool/queue resize the controller made
        assert tuned_rows == fixed_rows
        # the controller actually adjusted knobs (bounded number of pulses)
        grows = [d for d in tuner.log if d["knob"] == "workers"]
        assert grows and grows[0]["to"] > grows[0]["from"], tuner.log
        assert tuner.control.workers > 1

    @pytest.mark.slow
    @pytest.mark.perf
    def test_autotune_recovers_hand_tuned_throughput(self, tmp_path):
        out = write_dataset(tmp_path, n_shards=6, rows_per_shard=40)
        fixed_rows, fixed_times, _ = self._run(
            out, 16, num_workers=4, prefetch=4
        )
        tuned_rows, tuned_times, tuner = self._run(
            out, 16, num_workers=1, prefetch=1,
            autotune="on", autotune_interval_s=0.1,
        )
        assert tuned_rows == fixed_rows
        # converged throughput: compare best epoch over the tail halves
        # (the head pays the deliberate mis-configuration + the climb).
        # Best-of, not mean-of: interference on this shared box is
        # one-sided — other tenants only slow an epoch down — so the min
        # epoch time is the noise-robust estimator (the same argument the
        # bench and perf-floor tests document), and the injected stalls
        # dominate each epoch's floor, which is exactly what the worker
        # pool parallelizes.
        tail = max(2, len(tuned_times) // 2)
        tuned_rate = 1.0 / min(tuned_times[-tail:])
        fixed_rate = 1.0 / min(fixed_times[-tail:])
        assert tuned_rate >= 0.9 * fixed_rate, (
            f"autotuned best-epoch throughput {tuned_rate:.2f} epochs/s is "
            f"below 90% of hand-tuned {fixed_rate:.2f} epochs/s "
            f"(trajectory: {tuner.log})"
        )
