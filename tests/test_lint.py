"""graftlint: per-rule unit tests on synthetic violating/clean snippet
twins, pragma + baseline mechanics, vocabulary drift both directions,
lock-graph cycle detection, the HLO manifest (coverage + a deliberately
gathered toy entrypoint), the tree-is-clean gate the acceptance criteria
pin, and the ``tfrecord_doctor lint`` subcommand round trip.

The synthetic-file tests exercise rules by writing small modules into a
tmp dir and running the shared harness over them — file NAMES matter for
the scoped rules (clock discipline applies to ``service.py``, not
``other.py``), which is exactly how the tests pin the scoping.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.graftlint import (  # noqa: E402
    DEFAULT_BASELINE,
    REPO_ROOT,
    run_lint,
)
from tools.graftlint.harness import (  # noqa: E402
    RepoContext,
    apply_baseline,
    lint_paths,
    load_baseline,
)
from tools.graftlint.rules import default_rules  # noqa: E402
from tpu_tfrecord import vocabulary  # noqa: E402

DOCTOR = os.path.join(REPO, "tools", "tfrecord_doctor.py")


def lint_snippets(tmp_path, files, rules=None, readme=None):
    """Write ``{name: source}`` into tmp_path and lint it. The README
    check is pointed at the real repo README unless a test overrides it —
    synthetic dirs should not trip vocab-docs by accident."""
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    repo = RepoContext(
        str(tmp_path), readme=readme or os.path.join(REPO, "README.md")
    )
    findings, errors = lint_paths(
        [str(tmp_path)], rules or default_rules(), str(tmp_path), repo=repo
    )
    assert not errors, errors
    return findings


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# clock-discipline
# ---------------------------------------------------------------------------


class TestClockDiscipline:
    def test_bare_sleep_in_policy_module_flagged(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "service.py": """
                import time
                def wait_a_bit():
                    time.sleep(0.2)
            """,
        })
        (f,) = by_rule(fs, "clock-discipline")
        assert "time.sleep" in f.message and f.line == 4

    def test_injected_seam_twin_clean(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "elastic.py": """
                import time
                class Scaler:
                    def __init__(self, clock=time.monotonic, sleep=time.sleep):
                        self.clock = clock
                        self.sleep = sleep
                    def step(self):
                        now = self.clock()
                        self.sleep(0.1)
                        return now
            """,
        })
        assert not by_rule(fs, "clock-discipline")

    def test_non_policy_module_out_of_scope(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "other.py": "import time\ntime.sleep(1)\n",
        })
        assert not by_rule(fs, "clock-discipline")

    def test_all_three_calls_flagged(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "retry.py": """
                import time
                def f():
                    return time.time(), time.monotonic()
            """,
        })
        assert len(by_rule(fs, "clock-discipline")) == 2


# ---------------------------------------------------------------------------
# atomic-write
# ---------------------------------------------------------------------------


class TestAtomicWrite:
    def test_bare_write_open_flagged(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def save(path, data):
                    with open(path, "w") as fh:
                        fh.write(data)
            """,
        })
        (f,) = by_rule(fs, "atomic-write")
        assert "atomic_write_bytes" in f.hint

    def test_stage_then_replace_twin_clean(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                import os
                def save(path, data):
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as fh:
                        fh.write(data)
                    os.replace(tmp, path)
            """,
        })
        assert not by_rule(fs, "atomic-write")

    def test_read_mode_ignored(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": 'def load(p):\n    return open(p).read() + open(p, "rb").read().decode()\n',
        })
        assert not by_rule(fs, "atomic-write")

    def test_truncating_plus_modes_flagged(self, tmp_path):
        # "w+" tears the destination exactly like "w"; "r+" never truncates
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def save(path, data):
                    with open(path, "w+") as fh:
                        fh.write(data)
                def patch(path, data):
                    with open(path, "r+") as fh:
                        fh.write(data)
            """,
        })
        flagged = by_rule(fs, "atomic-write")
        assert len(flagged) == 1 and "'w+'" in flagged[0].message

    def test_str_replace_does_not_exempt(self, tmp_path):
        # only os.replace / an fs object's rename is a staging rename —
        # string manipulation on an unrelated variable must not exempt
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def save(path, data):
                    name = path.replace(".json", ".txt")
                    with open(path, "w") as fh:
                        fh.write(data)
            """,
        })
        assert len(by_rule(fs, "atomic-write")) == 1

    def test_fs_object_rename_still_exempts(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def save(fs, path, data):
                    stage = path + ".part"
                    with open(stage, "wb") as fh:
                        fh.write(data)
                    fs.rename(stage, path)
            """,
        })
        assert not by_rule(fs, "atomic-write")

    def test_manifest_last_idiom_clean(self, tmp_path):
        # the sharded-generation idiom (ISSUE 16): staged shard writes are
        # compliant when the SAME function commits a manifest afterwards
        # through one of the shared durable-write helpers
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                from tpu_tfrecord.checkpoint import durable_write
                def commit_generation(gen, shards, manifest):
                    for name, data in shards.items():
                        with open(gen + "/" + name, "wb") as fh:
                            fh.write(data)
                    durable_write(gen + "/MANIFEST.json", manifest)
            """,
        })
        assert not by_rule(fs, "atomic-write")

    def test_manifest_first_writer_still_flagged(self, tmp_path):
        # a manifest committed BEFORE the shard bytes covers nothing: a
        # crash mid-shard leaves a manifest naming torn files
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                from tpu_tfrecord.checkpoint import durable_write
                def commit_generation(gen, shards, manifest):
                    durable_write(gen + "/MANIFEST.json", manifest)
                    for name, data in shards.items():
                        with open(gen + "/" + name, "wb") as fh:
                            fh.write(data)
            """,
        })
        assert len(by_rule(fs, "atomic-write")) == 1

    def test_helper_method_call_also_commits(self, tmp_path):
        # atomic_write_bytes reached as telemetry.atomic_write_bytes (an
        # Attribute call) counts the same as the bare-name helper
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                from tpu_tfrecord import telemetry
                def commit(gen, data, manifest):
                    with open(gen + "/shard-0", "wb") as fh:
                        fh.write(data)
                    telemetry.atomic_write_bytes(gen + "/MANIFEST.json", manifest)
            """,
        })
        assert not by_rule(fs, "atomic-write")

    def test_allow_pragma_suppresses_with_reason(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def mark(path):
                    open(path, "wb").close()  # graftlint: allow(atomic-write: zero-byte marker)
            """,
        })
        assert not by_rule(fs, "atomic-write")

    def test_reasonless_allow_pragma_still_fails(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def mark(path):
                    open(path, "wb").close()  # graftlint: allow(atomic-write:)
            """,
        })
        (f,) = by_rule(fs, "atomic-write")
        assert "no reason" in f.message


# ---------------------------------------------------------------------------
# lock-guard
# ---------------------------------------------------------------------------

_LOCKED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []      # init writes are pre-publication
        def put(self, x):
            with self._lock:
                self._items.append(x)
        def _drain_locked(self):
            self._items.clear()   # *_locked convention: caller holds it
        def size(self):
            with self._lock:
                return len(self._items)
"""


class TestLockGuard:
    def test_unlocked_mutation_of_guarded_attr_flagged(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": _LOCKED_CLASS + """
        def reset(self):
            self._items = []      # guarded attr, no lock: the race
            """,
        })
        (f,) = by_rule(fs, "lock-guard")
        assert "Box._items" in f.message and "reset" in f.message

    def test_all_locked_twin_clean(self, tmp_path):
        fs = lint_snippets(tmp_path, {"mod.py": _LOCKED_CLASS})
        assert not by_rule(fs, "lock-guard")

    def test_class_without_lock_contract_out_of_scope(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                class Free:
                    def __init__(self):
                        self.items = []
                    def put(self, x):
                        self.items.append(x)
            """,
        })
        assert not by_rule(fs, "lock-guard")


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

_INVERSION = """
    import threading
    a_lock = threading.Lock()
    b_lock = threading.Lock()

    def forward():
        with a_lock:
            with b_lock:
                pass

    def backward():
        with b_lock:
            with a_lock:
                pass
"""


class TestLockOrder:
    def test_constructed_inversion_is_a_cycle(self, tmp_path):
        fs = lint_snippets(tmp_path, {"mod.py": _INVERSION})
        (f,) = by_rule(fs, "lock-order")
        assert "cycle" in f.message
        assert "mod.a_lock" in f.message and "mod.b_lock" in f.message

    def test_consistent_order_clean(self, tmp_path):
        consistent = _INVERSION.replace(
            "with b_lock:\n            with a_lock:",
            "with a_lock:\n            with b_lock:",
        )
        fs = lint_snippets(tmp_path, {"mod.py": consistent})
        assert not by_rule(fs, "lock-order")

    def test_self_lock_nesting_is_a_self_deadlock(self, tmp_path):
        """`with self._lock: with self._lock:` is the same instance by
        construction — a guaranteed deadlock on a non-reentrant Lock,
        reported as a self-loop cycle."""
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                import threading
                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()
                    def oops(self):
                        with self._lock:
                            with self._lock:
                                pass
            """,
        })
        (f,) = by_rule(fs, "lock-order")
        assert "mod.Box._lock" in f.message

    def test_multi_item_with_contributes_edges(self, tmp_path):
        """`with a_lock, b_lock:` acquires in item order — an inverted
        nested acquisition elsewhere must still register as a cycle."""
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                import threading
                a_lock = threading.Lock()
                b_lock = threading.Lock()
                def forward():
                    with a_lock, b_lock:
                        pass
                def backward():
                    with b_lock:
                        with a_lock:
                            pass
            """,
        })
        (f,) = by_rule(fs, "lock-order")
        assert "cycle" in f.message

    def test_cross_module_cycle_detected(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "m1.py": """
                import threading
                a_lock = threading.Lock()
                b_lock = threading.Lock()
                def f():
                    with a_lock:
                        with b_lock:
                            pass
            """,
            "m2.py": """
                from m1 import a_lock, b_lock
                def g():
                    with b_lock:
                        with a_lock:
                            pass
            """,
        })
        # conservative identity is module-scoped names, so the inversion
        # must be constructed within matching ids to register — here each
        # module contributes one edge under ITS name; no false cycle
        assert not by_rule(fs, "lock-order")

    def test_real_tree_lock_graph_is_acyclic(self):
        result = run_lint(baseline=None)
        assert not [
            f for f in result["findings"] if f.rule == "lock-order"
        ]


# ---------------------------------------------------------------------------
# except-swallow
# ---------------------------------------------------------------------------


class TestExceptSwallow:
    def test_silent_swallow_flagged(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def f():
                    try:
                        risky()
                    except Exception:
                        pass
            """,
        })
        (f,) = by_rule(fs, "except-swallow")
        assert "swallow" in f.hint

    @pytest.mark.parametrize("body,label", [
        ("raise", "reraise"),
        ("METRICS.count('mod.errors')", "counter"),
    ])
    def test_compliant_twins_clean(self, tmp_path, body, label):
        fs = lint_snippets(tmp_path, {
            "mod.py": f"""
                def f():
                    try:
                        risky()
                    except Exception:
                        {body}
            """,
        })
        assert not by_rule(fs, "except-swallow"), label

    def test_swallow_pragma_with_reason_clean(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def f():
                    try:
                        risky()
                    except Exception:  # graftlint: swallow(teardown path; nothing to report to)
                        pass
            """,
        })
        assert not by_rule(fs, "except-swallow")

    def test_reasonless_swallow_pragma_flagged(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def f():
                    try:
                        risky()
                    except Exception:  # graftlint: swallow()
                        pass
            """,
        })
        (f,) = by_rule(fs, "except-swallow")
        assert "no reason" in f.message

    def test_bare_except_and_base_exception_in_scope(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def f():
                    try:
                        risky()
                    except BaseException:
                        pass
                def g():
                    try:
                        risky()
                    except:
                        pass
            """,
        })
        assert len(by_rule(fs, "except-swallow")) == 2

    def test_list_count_is_not_a_counter_bump(self, tmp_path):
        # the receiver must look like a metrics registry — list.count /
        # str.count in the handler must not satisfy the audit
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def f(xs, x):
                    try:
                        risky()
                    except Exception:
                        n = xs.count(x)
            """,
        })
        assert len(by_rule(fs, "except-swallow")) == 1

    def test_raise_in_nested_def_does_not_comply(self, tmp_path):
        # a raise inside a closure defined in the handler never fires on
        # the except path
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def f():
                    try:
                        risky()
                    except Exception:
                        def later():
                            raise RuntimeError("not on this path")
            """,
        })
        assert len(by_rule(fs, "except-swallow")) == 1

    def test_narrow_except_out_of_scope(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                def f():
                    try:
                        risky()
                    except (OSError, ValueError):
                        pass
            """,
        })
        assert not by_rule(fs, "except-swallow")


# ---------------------------------------------------------------------------
# vocabulary: call sites and docs, drift in BOTH directions
# ---------------------------------------------------------------------------


class TestVocabulary:
    def test_unregistered_counter_name_flagged(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                from tpu_tfrecord.metrics import METRICS
                METRICS.count("bogus.name")
            """,
        })
        (f,) = by_rule(fs, "vocab-unregistered")
        assert "bogus.name" in f.message

    def test_registered_names_clean_and_set_add_not_confused(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                from tpu_tfrecord.metrics import METRICS
                METRICS.count("cache.hits")
                METRICS.gauge("prefetch.occupancy", 0.5)
                seen = set()
                seen.add("not a metric name")   # receiver is not a registry
            """,
        })
        assert not by_rule(fs, "vocab-unregistered")

    def test_dynamic_fstring_prefix_checked(self, tmp_path):
        fs = lint_snippets(tmp_path, {
            "mod.py": """
                from tpu_tfrecord.metrics import METRICS
                def f(knob, v):
                    METRICS.gauge(f"autotune.{knob}", v)    # registered prefix
                    METRICS.gauge(f"mystery.{knob}", v)     # not registered
            """,
        })
        (f,) = by_rule(fs, "vocab-unregistered")
        assert "mystery." in f.message

    def test_derived_errors_suffix_is_registered(self):
        assert vocabulary.is_registered("decode.errors", "counter")
        assert not vocabulary.is_registered("nonexistent.errors", "counter")

    def test_kind_matters(self):
        assert vocabulary.is_registered("cache.hits", "counter")
        assert not vocabulary.is_registered("cache.hits", "gauge")

    def test_readme_block_matches_registry(self):
        # docs-drift direction 1: the committed README block is current
        result = run_lint(baseline=None)
        assert not [f for f in result["findings"] if f.rule == "vocab-docs"]

    def test_stale_readme_block_flagged(self, tmp_path):
        # docs-drift direction 2: remove one registered name from the
        # block and the rule names the drifted entry
        readme = tmp_path / "README.md"
        block = vocabulary.vocabulary_markdown()
        assert "| `cache.hits` |" in block
        stale = "\n".join(
            ln for ln in block.splitlines() if "`cache.hits`" not in ln
        )
        readme.write_text("# doc\n\n" + stale + "\n")
        fs = lint_snippets(
            tmp_path, {"mod.py": "x = 1\n"}, readme=str(readme)
        )
        (f,) = by_rule(fs, "vocab-docs")
        assert "stale" in f.message and "cache.hits" in f.message

    def test_missing_readme_block_flagged(self, tmp_path):
        readme = tmp_path / "README.md"
        readme.write_text("# no block here\n")
        fs = lint_snippets(
            tmp_path, {"mod.py": "x = 1\n"}, readme=str(readme)
        )
        (f,) = by_rule(fs, "vocab-docs")
        assert "no generated vocabulary block" in f.message


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------

_VIOLATION = """
    def f():
        try:
            risky()
        except Exception:
            pass
"""


class TestBaseline:
    def _findings(self, tmp_path):
        return lint_snippets(tmp_path, {"mod.py": _VIOLATION})

    def test_new_finding_fails(self, tmp_path):
        fs = self._findings(tmp_path)
        base = tmp_path / "baseline.txt"
        base.write_text("# empty baseline: nothing grandfathered\n")
        new, stale = apply_baseline(fs, load_baseline(str(base)))
        assert new and not stale

    def test_baselined_finding_passes(self, tmp_path):
        fs = self._findings(tmp_path)
        base = tmp_path / "baseline.txt"
        base.write_text(
            "# justified: synthetic grandfather\n"
            + "\n".join(f.key for f in fs) + "\n"
        )
        new, stale = apply_baseline(fs, load_baseline(str(base)))
        assert not new and not stale

    def test_stale_baseline_entry_warns(self, tmp_path):
        fs = self._findings(tmp_path)
        base = tmp_path / "baseline.txt"
        base.write_text(
            "# one real, one stale\n"
            + "\n".join(f.key for f in fs)
            + "\nexcept-swallow\tgone.py\texcept@f#0\n"
        )
        new, stale = apply_baseline(fs, load_baseline(str(base)))
        assert not new
        assert stale == ["except-swallow\tgone.py\texcept@f#0"]

    def test_baseline_key_stable_under_line_drift(self, tmp_path):
        fs1 = lint_snippets(tmp_path, {"mod.py": _VIOLATION})
        shifted = "# a new leading comment\n\n\n" + textwrap.dedent(_VIOLATION)
        (tmp_path / "mod.py").write_text(shifted)
        repo = RepoContext(
            str(tmp_path), readme=os.path.join(REPO, "README.md")
        )
        fs2, _ = lint_paths(
            [str(tmp_path)], default_rules(), str(tmp_path), repo=repo
        )
        k1 = [f.key for f in fs1 if f.rule == "except-swallow"]
        k2 = [f.key for f in fs2 if f.rule == "except-swallow"]
        assert k1 == k2
        assert [f.line for f in fs1 if f.rule == "except-swallow"] != [
            f.line for f in fs2 if f.rule == "except-swallow"
        ]


# ---------------------------------------------------------------------------
# the tree itself: the acceptance pins
# ---------------------------------------------------------------------------


class TestTreeIsClean:
    """`python -m tools.graftlint` exits 0 on the tree; deleting any single
    baseline line or reverting any one of this PR's violation fixes makes
    it exit 1 — the acceptance criteria, demonstrated in-process."""

    def test_tree_clean_against_committed_baseline(self):
        result = run_lint()
        assert result["findings"] == [], [
            f.format() for f in result["findings"]
        ]
        assert result["errors"] == []
        assert result["stale_baseline"] == []
        # the baseline absorbs exactly the justified grandfathers
        assert result["baselined"] == len(
            [
                k for k in load_baseline(DEFAULT_BASELINE).elements()
            ]
        )

    def test_deleting_any_single_baseline_line_fails(self, tmp_path):
        entries = list(load_baseline(DEFAULT_BASELINE).elements())
        assert entries, "committed baseline unexpectedly empty"
        for i in range(len(entries)):
            kept = entries[:i] + entries[i + 1 :]
            b = tmp_path / f"baseline_{i}.txt"
            b.write_text("\n".join(kept) + "\n")
            result = run_lint(baseline=str(b))
            assert len(result["findings"]) == 1, (
                i, [f.format() for f in result["findings"]],
            )
            assert result["findings"][0].key == entries[i]

    def test_reverting_the_service_clock_fix_fails(self, tmp_path):
        src = open(os.path.join(REPO, "tpu_tfrecord", "service.py")).read()
        assert "stop_event.wait(0.2)" in src  # the PR's fix
        reverted = src.replace(
            "while not stop_event.wait(0.2):\n            pass",
            "while not stop_event.is_set():\n            time.sleep(0.2)",
        )
        assert reverted != src
        (tmp_path / "service.py").write_text(reverted)
        fs = lint_snippets(tmp_path, {})  # files already written
        assert by_rule(fs, "clock-discipline")

    def test_removing_a_swallow_pragma_fails(self, tmp_path):
        src = open(os.path.join(REPO, "tpu_tfrecord", "elastic.py")).read()
        assert "# graftlint: swallow(" in src
        import re

        reverted = re.sub(r"\s*# graftlint: swallow\([^\n]*\)", "", src, count=1)
        (tmp_path / "elastic.py").write_text(reverted)
        fs = lint_snippets(tmp_path, {})
        assert by_rule(fs, "except-swallow")


# ---------------------------------------------------------------------------
# HLO contract manifest
# ---------------------------------------------------------------------------


class TestHloManifest:
    def test_manifest_covers_the_required_entrypoints(self):
        from tools.graftlint import hlo_contracts as hc

        # acceptance: >= 4 jitted entrypoints, reproducing every
        # historical collective pin exactly
        assert len(hc.CONTRACTS) >= 4
        want = {
            "pipeline_feed_ring": (
                ("collective-permute",),
                ("all-gather", "all-reduce", "all-to-all"),
            ),
            "pipeline_feed_ring_dp": (("collective-permute",), ("all-gather",)),
            "pipeline_diagnostics": (("collective-permute",), ("all-gather",)),
            "moe_apply_ep": (("all-to-all",), ("all-gather",)),
            "moe_apply_ep_diagnostics": (("all-to-all",), ("all-gather",)),
            "lm_train_step": (("collective-permute",), ("all-gather",)),
        }
        for name, (contains, absent) in want.items():
            c = hc.get(name)
            assert tuple(c.contains) == contains, name
            assert tuple(c.absent) == absent, name
        # diagnostics on AND off variants both present
        assert any(c.diagnostics for c in hc.CONTRACTS.values())
        assert any(not c.diagnostics for c in hc.CONTRACTS.values())

    def test_unknown_contract_is_loud(self):
        from tools.graftlint import hlo_contracts as hc

        with pytest.raises(KeyError, match="unknown HLO contract"):
            hc.get("nope")

    def test_manifest_catches_a_deliberately_gathered_toy(self):
        """The driver must FAIL a function that all-gathers: jit an
        identity whose output is replicated from a sharded input — the
        partitioner has to materialize an all-gather."""
        import dataclasses

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tools.graftlint import hlo_contracts as hc
        from tpu_tfrecord.tpu import create_mesh

        def toy_builder():
            mesh = create_mesh({"x": 4}, jax.devices()[:4])
            x = jax.device_put(
                jnp.zeros((8, 8), jnp.float32),
                NamedSharding(mesh, P("x", None)),
            )
            fn = jax.jit(
                lambda x: x * 2.0,
                out_shardings=NamedSharding(mesh, P()),
            )
            return fn, (x,)

        toy = hc.HloContract(
            name="gathered_toy",
            entrypoint="<toy>",
            contains=(),
            absent=("all-gather",),
            builder=toy_builder,
        )
        with pytest.raises(AssertionError, match="forbidden 'all-gather'"):
            hc.verify(toy)
        # and the same toy under a permissive contract passes: the failure
        # above is the contract, not the harness
        ok = dataclasses.replace(toy, absent=(), contains=("all-gather",))
        hc.verify(ok)


# ---------------------------------------------------------------------------
# CLI + doctor subcommand
# ---------------------------------------------------------------------------


def _write_violating_dir(tmp_path):
    d = tmp_path / "proj"
    d.mkdir()
    (d / "mod.py").write_text(textwrap.dedent(_VIOLATION))
    return d


class TestCli:
    def test_module_cli_clean_tree_exit_0(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 0, (out.stdout, out.stderr)
        summary = json.loads(out.stdout.strip().splitlines()[-1])
        assert summary["findings"] == 0 and summary["errors"] == 0

    def test_module_cli_findings_exit_1(self, tmp_path):
        d = _write_violating_dir(tmp_path)
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", str(d)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 1, (out.stdout, out.stderr)
        assert "except-swallow" in out.stdout

    def test_module_cli_unreadable_exit_2(self, tmp_path):
        out = subprocess.run(
            [
                sys.executable, "-m", "tools.graftlint",
                str(tmp_path / "does_not_exist"),
            ],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 2, (out.stdout, out.stderr)

    def test_syntax_error_is_exit_2_not_crash(self, tmp_path):
        d = tmp_path / "proj"
        d.mkdir()
        (d / "bad.py").write_text("def broken(:\n")
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", str(d)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 2, (out.stdout, out.stderr)
        assert "bad.py" in out.stdout

    def test_write_baseline_keeps_already_baselined_keys(self, tmp_path):
        """--write-baseline must see EVERY finding: filtering through the
        existing baseline first would rewrite the file with only the NEW
        keys, so the very next plain run fails on the dropped ones."""
        d = tmp_path / "proj"
        d.mkdir()
        (d / "mod.py").write_text(textwrap.dedent(_VIOLATION))
        base = tmp_path / "base.txt"

        def graft(*extra):
            return subprocess.run(
                [
                    sys.executable, "-m", "tools.graftlint", str(d),
                    "--baseline", str(base), *extra,
                ],
                capture_output=True, text=True, cwd=REPO,
            )

        assert graft("--write-baseline").returncode == 0
        assert graft().returncode == 0  # first key grandfathered
        (d / "mod2.py").write_text(textwrap.dedent(_VIOLATION))
        assert graft().returncode == 1  # second violation is NEW
        assert graft("--write-baseline").returncode == 0
        keys = [
            l for l in base.read_text().splitlines()
            if l.strip() and not l.startswith("#")
        ]
        assert len(keys) == 2, keys  # both keys kept, none dropped
        assert graft().returncode == 0

    def test_vocab_md_matches_registry(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--vocab-md"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 0
        assert out.stdout.strip() == vocabulary.vocabulary_markdown().strip()


class TestDoctorLint:
    def test_clean_tree_exit_0(self):
        out = subprocess.run(
            [sys.executable, DOCTOR, "lint"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 0, (out.stdout, out.stderr)
        lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
        assert lines[-1]["event"] == "lint"
        assert lines[-1]["findings"] == 0

    def test_findings_exit_1_with_finding_events(self, tmp_path):
        d = _write_violating_dir(tmp_path)
        out = subprocess.run(
            [sys.executable, DOCTOR, "lint", str(d)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 1, (out.stdout, out.stderr)
        lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
        kinds = [l["event"] for l in lines]
        assert "finding" in kinds and kinds[-1] == "lint"

    def test_unreadable_exit_2(self, tmp_path):
        out = subprocess.run(
            [sys.executable, DOCTOR, "lint", str(tmp_path / "nope")],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 2, (out.stdout, out.stderr)

    @pytest.mark.parametrize("scenario", ["clean", "findings"])
    def test_json_round_trips_text(self, tmp_path, scenario):
        """--json emits ONE document whose events mirror the text lines
        exactly (same objects, same order, same exit code) — the
        _Emitter contract fleet/train/serve-status already pin."""
        args = [sys.executable, DOCTOR, "lint"]
        if scenario == "findings":
            args.append(str(_write_violating_dir(tmp_path)))
        text = subprocess.run(
            args, capture_output=True, text=True, cwd=REPO
        )
        doc = subprocess.run(
            args + ["--json"], capture_output=True, text=True, cwd=REPO
        )
        assert text.returncode == doc.returncode
        text_events = [
            json.loads(l) for l in text.stdout.splitlines() if l.strip()
        ]
        doc_events = json.loads(doc.stdout)["events"]
        assert doc_events == text_events


# ---------------------------------------------------------------------------
# vocabulary registry internals
# ---------------------------------------------------------------------------


class TestVocabularyRegistry:
    def test_every_registered_name_in_markdown(self):
        md = vocabulary.vocabulary_markdown()
        for name in vocabulary.registered_names():
            assert f"`{name}`" in md, name

    def test_kinds_cover_the_flagship_names(self):
        assert "train.steps" in vocabulary.COUNTERS
        assert "decode" in vocabulary.STAGES
        assert "prefetch.occupancy" in vocabulary.GAUGES
        assert "autotune.adjust" in vocabulary.SPANS

    def test_dynamic_prefixes_cover_autotune_and_train(self):
        assert vocabulary.is_registered("autotune.workers", "gauge")
        assert vocabulary.is_registered("train.share.compute", "gauge")
        assert vocabulary.is_registered("train.data_wait", "stage")
