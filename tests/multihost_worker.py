"""Worker process for multi-host tests: spawned N times by test_multihost.py.

Each process initializes jax.distributed against a shared coordinator,
reads ITS shard assignment of a common dataset, runs the distributed schema
merge, assembles a global sharded batch, and prints JSON results for the
parent to compare.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coord = sys.argv[1]
    num_procs = int(sys.argv[2])
    pid = int(sys.argv[3])
    data_dir = sys.argv[4]

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_tfrecord.tpu import distributed

    distributed.initialize(coord, num_procs, pid)
    assert jax.process_count() == num_procs, jax.process_count()

    # --- cross-process trace propagation: every host adopts process 0's
    # trace id over the allgather, so spans/pulses/spools from all hosts
    # correlate and merged Perfetto timelines share one trace ---
    trace_ctx = distributed.adopt_shared_trace_context(role="mh_worker")

    import numpy as np

    from tpu_tfrecord import wire
    from tpu_tfrecord.infer import infer_from_records
    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.io.paths import discover_shards
    from tpu_tfrecord.options import RecordType
    from tpu_tfrecord.tpu.mesh import assign_shards, create_mesh

    # --- distributed schema inference: per-host seqOp + allgather combOp,
    # through the public entry (native seqOp + 2-worker thread pool), and
    # the oracle fold cross-checked against it ---
    import tpu_tfrecord.io as tfio

    schema = tfio.reader(data_dir).infer_schema_multihost(num_workers=2)
    distributed.assert_same_across_hosts(schema.json().encode(), "schema")
    shards = discover_shards(data_dir)
    mine = assign_shards(shards)
    local_map = {}
    from tpu_tfrecord.infer import merge_type_maps

    for sh in mine:
        partial = infer_from_records(
            wire.read_records(sh.path), RecordType.EXAMPLE
        )
        local_map = merge_type_maps(local_map, partial)
    oracle_schema = distributed.merge_schema_across_hosts(local_map)
    assert oracle_schema == schema, (oracle_schema, schema)

    # --- global batch assembly across processes ---
    mesh = create_mesh()  # all global devices on 'data'
    ds = TFRecordDataset(
        data_dir,
        batch_size=8,  # per-host rows
        schema=schema,
        process_index=pid,
        process_count=num_procs,
    )
    with ds.batches() as it:
        cb = next(it)
    from tpu_tfrecord.tpu import host_batch_from_columnar, make_global_batch

    hb = host_batch_from_columnar(cb, ds.schema)
    gb = make_global_batch(hb, mesh)
    uid = gb["uid"]
    global_sum = float(jax.jit(lambda x: x.sum())(uid))

    # --- fingerprint-guarded mid-stream resume on this host's assignment ---
    # First batch -> state() (stamped with the dataset fingerprint) -> a NEW
    # dataset resumes from it; first + rest must equal a straight full read.
    ds_a = TFRecordDataset(
        data_dir, batch_size=4, schema=schema, drop_remainder=False,
        process_index=pid, process_count=num_procs,
    )
    with ds_a.batches() as it:
        first = next(it)["uid"].values.tolist()
        state = it.state()
    ds_b = TFRecordDataset(
        data_dir, batch_size=4, schema=schema, drop_remainder=False,
        process_index=pid, process_count=num_procs,
    )
    rest = []
    with ds_b.batches(state) as it:
        for cb in it:
            rest.extend(cb["uid"].values.tolist())
    full = []
    ds_c = TFRecordDataset(
        data_dir, batch_size=4, schema=schema, drop_remainder=False,
        process_index=pid, process_count=num_procs,
    )
    with ds_c.batches() as it:
        for cb in it:
            full.extend(cb["uid"].values.tolist())
    resume_ok = (first + rest == full) and state.fingerprint is not None

    # --- windowed row shuffle under multi-process sharding: each host
    # shuffles ITS assignment; mid-window resume is exact and coverage
    # matches the unshuffled stream ---
    def shuffled_ds():
        return TFRecordDataset(
            data_dir, batch_size=4, schema=schema, drop_remainder=False,
            process_index=pid, process_count=num_procs,
            shuffle_window=2, seed=13,
        )

    with shuffled_ds().batches() as it:
        s_first = next(it)["uid"].values.tolist()
        s_state = it.state()
    s_rest = []
    with shuffled_ds().batches(s_state) as it:
        for cb in it:
            s_rest.extend(cb["uid"].values.tolist())
    s_full = []
    with shuffled_ds().batches() as it:
        for cb in it:
            s_full.extend(cb["uid"].values.tolist())
    shuffle_ok = (
        s_first + s_rest == s_full
        and sorted(s_full) == sorted(full)
        and s_full != full  # rows actually moved
    )

    # --- coordinated multi-host write: per-host shards, one _SUCCESS ---
    from tpu_tfrecord.io.writer import DatasetWriter
    from tpu_tfrecord.options import TFRecordOptions

    out_dir = os.path.join(os.path.dirname(data_dir), "mh_out")
    os.makedirs(out_dir, exist_ok=True)
    local_rows = [[int(v) + 1000 * pid] for v in range(4)]
    from tpu_tfrecord.schema import LongType, StructField, StructType

    w_schema = StructType([StructField("uid", LongType())])
    writer = DatasetWriter(
        out_dir, w_schema, TFRecordOptions(), mode="append", write_success=False
    )
    writer.write_rows(local_rows, task_id=pid)
    marker_before = os.path.exists(os.path.join(out_dir, "_SUCCESS"))
    distributed.finalize_distributed_write(out_dir)
    # the double barrier guarantees the marker exists once the call returns
    marker_after = os.path.exists(os.path.join(out_dir, "_SUCCESS"))

    # --- coordinated partitionBy write: col=value dirs from every host ---
    part_dir = os.path.join(os.path.dirname(data_dir), "mh_part")
    os.makedirs(part_dir, exist_ok=True)
    p_schema = StructType(
        [StructField("uid", LongType()), StructField("par", LongType())]
    )
    p_writer = DatasetWriter(
        part_dir, p_schema, TFRecordOptions(), mode="append",
        partition_by=["par"], write_success=False,
    )
    p_writer.write_rows(
        [[1000 * pid + v, v % 2] for v in range(4)], task_id=pid
    )
    distributed.finalize_distributed_write(part_dir)

    print(
        json.dumps(
            {
                "pid": pid,
                "schema": schema.json(),
                "n_shards": len(mine),
                "global_shape": list(uid.shape),
                "global_sum": global_sum,
                "local_rows": int(hb["uid"].shape[0]),
                "marker_before": marker_before,
                "marker_after": marker_after,
                "resume_ok": resume_ok,
                "shuffle_ok": shuffle_ok,
                "host_rows_total": len(full),
                "trace_id": trace_ctx.trace_id,
                "trace_parent": trace_ctx.parent_span_id,
            }
        )
    )


if __name__ == "__main__":
    main()
