"""Worker process for multi-host tests: spawned N times by test_multihost.py.

Each process initializes jax.distributed against a shared coordinator,
reads ITS shard assignment of a common dataset, runs the distributed schema
merge, assembles a global sharded batch, and prints JSON results for the
parent to compare.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coord = sys.argv[1]
    num_procs = int(sys.argv[2])
    pid = int(sys.argv[3])
    data_dir = sys.argv[4]

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_tfrecord.tpu import distributed

    distributed.initialize(coord, num_procs, pid)
    assert jax.process_count() == num_procs, jax.process_count()

    import numpy as np

    from tpu_tfrecord import wire
    from tpu_tfrecord.infer import infer_from_records
    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.io.paths import discover_shards
    from tpu_tfrecord.options import RecordType
    from tpu_tfrecord.tpu.mesh import assign_shards, create_mesh

    # --- distributed schema inference: per-host seqOp + allgather combOp ---
    shards = discover_shards(data_dir)
    mine = assign_shards(shards)
    local_map = {}
    from tpu_tfrecord.infer import merge_type_maps

    for sh in mine:
        partial = infer_from_records(
            wire.read_records(sh.path), RecordType.EXAMPLE
        )
        local_map = merge_type_maps(local_map, partial)
    schema = distributed.merge_schema_across_hosts(local_map)
    distributed.assert_same_across_hosts(schema.json().encode(), "schema")

    # --- global batch assembly across processes ---
    mesh = create_mesh()  # all global devices on 'data'
    ds = TFRecordDataset(
        data_dir,
        batch_size=8,  # per-host rows
        schema=schema,
        process_index=pid,
        process_count=num_procs,
    )
    with ds.batches() as it:
        cb = next(it)
    from tpu_tfrecord.tpu import host_batch_from_columnar, make_global_batch

    hb = host_batch_from_columnar(cb, ds.schema)
    gb = make_global_batch(hb, mesh)
    uid = gb["uid"]
    global_sum = float(jax.jit(lambda x: x.sum())(uid))

    # --- coordinated multi-host write: per-host shards, one _SUCCESS ---
    from tpu_tfrecord.io.writer import DatasetWriter
    from tpu_tfrecord.options import TFRecordOptions

    out_dir = os.path.join(os.path.dirname(data_dir), "mh_out")
    os.makedirs(out_dir, exist_ok=True)
    local_rows = [[int(v) + 1000 * pid] for v in range(4)]
    from tpu_tfrecord.schema import LongType, StructField, StructType

    w_schema = StructType([StructField("uid", LongType())])
    writer = DatasetWriter(
        out_dir, w_schema, TFRecordOptions(), mode="append", write_success=False
    )
    writer.write_rows(local_rows, task_id=pid)
    marker_before = os.path.exists(os.path.join(out_dir, "_SUCCESS"))
    distributed.finalize_distributed_write(out_dir)
    # the double barrier guarantees the marker exists once the call returns
    marker_after = os.path.exists(os.path.join(out_dir, "_SUCCESS"))

    print(
        json.dumps(
            {
                "pid": pid,
                "schema": schema.json(),
                "n_shards": len(mine),
                "global_shape": list(uid.shape),
                "global_sum": global_sum,
                "local_rows": int(hb["uid"].shape[0]),
                "marker_before": marker_before,
                "marker_after": marker_after,
            }
        )
    )


if __name__ == "__main__":
    main()
