"""Tier-1 tests for the hand-rolled Example/SequenceExample protobuf codec.

Includes a cross-check against the official protobuf runtime (compiling
tensorflow's example.proto/feature.proto with protoc at test time) so our
wire bytes are provably interoperable with TensorFlow readers.
"""

import importlib.util
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from tpu_tfrecord import proto
from tpu_tfrecord.proto import (
    BYTES_LIST,
    FLOAT_LIST,
    INT64_LIST,
    Example,
    Feature,
    FeatureList,
    SequenceExample,
)


def make_example():
    return Example(
        features={
            "long": Feature.int64_list([7]),
            "longs": Feature.int64_list([-2, 20, 2**62, -(2**62)]),
            "float": Feature.float_list([2.5]),
            "floats": Feature.float_list([1.5, -3.25, 1e30]),
            "bytes": Feature.bytes_list([b"r1"]),
            "strs": Feature.bytes_list(["héllo".encode("utf-8"), b"", b"\x00\xff"]),
            "empty_int": Feature(INT64_LIST, []),
            "empty_float": Feature(FLOAT_LIST, []),
            "empty_bytes": Feature(BYTES_LIST, []),
        }
    )


def make_sequence_example():
    return SequenceExample(
        context={"id": Feature.int64_list([42]), "name": Feature.bytes_list([b"seq"])},
        feature_lists={
            "frames": FeatureList(
                [Feature.float_list([1.0, 2.0]), Feature.float_list([3.0])]
            ),
            "tokens": FeatureList([Feature.bytes_list([b"a", b"b"])]),
            "empty": FeatureList([]),
        },
    )


class TestRoundTrip:
    def test_example_round_trip(self):
        ex = make_example()
        parsed = proto.parse_example(proto.encode_example(ex))
        assert set(parsed.features) == set(ex.features)
        for name, feat in ex.features.items():
            got = parsed.features[name]
            assert got.kind == feat.kind, name
            if feat.kind == FLOAT_LIST:
                np.testing.assert_allclose(got.values, np.float32(feat.values))
            else:
                assert list(got.values) == list(feat.values), name

    def test_sequence_example_round_trip(self):
        se = make_sequence_example()
        parsed = proto.parse_sequence_example(proto.encode_sequence_example(se))
        assert set(parsed.context) == {"id", "name"}
        assert parsed.context["id"].values == [42]
        assert set(parsed.feature_lists) == {"frames", "tokens", "empty"}
        frames = parsed.feature_lists["frames"].feature
        assert [list(np.float32(f.values)) for f in frames] == [[1.0, 2.0], [3.0]]
        assert parsed.feature_lists["tokens"].feature[0].values == [b"a", b"b"]
        assert parsed.feature_lists["empty"].feature == []

    def test_empty_example(self):
        parsed = proto.parse_example(proto.encode_example(Example()))
        assert parsed.features == {}

    def test_deterministic_encoding(self):
        e1 = Example(features={"b": Feature.int64_list([1]), "a": Feature.int64_list([2])})
        e2 = Example(features={"a": Feature.int64_list([2]), "b": Feature.int64_list([1])})
        assert proto.encode_example(e1) == proto.encode_example(e2)

    def test_negative_int64_ten_bytes(self):
        ex = Example(features={"v": Feature.int64_list([-1])})
        parsed = proto.parse_example(proto.encode_example(ex))
        assert parsed.features["v"].values == [-1]

    def test_unpacked_varints_accepted(self):
        # Hand-build an Int64List with UNPACKED encoding (proto2-style);
        # readers must accept both packed and unpacked.
        int64_list = bytes([0x08, 0x05, 0x08, 0x07])  # field 1 varint 5, varint 7
        feature = bytes([0x1A, len(int64_list)]) + int64_list  # field 3 LEN
        entry = bytes([0x0A, 1, ord("v"), 0x12, len(feature)]) + feature
        features = bytes([0x0A, len(entry)]) + entry
        example = bytes([0x0A, len(features)]) + features
        parsed = proto.parse_example(example)
        assert parsed.features["v"].values == [5, 7]

    def test_unpacked_floats_accepted(self):
        f = struct.pack("<f", 1.5)
        float_list = bytes([0x0D]) + f  # field 1 wire type I32
        feature = bytes([0x12, len(float_list)]) + float_list  # field 2 LEN
        entry = bytes([0x0A, 1, ord("f"), 0x12, len(feature)]) + feature
        features = bytes([0x0A, len(entry)]) + entry
        example = bytes([0x0A, len(features)]) + features
        parsed = proto.parse_example(example)
        assert parsed.features["f"].values == [1.5]

    def test_truncated_raises(self):
        data = proto.encode_example(make_example())
        with pytest.raises(proto.ProtoDecodeError):
            proto.parse_example(data[:-3])

    def test_kind_names(self):
        assert Feature.int64_list([1]).kind_name == "int64_list"
        assert Feature.float_list([1.0]).kind_name == "float_list"
        assert Feature.bytes_list([b"x"]).kind_name == "bytes_list"
        assert Feature().kind_name is None


# ---------------------------------------------------------------------------
# Cross-validation against the official protobuf runtime
# ---------------------------------------------------------------------------

_FEATURE_PROTO = """
syntax = "proto3";
package tfr_test;
message BytesList { repeated bytes value = 1; }
message FloatList { repeated float value = 1 [packed = true]; }
message Int64List { repeated int64 value = 1 [packed = true]; }
message Feature {
  oneof kind {
    BytesList bytes_list = 1;
    FloatList float_list = 2;
    Int64List int64_list = 3;
  }
}
message Features { map<string, Feature> feature = 1; }
message FeatureList { repeated Feature feature = 1; }
message FeatureLists { map<string, FeatureList> feature_list = 1; }
message Example { Features features = 1; }
message SequenceExample { Features context = 1; FeatureLists feature_lists = 2; }
"""


@pytest.fixture(scope="module")
def pb2(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("protos")
    proto_path = tmp / "tfr_test.proto"
    proto_path.write_text(_FEATURE_PROTO)
    try:
        subprocess.run(
            ["protoc", f"--python_out={tmp}", f"--proto_path={tmp}", str(proto_path)],
            check=True,
            capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError) as e:  # pragma: no cover
        pytest.skip(f"protoc unavailable: {e}")
    spec = importlib.util.spec_from_file_location("tfr_test_pb2", tmp / "tfr_test_pb2.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["tfr_test_pb2"] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception as e:  # pragma: no cover
        pytest.skip(f"generated pb2 incompatible with runtime: {e}")
    return mod


class TestProtobufInterop:
    def test_our_bytes_parse_with_official_runtime(self, pb2):
        data = proto.encode_example(make_example())
        official = pb2.Example()
        official.ParseFromString(data)
        fm = official.features.feature
        assert list(fm["long"].int64_list.value) == [7]
        assert list(fm["longs"].int64_list.value) == [-2, 20, 2**62, -(2**62)]
        np.testing.assert_allclose(
            list(fm["floats"].float_list.value), np.float32([1.5, -3.25, 1e30])
        )
        assert list(fm["strs"].bytes_list.value) == ["héllo".encode(), b"", b"\x00\xff"]
        assert fm["empty_int"].WhichOneof("kind") == "int64_list"

    def test_official_bytes_parse_with_ours(self, pb2):
        official = pb2.Example()
        official.features.feature["x"].int64_list.value.extend([1, -5, 2**40])
        official.features.feature["y"].float_list.value.extend([0.5, 7.0])
        official.features.feature["z"].bytes_list.value.append(b"blob")
        parsed = proto.parse_example(official.SerializeToString())
        assert parsed.features["x"].values == [1, -5, 2**40]
        assert parsed.features["y"].values == [0.5, 7.0]
        assert parsed.features["z"].values == [b"blob"]

    def test_sequence_example_interop(self, pb2):
        data = proto.encode_sequence_example(make_sequence_example())
        official = pb2.SequenceExample()
        official.ParseFromString(data)
        assert list(official.context.feature["id"].int64_list.value) == [42]
        frames = official.feature_lists.feature_list["frames"].feature
        assert [list(f.float_list.value) for f in frames] == [[1.0, 2.0], [3.0]]
        # and back
        parsed = proto.parse_sequence_example(official.SerializeToString())
        assert parsed.context["id"].values == [42]
        assert len(parsed.feature_lists["frames"].feature) == 2
