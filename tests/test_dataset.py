"""Tests for the streaming dataset pipeline: batching across shards,
prefetch, per-host shard assignment, checkpoint/resume."""

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord.io.dataset import IteratorState, TFRecordDataset
from tpu_tfrecord.schema import FloatType, LongType, StringType, StructField, StructType

SCHEMA = StructType(
    [
        StructField("uid", LongType()),
        StructField("score", FloatType()),
        StructField("tag", StringType()),
    ]
)


def write_shards(sandbox, num_shards=4, rows_per_shard=10):
    out = str(sandbox / "ds")
    rows = []
    uid = 0
    for s in range(num_shards):
        shard_rows = [[uid + i, float(uid + i) / 2, f"t{s}"] for i in range(rows_per_shard)]
        uid += rows_per_shard
        rows.append(shard_rows)
    # one write per shard => num_shards files (append accumulates)
    for shard_rows in rows:
        tfio.write(shard_rows, SCHEMA, out, mode="append")
    return out


class TestBatching:
    def test_batches_span_shards(self, sandbox):
        out = write_shards(sandbox, num_shards=4, rows_per_shard=10)
        ds = TFRecordDataset(out, batch_size=16, schema=SCHEMA)
        with ds.batches() as it:
            batches = list(it)
        assert [b.num_rows for b in batches] == [16, 16]  # 40 rows, drop rem 8
        all_uids = np.concatenate([b["uid"].values for b in batches])
        assert len(set(all_uids.tolist())) == 32

    def test_keep_remainder(self, sandbox):
        out = write_shards(sandbox, num_shards=2, rows_per_shard=5)
        ds = TFRecordDataset(out, batch_size=4, schema=SCHEMA, drop_remainder=False)
        with ds.batches() as it:
            sizes = [b.num_rows for b in it]
        assert sizes == [4, 4, 2]

    def test_multiple_epochs(self, sandbox):
        out = write_shards(sandbox, num_shards=2, rows_per_shard=4)
        ds = TFRecordDataset(out, batch_size=4, schema=SCHEMA, num_epochs=3)
        with ds.batches() as it:
            total = sum(b.num_rows for b in it)
        assert total == 24

    def test_column_pruning(self, sandbox):
        out = write_shards(sandbox, num_shards=1, rows_per_shard=4)
        ds = TFRecordDataset(out, batch_size=4, schema=SCHEMA, columns=["score"])
        with ds.batches() as it:
            b = next(it)
        assert set(b.columns) == {"score"}


class TestIteratorLifecycle:
    def test_next_after_close_raises_stop_iteration(self, sandbox):
        """close() makes the producer exit without its None sentinel; a
        subsequent __next__ must raise StopIteration, never block forever."""
        out = write_shards(sandbox, num_shards=2, rows_per_shard=10)
        ds = TFRecordDataset(out, batch_size=4, schema=SCHEMA)
        it = ds.batches()
        next(it)
        it.close()
        with pytest.raises(StopIteration):
            next(it)
        # and stays closed
        with pytest.raises(StopIteration):
            next(it)


class TestShardAssignment:
    def test_processes_partition_the_data(self, sandbox):
        out = write_shards(sandbox, num_shards=4, rows_per_shard=4)
        seen = []
        for pi in range(2):
            ds = TFRecordDataset(
                out, batch_size=4, schema=SCHEMA, process_index=pi, process_count=2
            )
            assert len(ds.shards) == 2
            with ds.batches() as it:
                for b in it:
                    seen.extend(b["uid"].values.tolist())
        assert sorted(seen) == list(range(16))


class TestCheckpointResume:
    def test_resume_continues_exactly(self, sandbox):
        out = write_shards(sandbox, num_shards=3, rows_per_shard=5)
        # the dataset's own deterministic order is the ground truth
        ref = TFRecordDataset(out, batch_size=4, schema=SCHEMA)
        expected = []
        with ref.batches() as it:
            for b in it:
                expected.extend(b["uid"].values.tolist())
        assert len(expected) == 12  # 15 rows, 12 in full batches

        ds = TFRecordDataset(out, batch_size=4, schema=SCHEMA)
        with ds.batches() as it:
            b1 = next(it)
            first_uids = b1["uid"].values.tolist()
            state = it.state()
        # resume from the saved state: must produce the NEXT batch, no overlap
        ds2 = TFRecordDataset(out, batch_size=4, schema=SCHEMA)
        resumed_uids = []
        with ds2.batches(state) as it2:
            for b in it2:
                resumed_uids.extend(b["uid"].values.tolist())
        assert first_uids == expected[:4]
        assert resumed_uids == expected[4:]

    def test_state_round_trips_json(self):
        s = IteratorState(epoch=1, shard_cursor=2, record_offset=3)
        assert IteratorState.from_json(s.to_json()) == s

    def test_fresh_state_is_zero(self, sandbox):
        out = write_shards(sandbox, num_shards=1, rows_per_shard=2)
        ds = TFRecordDataset(out, batch_size=2, schema=SCHEMA)
        with ds.batches() as it:
            assert it.state() == IteratorState()
            next(it)
            st = it.state()
        assert st.record_offset == 2
