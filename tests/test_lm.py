"""Causal LM: the end-to-end consumer of zigzag ring attention, the
scale-shaped pipeline, and the all-to-all MoE — every parallel mode must
reproduce the dense reference on the same params and data (the dp×pp
composition test ROADMAP #4a names), and the packed-batch feed must
checkpoint/resume byte-identically."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tools.graftlint import hlo_contracts
from tpu_tfrecord.models import lm
from tpu_tfrecord.tpu import TokenPacker, create_mesh

CFG = lm.LMConfig(vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16)


def batch(cfg=CFG, b=8, seed=0):
    return jnp.asarray(lm.make_synthetic_tokens(cfg, b, seed=seed))


class TestForwardParity:
    def test_zigzag_sp_matches_dense_reference(self):
        """mesh(dp×sp) + zigzag causal ring == the dense forward on the
        same params and tokens — the repo's most intricate code finally
        sits behind an end-to-end parity pin."""
        mesh = create_mesh({"data": 2, "seq": 4})
        params = lm.init_params(jax.random.key(0), CFG)
        toks = batch()
        want, _ = lm.forward(params, toks, CFG)
        sh = lm.batch_shardings(mesh)
        toks_sh = jax.device_put(toks, sh["tokens"])
        got, _ = jax.jit(
            functools.partial(
                lm.forward, cfg=CFG, mesh=mesh, data_axis="data",
                seq_axis="seq",
            )
        )(params, toks_sh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_pipeline_matches_dense_reference(self):
        """mesh(dp×pp): blocks as pipeline stages == the dense forward."""
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            n_micro=4,
        )
        mesh = create_mesh({"pipe": 4, "data": 2})
        params = lm.init_params(jax.random.key(0), cfg)
        toks = batch(cfg)
        want, _ = lm.forward(params, toks, cfg)
        p_sh = jax.device_put(
            params, lm.param_shardings(mesh, params, pipe_axis="pipe")
        )
        got, _ = jax.jit(
            functools.partial(
                lm.forward, cfg=cfg, mesh=mesh, data_axis="data",
                pipe_axis="pipe",
            )
        )(p_sh, toks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_interleaved_pipeline_matches_dense_reference(self):
        """mesh(dp×pp) with n_virtual=2: each device owns 2 round-robin
        layer chunks; the interleaved schedule must still equal the dense
        forward, and the measured bubble must beat the 1F1B analytic."""
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            n_micro=4, n_virtual=2,
        )
        mesh = create_mesh({"pipe": 2, "data": 2}, jax.devices()[:4])
        params = lm.init_params(jax.random.key(0), cfg)
        toks = batch(cfg)
        want, _ = lm.forward(params, toks, cfg)
        p_sh = jax.device_put(
            params, lm.param_shardings(mesh, params, pipe_axis="pipe")
        )
        got, _, diag = jax.jit(
            functools.partial(
                lm.forward, cfg=cfg, mesh=mesh, data_axis="data",
                pipe_axis="pipe", diagnostics=True,
            )
        )(p_sh, toks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
        # S=2, V=2, M=4: (S-1)/(V·M+S-1) = 1/9, below 1F1B's 1/5
        assert float(diag["bubble_fraction"]) == pytest.approx(
            1 / 9, abs=1e-6
        )

    def test_interleaved_layer_count_mismatch_rejected(self):
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16,
            n_virtual=2,
        )
        mesh = create_mesh({"pipe": 4, "data": 2})
        params = lm.init_params(jax.random.key(0), cfg)
        with pytest.raises(ValueError, match="n_virtual"):
            lm.forward(
                params, batch(cfg), cfg, mesh, data_axis="data",
                pipe_axis="pipe",
            )

    def test_moe_ep_matches_unsharded_moe(self):
        """expert_axis routes the FFN through the pinned all-to-all EP;
        per-shard capacity means parity holds vs moe_apply when the
        factor leaves headroom (no cross-shard drops at this scale)."""
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16,
            moe_experts=4, moe_top_k=2, moe_capacity_factor=4.0,
        )
        mesh = create_mesh({"data": 2, "expert": 4})
        params = lm.init_params(jax.random.key(0), cfg)
        toks = batch(cfg)
        want, aux_want = lm.forward(params, toks, cfg)
        p_sh = jax.device_put(
            params, lm.param_shardings(mesh, params, expert_axis="expert")
        )
        got, aux = jax.jit(
            functools.partial(
                lm.forward, cfg=cfg, mesh=mesh, data_axis="data",
                expert_axis="expert",
            )
        )(p_sh, toks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
        assert float(aux) > 0

    def test_mode_conflicts_rejected(self):
        mesh = create_mesh({"pipe": 4, "seq": 2})
        params = lm.init_params(jax.random.key(0), CFG)
        toks = batch()
        with pytest.raises(ValueError, match="mutually exclusive"):
            lm.forward(
                params, toks, CFG, mesh, seq_axis="seq", pipe_axis="pipe"
            )
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16,
            moe_experts=4,
        )
        with pytest.raises(ValueError, match="pipeline"):
            lm.forward(
                lm.init_params(jax.random.key(0), cfg), toks, cfg, mesh,
                pipe_axis="pipe",
            )


class TestComposition:
    """Same params + same data => same loss trajectory as pure dp — the
    missing dp×pp composition test."""

    def _trajectory(self, cfg, mesh=None, steps=6, **axes):
        params = lm.init_params(jax.random.key(0), cfg)
        if mesh is not None and axes.get("pipe_axis"):
            params = jax.device_put(
                params,
                lm.param_shardings(mesh, params, pipe_axis=axes["pipe_axis"]),
            )
        tx = optax.adam(3e-3)
        opt = tx.init(params)
        step = jax.jit(
            functools.partial(lm.train_step, cfg=cfg, tx=tx, mesh=mesh, **axes)
        )
        losses = []
        for i in range(steps):
            toks = batch(cfg, b=8, seed=100 + i)
            params, opt, loss = step(params, opt, toks)
            losses.append(float(loss))
        return losses

    def test_dp_pp_trajectory_matches_pure_dp(self):
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            n_micro=4,
        )
        ref = self._trajectory(cfg)
        mesh = create_mesh({"pipe": 4, "data": 2})
        got = self._trajectory(
            cfg, mesh=mesh, data_axis="data", pipe_axis="pipe"
        )
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_dp_sp_trajectory_matches_pure_dp(self):
        ref = self._trajectory(CFG)
        mesh = create_mesh({"data": 2, "seq": 4})
        got = self._trajectory(
            CFG, mesh=mesh, data_axis="data", seq_axis="seq"
        )
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_interleaved_dp_pp_trajectory_matches_pure_dp(self):
        """Grads unperturbed by interleaving: same params + same data =>
        same loss trajectory as pure dp, V=2."""
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            n_micro=2, n_virtual=2,
        )
        ref = self._trajectory(cfg)
        mesh = create_mesh({"pipe": 2, "data": 4})
        got = self._trajectory(
            cfg, mesh=mesh, data_axis="data", pipe_axis="pipe"
        )
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


class TestLMStream:
    """The serving flavor: streamed logits == the batch path bitwise, and
    both match the dense reference."""

    def _cfg(self):
        return lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            n_micro=4, n_virtual=2,
        )

    def test_streamed_logits_bitwise_equal_batch_path(self):
        cfg = self._cfg()
        mesh = create_mesh({"pipe": 2}, jax.devices()[:2])
        params = lm.init_params(jax.random.key(0), cfg)
        stream = lm.LMStream(params, cfg, mesh)
        reqs = [lm.make_synthetic_tokens(cfg, 4, seed=i) for i in range(6)]
        outs = []
        for r in reqs:
            outs.extend(stream.submit(r))
        outs.extend(stream.flush())
        assert len(outs) == len(reqs)
        ref = stream.batch_reference(reqs)
        for got, want in zip(outs, ref):
            np.testing.assert_array_equal(got, want)
        dense_cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16
        )
        for got, r in zip(outs, reqs):
            want, _ = lm.forward(params, jnp.asarray(r), dense_cfg)
            np.testing.assert_allclose(
                got, np.asarray(want), rtol=2e-4, atol=2e-4
            )

    def test_moe_rejected(self):
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16,
            moe_experts=4,
        )
        mesh = create_mesh({"pipe": 2}, jax.devices()[:2])
        params = lm.init_params(jax.random.key(0), cfg)
        with pytest.raises(ValueError, match="pipeline"):
            lm.LMStream(params, cfg, mesh)


class TestTraining:
    def test_zigzag_sp_loss_decreases(self):
        """The headline dryrun shape at test scale: zigzag causal ring
        attention inside a jitted train step, loss falls on the bigram
        language."""
        mesh = create_mesh({"data": 4, "seq": 2})
        params = lm.init_params(jax.random.key(0), CFG)
        tx = optax.adam(3e-3)
        opt = tx.init(params)
        step = jax.jit(
            functools.partial(
                lm.train_step, cfg=CFG, tx=tx, mesh=mesh, data_axis="data",
                seq_axis="seq",
            )
        )
        first = None
        for i in range(30):
            toks = batch(b=16, seed=i)
            params, opt, loss = step(params, opt, toks)
            first = first if first is not None else float(loss)
        assert float(loss) < first, (first, float(loss))

    def test_pipeline_hlo_no_gather_of_microbatch_stream(self):
        """The acceptance pin, at the TRAIN-STEP level: the compiled dp×pp
        step moves activations by collective-permute and never all-gathers
        the microbatch stream (grads over 'data' still all-reduce — that
        is dp's collective, not the pipeline's). Pin + construction live
        in the shared manifest."""
        hlo_contracts.verify("lm_train_step")


class TestTokenPacker:
    def test_packs_stream_exactly(self):
        pk = TokenPacker(batch_size=2, seq_len=4, eos_id=0)
        docs = [np.arange(1, 8), np.arange(10, 13), np.arange(20, 31)]
        pk.feed_docs(docs)
        stream = []
        for d in docs:
            stream.extend(d.tolist())
            stream.append(0)
        got = []
        while (b := pk.pop()) is not None:
            assert b.shape == (2, 5) and b.dtype == np.int32
            got.extend(b.reshape(-1).tolist())
        assert got == stream[: len(got)]
        assert len(stream) - len(got) < 2 * 5  # only the tail remains

    def test_state_resume_is_byte_identical(self):
        """Checkpoint mid-stream, feed the SAME remaining docs to a fresh
        packer restored from the state: the packed batches match the
        uninterrupted run exactly."""
        rng = np.random.default_rng(0)
        docs = [
            rng.integers(1, 50, size=rng.integers(3, 20)) for _ in range(40)
        ]
        a = TokenPacker(batch_size=2, seq_len=8)
        full = []
        for d in docs:
            a.feed_docs([d])
            while (b := a.pop()) is not None:
                full.append(b)
        # interrupted at doc 17 — with batches still pending in the carry
        b1 = TokenPacker(batch_size=2, seq_len=8)
        early = []
        for d in docs[:17]:
            b1.feed_docs([d])
        while len(early) < 3 and (bt := b1.pop()) is not None:
            early.append(bt)
        state = b1.state()
        b2 = TokenPacker(batch_size=2, seq_len=8)
        b2.restore(state)
        resumed = list(early)
        while (bt := b2.pop()) is not None:
            resumed.append(bt)
        for d in docs[17:]:
            b2.feed_docs([d])
            while (bt := b2.pop()) is not None:
                resumed.append(bt)
        assert len(resumed) == len(full)
        for x, y in zip(resumed, full):
            np.testing.assert_array_equal(x, y)

    def test_feed_column_matches_feed_docs(self):
        from tpu_tfrecord.columnar import Column
        from tpu_tfrecord.schema import LongType

        rng = np.random.default_rng(1)
        docs = [rng.integers(0, 9, size=n) for n in (3, 7, 2, 9)]
        values = np.concatenate(docs).astype(np.int64)
        offsets = np.cumsum([0] + [len(d) for d in docs]).astype(np.int64)
        a = TokenPacker(2, 3)
        a.feed_docs(docs)
        b = TokenPacker(2, 3)
        b.feed_column(
            Column("tokens", LongType(), values=values, offsets=offsets)
        )
        while (x := a.pop()) is not None:
            np.testing.assert_array_equal(x, b.pop())
        assert b.pop() is None

    def test_bad_sizes_rejected(self):
        with pytest.raises(ValueError, match="batch_size"):
            TokenPacker(0, 4)
