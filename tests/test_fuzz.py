"""Seeded fuzz: random schemas and rows cross-checked through every codec
path — row serde round-trip, Python vs native columnar decode, native
encode -> decode round-trip. One failure seed reproduces deterministically.
"""

import decimal

import numpy as np
import pytest

from tpu_tfrecord import _native
from tpu_tfrecord.columnar import ColumnarDecoder, batch_to_rows
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import TFRecordDeserializer, TFRecordSerializer, decode_record, encode_row

SCALARS = [IntegerType, LongType, FloatType, DoubleType, DecimalType, StringType, BinaryType]


def random_schema(rng, record_type):
    n = int(rng.integers(1, 8))
    fields = []
    for i in range(n):
        r = rng.random()
        base = SCALARS[int(rng.integers(0, len(SCALARS)))]()
        if r < 0.5:
            dt = base
        elif r >= 0.8 and record_type == RecordType.SEQUENCE_EXAMPLE:
            dt = ArrayType(ArrayType(base))
        else:
            dt = ArrayType(base)
        fields.append(StructField(f"f{i}", dt, nullable=True))
    return StructType(fields)


def random_value(rng, dt):
    if isinstance(dt, IntegerType):
        return int(rng.integers(-(2**31), 2**31))
    if isinstance(dt, LongType):
        # full int64 range including both boundaries
        return int(rng.integers(-(2**63), 2**63 - 1, endpoint=True))
    if isinstance(dt, (FloatType, DoubleType)):
        return float(np.float32(rng.normal() * 100))
    if isinstance(dt, DecimalType):
        return decimal.Decimal(str(float(np.float32(rng.normal()))))
    if isinstance(dt, StringType):
        n = int(rng.integers(0, 12))
        return "".join(chr(int(c)) for c in rng.integers(32, 0x2FF, size=n))
    if isinstance(dt, BinaryType):
        return bytes(rng.integers(0, 256, size=int(rng.integers(0, 10)), dtype=np.uint8))
    if isinstance(dt, ArrayType):
        return [random_value(rng, dt.element_type) for _ in range(int(rng.integers(0, 5)))]
    raise AssertionError(dt)


def random_row(rng, schema):
    row = []
    for f in schema:
        if rng.random() < 0.15:
            row.append(None)
        else:
            row.append(random_value(rng, f.data_type))
    return row


def rows_close(a, b):
    assert len(a) == len(b)
    for va, vb in zip(a, b):
        if vb is None:
            assert va is None
            continue
        if isinstance(vb, decimal.Decimal):
            assert float(va) == pytest.approx(float(vb), abs=1e-4, rel=1e-4)
        elif isinstance(vb, float):
            assert va == pytest.approx(vb, rel=1e-6)
        elif isinstance(vb, list):
            assert len(va) == len(vb)
            for xa, xb in zip(va, vb):
                if isinstance(xb, list):
                    rows_close([xa], [xb])
                elif isinstance(xb, (float, decimal.Decimal)):
                    assert float(xa) == pytest.approx(float(xb), abs=1e-4, rel=1e-4)
                else:
                    assert xa == xb
        else:
            assert va == vb


def _make_case(seed, rt):
    rng = np.random.default_rng((seed, rt is RecordType.EXAMPLE))
    schema = random_schema(rng, rt)
    rows = [random_row(rng, schema) for _ in range(int(rng.integers(1, 30)))]
    ser = TFRecordSerializer(schema)
    records = [encode_row(ser, rt, r) for r in rows]
    return schema, rows, records


@pytest.mark.parametrize("seed", range(25))
@pytest.mark.parametrize("rt", [RecordType.EXAMPLE, RecordType.SEQUENCE_EXAMPLE])
def test_fuzz_python_paths(seed, rt):
    schema, rows, records = _make_case(seed, rt)
    de = TFRecordDeserializer(schema)

    # 1. row serde round-trip: nulls come back as None, values survive (at
    # the wire's float32 precision for double/decimal)
    for rec, row in zip(records, rows):
        back = decode_record(de, rt, rec)
        rows_close(back, [normalize_value(v, f.data_type) for v, f in zip(row, schema)])

    # 2. batch_to_rows agrees with the row deserializer
    py_batch = ColumnarDecoder(schema, rt).decode_batch(records)
    via_batch = batch_to_rows(py_batch, schema)
    for got, rec in zip(via_batch, records):
        rows_close(got, decode_record(de, rt, rec))


@pytest.mark.skipif(
    not _native.available(), reason=f"native lib unavailable: {_native.load_error()}"
)
@pytest.mark.parametrize("seed", range(25))
@pytest.mark.parametrize("rt", [RecordType.EXAMPLE, RecordType.SEQUENCE_EXAMPLE])
def test_fuzz_native_paths(seed, rt):
    schema, rows, records = _make_case(seed, rt)
    from tests.test_native import assert_batches_equal

    # Python vs native columnar decode agree exactly
    py_batch = ColumnarDecoder(schema, rt).decode_batch(records)
    nat_batch = _native.NativeDecoder(schema, rt).decode_batch(records)
    assert_batches_equal(nat_batch, py_batch)

    # native encode -> decode round-trip preserves the batch
    enc = _native.NativeEncoder(schema, rt)
    buf = enc.encode_batch(nat_batch).tobytes()
    offsets, lengths = _native.scan(buf)
    back2 = _native.NativeDecoder(schema, rt).decode_spans(buf, offsets, lengths)
    assert_batches_equal(back2, nat_batch)


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _varint(len(payload)) + payload


def _int64_feature(vals, packed: bool) -> bytes:
    if packed:
        lst = _ld(0x0A, b"".join(_varint(v & (2**64 - 1)) for v in vals))
    else:
        lst = b"".join(b"\x08" + _varint(v & (2**64 - 1)) for v in vals)
    return _ld(0x1A, lst)


def _float_feature(vals, packed: bool) -> bytes:
    import struct as _s

    if packed:
        lst = _ld(0x0A, b"".join(_s.pack("<f", v) for v in vals))
    else:
        lst = b"".join(b"\x0d" + _s.pack("<f", v) for v in vals)
    return _ld(0x12, lst)


def _bytes_feature(vals) -> bytes:
    return _ld(0x0A, b"".join(_ld(0x0A, v) for v in vals))


def _raw_example(entries) -> bytes:
    payload = b"".join(
        _ld(0x0A, _ld(0x0A, k.encode()) + _ld(0x12, f)) for k, f in entries
    )
    return _ld(0x0A, payload)


@pytest.mark.skipif(
    not _native.available(), reason=f"native lib unavailable: {_native.load_error()}"
)
@pytest.mark.parametrize("seed", range(30))
def test_fuzz_turbo_adversarial(seed):
    """Differential fuzz targeting the turbo decode lanes specifically:
    hand-built Example bytes with shuffled key order, duplicate keys,
    missing fields, multi-value scalars (head semantics), packed/unpacked
    encodings, unknown extra keys, and drifting value byte-lengths (cache
    misses) — native (turbo + fallback) must match the Python oracle
    byte-for-byte, through BOTH decode_batch and the fused scan_decode."""
    from tests.test_native import assert_batches_equal
    from tpu_tfrecord import wire

    rng = np.random.default_rng(seed)
    n_fields = int(rng.integers(2, 7))
    kinds = rng.choice(["long", "float", "str", "hashed"], size=n_fields)
    fields, buckets = [], {}
    for i, k in enumerate(kinds):
        name = f"c{i}"
        if k == "long":
            dt = LongType()
        elif k == "float":
            dt = FloatType()
        else:
            dt = StringType()
            if k == "hashed":
                buckets[name] = 97
        fields.append(StructField(name, dt, nullable=True))
    schema = StructType(fields)

    records = []
    for _ in range(int(rng.integers(5, 60))):
        order = list(range(n_fields))
        if rng.random() < 0.3:
            rng.shuffle(order)  # key-order drift breaks the sticky prefix
        entries = []
        for i in order:
            if rng.random() < 0.12:
                continue  # missing (nullable) field
            k = kinds[i]
            packed = rng.random() < 0.8
            reps = 2 if rng.random() < 0.08 else 1  # duplicate map key
            for _ in range(reps):
                if k == "long":
                    nvals = 1 if rng.random() < 0.85 else int(rng.integers(2, 4))
                    vals = [
                        int(rng.integers(-(2**62), 2**62))
                        if rng.random() < 0.3
                        else int(rng.integers(0, 1 << int(rng.integers(1, 40))))
                        for _ in range(nvals)
                    ]
                    feat = _int64_feature(vals, packed)
                elif k == "float":
                    nvals = 1 if rng.random() < 0.85 else 3
                    feat = _float_feature(
                        [float(np.float32(rng.normal())) for _ in range(nvals)],
                        packed,
                    )
                else:
                    nvals = 1 if rng.random() < 0.9 else 2
                    blen = int(rng.integers(0, 24))
                    feat = _bytes_feature(
                        [
                            bytes(rng.integers(97, 123, size=blen, dtype=np.uint8))
                            for _ in range(nvals)
                        ]
                    )
                entries.append((f"c{i}", feat))
        if rng.random() < 0.1:
            entries.append(("zz_unknown", _int64_feature([1], True)))
        records.append(_raw_example(entries))

    # oracle path: plain decode, then hash the blobs post-hoc
    oracle = ColumnarDecoder(schema).decode_batch(records)
    nat = _native.NativeDecoder(schema, hash_buckets=buckets).decode_batch(records)
    for name, b in buckets.items():
        blobs = oracle[name].blobs
        mask = oracle[name].mask
        want = np.array(
            [
                (wire.crc32c_py(x) % b) if (mask is None or mask[i]) else 0
                for i, x in enumerate(blobs)
            ],
            dtype=np.int32,
        )
        np.testing.assert_array_equal(nat[name].values, want)
        np.testing.assert_array_equal(nat[name].mask, oracle[name].mask)
    plain_schema = StructType([f for f in schema if f.name not in buckets])
    if len(plain_schema):
        nat_plain = _native.NativeDecoder(schema).decode_batch(records)
        assert_batches_equal(nat_plain, oracle)

    # the fused scan path with a random resume skip must agree too
    framed = b"".join(wire.encode_record(r) for r in records)
    skip = int(rng.integers(0, len(records)))
    dec = _native.NativeDecoder(schema, hash_buckets=buckets)
    cb, n_sk, n_done, consumed = dec.scan_decode(
        framed, 0, True, skip, len(records)
    )
    assert (n_sk, n_done) == (skip, len(records) - skip)
    assert consumed == len(framed)
    if n_done:
        ref = _native.NativeDecoder(schema, hash_buckets=buckets).decode_batch(
            records[skip:]
        )
        assert_batches_equal(cb, ref)


def normalize_value(v, dt):
    """What the wire preserves: double/decimal narrow to f32."""
    if v is None:
        return None
    if isinstance(dt, (DoubleType, FloatType)):
        return float(np.float32(v))
    if isinstance(dt, DecimalType):
        return decimal.Decimal(str(float(np.float32(v))))
    if isinstance(dt, ArrayType):
        return [normalize_value(x, dt.element_type) for x in v]
    return v


# ---------------------------------------------------------------------------
# Byte-flip corruption corpus: on_corrupt="skip_record" salvage (resync)
# ---------------------------------------------------------------------------
#
# Every corruption class the wire can suffer — bad length field, bad
# length-CRC, bad payload, bad data-CRC, truncated tail — at the head,
# middle, and tail of a shard, uncompressed and gzip (framing corrupted
# BEFORE compression: codec-stream corruption is a different failure class,
# covered by the 'codec' salvage event). skip_record must recover every
# record except the corrupted frame, and the quota must escalate correctly.

import gzip
import os

from tpu_tfrecord import wire
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.metrics import METRICS

_UID_SCHEMA = StructType([StructField("uid", LongType(), nullable=False)])
_N_RECORDS = 30


def _uid_frames():
    ser = TFRecordSerializer(_UID_SCHEMA)
    frames = [
        wire.encode_record(encode_row(ser, RecordType.EXAMPLE, [i]))
        for i in range(_N_RECORDS)
    ]
    offs = [0]
    for f in frames:
        offs.append(offs[-1] + len(f))
    return frames, offs


def _flip_offset(offs, frames, frame_idx, kind):
    """Byte offset to corrupt for one (frame, corruption-kind) pair."""
    base = offs[frame_idx]
    payload_len = len(frames[frame_idx]) - wire.HEADER_BYTES - wire.FOOTER_BYTES
    return {
        "length": base + 2,
        "length_crc": base + 9,
        "payload": base + wire.HEADER_BYTES + 1,
        "data_crc": base + wire.HEADER_BYTES + payload_len + 1,
    }[kind]


def _write_corpus_shard(dirname, blob, codec):
    os.makedirs(dirname, exist_ok=True)
    name = "part-0.tfrecord" + (".gz" if codec == "gzip" else "")
    data = gzip.compress(bytes(blob), mtime=0) if codec == "gzip" else bytes(blob)
    path = os.path.join(dirname, name)
    with open(path, "wb") as fh:
        fh.write(data)
    return path


def _read_uids(dirname, **kw):
    ds = TFRecordDataset(
        dirname, batch_size=7, schema=_UID_SCHEMA, drop_remainder=False, **kw
    )
    out = []
    with ds.batches() as it:
        for cb in it:
            out.extend(cb["uid"].values.tolist())
    return out


class TestByteFlipSalvage:
    @pytest.mark.parametrize("codec", [None, "gzip"])
    @pytest.mark.parametrize("where", ["head", "middle", "tail"])
    @pytest.mark.parametrize(
        "kind", ["length", "length_crc", "payload", "data_crc"]
    )
    def test_skip_record_recovers_everything_else(
        self, tmp_path, codec, where, kind
    ):
        frames, offs = _uid_frames()
        k = {"head": 0, "middle": _N_RECORDS // 2, "tail": _N_RECORDS - 1}[where]
        blob = bytearray(b"".join(frames))
        blob[_flip_offset(offs, frames, k, kind)] ^= 0xFF
        d = str(tmp_path / f"flip_{codec}_{where}_{kind}")
        _write_corpus_shard(d, blob, codec)

        # default policy: byte-exact parity with today — it raises
        with pytest.raises(wire.TFRecordCorruptionError):
            _read_uids(d)

        corrupt0 = METRICS.counter("read.corrupt_records")
        resync0 = METRICS.counter("read.resyncs")
        got = _read_uids(d, on_corrupt="skip_record")
        assert got == [i for i in range(_N_RECORDS) if i != k]
        assert METRICS.counter("read.corrupt_records") > corrupt0
        if where != "tail":
            # mid-stream corruption must land a resync on the next frame
            assert METRICS.counter("read.resyncs") > resync0

    @pytest.mark.parametrize("codec", [None, "gzip"])
    def test_skip_record_truncated_tail(self, tmp_path, codec):
        frames, _ = _uid_frames()
        blob = bytearray(b"".join(frames))[:-3]  # cut into the last frame
        d = str(tmp_path / f"trunc_{codec}")
        _write_corpus_shard(d, blob, codec)
        with pytest.raises(wire.TFRecordCorruptionError):
            _read_uids(d)
        got = _read_uids(d, on_corrupt="skip_record")
        assert got == list(range(_N_RECORDS - 1))

    def test_codec_stream_corruption_is_one_event(self, tmp_path):
        """A flipped byte in the COMPRESSED stream (vs the framing) loses
        the rest of the shard but must charge the quota exactly once — the
        codec event, not codec + a trailing 'truncated' double-count."""
        frames, offs = _uid_frames()
        raw = gzip.compress(b"".join(frames), mtime=0)
        blob = bytearray(raw)
        blob[len(blob) // 2] ^= 0xFF  # corrupt the gzip stream itself
        d = str(tmp_path / "codec")
        os.makedirs(d)
        with open(os.path.join(d, "part-0.tfrecord.gz"), "wb") as fh:
            fh.write(bytes(blob))
        corrupt0 = METRICS.counter("read.corrupt_records")
        # quota 1: the single codec event must NOT escalate
        got = _read_uids(d, on_corrupt="skip_record", max_corrupt_records=1)
        assert got == list(range(len(got)))  # a valid prefix survives
        assert len(got) < _N_RECORDS
        assert METRICS.counter("read.corrupt_records") == corrupt0 + 1

    def test_quota_escalates_to_raise(self, tmp_path):
        frames, offs = _uid_frames()
        blob = bytearray(b"".join(frames))
        bad = (3, 11, 22)
        for k in bad:
            blob[_flip_offset(offs, frames, k, "payload")] ^= 0xFF
        d = str(tmp_path / "quota_raise")
        _write_corpus_shard(d, blob, None)
        # quota 3: all three regions tolerated
        got = _read_uids(d, on_corrupt="skip_record", max_corrupt_records=3)
        assert got == [i for i in range(_N_RECORDS) if i not in bad]
        # quota 2: the third region escalates to the default fallback (raise)
        with pytest.raises(wire.TFRecordCorruptionError, match="max_corrupt_records"):
            _read_uids(d, on_corrupt="skip_record", max_corrupt_records=2)

    def test_quota_escalates_to_skip_shard(self, tmp_path):
        frames, offs = _uid_frames()
        blob = bytearray(b"".join(frames))
        bad = (3, 11, 22)
        for k in bad:
            blob[_flip_offset(offs, frames, k, "payload")] ^= 0xFF
        d = str(tmp_path / "quota_skip")
        _write_corpus_shard(d, blob, None)
        skipped0 = METRICS.counter("read.skipped_shards")
        got = _read_uids(
            d,
            on_corrupt="skip_record",
            max_corrupt_records=2,
            corrupt_fallback="skip_shard",
        )
        # everything salvaged before the escalating third region
        assert got == [i for i in range(22) if i not in bad]
        assert METRICS.counter("read.skipped_shards") == skipped0 + 1

    def test_checkpoint_resume_under_skip_is_deterministic(self, tmp_path):
        """Skipped frames must not desync record-index accounting: a resume
        mid-way through a corrupt shard skips exactly the same frames."""
        frames, offs = _uid_frames()
        blob = bytearray(b"".join(frames))
        blob[_flip_offset(offs, frames, 4, "data_crc")] ^= 0xFF
        blob[_flip_offset(offs, frames, 17, "length_crc")] ^= 0xFF
        d = str(tmp_path / "resume")
        _write_corpus_shard(d, blob, None)
        kw = dict(
            batch_size=5, schema=_UID_SCHEMA, drop_remainder=False,
            on_corrupt="skip_record",
        )
        full = []
        with TFRecordDataset(d, **kw).batches() as it:
            for cb in it:
                full.extend(cb["uid"].values.tolist())
        assert full == [i for i in range(_N_RECORDS) if i not in (4, 17)]

        first = []
        it = TFRecordDataset(d, **kw).batches()
        for _ in range(2):
            first.extend(next(it)["uid"].values.tolist())
        state = it.state()
        it.close()
        rest = []
        with TFRecordDataset(d, **kw).batches(state) as it2:
            for cb in it2:
                rest.extend(cb["uid"].values.tolist())
        assert first + rest == full
