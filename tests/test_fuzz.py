"""Seeded fuzz: random schemas and rows cross-checked through every codec
path — row serde round-trip, Python vs native columnar decode, native
encode -> decode round-trip. One failure seed reproduces deterministically.
"""

import decimal

import numpy as np
import pytest

from tpu_tfrecord import _native
from tpu_tfrecord.columnar import ColumnarDecoder, batch_to_rows
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import TFRecordDeserializer, TFRecordSerializer, decode_record, encode_row

SCALARS = [IntegerType, LongType, FloatType, DoubleType, DecimalType, StringType, BinaryType]


def random_schema(rng, record_type):
    n = int(rng.integers(1, 8))
    fields = []
    for i in range(n):
        r = rng.random()
        base = SCALARS[int(rng.integers(0, len(SCALARS)))]()
        if r < 0.5:
            dt = base
        elif r >= 0.8 and record_type == RecordType.SEQUENCE_EXAMPLE:
            dt = ArrayType(ArrayType(base))
        else:
            dt = ArrayType(base)
        fields.append(StructField(f"f{i}", dt, nullable=True))
    return StructType(fields)


def random_value(rng, dt):
    if isinstance(dt, IntegerType):
        return int(rng.integers(-(2**31), 2**31))
    if isinstance(dt, LongType):
        # full int64 range including both boundaries
        return int(rng.integers(-(2**63), 2**63 - 1, endpoint=True))
    if isinstance(dt, (FloatType, DoubleType)):
        return float(np.float32(rng.normal() * 100))
    if isinstance(dt, DecimalType):
        return decimal.Decimal(str(float(np.float32(rng.normal()))))
    if isinstance(dt, StringType):
        n = int(rng.integers(0, 12))
        return "".join(chr(int(c)) for c in rng.integers(32, 0x2FF, size=n))
    if isinstance(dt, BinaryType):
        return bytes(rng.integers(0, 256, size=int(rng.integers(0, 10)), dtype=np.uint8))
    if isinstance(dt, ArrayType):
        return [random_value(rng, dt.element_type) for _ in range(int(rng.integers(0, 5)))]
    raise AssertionError(dt)


def random_row(rng, schema):
    row = []
    for f in schema:
        if rng.random() < 0.15:
            row.append(None)
        else:
            row.append(random_value(rng, f.data_type))
    return row


def rows_close(a, b):
    assert len(a) == len(b)
    for va, vb in zip(a, b):
        if vb is None:
            assert va is None
            continue
        if isinstance(vb, decimal.Decimal):
            assert float(va) == pytest.approx(float(vb), abs=1e-4, rel=1e-4)
        elif isinstance(vb, float):
            assert va == pytest.approx(vb, rel=1e-6)
        elif isinstance(vb, list):
            assert len(va) == len(vb)
            for xa, xb in zip(va, vb):
                if isinstance(xb, list):
                    rows_close([xa], [xb])
                elif isinstance(xb, (float, decimal.Decimal)):
                    assert float(xa) == pytest.approx(float(xb), abs=1e-4, rel=1e-4)
                else:
                    assert xa == xb
        else:
            assert va == vb


def _make_case(seed, rt):
    rng = np.random.default_rng((seed, rt is RecordType.EXAMPLE))
    schema = random_schema(rng, rt)
    rows = [random_row(rng, schema) for _ in range(int(rng.integers(1, 30)))]
    ser = TFRecordSerializer(schema)
    records = [encode_row(ser, rt, r) for r in rows]
    return schema, rows, records


@pytest.mark.parametrize("seed", range(25))
@pytest.mark.parametrize("rt", [RecordType.EXAMPLE, RecordType.SEQUENCE_EXAMPLE])
def test_fuzz_python_paths(seed, rt):
    schema, rows, records = _make_case(seed, rt)
    de = TFRecordDeserializer(schema)

    # 1. row serde round-trip: nulls come back as None, values survive (at
    # the wire's float32 precision for double/decimal)
    for rec, row in zip(records, rows):
        back = decode_record(de, rt, rec)
        rows_close(back, [normalize_value(v, f.data_type) for v, f in zip(row, schema)])

    # 2. batch_to_rows agrees with the row deserializer
    py_batch = ColumnarDecoder(schema, rt).decode_batch(records)
    via_batch = batch_to_rows(py_batch, schema)
    for got, rec in zip(via_batch, records):
        rows_close(got, decode_record(de, rt, rec))


@pytest.mark.skipif(
    not _native.available(), reason=f"native lib unavailable: {_native.load_error()}"
)
@pytest.mark.parametrize("seed", range(25))
@pytest.mark.parametrize("rt", [RecordType.EXAMPLE, RecordType.SEQUENCE_EXAMPLE])
def test_fuzz_native_paths(seed, rt):
    schema, rows, records = _make_case(seed, rt)
    from tests.test_native import assert_batches_equal

    # Python vs native columnar decode agree exactly
    py_batch = ColumnarDecoder(schema, rt).decode_batch(records)
    nat_batch = _native.NativeDecoder(schema, rt).decode_batch(records)
    assert_batches_equal(nat_batch, py_batch)

    # native encode -> decode round-trip preserves the batch
    enc = _native.NativeEncoder(schema, rt)
    buf = enc.encode_batch(nat_batch).tobytes()
    offsets, lengths = _native.scan(buf)
    back2 = _native.NativeDecoder(schema, rt).decode_spans(buf, offsets, lengths)
    assert_batches_equal(back2, nat_batch)


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _varint(len(payload)) + payload


def _int64_feature(vals, packed: bool) -> bytes:
    if packed:
        lst = _ld(0x0A, b"".join(_varint(v & (2**64 - 1)) for v in vals))
    else:
        lst = b"".join(b"\x08" + _varint(v & (2**64 - 1)) for v in vals)
    return _ld(0x1A, lst)


def _float_feature(vals, packed: bool) -> bytes:
    import struct as _s

    if packed:
        lst = _ld(0x0A, b"".join(_s.pack("<f", v) for v in vals))
    else:
        lst = b"".join(b"\x0d" + _s.pack("<f", v) for v in vals)
    return _ld(0x12, lst)


def _bytes_feature(vals) -> bytes:
    return _ld(0x0A, b"".join(_ld(0x0A, v) for v in vals))


def _raw_example(entries) -> bytes:
    payload = b"".join(
        _ld(0x0A, _ld(0x0A, k.encode()) + _ld(0x12, f)) for k, f in entries
    )
    return _ld(0x0A, payload)


@pytest.mark.skipif(
    not _native.available(), reason=f"native lib unavailable: {_native.load_error()}"
)
@pytest.mark.parametrize("seed", range(30))
def test_fuzz_turbo_adversarial(seed):
    """Differential fuzz targeting the turbo decode lanes specifically:
    hand-built Example bytes with shuffled key order, duplicate keys,
    missing fields, multi-value scalars (head semantics), packed/unpacked
    encodings, unknown extra keys, and drifting value byte-lengths (cache
    misses) — native (turbo + fallback) must match the Python oracle
    byte-for-byte, through BOTH decode_batch and the fused scan_decode."""
    from tests.test_native import assert_batches_equal
    from tpu_tfrecord import wire

    rng = np.random.default_rng(seed)
    n_fields = int(rng.integers(2, 7))
    kinds = rng.choice(["long", "float", "str", "hashed"], size=n_fields)
    fields, buckets = [], {}
    for i, k in enumerate(kinds):
        name = f"c{i}"
        if k == "long":
            dt = LongType()
        elif k == "float":
            dt = FloatType()
        else:
            dt = StringType()
            if k == "hashed":
                buckets[name] = 97
        fields.append(StructField(name, dt, nullable=True))
    schema = StructType(fields)

    records = []
    for _ in range(int(rng.integers(5, 60))):
        order = list(range(n_fields))
        if rng.random() < 0.3:
            rng.shuffle(order)  # key-order drift breaks the sticky prefix
        entries = []
        for i in order:
            if rng.random() < 0.12:
                continue  # missing (nullable) field
            k = kinds[i]
            packed = rng.random() < 0.8
            reps = 2 if rng.random() < 0.08 else 1  # duplicate map key
            for _ in range(reps):
                if k == "long":
                    nvals = 1 if rng.random() < 0.85 else int(rng.integers(2, 4))
                    vals = [
                        int(rng.integers(-(2**62), 2**62))
                        if rng.random() < 0.3
                        else int(rng.integers(0, 1 << int(rng.integers(1, 40))))
                        for _ in range(nvals)
                    ]
                    feat = _int64_feature(vals, packed)
                elif k == "float":
                    nvals = 1 if rng.random() < 0.85 else 3
                    feat = _float_feature(
                        [float(np.float32(rng.normal())) for _ in range(nvals)],
                        packed,
                    )
                else:
                    nvals = 1 if rng.random() < 0.9 else 2
                    blen = int(rng.integers(0, 24))
                    feat = _bytes_feature(
                        [
                            bytes(rng.integers(97, 123, size=blen, dtype=np.uint8))
                            for _ in range(nvals)
                        ]
                    )
                entries.append((f"c{i}", feat))
        if rng.random() < 0.1:
            entries.append(("zz_unknown", _int64_feature([1], True)))
        records.append(_raw_example(entries))

    # oracle path: plain decode, then hash the blobs post-hoc
    oracle = ColumnarDecoder(schema).decode_batch(records)
    nat = _native.NativeDecoder(schema, hash_buckets=buckets).decode_batch(records)
    for name, b in buckets.items():
        blobs = oracle[name].blobs
        mask = oracle[name].mask
        want = np.array(
            [
                (wire.crc32c_py(x) % b) if (mask is None or mask[i]) else 0
                for i, x in enumerate(blobs)
            ],
            dtype=np.int32,
        )
        np.testing.assert_array_equal(nat[name].values, want)
        np.testing.assert_array_equal(nat[name].mask, oracle[name].mask)
    plain_schema = StructType([f for f in schema if f.name not in buckets])
    if len(plain_schema):
        nat_plain = _native.NativeDecoder(schema).decode_batch(records)
        assert_batches_equal(nat_plain, oracle)

    # the fused scan path with a random resume skip must agree too
    framed = b"".join(wire.encode_record(r) for r in records)
    skip = int(rng.integers(0, len(records)))
    dec = _native.NativeDecoder(schema, hash_buckets=buckets)
    cb, n_sk, n_done, consumed = dec.scan_decode(
        framed, 0, True, skip, len(records)
    )
    assert (n_sk, n_done) == (skip, len(records) - skip)
    assert consumed == len(framed)
    if n_done:
        ref = _native.NativeDecoder(schema, hash_buckets=buckets).decode_batch(
            records[skip:]
        )
        assert_batches_equal(cb, ref)


def normalize_value(v, dt):
    """What the wire preserves: double/decimal narrow to f32."""
    if v is None:
        return None
    if isinstance(dt, (DoubleType, FloatType)):
        return float(np.float32(v))
    if isinstance(dt, DecimalType):
        return decimal.Decimal(str(float(np.float32(v))))
    if isinstance(dt, ArrayType):
        return [normalize_value(x, dt.element_type) for x in v]
    return v
