"""Tier-1 tests for the row<->record codec, mirroring the reference's
TFRecordSerializerTest.scala and TFRecordDeserializerTest.scala matrix."""

import decimal

import numpy as np
import pytest

from tpu_tfrecord import proto
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.proto import BYTES_LIST, FLOAT_LIST, INT64_LIST, Example, Feature, FeatureList, SequenceExample
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import (
    NullValueError,
    TFRecordDeserializer,
    TFRecordSerializer,
    UnsupportedDataTypeError,
    decode_record,
    encode_row,
)

COMPLEX_SCHEMA = StructType(
    [
        StructField("IntegerCol", IntegerType()),
        StructField("LongCol", LongType()),
        StructField("FloatCol", FloatType()),
        StructField("DoubleCol", DoubleType()),
        StructField("DecimalCol", DecimalType()),
        StructField("StrCol", StringType()),
        StructField("BinCol", BinaryType()),
        StructField("IntListCol", ArrayType(IntegerType())),
        StructField("LongListCol", ArrayType(LongType())),
        StructField("FloatListCol", ArrayType(FloatType())),
        StructField("DoubleListCol", ArrayType(DoubleType())),
        StructField("DecimalListCol", ArrayType(DecimalType())),
        StructField("StrListCol", ArrayType(StringType())),
        StructField("BinListCol", ArrayType(BinaryType())),
    ]
)

COMPLEX_ROW = [
    1,
    23,
    10.0,
    14.0,
    decimal.Decimal("2.5"),
    "r1",
    b"\x01\x02",
    [1, 2],
    [3, 4],
    [2.5, 5.0],
    [3.0, 7.5],
    [decimal.Decimal("1.5"), decimal.Decimal("2.0")],
    ["a", "b"],
    [b"x", b"yz"],
]


class TestSerializeExample:
    """Mirrors TFRecordSerializerTest.scala:46-141."""

    def test_complex_row_to_example(self):
        ser = TFRecordSerializer(COMPLEX_SCHEMA)
        ex = ser.serialize_example(COMPLEX_ROW)
        f = ex.features
        assert f["IntegerCol"].kind == INT64_LIST and f["IntegerCol"].values == [1]
        assert f["LongCol"].values == [23]
        assert f["FloatCol"].kind == FLOAT_LIST and f["FloatCol"].values == [10.0]
        assert f["DoubleCol"].kind == FLOAT_LIST and f["DoubleCol"].values == [14.0]
        assert f["DecimalCol"].values == [2.5]
        assert f["StrCol"].kind == BYTES_LIST and f["StrCol"].values == [b"r1"]
        assert f["BinCol"].values == [b"\x01\x02"]
        assert f["IntListCol"].values == [1, 2]
        assert f["LongListCol"].values == [3, 4]
        assert f["FloatListCol"].values == [2.5, 5.0]
        assert f["DoubleListCol"].values == [3.0, 7.5]
        assert f["DecimalListCol"].values == [1.5, 2.0]
        assert f["StrListCol"].values == [b"a", b"b"]
        assert f["BinListCol"].values == [b"x", b"yz"]

    def test_double_downcast_to_float32(self):
        schema = StructType([StructField("d", DoubleType())])
        ex = TFRecordSerializer(schema).serialize_example([1.0 + 1e-12])
        assert ex.features["d"].values == [np.float32(1.0 + 1e-12)]

    def test_null_nullable_field_omitted(self):
        """TFRecordSerializerTest.scala:247-288."""
        ser = TFRecordSerializer(COMPLEX_SCHEMA)
        row = list(COMPLEX_ROW)
        row[2] = None
        ex = ser.serialize_example(row)
        assert "FloatCol" not in ex.features
        assert "LongCol" in ex.features

    def test_null_non_nullable_raises(self):
        """TFRecordSerializerTest.scala:229-245."""
        schema = StructType([StructField("x", LongType(), nullable=False)])
        with pytest.raises(NullValueError):
            TFRecordSerializer(schema).serialize_example([None])

    def test_unsupported_type_raises_at_construction(self):
        """TFRecordSerializerTest.scala:290-299."""

        class BogusType:
            pass

        schema = StructType.__new__(StructType)
        schema.fields = (StructField("bad", BogusType(), True),)  # type: ignore[arg-type]
        schema._index = {"bad": 0}
        with pytest.raises(UnsupportedDataTypeError):
            TFRecordSerializer(schema)

    def test_nested_array_in_example_raises(self):
        schema = StructType([StructField("m", ArrayType(ArrayType(LongType())))])
        ser = TFRecordSerializer(schema)
        with pytest.raises(UnsupportedDataTypeError):
            ser.serialize_example([[[1, 2], [3]]])

    def test_null_array_element_raises(self):
        schema = StructType([StructField("a", ArrayType(StringType()))])
        with pytest.raises(NullValueError):
            TFRecordSerializer(schema).serialize_example([["ok", None]])

    def test_byte_array_passthrough(self):
        schema = StructType([StructField("byteArray", BinaryType())])
        ser = TFRecordSerializer(schema)
        assert ser.serialize_byte_array([b"raw-proto-bytes"]) == b"raw-proto-bytes"
        with pytest.raises(TypeError):
            ser.serialize_byte_array(["not-bytes"])


class TestSerializeSequenceExample:
    """Mirrors TFRecordSerializerTest.scala:143-227."""

    SCHEMA = StructType(
        [
            StructField("id", LongType()),
            StructField("name", StringType()),
            StructField("LongArrayOfArray", ArrayType(ArrayType(LongType()))),
            StructField("FloatArrayOfArray", ArrayType(ArrayType(FloatType()))),
            StructField("DoubleArrayOfArray", ArrayType(ArrayType(DoubleType()))),
            StructField("DecimalArrayOfArray", ArrayType(ArrayType(DecimalType()))),
            StructField("StrArrayOfArray", ArrayType(ArrayType(StringType()))),
            StructField("BinArrayOfArray", ArrayType(ArrayType(BinaryType()))),
        ]
    )

    ROW = [
        7,
        "seq",
        [[1, 2], [3]],
        [[1.5], [2.5, 3.5]],
        [[4.0]],
        [[decimal.Decimal("0.5")]],
        [["a"], ["b", "c"]],
        [[b"z"]],
    ]

    def test_context_vs_feature_lists_split(self):
        se = TFRecordSerializer(self.SCHEMA).serialize_sequence_example(self.ROW)
        assert set(se.context) == {"id", "name"}
        assert set(se.feature_lists) == {
            "LongArrayOfArray",
            "FloatArrayOfArray",
            "DoubleArrayOfArray",
            "DecimalArrayOfArray",
            "StrArrayOfArray",
            "BinArrayOfArray",
        }
        ll = se.feature_lists["LongArrayOfArray"].feature
        assert [f.values for f in ll] == [[1, 2], [3]]
        fl = se.feature_lists["FloatArrayOfArray"].feature
        assert [f.values for f in fl] == [[1.5], [2.5, 3.5]]
        sl = se.feature_lists["StrArrayOfArray"].feature
        assert [f.values for f in sl] == [[b"a"], [b"b", b"c"]]

    def test_scalar_arrays_go_to_context(self):
        schema = StructType([StructField("arr", ArrayType(FloatType()))])
        se = TFRecordSerializer(schema).serialize_sequence_example([[1.0, 2.0]])
        assert "arr" in se.context
        assert se.feature_lists == {}


def float_feature(vals):
    return Feature.float_list(vals)


class TestDeserializeExample:
    """Mirrors TFRecordDeserializerTest.scala:61-111, 164-253."""

    def test_complex_example_to_row(self):
        ser = TFRecordSerializer(COMPLEX_SCHEMA)
        de = TFRecordDeserializer(COMPLEX_SCHEMA)
        row = de.deserialize_example(ser.serialize_example(COMPLEX_ROW))
        assert row[0] == 1
        assert row[1] == 23
        assert row[2] == 10.0
        assert row[3] == 14.0
        assert float(row[4]) == 2.5 and isinstance(row[4], decimal.Decimal)
        assert row[5] == "r1"
        assert row[6] == b"\x01\x02"
        assert row[7] == [1, 2]
        assert row[8] == [3, 4]
        assert row[9] == [2.5, 5.0]
        assert row[10] == [3.0, 7.5]
        assert [float(v) for v in row[11]] == [1.5, 2.0]
        assert row[12] == ["a", "b"]
        assert row[13] == [b"x", b"yz"]

    def test_missing_nullable_is_none(self):
        schema = StructType([StructField("absent", FloatType())])
        row = TFRecordDeserializer(schema).deserialize_example(Example())
        assert row == [None]

    def test_missing_non_nullable_raises(self):
        schema = StructType([StructField("absent", FloatType(), nullable=False)])
        with pytest.raises(NullValueError):
            TFRecordDeserializer(schema).deserialize_example(Example())

    def test_kind_mismatch_raises(self):
        schema = StructType([StructField("x", FloatType())])
        ex = Example(features={"x": Feature.int64_list([3])})
        with pytest.raises(ValueError, match="FloatList"):
            TFRecordDeserializer(schema).deserialize_example(ex)

    def test_int_truncation_matches_scala_toInt(self):
        schema = StructType([StructField("x", IntegerType())])
        ex = Example(features={"x": Feature.int64_list([2**31 + 10])})
        row = TFRecordDeserializer(schema).deserialize_example(ex)
        assert row[0] == -(2**31) + 10

    def test_state_leak_regression(self):
        """Rows must not inherit values from previous records
        (TFRecordDeserializerTest.scala:313-346)."""
        schema = StructType([StructField("a", LongType()), StructField("b", StringType())])
        de = TFRecordDeserializer(schema)
        full = Example(features={"a": Feature.int64_list([1]), "b": Feature.bytes_list([b"x"])})
        partial = Example(features={"a": Feature.int64_list([2])})
        assert de.deserialize_example(full) == [1, "x"]
        assert de.deserialize_example(partial) == [2, None]

    def test_unsupported_type_raises_at_construction(self):
        class BogusType:
            pass

        schema = StructType.__new__(StructType)
        schema.fields = (StructField("bad", BogusType(), True),)  # type: ignore[arg-type]
        schema._index = {"bad": 0}
        with pytest.raises(UnsupportedDataTypeError):
            TFRecordDeserializer(schema)

    def test_byte_array(self):
        de = TFRecordDeserializer(StructType([StructField("byteArray", BinaryType())]))
        assert de.deserialize_byte_array(b"\x00\x01") == [b"\x00\x01"]


class TestDeserializeSequenceExample:
    """Mirrors TFRecordDeserializerTest.scala:113-162."""

    def test_mixed_context_and_feature_lists(self):
        schema = StructType(
            [
                StructField("id", LongType()),
                StructField("frames", ArrayType(ArrayType(FloatType()))),
                StructField("scalar_list", ArrayType(LongType())),
            ]
        )
        se = SequenceExample(
            context={"id": Feature.int64_list([9])},
            feature_lists={
                "frames": FeatureList([float_feature([1.0, 2.0]), float_feature([3.0])]),
                "scalar_list": FeatureList(
                    [Feature.int64_list([5]), Feature.int64_list([6])]
                ),
            },
        )
        row = TFRecordDeserializer(schema).deserialize_sequence_example(se)
        assert row[0] == 9
        assert row[1] == [[1.0, 2.0], [3.0]]
        # FeatureList of scalar features -> ArrayType(Long) via scalar writer
        assert row[2] == [5, 6]

    def test_context_takes_priority(self):
        schema = StructType([StructField("x", ArrayType(LongType()))])
        se = SequenceExample(
            context={"x": Feature.int64_list([1, 2])},
            feature_lists={"x": FeatureList([Feature.int64_list([9])])},
        )
        row = TFRecordDeserializer(schema).deserialize_sequence_example(se)
        assert row[0] == [1, 2]

    def test_missing_non_nullable_raises(self):
        schema = StructType([StructField("gone", ArrayType(LongType()), nullable=False)])
        with pytest.raises(NullValueError):
            TFRecordDeserializer(schema).deserialize_sequence_example(SequenceExample())


class TestRecordLevelHelpers:
    @pytest.mark.parametrize(
        "record_type,schema,row",
        [
            (RecordType.EXAMPLE, COMPLEX_SCHEMA, COMPLEX_ROW),
            (
                RecordType.SEQUENCE_EXAMPLE,
                TestSerializeSequenceExample.SCHEMA,
                TestSerializeSequenceExample.ROW,
            ),
            (
                RecordType.BYTE_ARRAY,
                StructType([StructField("byteArray", BinaryType())]),
                [b"opaque"],
            ),
        ],
    )
    def test_bytes_round_trip(self, record_type, schema, row):
        ser = TFRecordSerializer(schema)
        de = TFRecordDeserializer(schema)
        data = encode_row(ser, record_type, row)
        back = decode_record(de, record_type, data)
        if record_type == RecordType.BYTE_ARRAY:
            assert back == row
        else:
            for got, want, field in zip(back, row, schema):
                if isinstance(want, decimal.Decimal):
                    assert float(got) == pytest.approx(float(want))
                elif isinstance(want, list) and want and isinstance(want[0], decimal.Decimal):
                    assert [float(v) for v in got] == pytest.approx([float(v) for v in want])
                else:
                    assert got == want, field.name
