"""Subprocess worker for the checkpoint kill -9 chaos matrix
(tests/test_ckpt_chaos.py): run a deterministic train-shaped loop that
checkpoints through the async snapshot/commit path, printing one line per
step so the parent can correlate, and — when the chaos seam is armed via
``TFR_CKPT_CHAOS_STAGE``/``TFR_CKPT_CHAOS_MARK`` — park at the requested
commit stage so the parent can SIGKILL this process at exactly that
point. Relaunched without the seam, the worker resumes from the newest
COMPLETE generation and runs to the step budget; the parent compares its
step/row digests against an uninterrupted reference run.

Modes:
  pytree  AsyncCheckpointer over a numpy pytree (the tentpole path)
  lm      examples/train_lm.py's LMCheckpoint twin (same layout via its
          wrapper — proves the consumer wiring, not just the class)
  state   plain checkpoint.save_state + fsync (the O(1) input-state leg)

The state evolution is a pure function of (step, previous state) and the
per-step "row" digest is a pure function of the step, so a resumed run is
byte-identical to the uninterrupted one iff restore returned a complete,
uncorrupted generation.
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _digest(state: dict) -> str:
    h = hashlib.sha256()
    for k in sorted(state):
        h.update(k.encode())
        h.update(np.ascontiguousarray(state[k]).tobytes())
    return h.hexdigest()[:16]


def _row_digest(step: int) -> str:
    # the "input rows" consumed at this step, derived only from the step
    rng = np.random.default_rng(step)
    return hashlib.sha256(rng.integers(0, 256, 32).tobytes()).hexdigest()[:16]


def _update(state: dict, step: int) -> dict:
    # seeded per (step, key-rank) so the result is independent of dict
    # iteration order (tree.unflatten rebuilds dicts in sorted-key order)
    return {
        k: v * 0.9
        + np.random.default_rng([step, i]).standard_normal(v.shape)
        for i, (k, v) in enumerate(sorted(state.items()))
    }


def _init_state() -> dict:
    return {
        "w": np.arange(96, dtype=np.float64).reshape(8, 12),
        "b": np.zeros(12, dtype=np.float64),
    }


def run_model(mode: str, directory: str, steps: int, save_every: int) -> int:
    from tpu_tfrecord.checkpoint import AsyncCheckpointer

    if mode == "pytree":
        ck = AsyncCheckpointer(
            directory, keep=2, process_index=0, process_count=1
        )
    else:  # the train_lm consumer twin
        sys.path.insert(
            0,
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "examples",
            ),
        )
        from train_lm import LMCheckpoint

        ck = LMCheckpoint(directory)

    template = _init_state()
    start, state, payload = (
        ck.restore(template) if mode == "pytree" else ck.load(template)
    )
    if start is None:
        start = 0
        state = template
    state = {k: np.asarray(v) for k, v in state.items()}
    print(f"resumed {start}", flush=True)
    try:
        for step in range(start + 1, steps + 1):
            state = _update(state, step)
            print(
                f"step {step} state={_digest(state)} rows={_row_digest(step)}",
                flush=True,
            )
            if step % save_every == 0:
                ck.save(step, state, {"rows": _row_digest(step)})
        ck.wait()
        print(f"final {steps} {_digest(state)}", flush=True)
    finally:
        ck.close()
    return 0


def run_state(directory: str, steps: int, save_every: int) -> int:
    from tpu_tfrecord.checkpoint import load_state, save_state
    from tpu_tfrecord.io.dataset import IteratorState

    resume = load_state(directory)
    start = resume.shard_cursor if resume is not None else 0
    print(f"resumed {start}", flush=True)
    for step in range(start + 1, steps + 1):
        print(f"step {step} rows={_row_digest(step)}", flush=True)
        if step % save_every == 0:
            save_state(
                directory,
                IteratorState(
                    epoch=0, shard_cursor=step, record_offset=step * 7
                ),
                step=step,
            )
    print(f"final {steps} {_row_digest(steps)}", flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=("pytree", "lm", "state"))
    ap.add_argument("directory")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save-every", type=int, default=4)
    args = ap.parse_args()
    if args.mode == "state":
        return run_state(args.directory, args.steps, args.save_every)
    return run_model(args.mode, args.directory, args.steps, args.save_every)


if __name__ == "__main__":
    sys.exit(main())
