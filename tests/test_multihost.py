"""Multi-host tests: real jax.distributed processes on CPU.

The TPU-native analog of the reference's cluster behavior (Spark
driver/executor): N OS processes coordinate via jax.distributed, merge
schema partials with the allgather combOp, and assemble one global sharded
array from per-process local batches.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord.schema import FloatType, LongType, StringType, StructField, StructType

SCHEMA = StructType(
    [
        StructField("uid", LongType()),
        StructField("score", FloatType()),
    ]
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_schema_merge_and_global_batch(sandbox, tmp_path):
    num_procs = 2
    data = str(sandbox / "mh")
    # 4 shards; shard i carries disjoint uids; schemas differ per shard so the
    # merge must actually combine (uid everywhere; score only in odd shards)
    for s in range(4):
        if s % 2:
            tfio.write(
                [[s * 10 + i, float(i)] for i in range(8)], SCHEMA, data, mode="append"
            )
        else:
            tfio.write(
                [[s * 10 + i] for i in range(8)],
                StructType([StructField("uid", LongType())]),
                data,
                mode="append",
            )

    port = free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, str(num_procs), str(i), data],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for i in range(num_procs)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                pytest.fail("multihost worker timed out")
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # a failed worker must not orphan its peer on the coordinator port
        for q in procs:
            if q.poll() is None:
                q.kill()

    a, b = sorted(outs, key=lambda o: o["pid"])
    # identical merged schema on every host, containing both columns
    assert a["schema"] == b["schema"]
    assert "score" in a["schema"] and "uid" in a["schema"]
    # shards partitioned disjointly
    assert a["n_shards"] + b["n_shards"] == 4
    # the global array spans both processes' rows
    assert a["global_shape"] == [16]
    assert a["global_sum"] == b["global_sum"]
    # coordinated write: marker appears only after the global barrier, and
    # the combined dataset contains every host's rows
    assert not a["marker_before"] and not b["marker_before"]
    assert a["marker_after"] and b["marker_after"]
    out_dir = os.path.join(os.path.dirname(data), "mh_out")
    combined = tfio.read(out_dir)
    assert sorted(combined.column("uid")) == [0, 1, 2, 3, 1000, 1001, 1002, 1003]
