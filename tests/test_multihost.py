"""Multi-host tests: real jax.distributed processes on CPU.

The TPU-native analog of the reference's cluster behavior (Spark
driver/executor): N OS processes coordinate via jax.distributed, merge
schema partials with the allgather combOp, and assemble one global sharded
array from per-process local batches.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord.schema import FloatType, LongType, StringType, StructField, StructType

SCHEMA = StructType(
    [
        StructField("uid", LongType()),
        StructField("score", FloatType()),
    ]
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize(
    "num_procs,n_shards",
    [
        (2, 4),
        # 4 processes with shard_count % process_count != 0: the regime
        # where rank-arithmetic bugs surface (VERDICT r2 weak #4) — hosts
        # get 2/2/1/1 shards
        (4, 6),
    ],
)
def test_multi_process_schema_merge_and_global_batch(sandbox, tmp_path, num_procs, n_shards):
    data = str(sandbox / "mh")
    # shard i carries disjoint uids; schemas differ per shard so the merge
    # must actually combine (uid everywhere; score only in odd shards)
    for s in range(n_shards):
        if s % 2:
            tfio.write(
                [[s * 10 + i, float(i)] for i in range(8)], SCHEMA, data, mode="append"
            )
        else:
            tfio.write(
                [[s * 10 + i] for i in range(8)],
                StructType([StructField("uid", LongType())]),
                data,
                mode="append",
            )

    port = free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, str(num_procs), str(i), data],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for i in range(num_procs)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=360)
            except subprocess.TimeoutExpired:
                pytest.fail("multihost worker timed out")
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # a failed worker must not orphan its peers on the coordinator port
        for q in procs:
            if q.poll() is None:
                q.kill()

    outs.sort(key=lambda o: o["pid"])
    first = outs[0]
    # identical merged schema on every host, containing both columns
    assert all(o["schema"] == first["schema"] for o in outs)
    assert "score" in first["schema"] and "uid" in first["schema"]
    # shards partitioned disjointly and completely, even when
    # n_shards % num_procs != 0
    assert sum(o["n_shards"] for o in outs) == n_shards
    assert max(o["n_shards"] for o in outs) - min(o["n_shards"] for o in outs) <= 1
    # the global array spans every process's rows
    assert first["global_shape"] == [8 * num_procs]
    assert all(o["global_sum"] == first["global_sum"] for o in outs)
    # every host resumed mid-stream from a fingerprinted state without
    # dropping or duplicating rows, and hosts together saw all records
    assert all(o["resume_ok"] for o in outs)
    # per-host windowed row shuffle: mid-window resume exact, coverage
    # identical to the unshuffled stream, order actually permuted
    assert all(o["shuffle_ok"] for o in outs)
    # shared trace id: every host adopted process 0's over the allgather;
    # process 0 is the root (no parent), the rest point at its root span
    assert len({o["trace_id"] for o in outs}) == 1
    assert first["trace_parent"] is None
    assert all(o["trace_parent"] for o in outs[1:])
    assert sum(o["host_rows_total"] for o in outs) == 8 * n_shards
    # coordinated write: marker appears only after the global barrier, and
    # the combined dataset contains every host's rows
    assert not any(o["marker_before"] for o in outs)
    assert all(o["marker_after"] for o in outs)
    out_dir = os.path.join(os.path.dirname(data), "mh_out")
    combined = tfio.read(out_dir)
    want = sorted(1000 * p + v for p in range(num_procs) for v in range(4))
    assert sorted(combined.column("uid")) == want
    # coordinated partitionBy write: col=value layout with one _SUCCESS,
    # partition column merged back on read
    part_dir = os.path.join(os.path.dirname(data), "mh_part")
    layout = {d for d in os.listdir(part_dir) if d.startswith("par=")}
    assert layout == {"par=0", "par=1"}
    assert tfio.has_success_marker(part_dir)
    part = tfio.read(part_dir)
    assert sorted(part.column("uid")) == want
    by_par = {r["uid"]: r["par"] for r in part.to_dicts()}
    assert all(par == uid % 2 for uid, par in by_par.items())


@pytest.mark.slow
def test_dryrun_multichip_multiprocess(monkeypatch):
    """The driver's checked entry point in multi-process mode: 2
    jax.distributed processes x 4 CPU devices, shared dataset, full
    dp/tp/sp train step + cross-process ring attention (VERDICT r2
    next-step #3). The spawner must not touch the ambient backend."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from __graft_entry__ import dryrun_multichip

    monkeypatch.setenv("TFR_DRYRUN_PROCS", "2")
    dryrun_multichip(8)  # raises on any child failure


@pytest.mark.slow
def test_infer_error_propagates_to_all_hosts(sandbox):
    """A corrupt shard in ONE process's inference slice must fail EVERY
    process with the same DistributedInferenceError naming the culprit —
    not hang the healthy peers in the allgather (code-review r5 finding:
    a pre-collective raise on one host deadlocks the rest)."""
    data = str(sandbox / "mh_err")
    for s in range(2):
        tfio.write([[s * 10 + i] for i in range(8)],
                   StructType([StructField("uid", LongType())]),
                   data, mode="append")
    # corrupt the SECOND part file in sorted order = process 1's slice
    # (assign_shards interleaves the sorted global order)
    parts = sorted(n for n in os.listdir(data) if n.startswith("part-"))
    assert len(parts) == 2
    victim = os.path.join(data, parts[1])
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(blob))

    port = free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "multihost_infer_error_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, "2", str(i), data],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=180)  # a hang fails here
            except subprocess.TimeoutExpired:
                pytest.fail("worker hung: inference error did not propagate")
            assert p.returncode == 7, (
                f"pid {i} rc={p.returncode}\nstdout:{out[-1000:]}\nstderr:{err[-1000:]}"
            )
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
