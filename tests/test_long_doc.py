"""Long-document classifier: ring attention inside a full sharded train
step, fed by SequenceExample ingestion (8-device CPU mesh)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord.models import long_doc
from tpu_tfrecord.tpu.mesh import create_mesh

CFG = long_doc.LongDocConfig(
    seq_dim=8, d_model=16, n_heads=2, n_layers=2, n_classes=2, max_len=16,
    dtype=jnp.float32,
)


def _mesh(data=2, seq=4):
    return create_mesh({"data": data, "seq": seq}, jax.devices()[: data * seq])


class TestForward:
    def test_ring_matches_dense_reference(self):
        """forward(mesh) (ring attention, SP-sharded) must equal
        forward(None) (dense oracle) on identical weights and batch."""
        mesh = _mesh()
        params = long_doc.init_params(jax.random.key(0), CFG)
        hb = long_doc.make_synthetic_batch(CFG, 8, seed=1)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        want = long_doc.forward(params, batch, CFG)  # dense reference
        sh = long_doc.batch_shardings(mesh, hb)
        sharded = {
            k: jax.device_put(v, sh[k]) for k, v in batch.items()
        }
        got = jax.jit(
            functools.partial(
                long_doc.forward, cfg=CFG, mesh=mesh, data_axis="data"
            )
        )(params, sharded)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_padding_is_inert_moe_flavor(self):
        """The MoE FFN must keep the dense flavor's contract: logits (and
        the aux loss) depend ONLY on valid positions — padding content must
        neither route through experts nor consume their capacity."""
        import dataclasses

        cfg = dataclasses.replace(CFG, moe_experts=4, moe_capacity_factor=0.5)
        params = long_doc.init_params(jax.random.key(0), cfg)
        hb = long_doc.make_synthetic_batch(cfg, 8, seed=5)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        base, aux_base = long_doc.forward(params, batch, cfg, with_aux=True)
        frames = np.asarray(batch["frames"]).copy()
        lengths = np.asarray(batch["frames_len"])
        for i, n in enumerate(lengths):
            frames[i, n:] = 1e3  # garbage in every padded position
        poisoned = dict(batch, frames=jnp.asarray(frames))
        got, aux_got = long_doc.forward(params, poisoned, cfg, with_aux=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(base), rtol=1e-5)
        np.testing.assert_allclose(float(aux_got), float(aux_base), rtol=1e-6)

    def test_padding_is_inert(self):
        """Changing bytes past frames_len must not change the logits."""
        params = long_doc.init_params(jax.random.key(0), CFG)
        hb = long_doc.make_synthetic_batch(CFG, 4, seed=2)
        hb["frames_len"] = np.minimum(hb["frames_len"], CFG.max_len // 2)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        base = long_doc.forward(params, batch, CFG)
        hb2 = dict(hb)
        frames2 = hb["frames"].copy()
        frames2[:, CFG.max_len // 2 :] = 99.0  # garbage in the padding
        hb2["frames"] = frames2
        batch2 = {k: jnp.asarray(v) for k, v in hb2.items()}
        out2 = long_doc.forward(params, batch2, CFG)
        np.testing.assert_allclose(np.asarray(base), np.asarray(out2), rtol=1e-5)


class TestTraining:
    def test_sharded_training_decreases_loss(self):
        import optax

        mesh = _mesh()
        params = long_doc.init_params(jax.random.key(0), CFG)
        tx = optax.adam(3e-3)
        opt_state = tx.init(params)
        p_sh = long_doc.param_shardings(mesh, params)
        params = jax.device_put(params, p_sh)
        opt_state = jax.device_put(
            opt_state, jax.tree.map(lambda _: p_sh["pos"], opt_state)
        )
        hb = long_doc.make_synthetic_batch(CFG, 16, seed=3)
        b_sh = long_doc.batch_shardings(mesh, hb)
        batch = {k: jax.device_put(jnp.asarray(v), b_sh[k]) for k, v in hb.items()}
        step = jax.jit(
            functools.partial(
                long_doc.train_step, cfg=CFG, tx=tx, mesh=mesh, data_axis="data"
            ),
            donate_argnums=(0, 1),
        )
        first = float(
            long_doc.loss_fn(
                jax.device_put(long_doc.init_params(jax.random.key(0), CFG), p_sh),
                batch, CFG, mesh, data_axis="data",
            )
        )
        for _ in range(25):
            params, opt_state, loss = step(params, opt_state, batch)
        assert float(loss) < first

    def test_end_to_end_from_sequence_example_files(self, sandbox, tmp_path):
        """The full long-context path: ragged SequenceExample shards ->
        TFRecordDataset -> pad/bucket -> seq-sharded global batch -> one
        ring-attention train step."""
        import optax

        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.schema import (
            ArrayType,
            FloatType,
            LongType,
            StructField,
            StructType,
        )
        from tpu_tfrecord.tpu.ingest import host_batch_from_columnar

        schema = StructType(
            [
                StructField("label", LongType(), nullable=False),
                StructField("frames", ArrayType(ArrayType(FloatType()))),
            ]
        )
        rng = np.random.default_rng(5)
        rows = []
        for _ in range(16):
            n = int(rng.integers(1, CFG.max_len + 1))
            frames = [[float(x) for x in rng.normal(size=CFG.seq_dim)] for _ in range(n)]
            rows.append([int(rng.integers(0, CFG.n_classes)), frames])
        out = str(sandbox / "docs")
        tfio.write(rows, schema, out, mode="overwrite", recordType="SequenceExample")

        mesh = _mesh()
        ds = TFRecordDataset(out, batch_size=16, schema=schema,
                             recordType="SequenceExample")
        with ds.batches() as it:
            cb = next(it)
        hb = host_batch_from_columnar(
            cb, ds.schema, pad_to={"frames": (CFG.max_len, CFG.seq_dim)}
        )
        hb.pop("frames_inner_len")
        b_sh = long_doc.batch_shardings(mesh, hb)
        batch = {
            k: jax.make_array_from_process_local_data(b_sh[k], v)
            for k, v in hb.items()
        }
        params = long_doc.init_params(jax.random.key(1), CFG)
        tx = optax.sgd(1e-2)
        opt_state = tx.init(params)
        step = jax.jit(
            functools.partial(
                long_doc.train_step, cfg=CFG, tx=tx, mesh=mesh, data_axis="data"
            )
        )
        params, opt_state, loss = step(params, opt_state, batch)
        assert np.isfinite(float(loss))

    def test_remat_grads_match_non_remat(self):
        """jax.checkpoint changes memory, never math: gradients with
        remat=True must equal the plain backward."""
        import dataclasses

        params = long_doc.init_params(jax.random.key(0), CFG)
        hb = long_doc.make_synthetic_batch(CFG, 8, seed=4)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        cfg_r = dataclasses.replace(CFG, remat=True)
        g_plain = jax.grad(lambda p: long_doc.loss_fn(p, batch, CFG))(params)
        g_remat = jax.grad(lambda p: long_doc.loss_fn(p, batch, cfg_r))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            g_plain,
            g_remat,
        )

    def test_remat_grads_match_on_ring_attention_mesh(self):
        """remat=True is FOR the long-context SP path: jax.checkpoint must
        compile and differentiate through the shard_map + ppermute ring and
        produce the same gradients as the non-remat sharded backward."""
        import dataclasses

        mesh = _mesh()
        params = long_doc.init_params(jax.random.key(0), CFG)
        hb = long_doc.make_synthetic_batch(CFG, 8, seed=5)
        sh = long_doc.batch_shardings(mesh, hb)
        batch = {k: jax.device_put(jnp.asarray(v), sh[k]) for k, v in hb.items()}
        cfg_r = dataclasses.replace(CFG, remat=True)
        g_plain = jax.jit(
            jax.grad(
                lambda p: long_doc.loss_fn(p, batch, CFG, mesh, data_axis="data")
            )
        )(params)
        g_remat = jax.jit(
            jax.grad(
                lambda p: long_doc.loss_fn(p, batch, cfg_r, mesh, data_axis="data")
            )
        )(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            ),
            g_plain,
            g_remat,
        )

    def test_ring_hlo_has_collective_permute_no_allgather(self):
        """The SP path must ride ICI neighbor hops, not gather the sequence."""
        mesh = _mesh()
        params = long_doc.init_params(jax.random.key(0), CFG)
        hb = long_doc.make_synthetic_batch(CFG, 8, seed=1)
        b_sh = long_doc.batch_shardings(mesh, hb)
        batch = {k: jax.device_put(jnp.asarray(v), b_sh[k]) for k, v in hb.items()}
        from hlo_util import assert_hlo

        fn = jax.jit(
            functools.partial(
                long_doc.forward, cfg=CFG, mesh=mesh, data_axis="data"
            )
        )
        assert_hlo(
            fn, (params, batch),
            contains=["collective-permute"], absent=["all-gather"],
        )


class TestUlyssesFlavor:
    def test_ulysses_matches_dense_reference_end_to_end(self):
        """cfg.sp_attention='ulysses' routes the blocks through the
        all-to-all SP attention; logits must equal the dense oracle (and
        therefore the ring flavor) on identical weights and batch. n_heads=2
        covers the 2-way seq axis."""
        import dataclasses

        cfg = dataclasses.replace(CFG, sp_attention="ulysses")
        mesh = _mesh(data=2, seq=2)
        params = long_doc.init_params(jax.random.key(0), cfg)
        hb = long_doc.make_synthetic_batch(cfg, 8, seed=1)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        want = long_doc.forward(params, batch, cfg)  # dense reference
        sh = long_doc.batch_shardings(mesh, hb)
        sharded = {k: jax.device_put(v, sh[k]) for k, v in batch.items()}
        got = jax.jit(
            functools.partial(long_doc.forward, cfg=cfg, mesh=mesh, data_axis="data")
        )(params, sharded)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_bad_flavor_rejected(self):
        import dataclasses

        cfg = dataclasses.replace(CFG, sp_attention="flash")
        with pytest.raises(ValueError, match="sp_attention"):
            long_doc.init_params(jax.random.key(0), cfg)
        # a config mutated AFTER init_params must fail in forward too, not
        # silently run the ring flavor (code-review r5 finding)
        params = long_doc.init_params(jax.random.key(0), CFG)
        hb = long_doc.make_synthetic_batch(CFG, 4, seed=0)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        with pytest.raises(ValueError, match="sp_attention"):
            long_doc.forward(params, batch, cfg, mesh=_mesh(data=2, seq=2))


class TestMoEFlavor:
    """moe_experts > 0 swaps the blocks' FFN for the Switch MoE layer
    (models.moe) — SP attention and EP FFN compose in one model."""

    def _cfg(self, **kw):
        import dataclasses

        return dataclasses.replace(
            CFG, moe_experts=4, moe_aux_weight=0.01, **kw
        )

    def test_aux_loss_flows(self):
        cfg = self._cfg()
        params = long_doc.init_params(jax.random.key(0), cfg)
        assert "moe" in params["layers"][0] and "mlp_in" not in params["layers"][0]
        hb = long_doc.make_synthetic_batch(cfg, 8, seed=1)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        logits, aux = long_doc.forward(params, batch, cfg, with_aux=True)
        assert logits.shape == (8, cfg.n_classes)
        assert float(aux) > 0  # load-balance loss accumulated across layers
        # dense flavor reports exactly zero aux
        dp = long_doc.init_params(jax.random.key(0), CFG)
        _, aux0 = long_doc.forward(dp, batch, CFG, with_aux=True)
        assert float(aux0) == 0.0

    def test_ep_sharded_params_match_replicated(self):
        from tpu_tfrecord.models import moe as moe_mod

        cfg = self._cfg()
        mesh = create_mesh({"data": 2, "seq": 2, "expert": 2})
        params = long_doc.init_params(jax.random.key(0), cfg)
        hb = long_doc.make_synthetic_batch(cfg, 8, seed=2)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        want = long_doc.forward(params, batch, cfg)
        sh = moe_mod.param_shardings(mesh, expert_axis="expert")
        p_sh = dict(params)
        p_sh["layers"] = [
            {**layer, "moe": {k: jax.device_put(v, sh[k]) for k, v in layer["moe"].items()}}
            for layer in params["layers"]
        ]
        got = jax.jit(
            functools.partial(long_doc.forward, cfg=cfg)
        )(p_sh, batch)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_moe_longdoc_trains_on_sp_mesh(self):
        """Full composition: SP attention (mesh 'seq' axis) + EP-SHARDED
        experts (mesh 'expert' axis) + aux loss in ONE jit train step;
        loss must decrease and the experts must stay partitioned."""
        import optax

        from tpu_tfrecord.models import moe as moe_mod

        cfg = self._cfg()
        mesh = create_mesh({"data": 2, "seq": 2, "expert": 2})
        params = long_doc.init_params(jax.random.key(0), cfg)
        esh = moe_mod.param_shardings(mesh, expert_axis="expert")
        params["layers"] = [
            {**ly, "moe": {k: jax.device_put(v, esh[k]) for k, v in ly["moe"].items()}}
            for ly in params["layers"]
        ]
        tx = optax.adam(3e-3)
        opt = tx.init(params)
        hb = long_doc.make_synthetic_batch(cfg, 16, seed=3)
        batch = {k: jnp.asarray(v) for k, v in hb.items()}
        step = jax.jit(
            functools.partial(
                long_doc.train_step, cfg=cfg, tx=tx, mesh=mesh, data_axis="data"
            )
        )
        first = None
        for _ in range(30):
            params, opt, loss = step(params, opt, batch)
            first = first if first is not None else float(loss)
        assert float(loss) < first, (first, float(loss))
        # the updated expert weights are still EP-partitioned, not gathered
        w = params["layers"][0]["moe"]["w_in"]
        assert w.addressable_shards[0].data.shape[0] == cfg.moe_experts // 2


class TestGQAFlavor:
    def test_gqa_mesh_matches_dense_reference(self):
        """n_kv_heads < n_heads: the SP mesh path must equal the dense
        reference on identical weights/batch (both flavors)."""
        import dataclasses

        # ring takes any Hkv (MQA Hkv=1 here); ulysses also needs
        # Hkv % seq-axis == 0, so it runs GQA with Hkv=2 over 4 q heads
        for flavor, heads, kv in (("ring", 2, 1), ("ulysses", 4, 2)):
            cfg = dataclasses.replace(
                CFG, n_heads=heads, n_kv_heads=kv, sp_attention=flavor
            )
            mesh = _mesh(data=2, seq=2)
            params = long_doc.init_params(jax.random.key(0), cfg)
            hb = long_doc.make_synthetic_batch(cfg, 8, seed=4)
            batch = {k: jnp.asarray(v) for k, v in hb.items()}
            want = long_doc.forward(params, batch, cfg)
            sh = long_doc.batch_shardings(mesh, hb)
            sharded = {k: jax.device_put(v, sh[k]) for k, v in batch.items()}
            got = jax.jit(
                functools.partial(
                    long_doc.forward, cfg=cfg, mesh=mesh, data_axis="data"
                )
            )(params, sharded)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
            )

    def test_kv_heads_shrink_qkv_projection(self):
        import dataclasses

        cfg = dataclasses.replace(CFG, n_kv_heads=1)
        params = long_doc.init_params(jax.random.key(0), cfg)
        dh = cfg.d_model // cfg.n_heads
        assert params["layers"][0]["qkv"]["w"].shape[-1] == (cfg.n_heads + 2) * dh

    def test_indivisible_kv_heads_rejected(self):
        import dataclasses

        cfg = dataclasses.replace(CFG, n_heads=2, n_kv_heads=0)  # fine
        long_doc.init_params(jax.random.key(0), cfg)
        bad = dataclasses.replace(CFG, n_heads=4, n_kv_heads=3)
        with pytest.raises(ValueError, match="n_kv_heads"):
            long_doc.init_params(jax.random.key(0), bad)
