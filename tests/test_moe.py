"""MoE layer with expert parallelism vs the per-token oracle: top-1 and
top-2 routing, capacity semantics, and the pinned all-to-all EP dispatch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tools.graftlint import hlo_contracts
from tpu_tfrecord.models import moe
from tpu_tfrecord.tpu import create_mesh

CFG = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=1.25)


def setup(b=4, t=20, seed=0, cfg=CFG):
    params = moe.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(b, t, cfg.d_model)), dtype=jnp.float32)
    return params, x


class TestMoE:
    def test_matches_per_token_oracle(self):
        params, x = setup()
        y, aux = jax.jit(lambda p, x: moe.moe_apply(p, x, CFG))(params, x)
        want = moe.moe_reference(params, x, CFG)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        assert float(aux) > 0  # load-balance loss is positive by construction

    def test_valid_mask_excludes_padding_everywhere(self):
        """Masked (padding) tokens must not route, consume capacity, or
        feed the aux loss — outputs and aux depend only on valid content.
        Oracle implements the skip independently."""
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=0.5)
        params, x = setup(cfg=cfg)
        rng = np.random.default_rng(9)
        valid = jnp.asarray(rng.random(x.shape[:-1]) < 0.6)
        y, aux = jax.jit(
            lambda p, x, v: moe.moe_apply(p, x, cfg, valid=v)
        )(params, x, valid)
        want = moe.moe_reference(params, x, cfg, valid=valid)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        # invalid rows are exactly zero
        assert np.abs(np.asarray(y)[~np.asarray(valid)]).max() == 0.0
        # poisoning ONLY the masked positions changes nothing
        x2 = jnp.where(valid[..., None], x, 1e3)
        y2, aux2 = jax.jit(
            lambda p, x, v: moe.moe_apply(p, x, cfg, valid=v)
        )(params, x2, valid)
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=1e-5)
        np.testing.assert_allclose(float(aux2), float(aux), rtol=1e-6)

    def test_capacity_drops_tokens_in_arrival_order(self):
        """With capacity_factor tiny, late tokens routed to a full expert
        contribute ZERO (they ride the residual outside the layer) — the
        oracle implements the drop rule independently."""
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=0.3)
        params, x = setup(cfg=cfg)
        y, _ = jax.jit(lambda p, x: moe.moe_apply(p, x, cfg))(params, x)
        want = moe.moe_reference(params, x, cfg)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        # some tokens must actually have been dropped for this test to bite
        flat = np.asarray(y).reshape(-1, cfg.d_model)
        assert (np.abs(flat).sum(axis=-1) == 0).any()

    def test_expert_parallel_sharding_matches(self):
        """Experts sharded over the 'model' axis (EP): same numbers, expert
        weights never replicated."""
        mesh = create_mesh({"data": 2, "model": 4})
        params, x = setup()
        want = moe.moe_reference(params, x, CFG)
        sh = moe.param_shardings(mesh, expert_axis="model")
        p_sh = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        x_sh = jax.device_put(x, NamedSharding(mesh, P("data", None, None)))
        y, _ = jax.jit(lambda p, x: moe.moe_apply(p, x, CFG))(p_sh, x_sh)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        # the expert dim of the weights is genuinely partitioned: each
        # device holds E / axis_size experts, not all E (a regression to
        # replicated would show the full expert dim per shard)
        assert p_sh["w_in"].sharding.spec[0] == "model"
        shard = p_sh["w_in"].addressable_shards[0].data
        assert shard.shape[0] == CFG.n_experts // mesh.shape["model"]

    def test_grads_flow_and_match_shardings(self):
        mesh = create_mesh({"data": 2, "model": 4})
        params, x = setup()
        sh = moe.param_shardings(mesh, expert_axis="model")
        p_sh = {k: jax.device_put(v, sh[k]) for k, v in params.items()}

        def loss(p, x):
            y, aux = moe.moe_apply(p, x, CFG)
            return (y**2).sum() + 0.01 * aux

        g = jax.jit(jax.grad(loss))(p_sh, x)
        g_ref = jax.grad(loss)(params, x)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_bf16_compute(self):
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, dtype=jnp.bfloat16)
        params, x = setup(cfg=cfg)
        y, _ = moe.moe_apply(params, x, cfg)
        assert y.dtype == x.dtype  # output in the input dtype
        want = moe.moe_reference(params, x, cfg)
        np.testing.assert_allclose(np.asarray(y), want, rtol=5e-2, atol=5e-2)


class TestTop2:
    """Top-2 routing against the capacity-semantics oracle: rank-major
    arrival (every first choice queues before any second choice), raw-prob
    gates, capacity-dropped assignments contribute zero."""

    def test_matches_oracle_on_randomized_batches(self):
        cfg = moe.MoEConfig(
            d_model=16, d_ff=32, n_experts=4, capacity_factor=1.25, top_k=2
        )
        for seed in range(5):
            params, x = setup(b=3, t=24, seed=seed, cfg=cfg)
            y, aux = jax.jit(lambda p, x: moe.moe_apply(p, x, cfg))(params, x)
            want = moe.moe_reference(params, x, cfg)
            np.testing.assert_allclose(
                np.asarray(y), want, rtol=1e-4, atol=1e-5, err_msg=f"seed={seed}"
            )
            assert float(aux) > 0

    def test_tight_capacity_drops_second_choices_first(self):
        """Rank-major arrival means a flood of first choices can push
        second choices past capacity but never vice versa: with factor
        small enough to drop SOME assignments, every surviving slot must
        match the oracle, and top-2 output must dominate top-1 (each token
        keeps at least its first-choice contribution)."""
        cfg2 = moe.MoEConfig(
            d_model=16, d_ff=32, n_experts=4, capacity_factor=0.5, top_k=2
        )
        cfg1 = moe.MoEConfig(
            d_model=16, d_ff=32, n_experts=4, capacity_factor=0.5, top_k=1
        )
        params, x = setup(b=4, t=20, seed=11, cfg=cfg2)
        y2, _ = jax.jit(lambda p, x: moe.moe_apply(p, x, cfg2))(params, x)
        want2 = moe.moe_reference(params, x, cfg2)
        np.testing.assert_allclose(np.asarray(y2), want2, rtol=1e-4, atol=1e-5)
        # capacity budget scales with top_k, so the RANK-0 dispatch under
        # top_k=2 is a superset of top_k=1's: oracle pins both exactly
        want1 = moe.moe_reference(params, x, cfg1)
        assert not np.allclose(want1, want2)  # second choices contributed

    def test_valid_mask_composes_with_top2(self):
        cfg = moe.MoEConfig(
            d_model=16, d_ff=32, n_experts=4, capacity_factor=0.75, top_k=2
        )
        params, x = setup(cfg=cfg)
        rng = np.random.default_rng(3)
        valid = jnp.asarray(rng.random(x.shape[:-1]) < 0.6)
        y, aux = jax.jit(
            lambda p, x, v: moe.moe_apply(p, x, cfg, valid=v)
        )(params, x, valid)
        want = moe.moe_reference(params, x, cfg, valid=valid)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        assert np.abs(np.asarray(y)[~np.asarray(valid)]).max() == 0.0
        # poisoning ONLY the masked positions changes nothing
        x2 = jnp.where(valid[..., None], x, 1e3)
        y2, aux2 = jax.jit(
            lambda p, x, v: moe.moe_apply(p, x, cfg, valid=v)
        )(params, x2, valid)
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=1e-5)
        np.testing.assert_allclose(float(aux2), float(aux), rtol=1e-6)

    def test_bad_top_k_rejected(self):
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, top_k=5)
        params, x = setup(cfg=cfg)
        with pytest.raises(ValueError, match="top_k"):
            moe.moe_apply(params, x, cfg)


class TestExplicitEP:
    """moe_apply_ep: the comms-PINNED flavor — tokens and experts sharded
    on the expert axis, dispatch via lax.all_to_all, per-shard capacity."""

    def _sharded(self, mesh, params, x, cfg, expert_axis="expert",
                 x_spec=P(None, "expert", None)):
        sh = moe.param_shardings(mesh, expert_axis=expert_axis)
        p_sh = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        x_sh = jax.device_put(x, NamedSharding(mesh, x_spec))
        return p_sh, x_sh

    def test_matches_per_shard_oracle(self):
        """EP semantics = the oracle run with shards=P: each token shard
        applies its own capacity budget. The stream is 2-D [T, D] so one
        device's shard IS one contiguous oracle block. Randomized batches,
        both top_k."""
        mesh = create_mesh({"expert": 4}, jax.devices()[:4])
        for top_k in (1, 2):
            cfg = moe.MoEConfig(
                d_model=16, d_ff=32, n_experts=4, capacity_factor=0.75,
                top_k=top_k,
            )
            for seed in range(3):
                params, x3 = setup(b=2, t=16, seed=seed, cfg=cfg)
                x = x3.reshape(-1, cfg.d_model)                 # [32, D]
                p_sh, x_sh = self._sharded(
                    mesh, params, x, cfg, x_spec=P("expert", None)
                )
                y, aux = jax.jit(
                    lambda p, x: moe.moe_apply_ep(p, x, cfg, mesh)
                )(p_sh, x_sh)
                want = moe.moe_reference(params, x, cfg, shards=4)
                np.testing.assert_allclose(
                    np.asarray(y), want, rtol=1e-4, atol=1e-5,
                    err_msg=f"top_k={top_k} seed={seed}",
                )
                assert np.isfinite(float(aux))

    def test_hlo_all_to_all_no_all_gather(self):
        """THE pin moe.py's docstring used to claim without asserting: EP
        dispatch lowers to all-to-all; neither tokens nor expert weights
        are ever gathered. Contract + construction live in the shared
        manifest — this test is its tier-1 driver."""
        hlo_contracts.verify("moe_apply_ep")

    def test_expert_weights_stay_partitioned(self):
        mesh = create_mesh({"expert": 4}, jax.devices()[:4])
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4)
        params, x = setup(cfg=cfg)
        p_sh, x_sh = self._sharded(mesh, params, x, cfg)
        y, _ = jax.jit(lambda p, x: moe.moe_apply_ep(p, x, cfg, mesh))(
            p_sh, x_sh
        )
        shard = p_sh["w_in"].addressable_shards[0].data
        assert shard.shape[0] == cfg.n_experts // mesh.shape["expert"]

    def test_composes_with_data_axis(self):
        mesh = create_mesh({"data": 2, "expert": 4})
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, top_k=2)
        # B == data-axis size: each device's shard (one batch row × one
        # T/4 chunk) is one contiguous block of the global flat stream, so
        # oracle shards=8 models the partition exactly
        params, x = setup(b=2, t=16, cfg=cfg)
        p_sh, x_sh = self._sharded(
            mesh, params, x, cfg, x_spec=P("data", "expert", None)
        )
        y, _ = jax.jit(
            lambda p, x: moe.moe_apply_ep(p, x, cfg, mesh, data_axis="data")
        )(p_sh, x_sh)
        want = moe.moe_reference(params, x, cfg, shards=8)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)

    def test_grads_flow_through_all_to_all(self):
        mesh = create_mesh({"expert": 4}, jax.devices()[:4])
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, top_k=2)
        params, x = setup(b=2, t=16, cfg=cfg)
        p_sh, x_sh = self._sharded(mesh, params, x, cfg)

        def loss(p, x):
            y, aux = moe.moe_apply_ep(p, x, cfg, mesh)
            return (y**2).sum() + 0.01 * aux

        g = jax.jit(jax.grad(loss))(p_sh, x_sh)
        for k in g:
            assert np.isfinite(np.asarray(g[k])).all(), k
        # router grads must be nonzero (gates differentiate through probs)
        assert np.abs(np.asarray(g["router"])).max() > 0

    def test_indivisible_shapes_rejected(self):
        mesh = create_mesh({"expert": 4}, jax.devices()[:4])
        params, x = setup(b=2, t=15, cfg=CFG)  # 30 % 4 != 0 on the token dim
        with pytest.raises(ValueError, match="token dim"):
            moe.moe_apply_ep(params, x, CFG, mesh)
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=6)
        params6, x16 = setup(b=2, t=16, cfg=cfg)
        with pytest.raises(ValueError, match="n_experts"):
            moe.moe_apply_ep(params6, x16, cfg, mesh)
