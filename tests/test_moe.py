"""MoE layer with expert parallelism vs the per-token oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_tfrecord.models import moe
from tpu_tfrecord.tpu import create_mesh

CFG = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=1.25)


def setup(b=4, t=20, seed=0, cfg=CFG):
    params = moe.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(b, t, cfg.d_model)), dtype=jnp.float32)
    return params, x


class TestMoE:
    def test_matches_per_token_oracle(self):
        params, x = setup()
        y, aux = jax.jit(lambda p, x: moe.moe_apply(p, x, CFG))(params, x)
        want = moe.moe_reference(params, x, CFG)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        assert float(aux) > 0  # load-balance loss is positive by construction

    def test_valid_mask_excludes_padding_everywhere(self):
        """Masked (padding) tokens must not route, consume capacity, or
        feed the aux loss — outputs and aux depend only on valid content.
        Oracle implements the skip independently."""
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=0.5)
        params, x = setup(cfg=cfg)
        rng = np.random.default_rng(9)
        valid = jnp.asarray(rng.random(x.shape[:-1]) < 0.6)
        y, aux = jax.jit(
            lambda p, x, v: moe.moe_apply(p, x, cfg, valid=v)
        )(params, x, valid)
        want = moe.moe_reference(params, x, cfg, valid=valid)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        # invalid rows are exactly zero
        assert np.abs(np.asarray(y)[~np.asarray(valid)]).max() == 0.0
        # poisoning ONLY the masked positions changes nothing
        x2 = jnp.where(valid[..., None], x, 1e3)
        y2, aux2 = jax.jit(
            lambda p, x, v: moe.moe_apply(p, x, cfg, valid=v)
        )(params, x2, valid)
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=1e-5)
        np.testing.assert_allclose(float(aux2), float(aux), rtol=1e-6)

    def test_capacity_drops_tokens_in_arrival_order(self):
        """With capacity_factor tiny, late tokens routed to a full expert
        contribute ZERO (they ride the residual outside the layer) — the
        oracle implements the drop rule independently."""
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=0.3)
        params, x = setup(cfg=cfg)
        y, _ = jax.jit(lambda p, x: moe.moe_apply(p, x, cfg))(params, x)
        want = moe.moe_reference(params, x, cfg)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        # some tokens must actually have been dropped for this test to bite
        flat = np.asarray(y).reshape(-1, cfg.d_model)
        assert (np.abs(flat).sum(axis=-1) == 0).any()

    def test_expert_parallel_sharding_matches(self):
        """Experts sharded over the 'model' axis (EP): same numbers, expert
        weights never replicated."""
        mesh = create_mesh({"data": 2, "model": 4})
        params, x = setup()
        want = moe.moe_reference(params, x, CFG)
        sh = moe.param_shardings(mesh, expert_axis="model")
        p_sh = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
        x_sh = jax.device_put(x, NamedSharding(mesh, P("data", None, None)))
        y, _ = jax.jit(lambda p, x: moe.moe_apply(p, x, CFG))(p_sh, x_sh)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)
        # the expert dim of the weights is genuinely partitioned: each
        # device holds E / axis_size experts, not all E (a regression to
        # replicated would show the full expert dim per shard)
        assert p_sh["w_in"].sharding.spec[0] == "model"
        shard = p_sh["w_in"].addressable_shards[0].data
        assert shard.shape[0] == CFG.n_experts // mesh.shape["model"]

    def test_grads_flow_and_match_shardings(self):
        mesh = create_mesh({"data": 2, "model": 4})
        params, x = setup()
        sh = moe.param_shardings(mesh, expert_axis="model")
        p_sh = {k: jax.device_put(v, sh[k]) for k, v in params.items()}

        def loss(p, x):
            y, aux = moe.moe_apply(p, x, CFG)
            return (y**2).sum() + 0.01 * aux

        g = jax.jit(jax.grad(loss))(p_sh, x)
        g_ref = jax.grad(loss)(params, x)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_bf16_compute(self):
        cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, dtype=jnp.bfloat16)
        params, x = setup(cfg=cfg)
        y, _ = moe.moe_apply(params, x, cfg)
        assert y.dtype == x.dtype  # output in the input dtype
        want = moe.moe_reference(params, x, cfg)
        np.testing.assert_allclose(np.asarray(y), want, rtol=5e-2, atol=5e-2)
