"""Kill -9 chaos matrix for the async snapshot/commit checkpoint path
(ISSUE 16). A subprocess worker (tests/ckpt_chaos_worker.py) runs a
deterministic checkpointed loop; the chaos seam inside checkpoint.py
parks the writer at an exact commit stage and touches a marker file, the
parent lands SIGKILL there, and a clean relaunch must resume from the
newest COMPLETE generation and finish byte-identical to an uninterrupted
reference run.

Matrix points (each on generation 2 of 3, so a complete generation 1
exists to fall back to):
  snapshot      kill while the caller's thread copies device state
  shard         kill mid-shard-stage (tmp written, not yet renamed)
  pre_manifest  kill after the shard landed, before the manifest
  manifest      kill mid-manifest (manifest tmp fsynced, not renamed)
plus the plain save_state+fsync leg (a torn state write must never
surface: the previous complete state file survives the kill).
"""

import os
import signal
import subprocess
import sys
import time

import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "ckpt_chaos_worker.py")
STEPS, SAVE_EVERY = 12, 4  # generations at 4, 8, 12


def _run(mode, directory, env=None, timeout=120):
    full_env = {**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})}
    return subprocess.run(
        [sys.executable, WORKER, mode, directory,
         "--steps", str(STEPS), "--save-every", str(SAVE_EVERY)],
        capture_output=True, text=True, env=full_env, timeout=timeout,
    )


def _launch_and_kill_at(mode, directory, stage, mark, skip=1):
    """Arm the chaos seam, wait for the worker to park at ``stage``
    (generation ``skip``+1), SIGKILL it there."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TFR_CKPT_CHAOS_STAGE": stage,
        "TFR_CKPT_CHAOS_MARK": mark,
        "TFR_CKPT_CHAOS_SKIP": str(skip),
    }
    p = subprocess.Popen(
        [sys.executable, WORKER, mode, directory,
         "--steps", str(STEPS), "--save-every", str(SAVE_EVERY)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    try:
        deadline = time.time() + 120
        while not os.path.exists(mark):
            if p.poll() is not None:
                out, err = p.communicate()
                raise AssertionError(
                    f"worker exited before parking at {stage}:\n{out}\n{err}"
                )
            if time.time() > deadline:
                raise AssertionError(f"worker never parked at {stage}")
            time.sleep(0.02)
    finally:
        if p.poll() is None:
            os.kill(p.pid, signal.SIGKILL)
        p.wait()


def _digest_lines(stdout):
    """{step: 'state=... rows=...'} from the worker's step lines, plus
    the final digest."""
    steps, final = {}, None
    for line in stdout.splitlines():
        if line.startswith("step "):
            _, n, rest = line.split(" ", 2)
            steps[int(n)] = rest
        elif line.startswith("final "):
            final = line.split(" ", 2)[2]
    return steps, final


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """Uninterrupted run per mode: the byte-identity ground truth."""
    out = {}
    for mode in ("pytree", "lm", "state"):
        d = str(tmp_path_factory.mktemp(f"ref-{mode}"))
        p = _run(mode, d)
        assert p.returncode == 0, p.stderr
        out[mode] = _digest_lines(p.stdout)
    return out


@pytest.mark.parametrize(
    "stage", ["snapshot", "shard", "pre_manifest", "manifest"]
)
def test_kill9_matrix_resumes_complete_generation(
    stage, tmp_path, reference
):
    d = str(tmp_path / "ckpt")
    mark = str(tmp_path / "mark")
    _launch_and_kill_at("pytree", d, stage, mark)

    # the kill interrupted generation 8's commit: generation 4 must be
    # complete, generation 8 must NOT be restorable unless its manifest
    # fully landed (it never does: the seam parks before the rename)
    gens = sorted(n for n in os.listdir(d) if n.startswith("gen-"))
    assert "gen-00000004" in gens
    manifest8 = os.path.join(d, "gen-00000008", "MANIFEST.json")
    assert not os.path.exists(manifest8), (
        f"manifest landed despite kill at {stage}"
    )

    resumed = _run("pytree", d)
    assert resumed.returncode == 0, resumed.stderr
    assert "resumed 4" in resumed.stdout
    steps, final = _digest_lines(resumed.stdout)
    ref_steps, ref_final = reference["pytree"]
    assert final == ref_final, "resumed end state diverged from reference"
    for step, rest in steps.items():
        assert rest == ref_steps[step], f"step {step} diverged on resume"


def test_kill9_lm_twin_mid_commit(tmp_path, reference):
    """The train_lm LMCheckpoint consumer wiring under the same kill."""
    d = str(tmp_path / "ckpt")
    mark = str(tmp_path / "mark")
    _launch_and_kill_at("lm", d, "pre_manifest", mark)
    resumed = _run("lm", d)
    assert resumed.returncode == 0, resumed.stderr
    assert "resumed 4" in resumed.stdout
    _, final = _digest_lines(resumed.stdout)
    assert final == reference["lm"][1]


def test_kill9_state_leg_never_tears(tmp_path, reference):
    """save_state+fsync: a kill parked between fsync and rename leaves
    the PREVIOUS state file intact — load_state resumes from it, never
    from a torn write."""
    d = str(tmp_path / "ckpt")
    mark = str(tmp_path / "mark")
    _launch_and_kill_at("state", d, "state", mark)
    resumed = _run("state", d)
    assert resumed.returncode == 0, resumed.stderr
    assert "resumed 4" in resumed.stdout
    _, final = _digest_lines(resumed.stdout)
    assert final == reference["state"][1]
