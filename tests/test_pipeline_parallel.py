"""GPipe-style pipeline parallelism vs the sequential oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_tfrecord.models import pipeline
from tpu_tfrecord.tpu import create_mesh


def make_stages(n_stages=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    params = {
        "w": jnp.asarray(rng.normal(size=(n_stages, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(n_stages, d)) * 0.1, jnp.float32),
    }

    def stage_fn(p, x):
        return jax.nn.gelu(x @ p["w"] + p["b"])

    return params, stage_fn


class TestPipeline:
    def test_matches_sequential_oracle(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(1).normal(size=(6, 2, 8)), jnp.float32
        )
        want = pipeline.pipeline_reference(stage_fn, params, xs)
        got = jax.jit(
            lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh)
        )(params, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_eight_stages_single_microbatch_edge(self):
        """M=1 (pure bubble) and M > S both reduce to the same math."""
        mesh = create_mesh({"pipe": 8})
        params, stage_fn = make_stages(n_stages=8)
        for m in (1, 12):
            xs = jnp.asarray(
                np.random.default_rng(m).normal(size=(m, 3, 8)), jnp.float32
            )
            want = pipeline.pipeline_reference(stage_fn, params, xs)
            got = pipeline.pipeline_apply(stage_fn, params, xs, mesh)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
            )

    def test_grads_match_sequential(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(2).normal(size=(5, 2, 8)), jnp.float32
        )

        def loss_p(p, xs):
            return (pipeline.pipeline_apply(stage_fn, p, xs, mesh) ** 2).sum()

        def loss_r(p, xs):
            return (pipeline.pipeline_reference(stage_fn, p, xs) ** 2).sum()

        g = jax.jit(jax.grad(loss_p))(params, xs)
        g_ref = jax.grad(loss_r)(params, xs)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_stage_count_mismatch_rejected(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages(n_stages=3)  # != axis size 4
        xs = jnp.zeros((2, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="stack 4 stages"):
            pipeline.pipeline_apply(stage_fn, params, xs, mesh)

    def test_hlo_collective_permute(self):
        """The activation hops must be neighbor collective-permutes, not
        gathers of the stacked stage weights."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.zeros((4, 2, 8), jnp.float32)
        fn = jax.jit(lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh))
        hlo = fn.lower(params, xs).compile().as_text()
        assert "collective-permute" in hlo
