"""GPipe-style pipeline parallelism vs the sequential oracle, plus the
scale-shape pins: sharded input stream, O(mb) collectives, no gathers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hlo_util import per_device_argument_bytes
from tools.graftlint import hlo_contracts
from tpu_tfrecord.models import pipeline
from tpu_tfrecord.tpu import create_mesh


def make_stages(n_stages=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    params = {
        "w": jnp.asarray(rng.normal(size=(n_stages, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(n_stages, d)) * 0.1, jnp.float32),
    }

    def stage_fn(p, x):
        return jax.nn.gelu(x @ p["w"] + p["b"])

    return params, stage_fn


def sharded_args(mesh, params, xs, pipe_axis="pipe"):
    """Place params and the microbatch stream in their pipeline layout:
    stage-sharded weights, pipe-sharded stream (the scale-shape input
    contract — no device holds the full [M, mb, ...] tensor)."""
    p_sh = jax.device_put(params, NamedSharding(mesh, P(pipe_axis)))
    xs_sh = jax.device_put(
        xs, pipeline.microbatch_sharding(mesh, pipe_axis, ndim=xs.ndim)
    )
    return p_sh, xs_sh


class TestPipeline:
    def test_matches_sequential_oracle(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(1).normal(size=(6, 2, 8)), jnp.float32
        )
        want = pipeline.pipeline_reference(stage_fn, params, xs)
        got = jax.jit(
            lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh)
        )(params, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_eight_stages_single_microbatch_edge(self):
        """M=1 (pure bubble) and M > S both reduce to the same math."""
        mesh = create_mesh({"pipe": 8})
        params, stage_fn = make_stages(n_stages=8)
        for m in (1, 12):
            xs = jnp.asarray(
                np.random.default_rng(m).normal(size=(m, 3, 8)), jnp.float32
            )
            want = pipeline.pipeline_reference(stage_fn, params, xs)
            got = pipeline.pipeline_apply(stage_fn, params, xs, mesh)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
            )

    def test_grads_match_sequential(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(2).normal(size=(5, 2, 8)), jnp.float32
        )

        def loss_p(p, xs):
            return (pipeline.pipeline_apply(stage_fn, p, xs, mesh) ** 2).sum()

        def loss_r(p, xs):
            return (pipeline.pipeline_reference(stage_fn, p, xs) ** 2).sum()

        g = jax.jit(jax.grad(loss_p))(params, xs)
        g_ref = jax.grad(loss_r)(params, xs)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_stage_count_mismatch_rejected(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages(n_stages=3)  # != axis size 4
        xs = jnp.zeros((2, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="stack 4 stages"):
            pipeline.pipeline_apply(stage_fn, params, xs, mesh)


class TestScaleShape:
    """The GSPMD contract the rebuild exists for: per-device memory and
    communication scale with the SHARD of the microbatch stream, never the
    global [M, mb, ...] tensor (the old construction replicated it to
    every stage and psum-broadcast the output)."""

    def _jitted(self, mesh, stage_fn):
        return jax.jit(
            lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh)
        )

    def test_hlo_collective_permute_no_gather_no_reduce(self):
        """Activation/feed/output movement must be neighbor permutes of ONE
        microbatch slice: no all-gather of the stream, and no all-reduce —
        the old full-[M, mb, ...] psum broadcast is gone. The pin (required
        and forbidden collectives AND the canonical construction) lives in
        the shared manifest — this test is its tier-1 driver."""
        hlo_contracts.verify("pipeline_feed_ring")

    def test_per_device_input_flat_as_pipeline_grows(self):
        """Weak scaling — the scale shape itself: grow the machine (S) and
        the stream with it (M = 2S, fixed microbatches per stage) and ONE
        device's compiled argument bytes stay FLAT. The old replicated
        layout grew linearly in M even at fixed per-stage load."""
        sizes = []
        for s in (2, 4, 8):
            mesh = create_mesh({"pipe": s}, jax.devices()[:s])
            params, stage_fn = make_stages(n_stages=s)
            xs = jnp.zeros((2 * s, 2, 8), jnp.float32)
            p_sh, xs_sh = sharded_args(mesh, params, xs)
            sizes.append(
                per_device_argument_bytes(
                    self._jitted(mesh, stage_fn), p_sh, xs_sh
                )
            )
        assert sizes[0] == sizes[1] == sizes[2], sizes

    def test_per_device_input_is_the_shard(self):
        """Fixed S: growing M adds exactly mb_bytes/S per microbatch to one
        device (the 1/S shard slope; the old replicated input's slope was
        the full mb_bytes)."""
        s = 4
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(n_stages=s)
        mb_bytes = 2 * 8 * 4  # [2, 8] f32 slice
        got = {}
        for m in (8, 16):
            xs = jnp.zeros((m, 2, 8), jnp.float32)
            p_sh, xs_sh = sharded_args(mesh, params, xs)
            got[m] = per_device_argument_bytes(
                self._jitted(mesh, stage_fn), p_sh, xs_sh
            )
        assert got[16] - got[8] == (16 - 8) * mb_bytes // s, got

    def test_microbatch_sharding_is_block_layout(self):
        """Device d holds microbatches [d*R, (d+1)*R) and nothing else."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        xs = jnp.arange(8 * 2 * 8, dtype=jnp.float32).reshape(8, 2, 8)
        xs_sh = jax.device_put(
            xs, pipeline.microbatch_sharding(mesh, ndim=xs.ndim)
        )
        for d, shard in enumerate(xs_sh.addressable_shards):
            assert shard.data.shape == (2, 2, 8)
            np.testing.assert_array_equal(
                np.asarray(shard.data), np.asarray(xs[2 * d : 2 * d + 2])
            )

    def test_non_divisible_microbatch_count_pads_invisibly(self):
        """M % S != 0 pads internally; the caller-visible result is exact."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(7).normal(size=(7, 2, 8)), jnp.float32
        )
        got = jax.jit(
            lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh)
        )(params, xs)
        want = pipeline.pipeline_reference(stage_fn, params, xs)
        assert got.shape == (7, 2, 8)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )


class TestDpPpComposition:
    """batch_spec shards the PER-MICROBATCH dims over further axes: the
    dp×pp composed mesh ROADMAP #4a names."""

    def test_matches_oracle_on_composed_mesh(self):
        mesh = create_mesh({"pipe": 4, "data": 2})
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(3).normal(size=(8, 4, 8)), jnp.float32
        )
        want = pipeline.pipeline_reference(stage_fn, params, xs)
        p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
        xs_sh = jax.device_put(
            xs,
            pipeline.microbatch_sharding(
                mesh, ndim=xs.ndim, batch_spec=P("data")
            ),
        )
        got = jax.jit(
            lambda p, xs: pipeline.pipeline_apply(
                stage_fn, p, xs, mesh, batch_spec=P("data")
            )
        )(p_sh, xs_sh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_composed_grads_match_sequential(self):
        mesh = create_mesh({"pipe": 4, "data": 2})
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(4).normal(size=(4, 4, 8)), jnp.float32
        )

        def loss_p(p, xs):
            return (
                pipeline.pipeline_apply(
                    stage_fn, p, xs, mesh, batch_spec=P("data")
                )
                ** 2
            ).sum()

        def loss_r(p, xs):
            return (pipeline.pipeline_reference(stage_fn, p, xs) ** 2).sum()

        g = jax.jit(jax.grad(loss_p))(params, xs)
        g_ref = jax.grad(loss_r)(params, xs)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_composed_hlo_still_gather_free(self):
        """dp×pp composition pin, from the shared manifest."""
        hlo_contracts.verify("pipeline_feed_ring_dp")
