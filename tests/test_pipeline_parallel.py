"""GPipe-style pipeline parallelism vs the sequential oracle, plus the
scale-shape pins: sharded input stream, O(mb) collectives, no gathers —
and the INTERLEAVED virtual-stage schedule (stage weights [S, V, ...],
bubble shrinking toward (S-1)/(V·M+S-1), measured per tick)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hlo_util import per_device_argument_bytes
from tools.graftlint import hlo_contracts
from tpu_tfrecord.models import moe, pipeline
from tpu_tfrecord.tpu import create_mesh


def make_stages(n_stages=4, d=8, seed=0, n_virtual=1):
    rng = np.random.default_rng(seed)
    lead = (n_stages, n_virtual) if n_virtual > 1 else (n_stages,)
    params = {
        "w": jnp.asarray(
            rng.normal(size=lead + (d, d)) * 0.5, jnp.float32
        ),
        "b": jnp.asarray(rng.normal(size=lead + (d,)) * 0.1, jnp.float32),
    }

    def stage_fn(p, x):
        return jax.nn.gelu(x @ p["w"] + p["b"])

    return params, stage_fn


def sharded_args(mesh, params, xs, pipe_axis="pipe"):
    """Place params and the microbatch stream in their pipeline layout:
    stage-sharded weights, pipe-sharded stream (the scale-shape input
    contract — no device holds the full [M, mb, ...] tensor). ndim is
    inferred from the stream array itself."""
    p_sh = jax.device_put(params, NamedSharding(mesh, P(pipe_axis)))
    xs_sh = jax.device_put(
        xs, pipeline.microbatch_sharding(mesh, pipe_axis, ndim=xs)
    )
    return p_sh, xs_sh


def interleaved_bubble(n_stages, n_virtual, m):
    """The interleaved schedule's analytic bubble over the REAL stream
    (ragged M included): useful = M·V chunk ticks out of u_last + S."""
    r_last, i_last = (m - 1) // n_stages, (m - 1) % n_stages
    u_last = (
        r_last * n_virtual * n_stages + (n_virtual - 1) * n_stages + i_last
    )
    return 1.0 - m * n_virtual / (u_last + n_stages)


class TestPipeline:
    def test_matches_sequential_oracle(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(1).normal(size=(6, 2, 8)), jnp.float32
        )
        want = pipeline.pipeline_reference(stage_fn, params, xs)
        got = jax.jit(
            lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh)
        )(params, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    def test_eight_stages_single_microbatch_edge(self):
        """M=1 (pure bubble) and M > S both reduce to the same math."""
        mesh = create_mesh({"pipe": 8})
        params, stage_fn = make_stages(n_stages=8)
        for m in (1, 12):
            xs = jnp.asarray(
                np.random.default_rng(m).normal(size=(m, 3, 8)), jnp.float32
            )
            want = pipeline.pipeline_reference(stage_fn, params, xs)
            got = pipeline.pipeline_apply(stage_fn, params, xs, mesh)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
            )

    def test_grads_match_sequential(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(2).normal(size=(5, 2, 8)), jnp.float32
        )

        def loss_p(p, xs):
            return (pipeline.pipeline_apply(stage_fn, p, xs, mesh) ** 2).sum()

        def loss_r(p, xs):
            return (pipeline.pipeline_reference(stage_fn, p, xs) ** 2).sum()

        g = jax.jit(jax.grad(loss_p))(params, xs)
        g_ref = jax.grad(loss_r)(params, xs)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_stage_count_mismatch_rejected(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages(n_stages=3)  # != axis size 4
        xs = jnp.zeros((2, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="stack 4 stages"):
            pipeline.pipeline_apply(stage_fn, params, xs, mesh)


class TestScaleShape:
    """The GSPMD contract the rebuild exists for: per-device memory and
    communication scale with the SHARD of the microbatch stream, never the
    global [M, mb, ...] tensor (the old construction replicated it to
    every stage and psum-broadcast the output)."""

    def _jitted(self, mesh, stage_fn):
        return jax.jit(
            lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh)
        )

    def test_hlo_collective_permute_no_gather_no_reduce(self):
        """Activation/feed/output movement must be neighbor permutes of ONE
        microbatch slice: no all-gather of the stream, and no all-reduce —
        the old full-[M, mb, ...] psum broadcast is gone. The pin (required
        and forbidden collectives AND the canonical construction) lives in
        the shared manifest — this test is its tier-1 driver."""
        hlo_contracts.verify("pipeline_feed_ring")

    def test_per_device_input_flat_as_pipeline_grows(self):
        """Weak scaling — the scale shape itself: grow the machine (S) and
        the stream with it (M = 2S, fixed microbatches per stage) and ONE
        device's compiled argument bytes stay FLAT. The old replicated
        layout grew linearly in M even at fixed per-stage load."""
        sizes = []
        for s in (2, 4, 8):
            mesh = create_mesh({"pipe": s}, jax.devices()[:s])
            params, stage_fn = make_stages(n_stages=s)
            xs = jnp.zeros((2 * s, 2, 8), jnp.float32)
            p_sh, xs_sh = sharded_args(mesh, params, xs)
            sizes.append(
                per_device_argument_bytes(
                    self._jitted(mesh, stage_fn), p_sh, xs_sh
                )
            )
        assert sizes[0] == sizes[1] == sizes[2], sizes

    def test_per_device_input_is_the_shard(self):
        """Fixed S: growing M adds exactly mb_bytes/S per microbatch to one
        device (the 1/S shard slope; the old replicated input's slope was
        the full mb_bytes)."""
        s = 4
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(n_stages=s)
        mb_bytes = 2 * 8 * 4  # [2, 8] f32 slice
        got = {}
        for m in (8, 16):
            xs = jnp.zeros((m, 2, 8), jnp.float32)
            p_sh, xs_sh = sharded_args(mesh, params, xs)
            got[m] = per_device_argument_bytes(
                self._jitted(mesh, stage_fn), p_sh, xs_sh
            )
        assert got[16] - got[8] == (16 - 8) * mb_bytes // s, got

    def test_microbatch_sharding_is_block_layout(self):
        """Device d holds microbatches [d*R, (d+1)*R) and nothing else."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        xs = jnp.arange(8 * 2 * 8, dtype=jnp.float32).reshape(8, 2, 8)
        xs_sh = jax.device_put(
            xs, pipeline.microbatch_sharding(mesh, ndim=xs)
        )
        for d, shard in enumerate(xs_sh.addressable_shards):
            assert shard.data.shape == (2, 2, 8)
            np.testing.assert_array_equal(
                np.asarray(shard.data), np.asarray(xs[2 * d : 2 * d + 2])
            )

    def test_non_divisible_microbatch_count_pads_invisibly(self):
        """M % S != 0 pads internally; the caller-visible result is exact."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(7).normal(size=(7, 2, 8)), jnp.float32
        )
        got = jax.jit(
            lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh)
        )(params, xs)
        want = pipeline.pipeline_reference(stage_fn, params, xs)
        assert got.shape == (7, 2, 8)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )


class TestDpPpComposition:
    """batch_spec shards the PER-MICROBATCH dims over further axes: the
    dp×pp composed mesh ROADMAP #4a names."""

    def test_matches_oracle_on_composed_mesh(self):
        mesh = create_mesh({"pipe": 4, "data": 2})
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(3).normal(size=(8, 4, 8)), jnp.float32
        )
        want = pipeline.pipeline_reference(stage_fn, params, xs)
        p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
        xs_sh = jax.device_put(
            xs,
            pipeline.microbatch_sharding(
                mesh, ndim=xs.ndim, batch_spec=P("data")
            ),
        )
        got = jax.jit(
            lambda p, xs: pipeline.pipeline_apply(
                stage_fn, p, xs, mesh, batch_spec=P("data")
            )
        )(p_sh, xs_sh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_composed_grads_match_sequential(self):
        mesh = create_mesh({"pipe": 4, "data": 2})
        params, stage_fn = make_stages()
        xs = jnp.asarray(
            np.random.default_rng(4).normal(size=(4, 4, 8)), jnp.float32
        )

        def loss_p(p, xs):
            return (
                pipeline.pipeline_apply(
                    stage_fn, p, xs, mesh, batch_spec=P("data")
                )
                ** 2
            ).sum()

        def loss_r(p, xs):
            return (pipeline.pipeline_reference(stage_fn, p, xs) ** 2).sum()

        g = jax.jit(jax.grad(loss_p))(params, xs)
        g_ref = jax.grad(loss_r)(params, xs)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_composed_hlo_still_gather_free(self):
        """dp×pp composition pin, from the shared manifest."""
        hlo_contracts.verify("pipeline_feed_ring_dp")


class TestInterleaved:
    """GSPMD-style interleaved virtual stages (ROADMAP #2): stage weights
    [S, V, ...], device d owning the V round-robin chunks d, d+S, …; the
    schedule must stay oracle-exact while the measured bubble (the
    per-tick occupancy counter, not a closed form) shrinks toward
    (S-1)/(V·M+S-1)."""

    @pytest.mark.parametrize("n_stages", [2, 4])
    @pytest.mark.parametrize("n_virtual", [2, 4])
    @pytest.mark.parametrize("m_kind", ["eq", "2x", "ragged", "one"])
    def test_matches_sequential_oracle_sxvxm(
        self, n_stages, n_virtual, m_kind
    ):
        m = {
            "eq": n_stages,          # one round
            "2x": 2 * n_stages,      # two full rounds
            "ragged": 2 * n_stages + 3,  # non-dividing: padded internally
            "one": 1,                # pure bubble
        }[m_kind]
        mesh = create_mesh({"pipe": n_stages}, jax.devices()[:n_stages])
        params, stage_fn = make_stages(
            n_stages, seed=n_stages + n_virtual, n_virtual=n_virtual
        )
        xs = jnp.asarray(
            np.random.default_rng(m).normal(size=(m, 2, 8)), jnp.float32
        )
        want = pipeline.pipeline_reference(
            stage_fn, params, xs, n_virtual=n_virtual
        )
        if m % n_stages == 0:
            p_sh, xs_sh = sharded_args(mesh, params, xs)
        else:
            # a ragged stream arrives unsharded; pipeline_apply pads it
            # into the block layout internally
            p_sh, xs_sh = params, xs
        got = jax.jit(
            lambda p, x: pipeline.pipeline_apply(
                stage_fn, p, x, mesh, n_virtual=n_virtual
            )
        )(p_sh, xs_sh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_grads_unperturbed_vs_sequential(self):
        """Reverse mode through the interleaved fori_loop (per-tick
        dynamic chunk indexing included) == the sequential composition's
        gradients."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages(n_virtual=2)
        xs = jnp.asarray(
            np.random.default_rng(2).normal(size=(6, 2, 8)), jnp.float32
        )

        def loss_p(p, xs):
            return (
                pipeline.pipeline_apply(
                    stage_fn, p, xs, mesh, n_virtual=2
                ) ** 2
            ).sum()

        def loss_r(p, xs):
            return (
                pipeline.pipeline_reference(stage_fn, p, xs, n_virtual=2)
                ** 2
            ).sum()

        g = jax.jit(jax.grad(loss_p))(params, xs)
        g_ref = jax.grad(loss_r)(params, xs)
        for k in g:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-5
            )

    def test_bubble_shrinks_monotonically_in_v(self):
        """Fixed S and M: the MEASURED bubble (the PR 13 per-tick counter
        reading the interleaved schedule's own occupancy predicate) falls
        strictly as V grows, matching the interleaved analytic within
        1e-6 at every V — the acceptance number."""
        s, m = 4, 8
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        measured = {}
        for v in (1, 2, 4):
            params, stage_fn = make_stages(s, seed=v, n_virtual=v)
            xs = jnp.asarray(
                np.random.default_rng(0).normal(size=(m, 2, 8)), jnp.float32
            )
            out, diag = pipeline.pipeline_apply(
                stage_fn, params, xs, mesh, n_virtual=v, diagnostics=True
            )
            np.testing.assert_allclose(
                np.asarray(out),
                np.asarray(
                    pipeline.pipeline_reference(
                        stage_fn, params, xs, n_virtual=v
                    )
                ),
                rtol=1e-5, atol=1e-6,
            )
            measured[v] = float(diag["bubble_fraction"])
            assert measured[v] == pytest.approx(
                interleaved_bubble(s, v, m), abs=1e-6
            )
            assert measured[v] == pytest.approx(
                (s - 1) / (v * m + s - 1), abs=1e-6
            )
        assert measured[1] > measured[2] > measured[4], measured

    def test_ragged_m_bubble_over_real_microbatches(self):
        """Non-dividing M: padding never counts as useful work — the
        counter reports the bubble of the REAL stream."""
        s, v, m = 4, 2, 11
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        params, stage_fn = make_stages(s, n_virtual=v)
        xs = jnp.asarray(
            np.random.default_rng(3).normal(size=(m, 2, 8)), jnp.float32
        )
        _, diag = pipeline.pipeline_apply(
            stage_fn, params, xs, mesh, n_virtual=v, diagnostics=True
        )
        assert float(diag["bubble_fraction"]) == pytest.approx(
            interleaved_bubble(s, v, m), abs=1e-6
        )
        assert float(diag["useful_ticks"]) == m * v
        assert float(diag["virtual_stages"]) == v

    def test_stage_stack_shape_mismatch_rejected(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        params, stage_fn = make_stages(n_virtual=2)  # [S, 2, ...]
        xs = jnp.zeros((4, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match=r"\[S, V, \.\.\.\]"):
            pipeline.pipeline_apply(
                stage_fn, params, xs, mesh, n_virtual=4
            )

    def test_hlo_collective_permute_only(self):
        """Interleaving may not re-introduce a gather or broadcast of the
        stream; pin + construction live in the shared manifest."""
        hlo_contracts.verify("pipeline_interleaved")

    def test_per_device_input_still_the_shard(self):
        """The scale shape survives interleaving: one device's compiled
        argument bytes are identical at V=1 and V=4 for the same S, M
        (stage weights aside — the stream shard and the in-flight slice
        do not grow with V)."""
        s, m, d = 4, 8, 8
        mesh = create_mesh({"pipe": s}, jax.devices()[:s])
        got = {}
        for v in (1, 4):
            params, stage_fn = make_stages(s, d=d, n_virtual=v)
            xs = jnp.zeros((m, 2, d), jnp.float32)
            p_sh, xs_sh = sharded_args(mesh, params, xs)
            fn = jax.jit(
                lambda p, x, _v=v: pipeline.pipeline_apply(
                    stage_fn, p, x, mesh, n_virtual=_v
                )
            )
            # subtract this V's stage-weight bytes: what remains is the
            # stream shard + loop slices, which must not grow with V
            w_bytes = sum(
                a.size * a.dtype.itemsize for a in jax.tree.leaves(params)
            ) // s
            got[v] = per_device_argument_bytes(fn, p_sh, xs_sh) - w_bytes
        assert got[1] == got[4], got


class TestMicrobatchShardingNdim:
    def test_ndim_inferred_from_stream_array(self):
        """Passing the stream itself (anything with .ndim) matches the
        explicit-int spelling — call sites stop hand-threading
        ndim=xs.ndim."""
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        xs = jnp.zeros((8, 2, 8), jnp.float32)
        by_int = pipeline.microbatch_sharding(mesh, ndim=xs.ndim)
        by_arr = pipeline.microbatch_sharding(mesh, ndim=xs)
        assert by_int == by_arr
        np_arr = np.zeros((8, 2, 8), np.float32)
        assert pipeline.microbatch_sharding(mesh, ndim=np_arr) == by_int

    def test_explicit_int_still_works(self):
        mesh = create_mesh({"pipe": 4}, jax.devices()[:4])
        sh = pipeline.microbatch_sharding(mesh, ndim=2)
        assert sh.spec == P("pipe", None)


class TestEpUnderV:
    """EP composed under V (ISSUE 15): `moe.moe_ep_body` as an interleaved
    virtual-stage chunk inside the pipeline's pipe×expert shard_map — the
    all-to-all dispatch runs INSIDE the schedule, expert weights sharded
    via ``param_spec``, tokens via ``batch_spec``."""

    def _build(self):
        cfg = moe.MoEConfig(
            d_model=16, d_ff=32, n_experts=4, top_k=2, capacity_factor=2.0
        )
        s, v = 2, 2
        keys = jax.random.split(jax.random.key(0), s * v)
        layers = [moe.init_params(k, cfg) for k in keys]
        # chunk order k = v·S + s -> stacked[s][v]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs)
            .reshape((v, s) + xs[0].shape)
            .transpose((1, 0) + tuple(range(2, 2 + xs[0].ndim))),
            *layers,
        )

        def stage_fn(p_chunk, x):  # x [mb_local, T_local, D]
            y, _aux = moe.moe_ep_body(p_chunk, x, cfg, "expert")
            return x + y

        return cfg, layers, stacked, stage_fn

    def test_matches_sequential_ep_layers(self):
        """pipeline(pipe=2, V=2) of 4 MoE chunks == the same 4
        `moe_apply_ep` layers applied sequentially (capacity factor
        leaves headroom, so the differing shard budgets never bind)."""
        cfg, layers, stacked, stage_fn = self._build()
        mesh = create_mesh({"pipe": 2, "expert": 4})
        m, mb, t = 4, 2, 16
        xs = jnp.asarray(
            np.random.default_rng(0).normal(size=(m, mb, t, 16)),
            jnp.float32,
        )
        param_spec = {
            "router": P("pipe", None),
            "w_in": P("pipe", None, "expert", None, None),
            "w_out": P("pipe", None, "expert", None, None),
        }
        got = pipeline.pipeline_apply(
            stage_fn, stacked, xs, mesh, batch_spec=P(None, "expert"),
            n_virtual=2, param_spec=param_spec,
        )
        mesh_e = create_mesh({"expert": 4}, jax.devices()[:4])
        want = xs
        for k in range(4):
            flat = want.reshape(m * mb, t, 16)
            y, _ = moe.moe_apply_ep(layers[k], flat, cfg, mesh_e)
            want = (flat + y).reshape(m, mb, t, 16)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )

    def test_param_spec_must_lead_with_pipe_axis(self):
        """A param_spec leaf not leading with the pipe axis would hand
        every device the full stage stack (silently running stage 0's
        weights everywhere) — rejected loudly instead."""
        cfg, _, stacked, stage_fn = self._build()
        mesh = create_mesh({"pipe": 2, "expert": 4})
        xs = jnp.zeros((4, 2, 16, 16), jnp.float32)
        bad = {
            "router": P(),  # replicated: does not shard the stage dim
            "w_in": P("pipe", None, "expert", None, None),
            "w_out": P("pipe", None, "expert", None, None),
        }
        with pytest.raises(ValueError, match="lead with the pipe axis"):
            pipeline.pipeline_apply(
                stage_fn, stacked, xs, mesh,
                batch_spec=P(None, "expert"), n_virtual=2, param_spec=bad,
            )
        # a None leaf means "replicated" to shard_map and is DROPPED by a
        # naive tree flatten — it must hit the same loud rejection
        bad_none = dict(bad, router=None)
        with pytest.raises(ValueError, match="lead with the pipe axis"):
            pipeline.pipeline_apply(
                stage_fn, stacked, xs, mesh,
                batch_spec=P(None, "expert"), n_virtual=2,
                param_spec=bad_none,
            )

    def test_hlo_all_to_all_inside_schedule_no_gather(self):
        """The composed program carries BOTH contracts at once: the
        pipeline's collective-permute rings and EP's all-to-all dispatch,
        with no all-gather of tokens, stream, or expert weights."""
        cfg, _, stacked, stage_fn = self._build()
        mesh = create_mesh({"pipe": 2, "expert": 4})
        xs = jnp.zeros((4, 2, 16, 16), jnp.float32)
        param_spec = {
            "router": P("pipe", None),
            "w_in": P("pipe", None, "expert", None, None),
            "w_out": P("pipe", None, "expert", None, None),
        }
        p_sh = jax.device_put(
            stacked,
            {
                k: NamedSharding(mesh, param_spec[k])
                for k in ("router", "w_in", "w_out")
            },
        )
        xs_sh = jax.device_put(
            xs,
            pipeline.microbatch_sharding(
                mesh, ndim=xs, batch_spec=P(None, "expert")
            ),
        )
        fn = jax.jit(
            lambda p, x: pipeline.pipeline_apply(
                stage_fn, p, x, mesh, batch_spec=P(None, "expert"),
                n_virtual=2, param_spec=param_spec,
            )
        )
        import hlo_util

        hlo_util.assert_hlo(
            fn, (p_sh, xs_sh),
            contains=("collective-permute", "all-to-all"),
            absent=("all-gather",),
        )
