"""Columnar epoch cache suite (ISSUE 4): container round trips, the
invalidation matrix (source change / decode-affecting option change /
container version bump => miss; irrelevant option change => hit),
byte-identical rows and checkpoint-resume interchange between cached and
uncached reads, the corrupt-cache fallback guarantee, LRU eviction, and
chaos reaching cache-file opens."""

import importlib.util
import json
import os

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import cache as cache_mod
from tpu_tfrecord import wire
from tpu_tfrecord.columnar import batch_to_rows
from tpu_tfrecord.faults import FaultPlan, FaultRule, install_chaos
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import (
    ArrayType,
    FloatType,
    LongType,
    StringType,
    StructField,
    StructType,
)

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),  # nullable: exercises the mask
        StructField("arr", ArrayType(LongType())),  # ragged
    ]
)
# every 7th string is null -> masked-out rows round-trip through the cache
ROWS = [
    [i, None if i % 7 == 0 else f"v{i}" * (i % 3 + 1), list(range(i % 5))]
    for i in range(90)
]
PER_SHARD = 30  # 3 shards from one deterministic write job


@pytest.fixture
def data_dir(sandbox):
    out = str(sandbox / "ds")
    DatasetWriter(out, SCHEMA, mode="overwrite", max_records_per_file=PER_SHARD).write_rows(ROWS)
    return out


@pytest.fixture
def cache_dir(sandbox):
    return str(sandbox / "cache")


def collect(data_dir, state=None, schema=SCHEMA, **kw):
    ds = TFRecordDataset(
        data_dir, batch_size=8, schema=schema, drop_remainder=False,
        num_epochs=1, **kw,
    )
    got = []
    with ds.batches(state) as it:
        for b in it:
            got.extend(batch_to_rows(b, ds.schema))
    return got


def entries_in(cache_dir):
    if not os.path.isdir(cache_dir):
        return []
    return sorted(
        os.path.join(cache_dir, n)
        for n in os.listdir(cache_dir)
        if n.endswith(cache_mod.ENTRY_SUFFIX)
    )


def counters():
    return {
        k: METRICS.counter(f"cache.{k}")
        for k in ("hits", "misses", "bytes_written", "evictions", "corrupt_fallbacks")
    }


class TestOptions:
    def test_parse_cache_knobs(self):
        opts = TFRecordOptions.from_map(
            cache="auto", cacheDir="/tmp/x", cacheMaxBytes="1024"
        )
        assert opts.cache == "auto"
        assert opts.cache_dir == "/tmp/x"
        assert opts.cache_max_bytes == 1024
        snake = TFRecordOptions.from_map(
            cache="auto", cache_dir="/tmp/x", cache_max_bytes=1024
        )
        assert snake == opts

    def test_defaults_off(self):
        opts = TFRecordOptions.from_map()
        assert opts.cache == "off" and opts.cache_dir is None

    def test_bad_values_raise(self):
        with pytest.raises(ValueError, match="cache must be one of"):
            TFRecordOptions.from_map(cache="always")
        with pytest.raises(ValueError, match="cache_max_bytes"):
            TFRecordOptions.from_map(cache_max_bytes=0)


class TestRoundTrip:
    def test_rows_byte_identical_and_counted(self, data_dir, cache_dir):
        base = collect(data_dir)
        METRICS.reset()
        first = collect(data_dir, cache="auto", cache_dir=cache_dir)
        c = counters()
        assert first == base
        assert c["misses"] == 3 and c["hits"] == 0 and c["bytes_written"] > 0
        assert len(entries_in(cache_dir)) == 3
        METRICS.reset()
        served = collect(data_dir, cache="auto", cache_dir=cache_dir)
        c = counters()
        assert served == base
        assert c["hits"] == 3 and c["misses"] == 0 and c["bytes_written"] == 0

    def test_second_epoch_of_one_iterator_is_served(self, data_dir, cache_dir):
        base = collect(data_dir)
        METRICS.reset()
        ds = TFRecordDataset(
            data_dir, batch_size=8, schema=SCHEMA, drop_remainder=False,
            num_epochs=2, cache="auto", cache_dir=cache_dir,
        )
        got = []
        with ds.batches() as it:
            for b in it:
                got.extend(batch_to_rows(b, ds.schema))
        assert got[: len(base)] == base and got[len(base):] == base
        assert METRICS.counter("cache.hits") == 3

    def test_ragged2_sequence_example(self, sandbox):
        schema = StructType(
            [
                StructField("label", LongType(), nullable=False),
                StructField("frames", ArrayType(ArrayType(FloatType()))),
            ]
        )
        rows = [
            [i, [[float(i + j + k) for k in range(3)] for j in range(i % 4)]]
            for i in range(40)
        ]
        out = str(sandbox / "seq")
        DatasetWriter(
            out, schema,
            TFRecordOptions.from_map(recordType="SequenceExample"),
            mode="overwrite", max_records_per_file=20,
        ).write_rows(rows)
        cdir = str(sandbox / "seqcache")
        kw = dict(recordType="SequenceExample")
        base = collect(out, schema=schema, **kw)
        collect(out, schema=schema, cache="auto", cache_dir=cdir, **kw)
        METRICS.reset()
        served = collect(out, schema=schema, cache="auto", cache_dir=cdir, **kw)
        assert served == base and METRICS.counter("cache.hits") == 2

    def test_partitioned_dataset_cached(self, sandbox):
        schema = StructType(
            [
                StructField("id", LongType(), nullable=False),
                StructField("part", StringType(), nullable=False),
            ]
        )
        rows = [[i, f"p{i % 2}"] for i in range(40)]
        out = str(sandbox / "parts")
        tfio.write(rows, schema, out, mode="overwrite", partition_by=["part"])
        cdir = str(sandbox / "pcache")
        base = collect(out, schema=schema)
        collect(out, schema=schema, cache="auto", cache_dir=cdir)
        METRICS.reset()
        served = collect(out, schema=schema, cache="auto", cache_dir=cdir)
        assert served == base and METRICS.counter("cache.hits") > 0

    def test_parallel_workers_and_shuffle_window(self, data_dir, cache_dir):
        base = collect(data_dir, num_workers=3)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        served = collect(data_dir, num_workers=3, cache="auto", cache_dir=cache_dir)
        assert served == base
        shuf_u = collect(data_dir, shuffle=True, seed=5, shuffle_window=2)
        shuf_c = collect(
            data_dir, shuffle=True, seed=5, shuffle_window=2,
            cache="auto", cache_dir=cache_dir,
        )
        assert shuf_u == shuf_c


class TestInvalidation:
    def _shards(self, data_dir):
        return sorted(
            os.path.join(data_dir, n)
            for n in os.listdir(data_dir)
            if n.startswith("part-")
        )

    def _populate(self, data_dir, cache_dir, **kw):
        collect(data_dir, cache="auto", cache_dir=cache_dir, **kw)
        METRICS.reset()

    def test_mtime_change_misses(self, data_dir, cache_dir):
        self._populate(data_dir, cache_dir)
        os.utime(self._shards(data_dir)[0], (12345, 12345))
        served = collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert METRICS.counter("cache.hits") == 2
        assert METRICS.counter("cache.misses") == 1
        assert served == collect(data_dir)
        # the touched shard was repopulated: everything hits again
        METRICS.reset()
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert METRICS.counter("cache.hits") == 3

    def test_size_change_misses_and_serves_new_rows(self, data_dir, cache_dir):
        self._populate(data_dir, cache_dir)
        victim = self._shards(data_dir)[0]
        recs = list(wire.read_records(victim))
        wire.write_records(victim, recs + [recs[0]])  # one extra record
        served = collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert METRICS.counter("cache.misses") == 1
        assert served == collect(data_dir)
        assert len(served) == len(ROWS) + 1

    def test_schema_change_misses(self, data_dir, cache_dir):
        self._populate(data_dir, cache_dir)
        collect(data_dir, columns=["id", "arr"], cache="auto", cache_dir=cache_dir)
        assert METRICS.counter("cache.hits") == 0
        assert METRICS.counter("cache.misses") == 3
        # both fingerprints now coexist as separate entries
        assert len(entries_in(cache_dir)) == 6

    def test_verify_crc_change_misses(self, data_dir, cache_dir):
        self._populate(data_dir, cache_dir)
        collect(data_dir, verify_crc=False, cache="auto", cache_dir=cache_dir)
        assert METRICS.counter("cache.hits") == 0

    def test_irrelevant_option_change_hits(self, data_dir, cache_dir):
        self._populate(data_dir, cache_dir)
        ds = TFRecordDataset(
            data_dir, batch_size=17, schema=SCHEMA, drop_remainder=False,
            num_epochs=1, num_workers=2, prefetch=7, use_mmap=False,
            readahead_bytes=0, slab_bytes=1 << 20, read_retries=2,
            cache="auto", cache_dir=cache_dir,
        )
        got = []
        with ds.batches() as it:
            for b in it:
                got.extend(batch_to_rows(b, ds.schema))
        assert got == collect(data_dir)
        assert METRICS.counter("cache.hits") == 3
        assert METRICS.counter("cache.misses") == 0

    def test_container_version_bump_misses(self, data_dir, cache_dir, monkeypatch):
        self._populate(data_dir, cache_dir)
        monkeypatch.setattr(cache_mod, "VERSION", cache_mod.VERSION + 1)
        served = collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert METRICS.counter("cache.hits") == 0
        assert METRICS.counter("cache.misses") == 3
        assert served == collect(data_dir)

    def test_tolerant_corrupt_policy_disables_cache(self, data_dir, cache_dir):
        got = collect(
            data_dir, on_corrupt="skip_record", cache="auto", cache_dir=cache_dir
        )
        assert got == collect(data_dir)
        assert entries_in(cache_dir) == []


class TestCorruptFallback:
    def _flip_section_byte(self, entry_path, which=0):
        footer = cache_mod.load_footer(entry_path)
        sec = footer["chunks"][0]["columns"][which]["sections"][0][1]
        raw = bytearray(open(entry_path, "rb").read())
        raw[sec["off"]] ^= 0xFF
        open(entry_path, "wb").write(bytes(raw))

    def test_flipped_section_byte_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        self._flip_section_byte(entries_in(cache_dir)[0])
        METRICS.reset()
        served = collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert served == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1
        assert METRICS.counter("cache.hits") == 2
        # the corrupt entry was rewritten in place: clean hits afterwards
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.hits") == 3
        assert METRICS.counter("cache.corrupt_fallbacks") == 0

    def test_truncated_entry_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        entry = entries_in(cache_dir)[0]
        raw = open(entry, "rb").read()
        open(entry, "wb").write(raw[: len(raw) // 2])
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1

    def test_corrupt_footer_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        entry = entries_in(cache_dir)[0]
        raw = bytearray(open(entry, "rb").read())
        raw[-30] ^= 0xFF  # inside the footer JSON / tail
        open(entry, "wb").write(bytes(raw))
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1

    def test_corrupt_source_is_not_cached(self, data_dir, cache_dir):
        victim = self._corrupt_source_shard(data_dir)
        with pytest.raises(wire.TFRecordCorruptionError):
            collect(data_dir, cache="auto", cache_dir=cache_dir)
        # the failed shard's staging was aborted: no committed entry for it,
        # and no staging litter left behind
        fp = cache_mod.decode_fingerprint(
            TFRecordDataset(
                data_dir, batch_size=8, schema=SCHEMA, cache="auto",
                cache_dir=cache_dir,
            )._cache_ident()
        )
        bad = os.path.join(cache_dir, cache_mod.entry_filename(victim, fp))
        assert not os.path.exists(bad)
        temp_root = os.path.join(cache_dir, "_temporary")
        assert not os.path.isdir(temp_root) or os.listdir(temp_root) == []

    def _corrupt_source_shard(self, data_dir):
        victim = sorted(
            os.path.join(data_dir, n)
            for n in os.listdir(data_dir)
            if n.startswith("part-")
        )[0]
        raw = bytearray(open(victim, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(raw))
        return victim


class TestResumeInterchange:
    def _state_after(self, data_dir, n_batches, **kw):
        ds = TFRecordDataset(
            data_dir, batch_size=8, schema=SCHEMA, drop_remainder=False,
            num_epochs=1, **kw,
        )
        it = ds.batches()
        head = []
        for _ in range(n_batches):
            head.extend(batch_to_rows(next(it), ds.schema))
        state = it.state()
        it.close()
        return head, state

    @pytest.mark.parametrize("n_batches", [2, 5])  # mid-shard and cross-shard
    def test_saved_uncached_restored_cached(self, data_dir, cache_dir, n_batches):
        head, state = self._state_after(data_dir, n_batches)
        rest_uncached = collect(data_dir, state=state)
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # populate
        METRICS.reset()
        rest_cached = collect(data_dir, state=state, cache="auto", cache_dir=cache_dir)
        assert rest_cached == rest_uncached
        assert head + rest_cached == collect(data_dir)
        assert METRICS.counter("cache.hits") > 0

    def test_saved_cached_restored_uncached(self, data_dir, cache_dir):
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # populate
        head, state = self._state_after(
            data_dir, 5, cache="auto", cache_dir=cache_dir
        )
        rest = collect(data_dir, state=state)
        assert head + rest == collect(data_dir)

    def test_resume_miss_does_not_populate_partial_entry(self, data_dir, cache_dir):
        # a mid-shard resume with no entry decodes a SUFFIX: caching it
        # would freeze a partial shard — assert nothing was committed for
        # the straddled shard, then a fresh full pass populates all three
        _head, state = self._state_after(data_dir, 2)  # mid shard 0
        assert state.record_offset > 0
        collect(data_dir, state=state, cache="auto", cache_dir=cache_dir)
        assert len(entries_in(cache_dir)) == 2  # shards 1, 2 only
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert len(entries_in(cache_dir)) == 3


class TestEvictionAndHygiene:
    def test_lru_eviction_respects_budget(self, data_dir, cache_dir):
        METRICS.reset()
        collect(data_dir, cache="auto", cache_dir=cache_dir, cache_max_bytes=1)
        # budget of 1 byte: every commit sweeps earlier entries; the
        # just-committed one is protected, so exactly one survives
        assert len(entries_in(cache_dir)) == 1
        assert METRICS.counter("cache.evictions") == 2
        # correctness unaffected: the evicted shards just decode again
        assert (
            collect(data_dir, cache="auto", cache_dir=cache_dir, cache_max_bytes=1)
            == collect(data_dir)
        )

    def test_unbounded_by_default(self, data_dir, cache_dir):
        METRICS.reset()
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert METRICS.counter("cache.evictions") == 0
        assert len(entries_in(cache_dir)) == 3

    def test_chaos_open_fault_on_cache_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        plan = FaultPlan(
            [FaultRule(op="open", kind="transient_error", path=cache_mod.ENTRY_SUFFIX,
                       times=None)],
            seed=7,
        )
        METRICS.reset()
        with install_chaos(plan):
            served = collect(data_dir, cache="auto", cache_dir=cache_dir)
        plan.release()
        assert served == base
        assert METRICS.counter("cache.hits") == 0  # every open faulted -> miss
        assert any(e["op"] == "open" for e in plan.ledger)
        # after the fault clears, the (rewritten) entries serve again
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.hits") == 3


class TestRegistryAndRemote:
    def test_scheme_cache_dir_rejected(self, data_dir):
        with pytest.raises(ValueError, match="cache_dir must be a local path"):
            TFRecordDataset(
                data_dir, batch_size=8, schema=SCHEMA,
                cache="auto", cache_dir="memory://nope/cache",
            )

    def test_registry_skips_reverification_across_datasets(
        self, data_dir, cache_dir, monkeypatch
    ):
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # populate
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # verify+register
        calls = []
        orig = cache_mod.open_entry_file

        def spy(*a, **kw):
            calls.append(a)
            return orig(*a, **kw)

        monkeypatch.setattr(cache_mod, "open_entry_file", spy)
        METRICS.reset()
        served = collect(data_dir, cache="auto", cache_dir=cache_dir)
        assert served == collect(data_dir)
        assert METRICS.counter("cache.hits") == 3
        assert calls == []  # full verification paid once per process, not per dataset

    def test_registry_prunes_superseded_generations(self, data_dir, cache_dir):
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # register gen 1
        victim = sorted(
            os.path.join(data_dir, n)
            for n in os.listdir(data_dir)
            if n.startswith("part-")
        )[0]
        os.utime(victim, (777, 777))  # stale -> repopulate (gen 2, new inode)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # register gen 2
        for entry in entries_in(cache_dir):
            apath = os.path.abspath(entry)
            gens = [k for k in cache_mod._ENTRY_REGISTRY if k[0] == apath]
            assert len(gens) <= 1, gens  # old generation's mmap unpinned

    def test_in_place_flip_same_inode_size_still_detected(self, data_dir, cache_dir):
        # an in-place rewrite keeps inode AND size; mtime in the registry
        # key is what forces re-verification (and the corrupt fallback)
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # register entries
        entry = entries_in(cache_dir)[0]
        footer = cache_mod.load_footer(entry)
        off = footer["chunks"][0]["columns"][0]["sections"][0][1]["off"]
        raw = bytearray(open(entry, "rb").read())
        raw[off] ^= 0xFF
        with open(entry, "r+b") as fh:  # same inode, same size
            fh.write(bytes(raw))
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1

    def test_remote_same_size_rewrite_invalidates(self, sandbox):
        pytest.importorskip("fsspec")
        schema = StructType([StructField("id", LongType(), nullable=False)])
        src = "memory://tfr-cache-test/ds"
        cdir = str(sandbox / "rcache")
        tfio.write([[i] for i in range(20)], schema, src, mode="overwrite")
        first = collect(src, schema=schema, cache="auto", cache_dir=cdir)
        assert first == collect(src, schema=schema, cache="auto", cache_dir=cdir)
        # rewrite with DIFFERENT rows but identical byte length
        tfio.write([[i + 100] for i in range(20)], schema, src, mode="overwrite")
        METRICS.reset()
        served = collect(src, schema=schema, cache="auto", cache_dir=cdir)
        assert [r[0] for r in served] == [i + 100 for i in range(20)]
        assert METRICS.counter("cache.misses") >= 1  # stale, not served

    def test_failed_populator_setup_leaves_no_staging(
        self, data_dir, cache_dir, monkeypatch
    ):
        from tpu_tfrecord.cache import CachePopulator, ShardCache

        cache = ShardCache(cache_dir, ident={"x": 1})

        class MissingShard:
            path = os.path.join(data_dir, "does-not-exist.tfrecord")
            size = 0

        assert cache.populator(MissingShard()) is None  # os.stat fails

        # a failure AFTER the staging dir exists must remove it — the
        # marker names a live pid, so the orphan sweep never would
        def boom(self):
            raise OSError("disk full")

        monkeypatch.setattr(CachePopulator, "_write_marker", boom)

        class RealShard:
            path = sorted(
                os.path.join(data_dir, n)
                for n in os.listdir(data_dir)
                if n.startswith("part-")
            )[0]
            size = os.path.getsize(path)

        assert cache.populator(RealShard()) is None
        temp_root = os.path.join(cache_dir, "_temporary")
        assert not os.path.isdir(temp_root) or os.listdir(temp_root) == []


def _rewrite_footer(entry_path, mutate):
    """Re-author an entry's footer with a VALID CRC — the 'malformed but
    CRC-consistent metadata' producer-bug class."""
    import struct

    raw = bytearray(open(entry_path, "rb").read())
    (flen,) = struct.unpack("<Q", raw[-20:-12])
    footer = json.loads(raw[-20 - flen : -20].decode("utf-8"))
    mutate(footer)
    blob = json.dumps(footer, sort_keys=True, default=str).encode("utf-8")
    tail = struct.pack("<QI8s", len(blob), wire.crc32c(blob), cache_mod.TAIL_MAGIC)
    open(entry_path, "wb").write(bytes(raw[: -20 - flen]) + blob + tail)


class TestMalformedFooter:
    def test_missing_chunks_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        _rewrite_footer(entries_in(cache_dir)[0], lambda f: f.pop("chunks"))
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1

    def test_inconsistent_section_geometry_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)

        def bad_dtype(footer):
            sec = footer["chunks"][0]["columns"][0]["sections"][0][1]
            sec["dtype"] = "<i3"  # unparseable: view() would raise at serve

        _rewrite_footer(entries_in(cache_dir)[0], bad_dtype)
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1

    def test_unexpected_column_name_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)

        def rename_column(footer):
            footer["chunks"][0]["columns"][0]["name"] = "not_in_schema"

        _rewrite_footer(entries_in(cache_dir)[0], rename_column)
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1

    def test_row_count_mismatch_falls_back(self, data_dir, cache_dir):
        base = collect(data_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)

        def lie_about_rows(footer):
            footer["chunks"][0]["num_rows"] += 1  # sections cover one fewer

        _rewrite_footer(entries_in(cache_dir)[0], lie_about_rows)
        METRICS.reset()
        assert collect(data_dir, cache="auto", cache_dir=cache_dir) == base
        assert METRICS.counter("cache.corrupt_fallbacks") == 1

    def test_release_registry_unpins_cache_dir(self, data_dir, cache_dir):
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        collect(data_dir, cache="auto", cache_dir=cache_dir)  # register
        prefix = os.path.abspath(cache_dir) + os.sep
        assert any(k[0].startswith(prefix) for k in cache_mod._ENTRY_REGISTRY)
        n = cache_mod.release_registry(cache_dir)
        assert n == 3
        assert not any(k[0].startswith(prefix) for k in cache_mod._ENTRY_REGISTRY)

    def test_doctor_reports_malformed_footer_without_crashing(
        self, data_dir, cache_dir, capsys
    ):
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        _rewrite_footer(entries_in(cache_dir)[0], lambda f: f.pop("chunks"))
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "tfrecord_doctor_malformed_test",
            os.path.join(root, "tools", "tfrecord_doctor.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(["cache", cache_dir])
        lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
        statuses = [l["status"] for l in lines if l["event"] == "cache_entry"]
        assert rc == 1 and statuses.count("corrupt") == 1


class TestDoctorCacheSubcommand:
    def _doctor(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "tfrecord_doctor_cache_test",
            os.path.join(root, "tools", "tfrecord_doctor.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_list_verify_and_evict_stale(self, data_dir, cache_dir, capsys):
        doctor = self._doctor()
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        rc = doctor.main(["cache", cache_dir])
        lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
        entries = [l for l in lines if l["event"] == "cache_entry"]
        summary = [l for l in lines if l["event"] == "cache_summary"][0]
        assert rc == 0 and len(entries) == 3 and summary["status_ok"] == 3
        assert all(e["crc_verified"] and e["rows"] == PER_SHARD for e in entries)
        # stale one source shard; --evict-stale drops exactly its entry
        victim = sorted(
            n for n in os.listdir(data_dir) if n.startswith("part-")
        )[0]
        os.utime(os.path.join(data_dir, victim), (1, 1))
        rc = doctor.main(["cache", "--evict-stale", cache_dir])
        lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
        summary = [l for l in lines if l["event"] == "cache_summary"][0]
        assert rc == 1 and summary["status_stale"] == 1 and summary["evicted"] == 1
        assert len(entries_in(cache_dir)) == 2

    def test_corrupt_entry_reported_not_evicted(self, data_dir, cache_dir, capsys):
        doctor = self._doctor()
        collect(data_dir, cache="auto", cache_dir=cache_dir)
        entry = entries_in(cache_dir)[0]
        footer = cache_mod.load_footer(entry)
        off = footer["chunks"][0]["columns"][0]["sections"][0][1]["off"]
        raw = bytearray(open(entry, "rb").read())
        raw[off] ^= 0xFF
        open(entry, "wb").write(bytes(raw))
        rc = doctor.main(["cache", "--evict-stale", cache_dir])
        lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
        corrupt = [l for l in lines if l.get("status") == "corrupt"]
        assert rc == 1 and len(corrupt) == 1
        assert os.path.exists(entry)  # kept for inspection
        rc = doctor.main(["cache", "--evict-stale", "--evict-corrupt", cache_dir])
        capsys.readouterr()
        assert rc == 1 and not os.path.exists(entry)
