"""Ring attention vs the dense oracle on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hlo_util import assert_hlo
from tpu_tfrecord.models.attention import attention_reference, ring_attention
from tpu_tfrecord.tpu import create_mesh


def make_qkv(b=2, l=32, h=2, d=8, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)), dtype=dtype)
    return mk(), mk(), mk()


class TestRingAttention:
    def test_matches_dense_oracle_8way(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv()
        want = attention_reference(q, k, v)
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_matches_with_data_and_seq_axes(self):
        mesh = create_mesh({"data": 2, "seq": 4})
        q, k, v = make_qkv(b=4, l=16)
        want = attention_reference(q, k, v)
        # batch on 'data', sequence on 'seq'
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(mesh, P("data", "seq", None, None))
        q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_single_device_axis_degenerates(self):
        mesh = create_mesh({"seq": 1, "data": 8})
        q, k, v = make_qkv(l=8)
        want = attention_reference(q, k, v)
        got = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_bf16_inputs(self):
        mesh = create_mesh({"seq": 4, "data": 2})
        q, k, v = make_qkv(l=16, dtype=jnp.bfloat16)
        got = ring_attention(q, k, v, mesh)
        assert got.dtype == jnp.bfloat16
        want = attention_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                                   v.astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(want), rtol=3e-2, atol=3e-2
        )

    def test_grad_flows(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv(l=16)

        def loss(q, k, v):
            return ring_attention(q, k, v, mesh).sum()

        g = jax.jit(jax.grad(loss))(q, k, v)
        assert np.isfinite(np.asarray(g)).all()
        # oracle gradient agreement
        g_ref = jax.grad(lambda q, k, v: attention_reference(q, k, v).sum())(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-5)


class TestRingAttentionMaskAndSharding:
    def test_padding_mask_matches_oracle(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv(b=3, l=32)
        lengths = jnp.asarray([32, 10, 1], dtype=jnp.int32)
        want = attention_reference(q, k, v, lengths=lengths)
        got = jax.jit(
            lambda q, k, v, le: ring_attention(q, k, v, mesh, lengths=le)
        )(q, k, v, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_mask_actually_excludes_pad_keys(self):
        mesh = create_mesh({"seq": 4}, jax.devices()[:4])
        q, k, v = make_qkv(b=1, l=16)
        lengths = jnp.asarray([5], dtype=jnp.int32)
        base = ring_attention(q, k, v, mesh, lengths=lengths)
        # garbage in the padded K/V region must not change the output
        k2 = k.at[:, 5:].set(999.0)
        v2 = v.at[:, 5:].set(-999.0)
        got = ring_attention(q, k2, v2, mesh, lengths=lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(base), rtol=1e-6)

    def test_data_axis_keeps_batch_sharded(self):
        mesh = create_mesh({"data": 2, "seq": 4})
        q, k, v = make_qkv(b=4, l=16)
        want = attention_reference(q, k, v)
        fn = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, data_axis="data")
        )
        got = fn(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)
        # batch dim must be sharded on 'data' in the compiled output, and the
        # HLO must not all-gather the batch
        assert got.sharding.spec[0] == "data"
        assert_hlo(fn, (q, k, v), absent=["all-gather"])


class TestUlyssesAttention:
    """All-to-all (DeepSpeed-Ulysses) sequence parallelism: same contract
    as ring_attention, collective profile = 2 all_to_alls instead of p-1
    K/V rotations (SURVEY.md: 'ring attention OR all-to-all')."""

    def test_matches_dense_oracle_8way(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv(h=8)
        want = attention_reference(q, k, v)
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_matches_ring_and_oracle_with_mask(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"seq": 4, "data": 2})
        q, k, v = make_qkv(b=3, l=16, h=4)
        lengths = jnp.asarray([16, 9, 2], dtype=jnp.int32)
        want = attention_reference(q, k, v, lengths=lengths)
        got_u = jax.jit(
            lambda q, k, v, le: ulysses_attention(q, k, v, mesh, lengths=le)
        )(q, k, v, lengths)
        got_r = jax.jit(
            lambda q, k, v, le: ring_attention(q, k, v, mesh, lengths=le)
        )(q, k, v, lengths)
        np.testing.assert_allclose(np.asarray(got_u), np.asarray(want), rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(got_u), np.asarray(got_r), rtol=2e-5, atol=2e-6)

    def test_grad_matches_oracle(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv(l=16, h=8)
        g = jax.jit(
            jax.grad(lambda q, k, v: ulysses_attention(q, k, v, mesh).sum())
        )(q, k, v)
        g_ref = jax.grad(lambda q, k, v: attention_reference(q, k, v).sum())(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-5)

    def test_heads_must_cover_axis(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv(h=2)  # 2 heads cannot split 8 ways
        with pytest.raises(ValueError, match="num_heads"):
            ulysses_attention(q, k, v, mesh)

    def test_hlo_all_to_all_no_all_gather(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"data": 2, "seq": 4})
        q, k, v = make_qkv(b=4, l=16, h=4)
        fn = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, data_axis="data")
        )
        got = fn(q, k, v)
        assert got.sharding.spec[0] == "data"
        assert_hlo(fn, (q, k, v), contains=["all-to-all"], absent=["all-gather"])

    def test_bf16_inputs(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"seq": 4, "data": 2})
        q, k, v = make_qkv(l=16, h=4, dtype=jnp.bfloat16)
        got = ulysses_attention(q, k, v, mesh)
        assert got.dtype == jnp.bfloat16
        want = attention_reference(
            q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
        )
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(want), rtol=3e-2, atol=3e-2
        )


class TestGQA:
    """Grouped-query attention: k/v carry Hkv < H heads; each K/V head
    serves H/Hkv query heads. Both SP flavors stay comm-optimal (only the
    Hkv heads rotate/exchange; the repeat happens locally)."""

    @staticmethod
    def make_gqa(b=2, l=32, h=8, hkv=2, d=8, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(b, l, h, d)), dtype=dtype)
        k = jnp.asarray(rng.normal(size=(b, l, hkv, d)), dtype=dtype)
        v = jnp.asarray(rng.normal(size=(b, l, hkv, d)), dtype=dtype)
        return q, k, v

    def oracle(self, q, k, v, lengths=None):
        """Independent GQA oracle: explicit repeat to H heads + dense MHA
        (differentiable — the grad test traces through it)."""
        g = q.shape[2] // k.shape[2]
        kx = jnp.repeat(k, g, axis=2)
        vx = jnp.repeat(v, g, axis=2)
        return attention_reference(q, kx, vx, lengths=lengths)

    def test_ring_gqa_matches_oracle(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = self.make_gqa()
        want = self.oracle(q, k, v)
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_ulysses_gqa_matches_oracle_and_ring(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"seq": 2, "data": 4})
        q, k, v = self.make_gqa(b=4, l=16, h=4, hkv=2)
        lengths = jnp.asarray([16, 9, 4, 1], dtype=jnp.int32)
        want = self.oracle(q, k, v, lengths=lengths)
        got_u = jax.jit(
            lambda q, k, v, le: ulysses_attention(q, k, v, mesh, lengths=le)
        )(q, k, v, lengths)
        got_r = jax.jit(
            lambda q, k, v, le: ring_attention(q, k, v, mesh, lengths=le)
        )(q, k, v, lengths)
        np.testing.assert_allclose(np.asarray(got_u), np.asarray(want), rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(got_r), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_gqa_grads_match_oracle(self):
        mesh = create_mesh({"seq": 4}, jax.devices()[:4])
        q, k, v = self.make_gqa(l=16, h=4, hkv=2)
        g = jax.jit(
            jax.grad(lambda q, k, v: ring_attention(q, k, v, mesh).sum(), argnums=(0, 1, 2))
        )(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: self.oracle(q, k, v).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)

    def test_mqa_single_kv_head(self):
        """MQA (Hkv=1): ring rotates a single K/V head."""
        mesh = create_mesh({"seq": 4}, jax.devices()[:4])
        q, k, v = self.make_gqa(h=4, hkv=1, l=16)
        want = self.oracle(q, k, v)
        got = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_indivisible_heads_rejected(self):
        mesh = create_mesh({"seq": 4}, jax.devices()[:4])
        q, k, v = self.make_gqa(h=4, hkv=3, l=16)
        with pytest.raises(ValueError, match="num_kv_heads"):
            ring_attention(q, k, v, mesh)


class TestCausal:
    """Decoder/LM masking: keys after the query position get no mass.
    The ring must mask by GLOBAL positions across rotated blocks; ulysses
    inherits the mask locally after the exchange."""

    def test_ring_causal_matches_oracle(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv()
        want = attention_reference(q, k, v, causal=True)
        got = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=True)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_ulysses_causal_matches_oracle(self):
        from tpu_tfrecord.models.attention import ulysses_attention

        mesh = create_mesh({"seq": 4, "data": 2})
        q, k, v = make_qkv(l=16, h=4)
        want = attention_reference(q, k, v, causal=True)
        got = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_causal_composes_with_lengths_and_gqa(self):
        mesh = create_mesh({"seq": 4}, jax.devices()[:4])
        q = jnp.asarray(np.random.default_rng(0).normal(size=(3, 16, 4, 8)), jnp.float32)
        kv = [jnp.asarray(np.random.default_rng(i).normal(size=(3, 16, 2, 8)), jnp.float32) for i in (1, 2)]
        lengths = jnp.asarray([16, 7, 2], dtype=jnp.int32)
        g = 2
        want = attention_reference(
            q, jnp.repeat(kv[0], g, axis=2), jnp.repeat(kv[1], g, axis=2),
            lengths=lengths, causal=True,
        )
        got = jax.jit(
            lambda q, k, v, le: ring_attention(q, k, v, mesh, lengths=le, causal=True)
        )(q, kv[0], kv[1], lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_future_keys_are_inert(self):
        """Garbage in strictly-future K/V positions must not change any
        query's output (the operational meaning of causal)."""
        mesh = create_mesh({"seq": 4}, jax.devices()[:4])
        q, k, v = make_qkv(b=1, l=16)
        base = ring_attention(q, k, v, mesh, causal=True)
        # poison the second half; queries in the FIRST half must not move
        k2 = k.at[:, 8:].set(777.0)
        v2 = v.at[:, 8:].set(-777.0)
        got = ring_attention(q, k2, v2, mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(got)[:, :8], np.asarray(base)[:, :8], rtol=1e-6
        )

    def test_causal_grads_match_oracle(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv(l=16)
        g = jax.jit(
            jax.grad(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True).sum(),
                     argnums=(0, 1, 2))
        )(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: attention_reference(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


class TestZigzagCausal:
    """Balanced causal ring: internal strip re-striping, contiguous
    contract preserved, identical math."""

    def test_matches_contiguous_and_oracle(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv()
        want = attention_reference(q, k, v, causal=True)
        plain = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=True)
        )(q, k, v)
        zz = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=True, zigzag=True)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(zz), np.asarray(want), rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(zz), np.asarray(plain), rtol=2e-5, atol=2e-6)

    def test_composes_with_lengths_gqa_and_data_axis(self):
        mesh = create_mesh({"data": 2, "seq": 4})
        q = jnp.asarray(np.random.default_rng(0).normal(size=(4, 16, 4, 8)), jnp.float32)
        k = jnp.asarray(np.random.default_rng(1).normal(size=(4, 16, 2, 8)), jnp.float32)
        v = jnp.asarray(np.random.default_rng(2).normal(size=(4, 16, 2, 8)), jnp.float32)
        lengths = jnp.asarray([16, 9, 4, 1], dtype=jnp.int32)
        want = attention_reference(
            q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2),
            lengths=lengths, causal=True,
        )
        got = jax.jit(
            lambda q, k, v, le: ring_attention(
                q, k, v, mesh, data_axis="data", lengths=le,
                causal=True, zigzag=True,
            )
        )(q, k, v, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_grads_match_oracle(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv(l=16)
        g = jax.jit(
            jax.grad(
                lambda q, k, v: ring_attention(
                    q, k, v, mesh, causal=True, zigzag=True
                ).sum(),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: attention_reference(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)

    def test_work_is_balanced(self):
        """The schedule's justification: with the half-swap striping
        (device j owns strip 2j and its mirror 2p-1-2j) every device holds
        exactly the same number of unmasked causal (q, k) pairs — which is
        why the kernel's static half-block program (one [Lc, s] or [s, Lk]
        einsum per non-diagonal step, identical on every device) loses
        nothing. The contiguous layout is maximally imbalanced. Computed
        from the same position arithmetic the kernel uses."""
        p, lc = 8, 8  # 8 devices, Lc=8 (strips of 4), L=64
        s = lc // 2

        def dev_pos(dev, zigzag):
            if zigzag:
                half = np.arange(s)
                return np.concatenate(
                    [2 * dev * s + half, (2 * p - 1 - 2 * dev) * s + half]
                )
            return dev * lc + np.arange(lc)

        def unmasked(dev, zigzag):
            qp = dev_pos(dev, zigzag)
            total = 0
            for src in range(p):
                kp = dev_pos(src, zigzag)
                total += int((kp[None, :] <= qp[:, None]).sum())
            return total

        zz = [unmasked(d, True) for d in range(p)]
        plain = [unmasked(d, False) for d in range(p)]
        assert len(set(zz)) == 1, zz                    # perfectly equal
        assert max(plain) > 1.8 * min(plain), plain     # contiguous is not

    def test_zigzag_hlo_collective_permute_no_all_gather(self):
        """The re-stripe must be the in-kernel ppermute half-swap (finding
        r5: a host-level permute of the sharded seq axis could lower to an
        all-gather and break the L/p memory bound)."""
        mesh = create_mesh({"data": 2, "seq": 4})
        q, k, v = make_qkv(b=4, l=16)
        fn = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh, data_axis="data", causal=True, zigzag=True
            )
        )
        got = fn(q, k, v)
        assert got.sharding.spec[0] == "data"
        assert_hlo(
            fn, (q, k, v), contains=["collective-permute"], absent=["all-gather"]
        )

    def test_single_device_axis_self_swap(self):
        """p=1: the swap involution is a self-edge; must degenerate to
        plain causal attention."""
        mesh = create_mesh({"seq": 1, "data": 8})
        q, k, v = make_qkv(l=8)
        want = attention_reference(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, causal=True, zigzag=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)

    def test_zigzag_requires_causal_and_divisibility(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = make_qkv()
        with pytest.raises(ValueError, match="causal"):
            ring_attention(q, k, v, mesh, zigzag=True)
        q2, k2, v2 = make_qkv(l=24)  # 24 % 16 != 0
        with pytest.raises(ValueError, match="zigzag needs"):
            ring_attention(q2, k2, v2, mesh, causal=True, zigzag=True)
