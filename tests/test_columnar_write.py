"""Tests for the columnar (native-encode) write path."""

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import _native
from tpu_tfrecord.columnar import ColumnarDecoder, batch_to_rows
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.options import RecordType, TFRecordOptions
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import NullValueError, TFRecordSerializer, encode_row

SCHEMA = StructType(
    [
        StructField("i", IntegerType()),
        StructField("l", LongType()),
        StructField("f", FloatType()),
        StructField("d", DoubleType()),
        StructField("s", StringType()),
        StructField("b", BinaryType()),
        StructField("fv", ArrayType(FloatType())),
        StructField("lv", ArrayType(LongType())),
        StructField("sv", ArrayType(StringType())),
    ]
)


def make_batch(n=100, with_nulls=False):
    rows = []
    for k in range(n):
        rows.append(
            [
                k,
                k * (2**33),
                k / 2.0,
                None if (with_nulls and k % 5 == 0) else k / 4.0,
                f"s{k}",
                bytes([k % 256]),
                [float(j) for j in range(k % 4)],
                [k, k + 1],
                [f"t{j}" for j in range(k % 3)],
            ]
        )
    ser = TFRecordSerializer(SCHEMA)
    records = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
    return ColumnarDecoder(SCHEMA).decode_batch(records), rows


class TestColumnarWrite:
    def test_round_trip(self, sandbox):
        batch, rows = make_batch(100)
        out = str(sandbox / "cw")
        w = DatasetWriter(out, SCHEMA, TFRecordOptions(), mode="overwrite")
        files = w.write_batches([batch])
        assert len(files) == 1
        ds = TFRecordDataset(out, batch_size=100, schema=SCHEMA, drop_remainder=False)
        with ds.batches() as it:
            back = next(it)
        got_rows = batch_to_rows(back, SCHEMA)
        want_rows = batch_to_rows(batch, SCHEMA)
        for g, w_ in zip(got_rows, want_rows):
            for gv, wv, f in zip(g, w_, SCHEMA):
                if isinstance(wv, float):
                    assert gv == pytest.approx(wv, abs=1e-6), f.name
                elif isinstance(wv, list) and wv and isinstance(wv[0], float):
                    assert gv == pytest.approx(wv, abs=1e-6), f.name
                else:
                    assert gv == wv, f.name

    def test_nulls_round_trip_as_masked(self, sandbox):
        batch, rows = make_batch(50, with_nulls=True)
        out = str(sandbox / "cwn")
        DatasetWriter(out, SCHEMA, TFRecordOptions(), mode="overwrite").write_batches([batch])
        ds = TFRecordDataset(out, batch_size=50, schema=SCHEMA, drop_remainder=False)
        with ds.batches() as it:
            back = next(it)
        np.testing.assert_array_equal(back["d"].mask, batch["d"].mask)
        assert not back["d"].mask.all()

    def test_native_encode_matches_python_row_path(self, sandbox):
        """Force the Python fallback in a second write; decoded batches from
        both files must be identical."""
        if not _native.available():
            pytest.skip("native lib unavailable")
        batch, rows = make_batch(40)
        out_native = str(sandbox / "nat")
        DatasetWriter(out_native, SCHEMA, TFRecordOptions(), mode="overwrite").write_batches([batch])
        out_py = str(sandbox / "py")
        tfio.write(rows, SCHEMA, out_py, mode="overwrite")
        a = tfio.read(out_native, schema=SCHEMA).rows
        b = tfio.read(out_py, schema=SCHEMA).rows
        assert len(a) == len(b) == 40
        for ra, rb in zip(a, b):
            for va, vb in zip(ra, rb):
                if isinstance(vb, float):
                    assert va == pytest.approx(vb, abs=1e-6)
                elif (
                    isinstance(vb, list) and vb and isinstance(vb[0], float)
                ):
                    assert va == pytest.approx(vb, abs=1e-6)
                elif hasattr(vb, "as_tuple"):  # Decimal
                    assert float(va) == pytest.approx(float(vb))
                else:
                    assert va == vb

    def test_max_records_per_file_rollover(self, sandbox):
        batch, _ = make_batch(95)
        out = str(sandbox / "roll")
        w = DatasetWriter(out, SCHEMA, TFRecordOptions(), mode="overwrite",
                          max_records_per_file=30)
        files = w.write_batches([batch])
        assert len(files) == 4  # 30+30+30+5
        assert len(tfio.read(out, schema=SCHEMA)) == 95

    def test_gzip_columnar_write(self, sandbox):
        batch, _ = make_batch(20)
        out = str(sandbox / "gz")
        opts = TFRecordOptions.from_map({"codec": "gzip"})
        files = DatasetWriter(out, SCHEMA, opts, mode="overwrite").write_batches([batch])
        assert files[0].endswith(".tfrecord.gz")
        assert len(tfio.read(out, schema=SCHEMA)) == 20

    def test_non_nullable_mask_raises(self, sandbox):
        schema = StructType([StructField("x", FloatType(), nullable=False)])
        ser = TFRecordSerializer(StructType([StructField("x", FloatType())]))
        records = [
            encode_row(ser, RecordType.EXAMPLE, [1.0]),
        ]
        from tpu_tfrecord import proto
        records.append(proto.encode_example(proto.Example()))  # missing x
        batch = ColumnarDecoder(StructType([StructField("x", FloatType())])).decode_batch(records)
        out = str(sandbox / "nn")
        w = DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite")
        with pytest.raises(NullValueError):
            w.write_batches([batch])

    def test_partitioned_columnar_write(self, sandbox):
        import os

        schema = StructType(
            [StructField("x", LongType()), StructField("day", StringType())]
        )
        rows = [[i, "a" if i < 6 else "b"] for i in range(10)]
        ser = TFRecordSerializer(schema)
        records = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
        batch = ColumnarDecoder(schema).decode_batch(records)
        out = str(sandbox / "pcw")
        w = DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite",
                          partition_by=["day"])
        files = w.write_batches([batch])
        assert sorted(d for d in os.listdir(out) if d != "_SUCCESS") == [
            "day=a", "day=b",
        ]
        t = tfio.read(out)
        got = sorted(t.to_dicts(), key=lambda d: d["x"])
        assert [d["day"] for d in got] == ["a"] * 6 + ["b"] * 4
        assert [d["x"] for d in got] == list(range(10))

    def test_partitioned_columnar_interleaved_keys(self, sandbox):
        schema = StructType(
            [StructField("x", LongType()), StructField("k", LongType())]
        )
        rows = [[i, i % 3] for i in range(12)]  # worst case: alternating keys
        ser = TFRecordSerializer(schema)
        records = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
        batch = ColumnarDecoder(schema).decode_batch(records)
        out = str(sandbox / "pci")
        DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite",
                      partition_by=["k"]).write_batches([batch])
        t = tfio.read(out)
        assert sorted(t.column("x")) == list(range(12))
        assert sorted(set(t.column("k"))) == [0, 1, 2]

    def test_interleaved_keys_preserve_order_within_partition(self, sandbox):
        """The grouping plan (stable argsort + one gather) must keep each
        partition's rows in their original relative order — same guarantee
        the run-by-run path gives pre-clustered input."""
        schema = StructType(
            [StructField("x", LongType()), StructField("k", LongType())]
        )
        rows = [[i, i % 4] for i in range(64)]
        ser = TFRecordSerializer(schema)
        records = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
        batch = ColumnarDecoder(schema).decode_batch(records)
        out = str(sandbox / "pord")
        DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite",
                      partition_by=["k"]).write_batches([batch])
        for k in range(4):
            part = tfio.read(f"{out}/k={k}")
            xs = part.column("x")
            assert xs == sorted(xs), (k, xs)  # original order i, i+4, i+8...
            assert xs == list(range(k, 64, 4))

    def test_partition_plan_multi_column_mixed_types(self, sandbox):
        """Vectorized key codes across (string, long) columns with nulls:
        same directories and same row routing as the reference's
        col1=v/col2=v layout."""
        import os

        schema = StructType(
            [
                StructField("x", LongType()),
                StructField("day", StringType()),
                StructField("h", LongType()),
            ]
        )
        rows = [
            [0, "a", 1], [1, "b", 1], [2, "a", 2], [3, None, 1],
            [4, "a", 1], [5, "b", 1], [6, None, 1], [7, "a", 2],
        ]
        ser = TFRecordSerializer(schema)
        records = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
        batch = ColumnarDecoder(schema).decode_batch(records)
        out = str(sandbox / "pmc")
        DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite",
                      partition_by=["day", "h"]).write_batches([batch])
        assert sorted(d for d in os.listdir(out) if d != "_SUCCESS") == [
            "day=__HIVE_DEFAULT_PARTITION__", "day=a", "day=b",
        ]
        got = {d["x"]: (d["day"], d["h"]) for d in tfio.read(out).to_dicts()}
        for r in rows:
            assert got[r[0]] == (r[1], r[2])

    @pytest.mark.perf
    def test_interleaved_partition_write_throughput_ratio(self, sandbox):
        """VERDICT r4 item 6 done-bar: fully interleaved keys write within
        3x of the unpartitioned columnar path (grouping plan: one argsort +
        one gather instead of per-row runs). The row count is sized so that
        encode/plan compute dominates the fixed per-directory filesystem
        cost (16 partition dirs x ~0.5ms/metadata-op on container overlay
        filesystems): with the native encoder available, a small workload
        would measure mkdir+rename syscalls, not the grouping plan this
        test exists to pin."""
        import time

        import numpy as np

        from tpu_tfrecord.columnar import Column, ColumnarBatch

        schema = StructType(
            [StructField("x", LongType()), StructField("k", LongType())]
        )
        n = 240_000
        rng = np.random.default_rng(0)
        batch = ColumnarBatch(
            {
                "x": Column(
                    "x", LongType(),
                    values=rng.integers(0, 1 << 40, n, dtype=np.int64),
                ),
                "k": Column(
                    "k", LongType(),
                    values=np.arange(n, dtype=np.int64) % 16,
                ),
            },
            n,
        )

        def best_of(f, reps=3):
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                f()
                best = min(best, time.perf_counter() - t0)
            return best

        flat = best_of(lambda: DatasetWriter(
            str(sandbox / "flat"), schema, TFRecordOptions(), mode="overwrite"
        ).write_batches([batch]))
        part = best_of(lambda: DatasetWriter(
            str(sandbox / "part"), schema, TFRecordOptions(), mode="overwrite",
            partition_by=["k"],
        ).write_batches([batch]))
        assert part < flat * 3, (part, flat)

    def test_partitioned_columnar_null_key(self, sandbox):
        import os

        schema = StructType(
            [StructField("x", LongType()), StructField("day", StringType())]
        )
        rows = [[1, "a"], [2, None]]
        ser = TFRecordSerializer(schema)
        records = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
        batch = ColumnarDecoder(schema).decode_batch(records)
        out = str(sandbox / "pcn")
        DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite",
                      partition_by=["day"]).write_batches([batch])
        assert os.path.isdir(os.path.join(out, "day=__HIVE_DEFAULT_PARTITION__"))
        t = tfio.read(out)
        got = sorted(t.to_dicts(), key=lambda d: d["x"])
        assert got[1]["day"] is None

    def test_decimal_column_batch_write(self, sandbox):
        schema = StructType([StructField("dec", DecimalType())])
        ser = TFRecordSerializer(schema)
        import decimal

        records = [encode_row(ser, RecordType.EXAMPLE, [decimal.Decimal("1.5")])]
        batch = ColumnarDecoder(schema).decode_batch(records)
        out = str(sandbox / "dec")
        DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite").write_batches([batch])
        t = tfio.read(out, schema=schema)
        assert float(t.rows[0][0]) == 1.5


class TestSequenceExampleColumnarWrite:
    SCHEMA = StructType(
        [
            StructField("id", LongType()),
            StructField("toks", ArrayType(LongType())),
            StructField("frames", ArrayType(ArrayType(FloatType()))),
            StructField("names", ArrayType(ArrayType(StringType()))),
        ]
    )

    def make_batch(self, n=60):
        rows = []
        for k in range(n):
            rows.append(
                [
                    k,
                    [k, k + 1][: k % 3],
                    [[float(j) for j in range(k % 4)] for _ in range(k % 3)],
                    [[f"n{j}" for j in range(1 + k % 2)] for _ in range(k % 2 + 1)],
                ]
            )
        ser = TFRecordSerializer(self.SCHEMA)
        records = [encode_row(ser, RecordType.SEQUENCE_EXAMPLE, r) for r in rows]
        return ColumnarDecoder(self.SCHEMA, RecordType.SEQUENCE_EXAMPLE).decode_batch(records), rows

    def test_native_sequence_encode_round_trip(self, sandbox):
        if not _native.available():
            pytest.skip("native lib unavailable")
        batch, rows = self.make_batch()
        enc = _native.NativeEncoder(self.SCHEMA, RecordType.SEQUENCE_EXAMPLE)
        framed = enc.encode_batch(batch)
        # scan + decode the stream back and compare with the original batch
        offsets, lengths = _native.scan(framed.tobytes())
        back = _native.NativeDecoder(self.SCHEMA, RecordType.SEQUENCE_EXAMPLE).decode_spans(
            framed.tobytes(), offsets, lengths
        )
        from tests.test_native import assert_batches_equal

        assert_batches_equal(back, batch)

    def test_writer_sequence_batches(self, sandbox):
        batch, rows = self.make_batch(40)
        out = str(sandbox / "seqw")
        opts = TFRecordOptions.from_map({"recordType": "SequenceExample"})
        files = DatasetWriter(out, self.SCHEMA, opts, mode="overwrite").write_batches([batch])
        assert len(files) == 1
        t = tfio.read(out, schema=self.SCHEMA, recordType="SequenceExample")
        got = sorted(t.rows, key=lambda r: r[0])
        for g, w in zip(got, rows):
            assert g[0] == w[0] and g[1] == w[1]
            assert g[3] == w[3]
            for ga, wa in zip(g[2], w[2]):
                assert ga == pytest.approx(wa)

    def test_example_with_ragged2_rejected(self):
        if not _native.available():
            pytest.skip("native lib unavailable")
        with pytest.raises(ValueError, match="SequenceExample"):
            _native.NativeEncoder(self.SCHEMA, RecordType.EXAMPLE)

    def test_config_error_before_filesystem_mutation(self, sandbox):
        """An Example+ragged2 config error must raise BEFORE overwrite
        deletion or temp-dir creation (review regression)."""
        if not _native.available():
            pytest.skip("native lib unavailable")
        import os

        out = str(sandbox / "cfg")
        tfio.write([[1]], StructType([StructField("x", LongType())]), out,
                   mode="overwrite")
        files_before = sorted(os.listdir(out))
        w = DatasetWriter(out, self.SCHEMA, TFRecordOptions(), mode="overwrite")
        with pytest.raises(ValueError, match="SequenceExample"):
            w.write_batches([])
        assert sorted(os.listdir(out)) == files_before  # nothing touched

    def test_config_errors_before_overwrite_deletion(self, sandbox):
        """Ragged partition col / missing batch column must not destroy an
        existing dataset under mode=overwrite (review regression)."""
        import os

        out = str(sandbox / "keep")
        keep_schema = StructType([StructField("x", LongType())])
        tfio.write([[1]], keep_schema, out, mode="overwrite")
        before = sorted(os.listdir(out))
        # ragged partition column: rejected at constructor time
        rag = StructType([StructField("x", LongType()),
                          StructField("a", ArrayType(LongType()))])
        with pytest.raises(ValueError, match="cannot be an array"):
            DatasetWriter(out, rag, TFRecordOptions(), mode="overwrite",
                          partition_by=["a"])
        # batch missing the partition column: rejected before deletion
        schema = StructType([StructField("x", LongType()), StructField("k", LongType())])
        ser = TFRecordSerializer(keep_schema)
        b = ColumnarDecoder(keep_schema).decode_batch(
            [encode_row(ser, RecordType.EXAMPLE, [5])]
        )
        w = DatasetWriter(out, schema, TFRecordOptions(), mode="overwrite",
                          partition_by=["k"])
        with pytest.raises(ValueError, match="not present in"):
            w.write_batches([b])
        assert sorted(os.listdir(out)) == before

    def test_binary_partition_value_matches_row_path(self, sandbox):
        import os

        schema = StructType([StructField("x", LongType()),
                             StructField("b", BinaryType())])
        rows = [[1, b"\xff\xfe"], [2, b"ok"]]
        out_rows = str(sandbox / "rowp")
        tfio.write(rows, schema, out_rows, mode="overwrite", partition_by=["b"])
        ser = TFRecordSerializer(schema)
        batch = ColumnarDecoder(schema).decode_batch(
            [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
        )
        out_cols = str(sandbox / "colp")
        DatasetWriter(out_cols, schema, TFRecordOptions(), mode="overwrite",
                      partition_by=["b"]).write_batches([batch])
        assert sorted(os.listdir(out_rows)) == sorted(os.listdir(out_cols))
