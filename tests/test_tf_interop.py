"""Ecosystem interop pinned against the REAL TensorFlow runtime (VERDICT r2
next-step #4): files written by tf.io.TFRecordWriter (uncompressed / GZIP /
ZLIB) must read, infer, and decode here; files written here must parse with
tf.train.Example and stream through tf.data.TFRecordDataset.

TF import is heavy (~15s) — everything is module-level gated so the suite
still runs where TF is absent.
"""

import glob
import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import tpu_tfrecord.io as tfio
from tpu_tfrecord import infer, wire
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    ArrayType,
    FloatType,
    LongType,
    StringType,
    StructField,
    StructType,
)

SCHEMA = StructType(
    [
        StructField("uid", LongType()),
        StructField("score", FloatType()),
        StructField("emb", ArrayType(FloatType())),
        StructField("name", StringType()),
    ]
)


def _tf_example(uid, score, emb, name):
    return tf.train.Example(
        features=tf.train.Features(
            feature={
                "uid": tf.train.Feature(int64_list=tf.train.Int64List(value=[uid])),
                "score": tf.train.Feature(float_list=tf.train.FloatList(value=[score])),
                "emb": tf.train.Feature(float_list=tf.train.FloatList(value=emb)),
                "name": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[name.encode()])
                ),
            }
        )
    )


def _write_with_tf(path, n, compression=""):
    opts = tf.io.TFRecordOptions(compression_type=compression)
    with tf.io.TFRecordWriter(path, opts) as w:
        for i in range(n):
            w.write(
                _tf_example(i, i / 2.0, [float(i), float(i + 1)], f"n{i}")
                .SerializeToString()
            )


# TF's compression names -> ours (ZLIB is a bare zlib stream = deflate)
TF_CODECS = [("", None, ""), ("GZIP", "gzip", ".gz"), ("ZLIB", "deflate", ".deflate")]


class TestTFWritesWeRead:
    @pytest.mark.parametrize("tf_codec,codec,ext", TF_CODECS)
    def test_read_and_infer(self, sandbox, tf_codec, codec, ext):
        path = str(sandbox / f"tfw.tfrecord{ext}")
        _write_with_tf(path, 8, tf_codec)
        # explicit schema decode
        table = tfio.read(path, schema=SCHEMA, codec=codec)
        rows = sorted(table.to_dicts(), key=lambda d: d["uid"])
        assert rows[3]["uid"] == 3
        assert rows[3]["score"] == pytest.approx(1.5)
        assert rows[3]["emb"] == pytest.approx([3.0, 4.0])
        assert rows[3]["name"] == "n3"
        # schema inference from TF-written bytes (extension autodetect)
        inferred = tfio.reader(path).schema()
        assert {f.name for f in inferred} == {"uid", "score", "emb", "name"}

    def test_wire_level_crc_agreement(self, sandbox):
        """Byte-level: the records TF framed verify under our CRC check."""
        path = str(sandbox / "crc.tfrecord")
        _write_with_tf(path, 4)
        recs = list(wire.read_records(path))  # verify_crc on by default
        assert len(recs) == 4
        ex = tf.train.Example.FromString(recs[0])
        assert ex.features.feature["uid"].int64_list.value[0] == 0


class TestWeWriteTFReads:
    @pytest.mark.parametrize("tf_codec,codec,ext", TF_CODECS)
    def test_tf_data_pipeline_parses(self, sandbox, tf_codec, codec, ext):
        out = str(sandbox / f"ours_{codec}")
        rows = [[i, i / 2.0, [float(i)], f"n{i}"] for i in range(10)]
        tfio.write(rows, SCHEMA, out, mode="overwrite", codec=codec)
        shards = sorted(glob.glob(os.path.join(out, f"part-*.tfrecord{ext}")))
        assert shards
        ds = tf.data.TFRecordDataset(shards, compression_type=tf_codec)
        uids = []
        for raw in ds:
            ex = tf.train.Example.FromString(raw.numpy())
            uids.append(int(ex.features.feature["uid"].int64_list.value[0]))
        assert sorted(uids) == list(range(10))

    def test_sequence_example_cross_parse(self, sandbox):
        schema = StructType(
            [
                StructField("id", LongType()),
                StructField("frames", ArrayType(ArrayType(FloatType()))),
            ]
        )
        out = str(sandbox / "seq")
        tfio.write(
            [[7, [[1.0, 2.0], [3.0]]]], schema, out, mode="overwrite",
            recordType="SequenceExample",
        )
        shard = glob.glob(os.path.join(out, "part-*.tfrecord"))[0]
        raw = next(iter(tf.data.TFRecordDataset([shard]))).numpy()
        se = tf.train.SequenceExample.FromString(raw)
        assert se.context.feature["id"].int64_list.value[0] == 7
        fl = se.feature_lists.feature_list["frames"].feature
        assert [list(f.float_list.value) for f in fl] == [[1.0, 2.0], [3.0]]

    def test_tf_parse_example_op(self, sandbox):
        """Our bytes through TF's actual parsing op (tf.io.parse_example)."""
        out = str(sandbox / "pe")
        tfio.write([[1, 0.5, [1.0, 2.0], "a"], [2, 1.5, [3.0, 4.0], "b"]],
                   SCHEMA, out, mode="overwrite")
        shard = glob.glob(os.path.join(out, "part-*.tfrecord"))[0]
        raws = [r.numpy() for r in tf.data.TFRecordDataset([shard])]
        parsed = tf.io.parse_example(
            tf.constant(raws),
            {
                "uid": tf.io.FixedLenFeature([], tf.int64),
                "emb": tf.io.FixedLenFeature([2], tf.float32),
            },
        )
        np.testing.assert_array_equal(
            np.sort(parsed["uid"].numpy()), np.array([1, 2])
        )
