"""Tests for the parallel write pipeline (write_workers / num_shards).

Pins the three contracts ISSUE 1 demands of the slab pipeline:

- determinism: shard bytes are a function of (rows, options) — identical
  for write_workers=1 vs N at fixed num_shards, for every chunked codec;
- partitionBy routing under concurrency matches the sequential writer;
- abort hygiene: a worker failure mid-job leaves nothing outside
  ``_temporary/`` and writes no ``_SUCCESS``.
"""

import os
import re

import numpy as np
import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import proto, wire
from tpu_tfrecord.columnar import ColumnarDecoder
from tpu_tfrecord.io.writer import DatasetWriter
from tpu_tfrecord.options import RecordType, TFRecordOptions
from tpu_tfrecord.schema import (
    LongType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.serde import NullValueError, TFRecordSerializer, encode_row

SCHEMA = StructType(
    [StructField("x", LongType()), StructField("s", StringType())]
)


def make_batches(n_rows=2000, batch_size=512, schema=SCHEMA, key_mod=None):
    rows = []
    for i in range(n_rows):
        row = [i, f"value-{i}"]
        if key_mod is not None:
            row = [i, i % key_mod]
        rows.append(row)
    ser = TFRecordSerializer(schema)
    records = [encode_row(ser, RecordType.EXAMPLE, r) for r in rows]
    dec = ColumnarDecoder(schema)
    batches = [
        dec.decode_batch(records[i : i + batch_size])
        for i in range(0, len(records), batch_size)
    ]
    return batches, rows


def shard_bytes(out):
    """{(partition dir, cNNN-sequence): file bytes} — keyed by the stable
    per-dir file counter, not the per-job random uuid in the name."""
    got = {}
    for root, _dirs, files in os.walk(out):
        if os.path.basename(root) == "_temporary":
            continue
        for f in files:
            m = re.match(r"part-\d+-[0-9a-f]+\.(c\d+)\.", f)
            if m:
                rel = os.path.relpath(root, out)
                with open(os.path.join(root, f), "rb") as fh:
                    got[(rel, m.group(1))] = fh.read()
    return got


class TestDeterminism:
    @pytest.mark.parametrize("codec", [None, "zlib", "gzip"])
    def test_worker_count_never_changes_bytes(self, sandbox, codec):
        """Same rows + fixed num_shards -> byte-identical shards for
        write_workers=1 vs 4 (the pipeline's core guarantee: output is a
        function of data and options, not thread timing)."""
        batches, _ = make_batches(4000)
        outs = {}
        for w in (1, 4):
            out = str(sandbox / f"w{w}-{codec}")
            opts = TFRecordOptions.from_map(
                write_workers=w, num_shards=3, codec=codec
            )
            DatasetWriter(
                out, SCHEMA, opts, mode="overwrite", max_records_per_file=700
            ).write_batches(batches)
            outs[w] = shard_bytes(out)
        assert set(outs[1]) == set(outs[4])
        for key in outs[1]:
            assert outs[1][key] == outs[4][key], key

    def test_write_rows_worker_count_never_changes_bytes(self, sandbox):
        _, rows = make_batches(3000)
        outs = {}
        for w in (1, 3):
            out = str(sandbox / f"rw{w}")
            opts = TFRecordOptions.from_map(
                write_workers=w, num_shards=2, codec="zlib"
            )
            DatasetWriter(out, SCHEMA, opts, mode="overwrite").write_rows(rows)
            outs[w] = shard_bytes(out)
        assert outs[1] == outs[3]

    def test_default_path_stays_legacy(self, sandbox):
        """write_workers=1 without num_shards must take the sequential
        legacy path — stream compression, one compressobj per file — and
        stay byte-identical to the pre-pipeline writer (pinned by writing
        the stream by hand)."""
        batches, rows = make_batches(300, batch_size=300)
        out = str(sandbox / "legacy")
        opts = TFRecordOptions.from_map(codec="zlib")
        w = DatasetWriter(out, SCHEMA, opts, mode="overwrite")
        assert not w.use_pipeline
        (path,) = w.write_batches(batches)
        import zlib

        from tpu_tfrecord import _native

        encoder = _native.make_encoder(SCHEMA, RecordType.EXAMPLE)
        if encoder is not None:
            framed = b"".join(bytes(encoder.encode_batch(b)) for b in batches)
        else:
            ser = TFRecordSerializer(SCHEMA)
            framed = b"".join(
                wire.encode_record(encode_row(ser, RecordType.EXAMPLE, r))
                for r in rows
            )
        want = zlib.compressobj()
        expect = want.compress(framed) + want.flush()
        with open(path, "rb") as fh:
            assert fh.read() == expect


class TestPipelineSemantics:
    def test_round_trip_parallel(self, sandbox):
        batches, rows = make_batches(5000)
        out = str(sandbox / "rt")
        opts = TFRecordOptions.from_map(write_workers=4, num_shards=3)
        files = DatasetWriter(out, SCHEMA, opts, mode="overwrite").write_batches(
            batches
        )
        assert len(files) == 3  # round-robin kept all three streams busy
        got = sorted(tfio.read(out, schema=SCHEMA).rows)
        assert got == sorted(rows)

    def test_single_large_batch_spreads_over_num_shards(self, sandbox):
        """Round-robin advances per slab, so even ONE big batch fans out
        over the shard streams (review regression: per-submit advance left
        a single-batch materialization in one file)."""
        batches, rows = make_batches(20_000, batch_size=20_000)
        out = str(sandbox / "bigbatch")
        opts = TFRecordOptions.from_map(write_workers=2, num_shards=3)
        files = DatasetWriter(out, SCHEMA, opts, mode="overwrite").write_batches(
            batches
        )
        assert len(files) == 3  # ceil(20000/8192)=3 slabs round-robin
        assert sorted(tfio.read(out, schema=SCHEMA).rows) == sorted(rows)

    def test_num_shards_alone_engages_pipeline(self, sandbox):
        batches, rows = make_batches(1000)
        out = str(sandbox / "ns")
        opts = TFRecordOptions.from_map(num_shards=4)
        w = DatasetWriter(out, SCHEMA, opts, mode="overwrite")
        assert w.use_pipeline
        files = w.write_batches(batches)
        assert 1 < len(files) <= 4
        assert sorted(tfio.read(out, schema=SCHEMA).rows) == sorted(rows)

    def test_max_records_per_shard_option(self, sandbox):
        batches, rows = make_batches(950)
        out = str(sandbox / "roll")
        opts = TFRecordOptions.from_map(
            write_workers=2, max_records_per_shard=300
        )
        files = DatasetWriter(out, SCHEMA, opts, mode="overwrite").write_batches(
            batches
        )
        assert len(files) == 4  # 300+300+300+50 on the single stream
        counts = sorted(
            sum(1 for _ in wire.read_records(f)) for f in files
        )
        assert counts == [50, 300, 300, 300]
        assert len(tfio.read(out, schema=SCHEMA)) == 950

    def test_partition_by_parallel_routing(self, sandbox):
        schema = StructType(
            [StructField("x", LongType()), StructField("k", LongType())]
        )
        batches, rows = make_batches(
            3000, batch_size=256, schema=schema, key_mod=5
        )
        out = str(sandbox / "part")
        opts = TFRecordOptions.from_map(write_workers=4, num_shards=2)
        DatasetWriter(
            out, schema, opts, mode="overwrite", partition_by=["k"]
        ).write_batches(batches)
        assert sorted(d for d in os.listdir(out) if d != "_SUCCESS") == [
            f"k={i}" for i in range(5)
        ]
        got = {d["x"]: d["k"] for d in tfio.read(out).to_dicts()}
        assert got == {r[0]: r[1] for r in rows}

    def test_partition_by_parallel_matches_sequential(self, sandbox):
        """Same rows through the sequential writer and the pipeline land in
        the same partition directories with the same per-partition row
        sets."""
        schema = StructType(
            [StructField("x", LongType()), StructField("k", LongType())]
        )
        batches, rows = make_batches(
            2000, batch_size=333, schema=schema, key_mod=3
        )
        seq_out = str(sandbox / "seq")
        DatasetWriter(
            seq_out, schema, TFRecordOptions(), mode="overwrite",
            partition_by=["k"],
        ).write_batches(batches)
        par_out = str(sandbox / "par")
        DatasetWriter(
            par_out, schema,
            TFRecordOptions.from_map(write_workers=4),
            mode="overwrite", partition_by=["k"],
        ).write_batches(batches)
        for k in range(3):
            a = sorted(tfio.read(f"{seq_out}/k={k}", schema=schema.drop(["k"])).rows)
            b = sorted(tfio.read(f"{par_out}/k={k}", schema=schema.drop(["k"])).rows)
            assert a == b

    def test_write_rows_parallel_partitioned(self, sandbox):
        schema = StructType(
            [StructField("x", LongType()), StructField("k", LongType())]
        )
        rows = [[i, i % 4] for i in range(1000)]
        out = str(sandbox / "rowpart")
        opts = TFRecordOptions.from_map(write_workers=3)
        DatasetWriter(
            out, schema, opts, mode="overwrite", partition_by=["k"]
        ).write_rows(rows)
        got = {d["x"]: d["k"] for d in tfio.read(out).to_dicts()}
        assert got == {r[0]: r[1] for r in rows}

    def test_chunked_codecs_round_trip(self, sandbox):
        """Every chunked codec's concatenated-slab output reads back whole
        through the standard read path (multi-member gzip, concatenated
        zlib/zstd streams, whole Hadoop blocks)."""
        batches, rows = make_batches(1500, batch_size=97)
        codecs = ["gzip", "zlib", "snappy", "lz4", "bzip2"]
        if wire._zstandard() is not None:
            codecs.append("zstd")
        for codec in codecs:
            out = str(sandbox / f"cc-{codec}")
            opts = TFRecordOptions.from_map(
                write_workers=4, num_shards=2, codec=codec
            )
            DatasetWriter(out, SCHEMA, opts, mode="overwrite").write_batches(
                batches
            )
            got = sorted(tfio.read(out, schema=SCHEMA).rows)
            assert got == sorted(rows), codec


# NOTE: there is deliberately no wall-clock parallel-vs-sequential assertion
# here. On host-contended 2-vCPU boxes two GIL-free zlib threads can scale
# anywhere from 1.1x to 1.7x moment to moment, so a test-sized workload
# measures the neighbors, not the pipeline. The perf claim lives in
# bench_write.py, which discloses the box's attainable 2-thread ceiling
# (parallel_scaling_probe) next to the measured speedup.


class TestAbortHygiene:
    def test_worker_error_leaves_no_output(self, sandbox):
        """NullValueError raised on a worker thread mid-job: no stray files
        outside _temporary, no _SUCCESS, and the job-created output dir is
        removed so a retry sees the original save-mode world."""
        ns = StructType([StructField("x", LongType(), nullable=False)])
        nullable = StructType([StructField("x", LongType())])
        ser = TFRecordSerializer(nullable)
        bad = ColumnarDecoder(nullable).decode_batch(
            [
                encode_row(ser, RecordType.EXAMPLE, [1]),
                proto.encode_example(proto.Example()),  # missing x -> null
            ]
        )
        out = str(sandbox / "abort")
        w = DatasetWriter(
            out, ns, TFRecordOptions.from_map(write_workers=4), mode="overwrite"
        )
        with pytest.raises(NullValueError):
            w.write_batches([bad])
        assert not os.path.exists(out)

    def test_batch_source_error_leaves_no_output(self, sandbox):
        batches, _ = make_batches(2000)

        def gen():
            yield from batches[:2]
            raise RuntimeError("source failed")

        out = str(sandbox / "srcabort")
        w = DatasetWriter(
            out, SCHEMA,
            TFRecordOptions.from_map(write_workers=4, num_shards=2),
            mode="overwrite",
        )
        with pytest.raises(RuntimeError, match="source failed"):
            w.write_batches(gen())
        assert not os.path.exists(out)

    def test_abort_preserves_existing_output(self, sandbox):
        """mode=append + a mid-job failure must leave the pre-existing
        dataset exactly as it was (nothing leaks outside _temporary)."""
        out = str(sandbox / "keep")
        batches, _ = make_batches(100, batch_size=100)
        DatasetWriter(out, SCHEMA, TFRecordOptions(), mode="overwrite").write_batches(
            batches
        )
        before = shard_bytes(out)
        assert before

        def gen():
            yield batches[0]
            raise RuntimeError("boom")

        w = DatasetWriter(
            out, SCHEMA, TFRecordOptions.from_map(write_workers=2),
            mode="append",
        )
        with pytest.raises(RuntimeError):
            w.write_batches(gen())
        assert shard_bytes(out) == before
        leftovers = [
            d for d in os.listdir(out) if d.startswith("_temporary")
        ]
        assert leftovers in ([], ["_temporary"])
        if leftovers:  # job dir itself must be gone
            assert os.listdir(os.path.join(out, "_temporary")) == []


class TestAbortHygieneConstruction:
    def test_constructor_error_still_aborts_job(self, sandbox):
        """A pipeline/serializer construction failure (after the job temp
        dir exists) must clean up like any other mid-job error: no leftover
        _temporary/, and the job-created output dir removed so a retry sees
        the original save-mode world (review regression)."""
        from tpu_tfrecord.schema import ArrayType, NullType

        bad_schema = StructType([StructField("x", ArrayType(NullType()))])
        out = str(sandbox / "ctor")
        w = DatasetWriter(
            out, bad_schema, TFRecordOptions.from_map(write_workers=2),
            mode="error",
        )
        with pytest.raises(Exception):
            w.write_rows([[None]])
        assert not os.path.exists(out)
        # retry must hit the same save-mode world, not FileExistsError
        w2 = DatasetWriter(
            out, bad_schema, TFRecordOptions.from_map(write_workers=2),
            mode="error",
        )
        with pytest.raises(Exception) as ei:
            w2.write_rows([[None]])
        assert not isinstance(ei.value, FileExistsError)


class TestOptionsPlumbing:
    def test_from_map_spellings(self):
        o = TFRecordOptions.from_map(
            {"writeWorkers": "4", "numShards": "2", "maxRecordsPerShard": "10"}
        )
        assert (o.write_workers, o.num_shards, o.max_records_per_shard) == (4, 2, 10)
        o = TFRecordOptions.from_map(
            write_workers=2, num_shards=1, max_records_per_shard=5
        )
        assert (o.write_workers, o.num_shards, o.max_records_per_shard) == (2, 1, 5)

    @pytest.mark.parametrize(
        "kw", [{"write_workers": 0}, {"num_shards": 0}, {"max_records_per_shard": 0}]
    )
    def test_invalid_values_raise(self, kw):
        with pytest.raises(ValueError):
            TFRecordOptions.from_map(**kw)

    def test_unknown_key_suggestion_still_works(self):
        with pytest.raises(ValueError, match="writeWorkers"):
            TFRecordOptions.from_map(writeWorkerz=2)

    def test_write_metrics_wired(self, sandbox):
        from tpu_tfrecord.metrics import METRICS

        METRICS.reset()
        batches, _ = make_batches(1000)
        out = str(sandbox / "metrics")
        opts = TFRecordOptions.from_map(write_workers=2, codec="zlib")
        DatasetWriter(out, SCHEMA, opts, mode="overwrite").write_batches(batches)
        snap = METRICS.snapshot("write")
        assert snap["write"]["records"] == 1000
        assert snap["write.encode"]["records"] == 1000
        assert snap["write.compress"]["records"] == 1000
        assert snap["write.io"]["bytes"] > 0


class TestChunkedWire:
    """wire-level contracts the pipeline's per-slab compression rides on."""

    def test_deflate_concatenated_streams_read_back(self, tmp_path):
        import zlib

        a = wire.encode_record(b"first") * 3
        b = wire.encode_record(b"second") * 2
        path = str(tmp_path / "cat.tfrecord.deflate")
        with open(path, "wb") as fh:
            fh.write(wire.compress_chunk("zlib", a))
            fh.write(wire.compress_chunk("zlib", b))
        got = list(wire.read_records(path))
        assert got == [b"first"] * 3 + [b"second"] * 2
        # and whole-file equivalence with a single stream of the same bytes
        single = zlib.decompress(zlib.compress(a + b))
        assert b"".join(wire.encode_record(g) for g in got) == single

    def test_deflate_trailing_garbage_raises_corruption(self, tmp_path):
        """Bad bytes where a concatenated stream's header should be must
        surface as TFRecordCorruptionError, not raw zlib.error (review
        regression)."""
        path = str(tmp_path / "garb.tfrecord.deflate")
        with open(path, "wb") as fh:
            fh.write(wire.compress_chunk("zlib", wire.encode_record(b"ok")))
            fh.write(b"\x00\xffnot-zlib")
        with pytest.raises(wire.TFRecordCorruptionError, match="deflate"):
            list(wire.read_records(path))

    def test_deflate_truncated_second_stream_raises(self, tmp_path):
        a = wire.compress_chunk("zlib", wire.encode_record(b"ok"))
        b = wire.compress_chunk("zlib", wire.encode_record(b"lost"))
        path = str(tmp_path / "trunc.tfrecord.deflate")
        with open(path, "wb") as fh:
            fh.write(a)
            fh.write(b[: len(b) - 3])
        with pytest.raises(wire.TFRecordCorruptionError, match="truncated"):
            list(wire.read_records(path))

    def test_gzip_chunk_is_deterministic_member(self, tmp_path):
        data = b"x" * 10000
        assert wire.compress_chunk("gzip", data) == wire.compress_chunk("gzip", data)
        path = str(tmp_path / "m.gz")
        with open(path, "wb") as fh:
            fh.write(wire.compress_chunk("gzip", data))
            fh.write(wire.compress_chunk("gzip", data))
        import gzip

        with gzip.open(path, "rb") as fh:
            assert fh.read() == data * 2

    def test_hadoop_block_chunks_concatenate(self):
        from tpu_tfrecord.hadoop_codecs import compress_hadoop_blocks

        payload = os.urandom(300 * 1024)  # spans >1 block
        chunk = compress_hadoop_blocks("lz4", payload)
        two = chunk + compress_hadoop_blocks("lz4", payload)
        import io as _io

        from tpu_tfrecord.hadoop_codecs import HadoopBlockFile

        fh = HadoopBlockFile("<mem>", "rb", "lz4", fileobj=_io.BytesIO(two))
        assert fh.read() == payload * 2

    def test_codec_supports_chunks(self):
        for codec in (None, "gzip", "deflate", "snappy", "lz4", "bzip2"):
            assert wire.codec_supports_chunks(codec)
